#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line.

Runs the MD5 mask-attack fused pipeline on the real TPU (config 1's
throughput path).  The TPU is reached through a one-client-at-a-time
tunnel that can wedge if a previous client died mid-session, so the
device run happens in a subprocess under a watchdog; if it can't
complete, we emit a CPU-measured line tagged accordingly rather than
hanging the driver.

vs_baseline is measured rate / the BASELINE.json north-star target of
1e11 MD5 candidates/sec/chip (no published reference numbers exist;
see BASELINE.md).
"""

import json
import os
import subprocess
import sys

BASELINE_TARGET = 1.0e11   # MD5 H/s/chip north-star target
TIMEOUT_S = 600

_CHILD = r"""
import json
from dprf_tpu.bench import run_bench
res = run_bench(engine="md5", device="jax", mask="?a?a?a?a?a?a?a?a",
                batch=1 << 22, seconds=10.0)
print("BENCH_JSON:" + json.dumps(res))
"""


def main() -> int:
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    res = None
    try:
        proc = subprocess.run([sys.executable, "-c", _CHILD], env=env,
                              capture_output=True, text=True,
                              timeout=TIMEOUT_S)
        for line in proc.stdout.splitlines():
            if line.startswith("BENCH_JSON:"):
                res = json.loads(line[len("BENCH_JSON:"):])
        if res is None and proc.returncode != 0:
            sys.stderr.write(proc.stderr[-2000:] + "\n")
    except subprocess.TimeoutExpired:
        sys.stderr.write("bench: device run exceeded watchdog timeout "
                         "(TPU tunnel wedged?); falling back to CPU\n")

    if res is None:
        env["JAX_PLATFORMS"] = "cpu"
        child = _CHILD.replace('batch=1 << 22', 'batch=1 << 16')
        try:
            proc = subprocess.run([sys.executable, "-c", child], env=env,
                                  capture_output=True, text=True,
                                  timeout=TIMEOUT_S)
            for line in proc.stdout.splitlines():
                if line.startswith("BENCH_JSON:"):
                    res = json.loads(line[len("BENCH_JSON:"):])
        except subprocess.TimeoutExpired:
            sys.stderr.write("bench: CPU fallback also timed out\n")
        if res is not None:
            res["note"] = "CPU fallback - TPU unavailable"

    if res is None:
        print(json.dumps({"metric": "md5 candidates/sec/chip", "value": 0,
                          "unit": "H/s", "vs_baseline": 0.0,
                          "note": "bench failed"}))
        return 1

    out = {"metric": res["metric"], "value": res["value"],
           "unit": res["unit"],
           "vs_baseline": res["value"] / BASELINE_TARGET}
    for k in ("device", "batch", "batches", "elapsed_s", "compile_s", "note"):
        if k in res:
            out[k] = res[k]
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
