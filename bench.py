#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line.

Runs the MD5 mask-attack pipeline on the real TPU (config 1's
throughput path), measuring BOTH implementations -- the hand-written
Pallas kernel and the generic fused XLA pipeline -- and reporting the
better one as the headline number.

Wedge-safety (VERDICT r2 weak #1): the TPU is reached through a
one-client-at-a-time tunnel that WEDGES if a client process is killed
mid-session.  So nothing here ever kills a TPU client:

- the tunnel probe is tools/tpu_probe.py run detached, reporting
  through a status file; on deadline we fall back to CPU and simply
  stop watching it (the probe exits on its own whenever the tunnel
  answers);
- the device bench is likewise a detached child reporting through a
  result file, abandoned -- never killed -- on deadline.

The CPU fallback child never touches the tunnel (jax.config forces the
CPU backend before any device init), so it is safe to wait on directly.

vs_baseline is measured rate / the BASELINE.json north-star target of
1e11 MD5 candidates/sec/chip (no published reference numbers exist;
see BASELINE.md).
"""

import json
import os
import subprocess
import sys
import time

BASELINE_TARGET = 1.0e11   # MD5 H/s/chip north-star target
# BASELINE.md "MD5 kernel roofline": the chip's int32 VPU ceiling for
# MD5 is 4-8 GH/s (3-6e12 int32 ops/s over ~800 ops/candidate).  The
# north-star target sits ~15-25x ABOVE that ceiling, so vs_baseline
# alone misreads a near-roofline kernel as 5% of target; roofline_frac
# carries the physically meaningful fraction alongside it.
ROOFLINE_BAND_HS = (4.0e9, 8.0e9)
PROBE_DEADLINE_S = 240     # tunnel handshake + one tiny computation
DEVICE_DEADLINE_S = 900    # two compiles + calibrated timed runs
CPU_TIMEOUT_S = 300
TMP_SESSION_GLOB = "/tmp/tpu_session*results*.json"

# Each impl: calibrate with one 16-iteration device-side loop, then
# measure with an inner loop sized to ~5 s of compute per dispatch.
# The axon tunnel costs ~0.4 s per host round trip, so per-dispatch
# batches would measure the link, not the chip (BENCH_r02's md5-xla
# drained 16k queued dispatches for 108 min); run_bench(inner=N) loops
# on device instead.
_DEVICE_CHILD = r"""
import json, os
out = {{}}
from dprf_tpu.bench import run_bench

def save(done=False):
    if done:
        out["done"] = True
    tmp = {path!r} + ".tmp"
    with open(tmp, "w") as f:
        json.dump(out, f)
    os.replace(tmp, {path!r})

from dprf_tpu.bench import calibrated_inner

# persistent XLA compile cache (ISSUE 3): the second bench of a shape
# on this machine loads cached executables instead of re-running XLA
# (run_bench enables it too; enabling here covers the probe ordering)
from dprf_tpu import compilecache
compilecache.enable()

# warm-start from the tuning cache when `dprf tune` has swept this
# chip (ISSUE 2); a miss keeps the proven 1<<22 default
from dprf_tpu.tune import lookup_tuned_batch
_tb = lookup_tuned_batch("md5", attack="mask", device="jax",
                         extras={{"hit_cap": 64}})

for impl, batch in (("pallas", _tb or 1 << 22), ("xla", _tb or 1 << 22)):
    try:
        cal = run_bench(engine="md5", device="jax",
                        mask="?a?a?a?a?a?a?a?a", batch=batch,
                        seconds=0.1, inner=16, impl=impl)
        inner = calibrated_inner(cal["value"], batch)
        out[impl] = run_bench(engine="md5", device="jax",
                              mask="?a?a?a?a?a?a?a?a", batch=batch,
                              seconds=15.0, inner=inner, impl=impl)
        out[impl]["calibrate_hs"] = cal["value"]
        out[impl]["tuned"] = _tb is not None
    except Exception as e:
        out[impl] = {{"error": f"{{type(e).__name__}}: {{e}}"}}
    save()

# The production worker path (config 1 through run_config) is now the
# FASTEST md5 path: the wide-step dispatch fuses a whole multi-batch
# WorkUnit into one kernel program, beating the looped-step bench
# above (r4 session: 4.9 vs 3.6 GH/s).  Measure it too and let the
# headline pick the best.
try:
    from dprf_tpu.bench import run_config
    rec = run_config(1, device="jax", seconds=15.0,
                     batch=_tb or 1 << 22, unit_strides=64)
    rec["impl"] = "worker-wide"
    rec["tuned"] = _tb is not None
    out["worker"] = rec
except Exception as e:
    out["worker"] = {{"error": f"{{type(e).__name__}}: {{e}}"}}
save(done=True)
"""

_CPU_CHILD = r"""
import json
import jax
jax.config.update("jax_platforms", "cpu")
from dprf_tpu.bench import run_bench
res = run_bench(engine="md5", device="jax", mask="?a?a?a?a?a?a?a?a",
                batch=1 << 16, seconds=10.0, impl="xla")
print("BENCH_JSON:" + json.dumps(res))
"""


def _read_json(path):
    try:
        with open(path) as f:
            return json.load(f)
    except (FileNotFoundError, ValueError):
        return None


def _spawn_detached(cmd, env, log_path):
    """Start a child we will poll via files and NEVER kill."""
    with open(log_path, "ab") as log:
        return subprocess.Popen(cmd, env=env, stdout=log, stderr=log,
                                start_new_session=True)


def _poll(path, deadline_s, done):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        doc = _read_json(path)
        if doc is not None and done(doc):
            return doc
        time.sleep(2)
    return _read_json(path)   # last look; may still satisfy done()


def _tpu_available(env, workdir) -> bool:
    """Cooperative probe: detached tools/tpu_probe.py + status file."""
    status = os.path.join(workdir, "bench_probe_status.json")
    try:
        os.unlink(status)
    except FileNotFoundError:
        pass
    probe_env = dict(env, TPU_PROBE_STATUS=status)
    _spawn_detached(
        [sys.executable,
         os.path.join(os.path.dirname(os.path.abspath(__file__)),
                      "tools", "tpu_probe.py")],
        probe_env, os.path.join(workdir, "bench_probe.log"))
    doc = _poll(status, PROBE_DEADLINE_S,
                lambda d: d.get("stage") == "compute_ok")
    if doc is None or doc.get("stage") != "compute_ok":
        stage = (doc or {}).get("stage", "no status")
        sys.stderr.write(
            f"bench: TPU probe did not complete within "
            f"{PROBE_DEADLINE_S}s (stage: {stage}); probe left running, "
            "falling back to CPU\n")
        return False
    # give the probe process a moment to exit and release the tunnel's
    # single client slot before the bench child connects
    time.sleep(5)
    return True


def _run_device(env, workdir):
    result = os.path.join(workdir, "bench_device_result.json")
    try:
        os.unlink(result)
    except FileNotFoundError:
        pass
    code = _DEVICE_CHILD.format(path=result)
    _spawn_detached([sys.executable, "-c", code], env,
                    os.path.join(workdir, "bench_device.log"))
    doc = _poll(result, DEVICE_DEADLINE_S, lambda d: d.get("done"))
    if doc is None or not doc.get("done"):
        sys.stderr.write(
            f"bench: device run incomplete after {DEVICE_DEADLINE_S}s "
            f"(partial: {list((doc or {}))}); child left running, "
            "falling back to CPU\n")
        # a partial result with a finished impl is still usable
        if doc and any(isinstance(v, dict) and "value" in v
                       for v in doc.values()):
            return doc
        return None
    return doc


#: cap for CACHED records only.  Archived session files keep known-bad
#: "evidence" sections (pre-fix kernels, enqueue-speed measurements
#: that inflate ~50x into the 1e11-1e12 range), and the scan picks by
#: max value -- so the cached tier uses a physical cap: the md5 int-op
#: roofline on this chip is ~8 GH/s (BASELINE.md), 5e10 is 6x above
#: any honest measurement and below every observed inflation mode.
#: The LIVE path keeps the looser 1e12 poisoned-buffer cap so a better
#: future chip/kernel can still report.
CACHED_VALUE_CAP = 5e10


def _scan_tpu_md5(node, found):
    """Recursively collect md5 TPU bench records from a results tree.
    Matches any dict {device: "tpu", engine: "md5",
    0 < value < CACHED_VALUE_CAP}, whatever nesting the session file
    used."""
    if isinstance(node, dict):
        if (node.get("device") == "tpu" and node.get("engine") == "md5"
                and isinstance(node.get("value"), (int, float))
                and 0 < node["value"] < CACHED_VALUE_CAP):
            found.append(node)
        for v in node.values():
            _scan_tpu_md5(v, found)
    elif isinstance(node, list):
        for v in node:
            _scan_tpu_md5(v, found)


def _cached_session_result():
    """A real-TPU md5 measurement from a tools/tpu_session.py run, if
    one exists.  When the one-client tunnel is wedged at bench time but
    served a session earlier, the honest best number is that session's
    measurement (clearly labeled), not a CPU fallback.

    Fallback order (VERDICT r3 #1): this round's /tmp session files
    first (fresh measurements on this machine), then the checked-in
    TPU_RESULTS_r*.json from the latest round that has one -- those
    survive machine reboots, which is exactly when /tmp is empty.
    A /tmp file older than the newest committed results file is a
    LEFTOVER from a previous round (the checkout stamps the committed
    file at round start), so it must not shadow that round's record --
    it competes within the same tier instead."""
    import glob
    import re
    repo = os.path.dirname(os.path.abspath(__file__))
    committed = glob.glob(os.path.join(repo, "TPU_RESULTS_r*.json"))
    tmp_files = sorted(glob.glob(TMP_SESSION_GLOB))

    def round_no(p):
        m = re.search(r"_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1

    def mtime(p):
        try:
            return os.path.getmtime(p)
        except OSError:
            return 0.0

    committed = sorted(committed, key=round_no, reverse=True)
    cutoff = mtime(committed[0]) if committed else 0.0
    fresh = [p for p in tmp_files if mtime(p) > cutoff]
    stale = [p for p in tmp_files if mtime(p) <= cutoff]
    # this round's sessions first (even if slower -- fresh beats
    # stale), then newest committed round + any older /tmp leftovers
    # as one tier, then older rounds
    groups = [fresh]
    groups.append(stale + committed[:1])
    for path in committed[1:]:
        groups.append([path])

    for tier in groups:
        best, src = None, None
        for path in tier:
            doc = _read_json(path)
            if not doc:
                continue
            found = []
            _scan_tpu_md5(doc, found)
            for res in found:
                if best is None or res["value"] > best["value"]:
                    best, src = dict(res), path
        if best is not None:
            best["note"] = (f"cached session measurement from {src}; "
                            "tunnel unavailable at bench time")
            return best
    return None


#: driver-state file (in the bench workdir, surviving between driver
#: invocations on one machine) recording whether the LAST reported
#: headline was a fresh measurement.  VERDICT r5: the 5.18 GH/s
#: headline was a silently-cached session number -- the `fresh` field
#: makes the tier machine-checkable, and the state file lets the
#: driver refuse to serve the cached tier twice in a row.
FRESHNESS_STATE = "bench_freshness_state.json"


def _freshness_state_path(workdir):
    return os.path.join(workdir, FRESHNESS_STATE)


def _record_freshness(workdir, fresh, value):
    doc = {"last_fresh": bool(fresh), "last_value": value,
           "ts": time.time()}
    path = _freshness_state_path(workdir)
    try:
        with open(path + ".tmp", "w") as f:
            json.dump(doc, f)
        os.replace(path + ".tmp", path)
    except OSError:
        pass


def _cached_tier_allowed(workdir):
    """A cached-session headline is allowed only if the PREVIOUS
    driver report was fresh: two consecutive cached reports would mean
    nobody has measured the chip across a whole round, which is
    exactly the liveness hole VERDICT flagged."""
    doc = _read_json(_freshness_state_path(workdir))
    if doc is None:
        return True
    return bool(doc.get("last_fresh", True))


def _run_cpu(env):
    try:
        proc = subprocess.run([sys.executable, "-c", _CPU_CHILD], env=env,
                              capture_output=True, text=True,
                              timeout=CPU_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        return None
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_JSON:"):
            return json.loads(line[len("BENCH_JSON:"):])
    sys.stderr.write(f"bench: CPU fallback failed "
                     f"({proc.stderr[-2000:]})\n")
    return None


def main() -> int:
    repo = os.path.dirname(os.path.abspath(__file__))
    sys.path.insert(0, repo)
    from dprf_tpu.utils import env as envreg
    workdir = envreg.get_path("DPRF_BENCH_DIR")
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")

    res, extras = None, {}
    fresh = True          # live measurement this invocation?
    if _tpu_available(env, workdir):
        device_doc = _run_device(env, workdir)
        if device_doc:
            # physical sanity cap: nothing in this class exceeds ~1e11
            # H/s on one chip; a dead backend once "measured" 1.3e15
            # (poisoned buffers complete instantly without raising)
            impls = {k: v for k, v in device_doc.items()
                     if isinstance(v, dict) and "value" in v
                     and 0 < v["value"] < 1e12}
            if impls:
                best = max(impls, key=lambda k: impls[k]["value"])
                res = impls[best]
                for k, v in impls.items():
                    extras[f"{k}_hs"] = v["value"]
                for k, v in device_doc.items():
                    if isinstance(v, dict) and "error" in v:
                        extras[f"{k}_error"] = v["error"]

    if res is None:
        cached = _cached_session_result()
        if cached is not None and not _cached_tier_allowed(workdir):
            sys.stderr.write(
                "bench: refusing to report the cached-session tier "
                "twice in a row (last report was already cached); "
                "falling back to a live CPU measurement\n")
            extras["cached_suppressed_hs"] = cached["value"]
            cached = None
        if cached is not None:
            res = cached
            fresh = False

    if res is None:
        res = _run_cpu(env)
        if res is not None:
            res["note"] = "CPU fallback - TPU unavailable"

    if res is None:
        _record_freshness(workdir, False, 0)
        print(json.dumps({"metric": "md5 candidates/sec/chip", "value": 0,
                          "unit": "H/s", "vs_baseline": 0.0,
                          "fresh": False, "tuned": False,
                          "note": "bench failed"}))
        return 1

    # fresh: this invocation ran the measurement (live chip or live
    # CPU); false ONLY for the cached-session tier.  Machine-checkable
    # liveness per the VERDICT r5 mandate.
    # tuned: the measurement ran at a batch loaded from the tuning
    # cache (`dprf tune`); false = default/pinned batch.  Same
    # machine-checkable contract as `fresh` (ISSUE 2).
    out = {"metric": "md5 candidates/sec/chip", "value": res["value"],
           "unit": "H/s", "vs_baseline": res["value"] / BASELINE_TARGET,
           "fresh": fresh, "tuned": bool(res.get("tuned", False))}
    if res.get("device") == "tpu":
        # conservative fraction (vs the 8 GH/s upper ceiling) plus the
        # optimistic one (vs 4 GH/s); the truth is in the band
        lo, hi = ROOFLINE_BAND_HS
        out["roofline_frac"] = round(res["value"] / hi, 4)
        out["roofline_frac_hi"] = round(res["value"] / lo, 4)
        out["roofline_band_hs"] = [lo, hi]
    for k in ("impl", "device", "batch", "batches", "inner",
              "calibrate_hs", "elapsed_s", "compile_s", "note",
              "compile_cold_s", "compile_warm_s", "phases"):
        if k in res:
            out[k] = res[k]
    # compile-cache classification (ISSUE 3): machine-checkable like
    # `fresh`/`tuned` -- "hit" means this measurement paid ~zero
    # compile cost, "miss" means it also populated the cache, "off"
    # means no persistent cache was in play (e.g. cached-session tier)
    out["compile_cache"] = res.get("compile_cache", "off")
    out.update(extras)
    _record_freshness(workdir, fresh, res["value"])
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
