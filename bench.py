#!/usr/bin/env python
"""Driver benchmark entry: prints ONE JSON line.

Runs the MD5 mask-attack fused pipeline on the real TPU (config 1's
throughput path).  The TPU is reached through a one-client-at-a-time
tunnel that can wedge if a previous client died mid-session, so the
device run happens in a subprocess under a watchdog; if it can't
complete, we emit a CPU-measured line tagged accordingly rather than
hanging the driver.

vs_baseline is measured rate / the BASELINE.json north-star target of
1e11 MD5 candidates/sec/chip (no published reference numbers exist;
see BASELINE.md).
"""

import json
import os
import subprocess
import sys

BASELINE_TARGET = 1.0e11   # MD5 H/s/chip north-star target
TIMEOUT_S = 540

_PROBE = "import jax; jax.devices()"

# The tunnel serves one client at a time and wedges if a client dies
# mid-session, so: probe first, keep all device work in watchdogged
# subprocesses, and force the CPU backend via jax.config (env vars
# alone cannot override the site-registered axon platform).
_CHILD = r"""
import json
{force_cpu}
from dprf_tpu.bench import run_bench
res = run_bench(engine="md5", device="jax", mask="?a?a?a?a?a?a?a?a",
                batch={batch}, seconds=10.0)
print("BENCH_JSON:" + json.dumps(res))
"""
_FORCE_CPU = 'import jax; jax.config.update("jax_platforms", "cpu")'


def _run_child(env, force_cpu: bool, batch: int, timeout: int):
    code = _CHILD.format(force_cpu=_FORCE_CPU if force_cpu else "",
                         batch=batch)
    try:
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True,
                              timeout=timeout)
    except subprocess.TimeoutExpired:
        return None, "watchdog timeout"
    for line in proc.stdout.splitlines():
        if line.startswith("BENCH_JSON:"):
            return json.loads(line[len("BENCH_JSON:"):]), None
    return None, proc.stderr[-2000:]


def main() -> int:
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    res = None

    # cheap tunnel-health probe before committing to a long device run
    tpu_ok = False
    try:
        tpu_ok = subprocess.run([sys.executable, "-c", _PROBE], env=env,
                                capture_output=True,
                                timeout=120).returncode == 0
    except subprocess.TimeoutExpired:
        sys.stderr.write("bench: TPU tunnel probe hung (wedged tunnel); "
                         "using CPU backend\n")

    if tpu_ok:
        res, err = _run_child(env, force_cpu=False, batch=1 << 22,
                              timeout=TIMEOUT_S)
        if res is None:
            sys.stderr.write(f"bench: device run failed ({err}); "
                             "falling back to CPU\n")

    if res is None:
        res, err = _run_child(env, force_cpu=True, batch=1 << 16,
                              timeout=TIMEOUT_S)
        if res is not None:
            res["note"] = "CPU fallback - TPU unavailable"
        elif err:
            sys.stderr.write(f"bench: CPU fallback failed ({err})\n")

    if res is None:
        print(json.dumps({"metric": "md5 candidates/sec/chip", "value": 0,
                          "unit": "H/s", "vs_baseline": 0.0,
                          "note": "bench failed"}))
        return 1

    out = {"metric": res["metric"], "value": res["value"],
           "unit": res["unit"],
           "vs_baseline": res["value"] / BASELINE_TARGET}
    for k in ("device", "batch", "batches", "elapsed_s", "compile_s", "note"):
        if k in res:
            out[k] = res[k]
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
