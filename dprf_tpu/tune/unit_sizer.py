"""Throughput-adaptive work-unit sizing.

The Dispatcher splits the keyspace with one static ``unit_size``; in a
heterogeneous fleet (a TPU pod slice next to a CPU box, or chips
behind links of very different latency) that single constant is wrong
for everyone at once: too small and the fast workers pay per-unit RPC
overhead, too large and a slow worker's lease spans hours (and its
death re-runs hours of work).  The sizer keeps a per-worker EWMA of
completion throughput -- reported over the existing RPC complete path
-- and sizes each worker's NEXT unit toward a target seconds-per-unit,
so every worker settles at units roughly `target_seconds` long no
matter how fast it drains them (HashKitty's per-node work-sizing
lesson, PAPERS.md).

Crash history (ISSUE 4 satellite of a ROADMAP item): throughput alone
never shrinks a worker that is FAST but keeps dying -- its lease
expiries re-run target_seconds of work every time, and a worker OOMing
on big units retries the same fatal size forever.  The Dispatcher
reports every failed attempt / lease expiry via ``observe_failure``;
each recent failure HALVES the worker's next units (capped at 1/2**4),
and each successful completion decays one failure off -- so a host
whose crash was environmental earns its size back, while a flaky one
keeps re-running minutes, not hours.  The failure/reissue spans in the
trace timeline (telemetry/trace.py) carry the same per-worker history
an operator sees.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from dprf_tpu.telemetry import get_registry


class AdaptiveUnitSizer:
    """EWMA per-worker throughput -> next unit length.

    Lazily-generated units only: already-split units (resume gaps,
    reissues) keep their geometry -- resizing them would tear the
    coverage ledger.  Thread-safe: the RPC server observes completions
    from handler threads while the dispatcher leases under its own
    lock.
    """

    def __init__(self, initial: int, target_seconds: float = 20.0,
                 min_unit: int = 1 << 10, max_unit: int = 1 << 28,
                 align: int = 1, alpha: float = 0.4, registry=None,
                 headroom_fn=None):
        if initial <= 0:
            raise ValueError("initial unit size must be positive")
        if target_seconds <= 0:
            raise ValueError("target_seconds must be positive")
        self.initial = initial
        self.target_seconds = target_seconds
        self.align = max(1, int(align))
        # floors/ceilings keep a cold or glitching EWMA from issuing
        # degenerate units (1-index units, or one unit = whole keyspace)
        self.min_unit = max(self.align, int(min_unit))
        self.max_unit = max(self.min_unit, int(max_unit))
        self.alpha = alpha
        #: OOM-headroom estimate (ISSUE 13).  The signal must match
        #: the ALTITUDE: the local-crack path (worker in THIS process)
        #: wires ``headroom_fn=devstats.headroom_frac``; the serve
        #: plane instead feeds each remote worker's heartbeat-reported
        #: HBM through ``observe_headroom`` -- the coordinator's own
        #: allocator state says nothing about a worker's.  Default
        #: None = no headroom behavior until a caller wires a signal.
        self._headroom_fn = headroom_fn
        #: per-worker free fraction from heartbeats (serve plane)
        self._headroom: dict[str, float] = {}
        self._rates: dict[str, float] = {}
        #: per-worker recent-failure score (fail() or lease expiry);
        #: decays by one per successful completion
        self._failures: dict[str, int] = {}
        self._lock = threading.Lock()
        m = get_registry(registry)
        m.gauge("dprf_unit_target_seconds",
                "adaptive unit sizing: target seconds per WorkUnit"
                ).set(target_seconds)
        self._g_size = m.gauge(
            "dprf_unit_size",
            "last adaptively-sized WorkUnit length issued")
        self._g_size.set(self._clamp(initial))

    def _clamp(self, size: int) -> int:
        size = max(self.min_unit, min(self.max_unit, int(size)))
        if self.align > 1:
            size = max(self.align, (size // self.align) * self.align)
        return size

    #: penalty halvings stop at 1/2**MAX_PENALTY_BITS of the computed
    #: size: units must stay big enough to measure recovery with
    MAX_PENALTY_BITS = 4
    #: failure score ceiling: bounds how many clean completions a
    #: recovered worker owes before its units are full-size again
    MAX_FAILURES = 8

    def observe(self, worker_id: str, length: int, elapsed: float) -> None:
        """Fold one completed unit into the worker's throughput EWMA.
        Non-positive reports (clock skew, zero-length tails) are
        dropped rather than poisoning the estimate.  A clean
        completion also decays one recent failure: size comes back
        gradually, each probe unit a little bigger."""
        if length <= 0 or not elapsed or elapsed <= 0:
            return
        rate = length / float(elapsed)
        with self._lock:
            prev = self._rates.get(worker_id)
            self._rates[worker_id] = (
                rate if prev is None
                else self.alpha * rate + (1.0 - self.alpha) * prev)
            f = self._failures.get(worker_id, 0)
            if f > 1:
                self._failures[worker_id] = f - 1
            elif f:
                del self._failures[worker_id]

    def observe_headroom(self, worker_id: str,
                         frac: Optional[float]) -> None:
        """Fold one worker's reported free-HBM fraction in (the serve
        plane's heartbeat path); None clears the worker's entry (a
        backend that stopped reporting is 'no signal', not 'full')."""
        with self._lock:
            if frac is None:
                self._headroom.pop(worker_id, None)
            else:
                self._headroom[worker_id] = max(0.0, float(frac))

    def observe_failure(self, worker_id: str) -> None:
        """One failed attempt / lease expiry (reported by the
        Dispatcher's requeue path): the worker's next units halve per
        recent failure, so a crash re-runs less and an OOM-sized unit
        is not retried at the fatal size."""
        with self._lock:
            self._failures[worker_id] = min(
                self._failures.get(worker_id, 0) + 1, self.MAX_FAILURES)

    def failures(self, worker_id: str) -> int:
        with self._lock:
            return self._failures.get(worker_id, 0)

    def rate(self, worker_id: str) -> Optional[float]:
        with self._lock:
            return self._rates.get(worker_id)

    def next_size(self, worker_id: str) -> int:
        """Unit length for this worker's next lease: EWMA rate x the
        target seconds, halved per recent failure, clamped and
        alignment-rounded.  A worker with no history gets the
        configured initial size (the first unit is the measurement).

        OOM headroom (ISSUE 13): when THIS worker's device allocator
        reports under LOW_HEADROOM_FRAC of its limit free -- its own
        heartbeat report on the serve plane, the local devstats
        callable on the in-process path -- the next unit halves too:
        a longer unit holds more queued dispatches (and their
        super-step buffers) live at once, and shrinking units is the
        one lever this layer has before the allocator ceiling.  No
        signal (no stats backend, no report) changes nothing."""
        from dprf_tpu.telemetry.devstats import LOW_HEADROOM_FRAC
        with self._lock:
            rate = self._rates.get(worker_id)
            fails = self._failures.get(worker_id, 0)
            headroom = self._headroom.get(worker_id)
        size = (self.initial if rate is None
                else int(rate * self.target_seconds))
        size >>= min(fails, self.MAX_PENALTY_BITS)
        if headroom is None and self._headroom_fn is not None:
            try:
                headroom = self._headroom_fn()
            except Exception:   # noqa: BLE001 -- an estimate, never
                headroom = None              # a gate
        if headroom is not None and headroom < LOW_HEADROOM_FRAC:
            size >>= 1
        size = self._clamp(size)
        self._g_size.set(size)
        return size
