"""Throughput-adaptive work-unit sizing.

The Dispatcher splits the keyspace with one static ``unit_size``; in a
heterogeneous fleet (a TPU pod slice next to a CPU box, or chips
behind links of very different latency) that single constant is wrong
for everyone at once: too small and the fast workers pay per-unit RPC
overhead, too large and a slow worker's lease spans hours (and its
death re-runs hours of work).  The sizer keeps a per-worker EWMA of
completion throughput -- reported over the existing RPC complete path
-- and sizes each worker's NEXT unit toward a target seconds-per-unit,
so every worker settles at units roughly `target_seconds` long no
matter how fast it drains them (HashKitty's per-node work-sizing
lesson, PAPERS.md).
"""

from __future__ import annotations

import threading
from typing import Callable, Optional

from dprf_tpu.telemetry import get_registry


class AdaptiveUnitSizer:
    """EWMA per-worker throughput -> next unit length.

    Lazily-generated units only: already-split units (resume gaps,
    reissues) keep their geometry -- resizing them would tear the
    coverage ledger.  Thread-safe: the RPC server observes completions
    from handler threads while the dispatcher leases under its own
    lock.
    """

    def __init__(self, initial: int, target_seconds: float = 20.0,
                 min_unit: int = 1 << 10, max_unit: int = 1 << 28,
                 align: int = 1, alpha: float = 0.4, registry=None):
        if initial <= 0:
            raise ValueError("initial unit size must be positive")
        if target_seconds <= 0:
            raise ValueError("target_seconds must be positive")
        self.initial = initial
        self.target_seconds = target_seconds
        self.align = max(1, int(align))
        # floors/ceilings keep a cold or glitching EWMA from issuing
        # degenerate units (1-index units, or one unit = whole keyspace)
        self.min_unit = max(self.align, int(min_unit))
        self.max_unit = max(self.min_unit, int(max_unit))
        self.alpha = alpha
        self._rates: dict[str, float] = {}
        self._lock = threading.Lock()
        m = get_registry(registry)
        m.gauge("dprf_unit_target_seconds",
                "adaptive unit sizing: target seconds per WorkUnit"
                ).set(target_seconds)
        self._g_size = m.gauge(
            "dprf_unit_size",
            "last adaptively-sized WorkUnit length issued")
        self._g_size.set(self._clamp(initial))

    def _clamp(self, size: int) -> int:
        size = max(self.min_unit, min(self.max_unit, int(size)))
        if self.align > 1:
            size = max(self.align, (size // self.align) * self.align)
        return size

    def observe(self, worker_id: str, length: int, elapsed: float) -> None:
        """Fold one completed unit into the worker's throughput EWMA.
        Non-positive reports (clock skew, zero-length tails) are
        dropped rather than poisoning the estimate."""
        if length <= 0 or not elapsed or elapsed <= 0:
            return
        rate = length / float(elapsed)
        with self._lock:
            prev = self._rates.get(worker_id)
            self._rates[worker_id] = (
                rate if prev is None
                else self.alpha * rate + (1.0 - self.alpha) * prev)

    def rate(self, worker_id: str) -> Optional[float]:
        with self._lock:
            return self._rates.get(worker_id)

    def next_size(self, worker_id: str) -> int:
        """Unit length for this worker's next lease: EWMA rate x the
        target seconds, clamped and alignment-rounded.  A worker with
        no history gets the configured initial size (the first unit is
        the measurement)."""
        with self._lock:
            rate = self._rates.get(worker_id)
        size = (self.initial if rate is None
                else int(rate * self.target_seconds))
        size = self._clamp(size)
        self._g_size.set(size)
        return size
