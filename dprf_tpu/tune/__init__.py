"""Adaptive tuning subsystem (ISSUE 2).

Replaces the hard-coded batch/unit-size constants strewn across
workers, bench, and the CLI with one subsystem every execution path
consults:

  - autotuner.sweep        geometric batch ladder over the real worker
                           path, best batch under a compile budget;
  - cache.TuningCache      persistent JSON cache ($DPRF_TUNE_DIR /
                           session dir) with environment-fingerprint
                           invalidation (jax version, device kind,
                           engine source rev);
  - unit_sizer.AdaptiveUnitSizer
                           per-worker EWMA throughput -> WorkUnit
                           length targeting seconds-per-unit, fed by
                           the RPC complete path.

Metric surface: ``dprf_tuned_batch{engine,device,attack}``,
``dprf_unit_target_seconds``, ``dprf_unit_size``,
``dprf_units_poisoned_total`` (dispatcher retry-cap guard).
"""

from __future__ import annotations

from typing import Optional

from dprf_tpu.tune.autotuner import (Probe, TuneResult, geometric_ladder,
                                     sweep, sweep_values)
from dprf_tpu.tune.cache import (TuningCache, cache_path, default_cache,
                                 engine_rev, env_fingerprint, make_key,
                                 tune_dir)
from dprf_tpu.tune.unit_sizer import AdaptiveUnitSizer


def publish_tuned_batch(engine: str, device: str, attack: str,
                        batch: int, registry=None) -> None:
    """ONE declaration site for the dprf_tuned_batch gauge (CLI, bench,
    and the tune command all publish through here, so the labels can
    never drift)."""
    from dprf_tpu.telemetry import get_registry
    get_registry(registry).gauge(
        "dprf_tuned_batch",
        "device batch size selected by the tuning subsystem",
        labelnames=("engine", "device", "attack")
    ).set(batch, engine=engine, device=device, attack=attack)


def lookup_tuned_batch(engine: str, attack: str = "mask",
                       device: str = "jax",
                       session_path: Optional[str] = None,
                       registry=None,
                       extras: Optional[dict] = None) -> Optional[int]:
    """Environment-validated cache lookup; the warm-start path bench
    and ``--batch auto`` jobs take.  Returns the tuned batch (and
    publishes the gauge) or None -- never raises: a broken cache reads
    as a miss and the caller's default stands.

    extras: additional key dimensions that fork the optimum --
    hit_capacity (a raised --hit-cap scales every hit buffer, moving
    the HBM ceiling) and rules-set cardinality (word_batch = batch //
    n_rules, so the same batch means different step shapes) -- folded
    into the cache key so a stale optimum can never alias."""
    try:
        cache = default_cache(session_path)
        env = env_fingerprint(engine, device)
        entry = cache.get(make_key(engine, attack=attack, device=device,
                                   **(extras or {})),
                          env)
        if not entry:
            return None
        batch = int(entry["batch"])
        if batch <= 0:
            return None
        publish_tuned_batch(engine, device, attack, batch,
                            registry=registry)
        return batch
    except Exception:
        return None


def record_tuned_batch(engine: str, attack: str, device: str,
                       result: TuneResult,
                       session_path: Optional[str] = None,
                       registry=None,
                       extras: Optional[dict] = None) -> str:
    """Persist a sweep result and publish the gauge; returns the cache
    file path written.  `extras` must match what the consuming job's
    lookup passes (see lookup_tuned_batch)."""
    cache = default_cache(session_path)
    cache.put(make_key(engine, attack=attack, device=device,
                       **(extras or {})),
              result.as_record(), env_fingerprint(engine, device))
    publish_tuned_batch(engine, device, attack, result.batch,
                        registry=registry)
    return cache.path


def lookup_tuned_value(engine: str, knob: str, attack: str = "mask",
                       device: str = "jax",
                       session_path: Optional[str] = None,
                       extras: Optional[dict] = None) -> Optional[int]:
    """Environment-validated lookup of a tuned KNOB value (superstep
    ``inner`` window, kernel ``sub`` tile size, ...): the value rides
    in the record's ``batch`` field (sweep_values keeps one record
    schema for every tuned quantity) under a key forked by
    ``knob=<name>``.  Returns the value or None -- never raises, so a
    broken cache reads as a miss and the caller's default stands."""
    try:
        cache = default_cache(session_path)
        entry = cache.get(
            make_key(engine, attack=attack, device=device, knob=knob,
                     **(extras or {})),
            env_fingerprint(engine, device))
        if not entry:
            return None
        value = int(entry["batch"])
        return value if value > 0 else None
    except Exception:
        return None


def record_tuned_value(engine: str, knob: str, attack: str, device: str,
                       result: TuneResult,
                       session_path: Optional[str] = None,
                       extras: Optional[dict] = None) -> str:
    """Persist a sweep_values result under the ``knob=<name>``-forked
    key; returns the cache file path written.  The consuming lookup
    (lookup_tuned_value) must pass the same knob/extras."""
    cache = default_cache(session_path)
    cache.put(make_key(engine, attack=attack, device=device, knob=knob,
                       **(extras or {})),
              result.as_record(), env_fingerprint(engine, device))
    return cache.path


__all__ = ["AdaptiveUnitSizer", "Probe", "TuneResult", "TuningCache",
           "cache_path", "default_cache", "engine_rev",
           "env_fingerprint", "geometric_ladder", "lookup_tuned_batch",
           "lookup_tuned_value", "make_key", "publish_tuned_batch",
           "record_tuned_batch", "record_tuned_value", "sweep",
           "sweep_values", "tune_dir"]
