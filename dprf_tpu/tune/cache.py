"""Persistent tuning cache: (engine, device, attack) -> tuned batch.

One JSON document under ``$DPRF_TUNE_DIR`` (or, when a job has a
session journal, the journal's directory; else ``~/.cache/dprf``).
Entries carry an *environment fingerprint* -- jax version, device
kind, and a content hash of the engine's source module -- so a cache
recorded under a different toolchain or engine revision is IGNORED,
never reused: a batch tuned for one compiler/chip generation says
nothing about another, and silently trusting it would pin every later
job to a stale optimum.

The cache is advisory: any read/write failure degrades to "no entry"
(the caller falls back to its default batch), never to a crashed job.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

CACHE_BASENAME = "tune_cache.json"
CACHE_VERSION = 1


def tune_dir(session_path: Optional[str] = None) -> str:
    """Resolution order: $DPRF_TUNE_DIR > the session journal's
    directory > ~/.cache/dprf.  The session-dir tier keeps a resumable
    job's tuning next to its coverage ledger, so copying the session
    directory to another host carries the whole resume state."""
    from dprf_tpu.utils import env as envreg
    d = envreg.get_raw("DPRF_TUNE_DIR")
    if d:
        return d
    if session_path:
        return os.path.dirname(os.path.abspath(session_path)) or "."
    return os.path.join(os.path.expanduser("~"), ".cache", "dprf")


def cache_path(session_path: Optional[str] = None) -> str:
    return os.path.join(tune_dir(session_path), CACHE_BASENAME)


def make_key(engine: str, attack: str = "mask", device: str = "jax",
             **extra) -> str:
    """Stable cache key.  The engine name is normalized exactly as the
    engine registry normalizes it (lower-cased), so `dprf tune -m MD5`
    and a serve job keyed on the canonical engine.name can never fork
    the key space; extras (e.g. rules=n_rules) are sorted so call-site
    argument order cannot either."""
    parts = [f"engine={engine.lower()}", f"device={device}",
             f"attack={attack}"]
    parts += [f"{k}={extra[k]}" for k in sorted(extra)
              if extra[k] is not None]
    return "|".join(parts)


def engine_rev(engine_name: str, device: str = "jax") -> str:
    """Content hash of the engine's source module: a kernel edit means
    re-tuning, and the rev makes that automatic instead of a tribal
    "clear your cache" ritual."""
    import hashlib
    import inspect
    try:
        from dprf_tpu.engines import engine_class
        try:
            cls = engine_class(engine_name,
                               "jax" if device == "jax" else "cpu")
        except KeyError:
            cls = engine_class(engine_name, "cpu")
        src = inspect.getsourcefile(cls)
        with open(src, "rb") as fh:
            return hashlib.sha256(fh.read()).hexdigest()[:12]
    except Exception:
        return "unknown"


def env_fingerprint(engine_name: str, device: str = "jax") -> dict:
    """What a tuned batch is conditional on: jax/XLA version, the chip
    generation, and the engine source rev.  Any mismatch invalidates."""
    env = {"jax": "none", "device_kind": "cpu"}
    if device == "jax":
        try:
            import jax
            env["jax"] = jax.__version__
            dev = jax.devices()[0]
            env["device_kind"] = getattr(dev, "device_kind", dev.platform)
        except Exception:
            env["device_kind"] = "unknown"
    else:
        try:
            import jax
            env["jax"] = jax.__version__
        except Exception:
            pass
    env["engine_rev"] = engine_rev(engine_name, device)
    return env


#: `dprf check` locks analyzer: the lazily-loaded document is shared
#: by every thread that consults the cache (autotuner, serve-plane
#: job setup, prewarm); all access goes through _lock.
GUARDED_BY = {
    "TuningCache": {"_lock": ("_doc",)},
}


class TuningCache:
    """Load/validate/update one tuning-cache JSON file.  Writes are
    atomic (tmp + replace) so a killed run can never leave a torn
    document; a torn or alien file reads as empty."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._doc: Optional[dict] = None

    def _load(self) -> dict:
        if self._doc is None:
            try:
                with open(self.path, encoding="utf-8") as fh:
                    doc = json.load(fh)
                if (not isinstance(doc, dict)
                        or doc.get("version") != CACHE_VERSION
                        or not isinstance(doc.get("entries"), dict)):
                    doc = {"version": CACHE_VERSION, "entries": {}}
            except (OSError, ValueError):
                doc = {"version": CACHE_VERSION, "entries": {}}
            self._doc = doc
        return self._doc

    _load._holds_lock = "_lock"   # every caller holds self._lock

    def get(self, key: str, env: dict) -> Optional[dict]:
        """The entry for `key`, or None if absent OR recorded under a
        different environment fingerprint (jax version / device kind /
        engine rev) -- stale entries must be ignored, not reused."""
        with self._lock:
            entry = self._load()["entries"].get(key)
        if not isinstance(entry, dict):
            return None
        recorded = entry.get("env")
        if not isinstance(recorded, dict):
            return None
        for k, v in env.items():
            if recorded.get(k) != v:
                return None
        return dict(entry)

    def put(self, key: str, record: dict, env: dict) -> None:
        with self._lock:
            doc = self._load()
            doc["entries"][key] = {**record, "env": dict(env),
                                   "ts": time.time()}
            self._save(doc)

    def _save(self, doc: dict) -> None:
        try:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
            os.replace(tmp, self.path)
        except OSError:
            pass   # advisory cache: a read-only FS must not kill the job

    def entries(self) -> dict:
        with self._lock:
            return dict(self._load()["entries"])


def default_cache(session_path: Optional[str] = None) -> TuningCache:
    return TuningCache(cache_path(session_path))
