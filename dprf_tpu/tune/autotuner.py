"""Batch autotuner: sweep a geometric batch ladder, pick the fastest
batch whose fixed cost fits the compile budget.

The sweep drives the REAL worker path (``make_worker(batch)`` builds
the same worker a job would run, ``process`` covers real WorkUnits),
so the measured H/s includes candidate generation, compare, and hit
readback -- the number a job sustains, not a stripped kernel.  Compile
time is the worker's warmup + first-unit cost; workers publish it into
the existing ``dprf_compile_seconds`` telemetry histogram as a side
effect, so a scrape during a sweep shows exactly where the time went.

Ladder policy: batches climb geometrically (default x4) because
throughput-vs-batch curves for these pipelines are smooth and
saturating -- fine-grained probing buys nothing.  The climb stops
early when (a) a rung's compile time exceeds the budget (bigger
batches compile strictly longer), (b) a rung fails to build/allocate
(the HBM ceiling), or (c) `patience` consecutive rungs improve the
best rate by less than `improve_eps` (saturation).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

from dprf_tpu.runtime.workunit import WorkUnit


@dataclasses.dataclass
class Probe:
    """One ladder rung's measurement."""
    batch: int
    rate_hs: float
    compile_s: float
    error: Optional[str] = None
    #: persistent-compile-cache classification of this rung's fixed
    #: cost ("hit" | "miss" | "off"): a hit rung's compile is ~free,
    #: which is how a cached sweep reaches bigger batches within the
    #: same compile budget
    cache: Optional[str] = None

    def as_dict(self) -> dict:
        d = {"batch": self.batch, "rate_hs": self.rate_hs,
             "compile_s": round(self.compile_s, 3)}
        if self.error:
            d["error"] = self.error
        if self.cache is not None:
            d["cache"] = self.cache
        return d


@dataclasses.dataclass
class TuneResult:
    batch: int
    rate_hs: float
    compile_s: float
    swept: List[Probe]
    source: str = "swept"        # "swept" | "cache" | "session" | "default"

    @property
    def tuned(self) -> bool:
        return self.source in ("swept", "cache", "session")

    def as_record(self) -> dict:
        """The cache/session payload (environment fingerprint is added
        by the cache layer)."""
        return {"batch": self.batch, "rate_hs": self.rate_hs,
                "compile_s": round(self.compile_s, 3),
                "swept": [p.as_dict() for p in self.swept]}


def geometric_ladder(lo: int = 1 << 14, hi: int = 1 << 22,
                     factor: int = 4) -> List[int]:
    if lo <= 0 or hi < lo or factor < 2:
        raise ValueError(f"bad ladder bounds {lo}..{hi} x{factor}")
    out, b = [], lo
    while b < hi:
        out.append(b)
        b *= factor
    out.append(hi)
    return out


def _probe_rate(worker, keyspace: int, seconds: float,
                clock: Callable[[], float],
                unit_strides: int = 1) -> float:
    """Steady-state H/s: process whole units (``unit_strides`` worker
    strides each -- 1 is the production dispatch granularity; value
    sweeps over superstep knobs pass more so the fused window actually
    engages) until the window closes.  Always at least one unit, so an
    injected/fake clock cannot starve the measurement."""
    stride = (getattr(worker, "stride", None)
              or getattr(worker, "chunk", None) or 2048)
    unit_len = max(1, min(int(stride) * max(1, unit_strides), keyspace))
    n, start = 0, 0
    t0 = clock()
    while True:
        if start + unit_len > keyspace:
            start = 0
        worker.process(WorkUnit(-1, start, unit_len))
        n += unit_len
        start += unit_len
        if clock() - t0 >= seconds:
            break
    elapsed = max(clock() - t0, 1e-9)
    return n / elapsed


def _over_hbm_headroom(worker, batch: int, rest: list, log=None) -> bool:
    """OOM-headroom guard for the ladder (ISSUE 13): analyze the
    rung's just-compiled program (recording its cost/memory into the
    program registry is the tune side effect `dprf tune --all` banks
    on), then project the NEXT rung's device footprint by scaling this
    rung's analyzed peak bytes -- a projection past the allocator's
    free bytes stops the climb BEFORE the allocation failure, which on
    some backends wedges the process rather than raising cleanly.
    Backends without memory stats (CPU) return None free bytes and
    never stop the ladder."""
    from dprf_tpu.telemetry import devstats
    from dprf_tpu.telemetry import programs as programs_mod
    programs_mod.analyze_pending()
    if not rest or batch <= 0:
        return False
    free = devstats.bytes_free()
    if free is None:
        return False
    eng = getattr(getattr(worker, "engine", None), "name", None)
    if eng is None:
        return False        # no identity: never project from an
        # unrelated engine's programs
    # THIS rung's program only: other shapes (a bench program, another
    # attack, a bigger batch from an earlier run) scale differently
    # and would stop the ladder on someone else's footprint
    peak = programs_mod.get_programs().peak_bytes_for(eng, batch)
    if not peak:
        return False
    projected = peak * (rest[0] / batch)
    if projected <= free:
        return False
    if log:
        log.warn("tune rung projects past free device memory; "
                 "stopping ladder", next_batch=rest[0],
                 projected_bytes=int(projected), free_bytes=free)
    return True


def sweep(make_worker: Callable[[int], object], keyspace: int,
          ladder: Optional[List[int]] = None, *,
          probe_seconds: float = 1.0, compile_budget_s: float = 120.0,
          improve_eps: float = 0.05, patience: int = 2,
          clock: Callable[[], float] = time.perf_counter,
          log=None) -> TuneResult:
    """Measure each ladder rung through `make_worker(batch)`; return
    the best batch under the compile budget.  Raises ValueError when no
    rung produces a worker at all (the caller's default batch stands).
    """
    from dprf_tpu import compilecache

    # Persistent compile cache ON for the sweep: a previously-swept
    # (or prewarmed) rung's fixed cost collapses to a cache load, so
    # the ladder reaches bigger batches inside the same compile budget
    # instead of burning it on recompiles of known shapes.
    compilecache.enable(log=log)
    ladder = ladder or geometric_ladder()
    swept: List[Probe] = []
    best: Optional[Probe] = None
    stall = 0
    for i, batch in enumerate(ladder):
        try:
            entries0 = compilecache.entry_count()
            t0 = clock()
            worker = make_worker(batch)
            # prime: the first unit pays warmup/compile (workers built
            # by the engine factories have already warmed their step;
            # this also covers super/wide program builds)
            stride = (getattr(worker, "stride", None)
                      or getattr(worker, "chunk", None) or 2048)
            worker.process(WorkUnit(-1, 0, max(1, min(int(stride),
                                                      keyspace))))
            # fixed cost = construction + warmup + first unit; a worker
            # whose step was warmed before make_worker returned (a
            # caller-level cache) still reports its own warmup time via
            # compile_seconds (runtime/worker.py), so take the max
            compile_s = max(clock() - t0,
                            getattr(worker, "compile_seconds", 0.0))
            # delta-only: the rung window includes a whole prime unit
            # of hashing, so wall time says nothing about the compile
            rung_cache = compilecache.classify_delta(
                entries0, compilecache.entry_count())
        except Exception as e:   # noqa: BLE001 -- compiler/alloc errors
            swept.append(Probe(batch, 0.0, 0.0,
                               error=f"{type(e).__name__}: {e}"))
            if log:
                log.warn("tune rung failed to build; stopping ladder",
                         batch=batch, error=str(e))
            break                # bigger batches will only fail harder
        if compile_s > compile_budget_s:
            swept.append(Probe(batch, 0.0, compile_s,
                               error="over compile budget",
                               cache=rung_cache))
            if log:
                log.warn("tune rung over compile budget; stopping "
                         "ladder", batch=batch,
                         compile_s=f"{compile_s:.1f}",
                         budget_s=compile_budget_s)
            break                # compile time grows with batch
        rate = _probe_rate(worker, keyspace, probe_seconds, clock)
        p = Probe(batch, rate, compile_s, cache=rung_cache)
        swept.append(p)
        if log:
            log.info("tune rung", batch=batch, rate=f"{rate:,.0f}/s",
                     compile_s=f"{compile_s:.2f}", cache=rung_cache)
        improved = best is None or rate > best.rate_hs * (1.0 + improve_eps)
        if best is None or rate > best.rate_hs:
            best = p
        if _over_hbm_headroom(worker, batch, ladder[i + 1:], log=log):
            break                # next rung projects past free HBM
        if improved:
            stall = 0
        else:
            stall += 1
            if stall >= patience:
                break            # saturated: bigger batches buy nothing
    if best is None:
        errs = "; ".join(p.error or "?" for p in swept) or "empty ladder"
        raise ValueError(f"batch autotune failed on every rung ({errs})")
    return TuneResult(best.batch, best.rate_hs, best.compile_s, swept,
                      source="swept")


def sweep_values(make_worker: Callable[[int], object], values: List[int],
                 keyspace: int, *, probe_seconds: float = 1.0,
                 compile_budget_s: float = 120.0, unit_strides: int = 1,
                 clock: Callable[[], float] = time.perf_counter,
                 log=None, label: str = "value") -> TuneResult:
    """Measure each candidate KNOB value through ``make_worker(value)``
    and return the fastest -- the generic rung sweep behind the
    superstep ``inner`` window and kernel tile-size tunes.

    Unlike sweep()'s geometric batch ladder, the values are unordered
    knob settings with no bigger-fails-harder monotonicity, so every
    value is probed: a rung that fails to build is recorded and
    SKIPPED, never a ladder stop.  The winning value rides in the
    TuneResult/Probe ``batch`` field (one cache record schema for
    every tuned quantity); ``unit_strides`` sizes the probe WorkUnits
    so multi-batch fusion actually engages during measurement."""
    from dprf_tpu import compilecache

    compilecache.enable(log=log)
    swept: List[Probe] = []
    best: Optional[Probe] = None
    for v in values:
        try:
            entries0 = compilecache.entry_count()
            t0 = clock()
            worker = make_worker(v)
            stride = (getattr(worker, "stride", None)
                      or getattr(worker, "chunk", None) or 2048)
            worker.process(WorkUnit(-1, 0, max(1, min(
                int(stride) * max(1, unit_strides), keyspace))))
            compile_s = max(clock() - t0,
                            getattr(worker, "compile_seconds", 0.0))
            rung_cache = compilecache.classify_delta(
                entries0, compilecache.entry_count())
        except Exception as e:   # noqa: BLE001 -- compiler/alloc errors
            swept.append(Probe(v, 0.0, 0.0,
                               error=f"{type(e).__name__}: {e}"))
            if log:
                log.warn("tune rung failed to build; skipping",
                         **{label: v}, error=str(e))
            continue
        if compile_s > compile_budget_s:
            swept.append(Probe(v, 0.0, compile_s,
                               error="over compile budget",
                               cache=rung_cache))
            if log:
                log.warn("tune rung over compile budget; skipping",
                         **{label: v}, compile_s=f"{compile_s:.1f}",
                         budget_s=compile_budget_s)
            continue
        rate = _probe_rate(worker, keyspace, probe_seconds, clock,
                           unit_strides=unit_strides)
        p = Probe(v, rate, compile_s, cache=rung_cache)
        swept.append(p)
        if log:
            log.info("tune rung", **{label: v}, rate=f"{rate:,.0f}/s",
                     compile_s=f"{compile_s:.2f}", cache=rung_cache)
        if best is None or rate > best.rate_hs:
            best = p
    if best is None:
        errs = "; ".join(p.error or "?" for p in swept) or "no values"
        raise ValueError(f"value sweep failed on every rung ({errs})")
    return TuneResult(best.batch, best.rate_hs, best.compile_s, swept,
                      source="swept")
