"""Device sha256crypt engine ($5$; hashcat 7400).

Same TPU mapping as the sha512crypt engine (byte-level message
construction, multi-block compression with where-masked state
advance, on-the-fly repeated-salt blocks, runtime rounds) with
SHA-256's 64-byte blocks -- round messages reach 78 bytes, so each
round chains TWO compressions.  See engines/device/sha512crypt.py for
the design commentary.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Sha256cryptEngine
from dprf_tpu.engines.device.phpass import ShardedPhpassMaskWorker
from dprf_tpu.engines.device.sha512crypt import (Sha512cryptMaskWorker,
                                                 Sha512cryptWordlistWorker,
                                                 _targs)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.sha256 import INIT, sha256_compress

MAX_PASS_LEN = 15
A_CTX_BLOCKS = 3      # 15+16+15+4*32 = 174 (+9 pad) -> 3 x 64
DP_BLOCKS = 4         # 15*15 = 225 (+9) -> 4 x 64
DS_BLOCKS = 68        # (16+255)*16 = 4336 (+9) -> 68 x 64
ROUND_BLOCKS = 2      # 32+15+16+15 = 78 (+9) -> 2 x 64


def _be_words(msg: jnp.ndarray) -> jnp.ndarray:
    coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                dtype=np.uint32))
    grouped = msg.reshape(msg.shape[0], -1, 4).astype(jnp.uint32)
    return (grouped * coef).sum(axis=-1, dtype=jnp.uint32)


def _init_state(B: int) -> jnp.ndarray:
    return jnp.broadcast_to(jnp.asarray(INIT), (B, 8))


def _sha256_multiblock(msg: jnp.ndarray, lens: jnp.ndarray,
                       n_blocks_max: int) -> jnp.ndarray:
    """SHA-256 of per-lane `lens` bytes in msg uint8[B, 64*max] (bytes
    beyond lens zero) -> uint32[B, 8]."""
    B = msg.shape[0]
    pos = jnp.arange(msg.shape[1], dtype=jnp.int32)[None, :]
    msg = (msg + jnp.where(pos == lens[:, None], jnp.uint8(0x80),
                           jnp.uint8(0))).astype(jnp.uint8)
    words = _be_words(msg)
    n_blocks = (lens + 9 + 63) // 64
    widx = n_blocks * 16 - 1
    warange = jnp.arange(words.shape[1], dtype=jnp.int32)[None, :]
    words = jnp.where(warange == widx[:, None],
                      (lens[:, None].astype(jnp.uint32) * 8), words)
    state = _init_state(B)
    for k in range(n_blocks_max):
        new = sha256_compress(state, words[:, k * 16:(k + 1) * 16])
        state = jnp.where((k < n_blocks)[:, None], new, state)
    return state


def _digest_bytes(state: jnp.ndarray) -> jnp.ndarray:
    shifts = jnp.asarray(np.array([24, 16, 8, 0], np.uint32))
    b = (state[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return b.reshape(state.shape[0], 32).astype(jnp.uint8)


def _pad_to(x: jnp.ndarray, width: int) -> jnp.ndarray:
    B, w = x.shape
    return jnp.zeros((B, width), jnp.uint8).at[:, :w].set(x)


def _gat(src_pad, idx):
    return jnp.take_along_axis(src_pad,
                               jnp.clip(idx, 0, src_pad.shape[1] - 1),
                               axis=1)


def sha256crypt_digest_batch(cand: jnp.ndarray, lens: jnp.ndarray,
                             salt: jnp.ndarray, salt_len,
                             rounds) -> jnp.ndarray:
    B = cand.shape[0]
    L = lens[:, None]
    S = jnp.broadcast_to(salt_len, (B,))[:, None].astype(jnp.int32)
    Ls, Ss = lens, S[:, 0]

    W1 = 64
    pos1 = jnp.arange(W1, dtype=jnp.int32)[None, :]
    pw1 = _pad_to(cand, W1)
    salt1 = jnp.broadcast_to(
        jnp.pad(salt, (0, W1 - salt.shape[0]))[None, :],
        (B, W1)).astype(jnp.uint8)

    # -- B_alt = sha256(pw + salt + pw): 46 bytes max, one block --------
    msg = jnp.where(pos1 < L, _gat(pw1, pos1), 0)
    msg = jnp.where((pos1 >= L) & (pos1 < L + S),
                    _gat(salt1, pos1 - L), msg)
    msg = jnp.where((pos1 >= L + S) & (pos1 < 2 * L + S),
                    _gat(pw1, pos1 - L - S), msg).astype(jnp.uint8)
    Bb = _digest_bytes(_sha256_multiblock(msg, 2 * Ls + Ss, 1))

    # -- A context ------------------------------------------------------
    WA = A_CTX_BLOCKS * 64
    posA = jnp.arange(WA, dtype=jnp.int32)[None, :]
    pwA = _pad_to(cand, WA)
    saltA = jnp.broadcast_to(
        _pad_to(salt[None, :].astype(jnp.uint8), WA), (B, WA))
    BbA = _pad_to(Bb, WA)
    msg = jnp.where(posA < L, _gat(pwA, posA), 0)
    msg = jnp.where((posA >= L) & (posA < L + S),
                    _gat(saltA, posA - L), msg)
    o = L + S
    msg = jnp.where((posA >= o) & (posA < o + L), _gat(BbA, posA - o),
                    msg)
    off = o + L
    for j in range(4):
        seg_present = (Ls >> j) > 0
        bit = ((Ls >> j) & 1) == 1
        seg_len = jnp.where(seg_present,
                            jnp.where(bit, 32, Ls), 0)[:, None]
        src = jnp.where(bit[:, None], _gat(BbA, posA - off),
                        _gat(pwA, posA - off))
        msg = jnp.where((posA >= off) & (posA < off + seg_len), src, msg)
        off = off + seg_len
    A = _sha256_multiblock(msg.astype(jnp.uint8), off[:, 0],
                           A_CTX_BLOCKS)

    # -- P sequence -----------------------------------------------------
    WP = DP_BLOCKS * 64
    posP = jnp.arange(WP, dtype=jnp.int32)[None, :]
    Lsafe = jnp.maximum(Ls, 1)[:, None]
    rep = _gat(_pad_to(cand, WP), posP % Lsafe)
    msg = jnp.where(posP < L * L, rep, 0).astype(jnp.uint8)
    Pb = _digest_bytes(_sha256_multiblock(msg, Ls * Ls, DP_BLOCKS))

    # -- S sequence (on-the-fly repeated salt) --------------------------
    A0 = (A[:, 0] >> jnp.uint32(24)).astype(jnp.int32)
    ds_len = (16 + A0) * Ss
    n_blocks = (ds_len + 9 + 63) // 64
    Ssafe = jnp.maximum(Ss, 1)[:, None]

    def ds_block(k, state):
        gpos = k * 64 + pos1
        blk = _gat(salt1, gpos % Ssafe)
        blk = jnp.where(gpos < ds_len[:, None], blk, 0)
        blk = (blk + jnp.where(gpos == ds_len[:, None], jnp.uint8(0x80),
                               jnp.uint8(0))).astype(jnp.uint8)
        words = _be_words(blk)
        is_last = (n_blocks - 1) == k
        words = words.at[:, 15].set(
            jnp.where(is_last, ds_len.astype(jnp.uint32) * 8,
                      words[:, 15]))
        new = sha256_compress(state, words)
        return jnp.where((k < n_blocks)[:, None], new, state)

    Sb = _digest_bytes(lax.fori_loop(0, DS_BLOCKS, ds_block,
                                     _init_state(B)))

    # -- rounds (two-block messages) ------------------------------------
    WR = ROUND_BLOCKS * 64
    posR = jnp.arange(WR, dtype=jnp.int32)[None, :]
    P_R = _pad_to(Pb, WR)
    S_R = _pad_to(Sb, WR)

    def body(i, prev):
        odd = (i & 1) == 1
        s3 = (i % 3) != 0
        s7 = (i % 7) != 0
        d = _pad_to(_digest_bytes(prev), WR)
        l1 = jnp.where(odd, L, 32)
        l4 = jnp.where(odd, 32, L)
        c1 = l1
        c2 = c1 + jnp.where(s3, S, 0)
        c3 = c2 + jnp.where(s7, L, 0)
        total = (c3 + l4)[:, 0]
        src1 = jnp.where(odd, _gat(P_R, posR), _gat(d, posR))
        src4 = jnp.where(odd, _gat(d, posR - c3), _gat(P_R, posR - c3))
        msg = jnp.where(posR < c1, src1, 0)
        msg = jnp.where((posR >= c1) & (posR < c2),
                        _gat(S_R, posR - c1), msg)
        msg = jnp.where((posR >= c2) & (posR < c3),
                        _gat(P_R, posR - c2), msg)
        msg = jnp.where((posR >= c3) & (posR < total[:, None]), src4,
                        msg).astype(jnp.uint8)
        return _sha256_multiblock(msg, total, ROUND_BLOCKS)

    return lax.fori_loop(0, rounds, body, A)


def make_sha256crypt_mask_step(gen, batch: int, hit_capacity: int = 64):
    flat = gen.flat_charsets
    length = gen.length
    if length > MAX_PASS_LEN:
        raise ValueError(
            f"candidates of {length} bytes exceed this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, rounds, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        digest = sha256crypt_digest_batch(cand, lens, salt, salt_len,
                                          rounds)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_sha256crypt_wordlist_step(gen, word_batch: int,
                                   hit_capacity: int = 64):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    if gen.max_len > MAX_PASS_LEN:
        raise ValueError(
            f"wordlist max_len {gen.max_len} exceeds this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, rounds, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        digest = sha256crypt_digest_batch(cw, cl, salt, salt_len, rounds)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class Sha256cryptMaskWorker(Sha512cryptMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 12,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        self.step = make_sha256crypt_mask_step(gen, batch, hit_capacity)


class Sha256cryptWordlistWorker(Sha512cryptWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 12,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        self.step = make_sha256crypt_wordlist_step(gen, self.word_batch,
                                                   hit_capacity)


class ShardedSha256cryptMaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 11, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _targs(self.targets)
        if gen.length > MAX_PASS_LEN:
            raise ValueError(
                f"candidates of {gen.length} bytes exceed this engine's "
                f"{MAX_PASS_LEN}-byte single-block budget")
        self.step = make_sharded_pertarget_step(
            gen, mesh, batch_per_device, sha256crypt_digest_batch, 3,
            hit_capacity)


@register("sha256crypt", device="jax")
class JaxSha256cryptEngine(Sha256cryptEngine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Sha256cryptMaskWorker(self, gen, targets,
                                     batch=min(batch, 1 << 12),
                                     hit_capacity=hit_capacity,
                                     oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Sha256cryptWordlistWorker(self, gen, targets,
                                         batch=min(batch, 1 << 12),
                                         hit_capacity=hit_capacity,
                                         oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedSha256cryptMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, 1 << 11),
            hit_capacity=hit_capacity, oracle=oracle)
