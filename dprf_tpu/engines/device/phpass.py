"""Device phpass engine (iterated MD5; hashcat 400).

The chain h = md5(salt+pass); count x h = md5(h+pass) maps cleanly onto
the TPU: because MD5's digest is the little-endian serialization of its
4 state words and messages pack little-endian, the iteration block's
first four words ARE the previous digest words -- so each step is one
`concatenate` and one shared-md5 compression under `lax.fori_loop`,
with the password's words 4..15 precomputed once per batch.  count is
a runtime argument: one compiled step serves every target/cost.

Password limit: 16 (digest) + len <= 55 one-block bytes -> 39 bytes.
Like bcrypt/PMKID this is a slow per-target sweep; the workers mirror
the salted-engine per-target structure.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import PhpassEngine
from dprf_tpu.engines.cpu.phpass import MAX_PASS_LEN
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.md5 import md5_digest_words
from dprf_tpu.runtime.worker import (Hit, CpuWorker, word_cover_range,
                                     wordlist_lane_to_gidx)
from dprf_tpu.runtime.workunit import WorkUnit


def _le_words(msg: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, 64] -> uint32[B, 16] little-endian."""
    coef = jnp.asarray(np.array([1, 1 << 8, 1 << 16, 1 << 24],
                                dtype=np.uint32))
    grouped = msg.reshape(msg.shape[0], 16, 4).astype(jnp.uint32)
    return (grouped * coef).sum(axis=-1, dtype=jnp.uint32)


def _prefixed_block(cand, lens, prefix_len: int):
    """Candidate bytes placed at a fixed offset in an MD5 block, with
    per-lane 0x80 marker and bit length; words [B, 16] with words
    [0, prefix_len/4) left ZERO for the caller to fill."""
    B, maxlen = cand.shape
    pos = jnp.arange(64, dtype=jnp.int32)[None, :]
    body = jnp.zeros((B, 64), jnp.uint8)
    body = body.at[:, prefix_len:prefix_len + maxlen].set(cand)
    end = prefix_len + lens[:, None]
    msg = jnp.where((pos >= prefix_len) & (pos < end), body, 0)
    msg = (msg + jnp.where(pos == end, jnp.uint8(0x80), jnp.uint8(0))
           ).astype(jnp.uint8)
    words = _le_words(msg)
    return words.at[:, 14].set((prefix_len + lens).astype(jnp.uint32) * 8)


def phpass_digest_batch(cand: jnp.ndarray, lens: jnp.ndarray,
                        salt: jnp.ndarray, count) -> jnp.ndarray:
    """cand uint8[B, maxlen] (lens <= 39) + salt uint8[8] + count ->
    uint32[B, 4] digest words."""
    # initial block: salt(8) + password
    w0 = _prefixed_block(cand, lens, 8)
    salt_words = _le_words(
        jnp.zeros((1, 64), jnp.uint8).at[0, :8].set(salt))[0, :2]
    w0 = w0.at[:, 0].set(salt_words[0]).at[:, 1].set(salt_words[1])
    h = md5_digest_words(w0)
    # iteration block: digest(16) + password; words 4..15 constant
    wp = _prefixed_block(cand, lens, 16)

    def body(_, h):
        w = jnp.concatenate([h, wp[:, 4:]], axis=-1)
        return md5_digest_words(w)

    return lax.fori_loop(0, count, body, h)


def make_phpass_mask_step(gen, batch: int, hit_capacity: int = 64):
    """step(base_digits, n_valid, salt uint8[8], count int32,
    target uint32[4]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    if length > MAX_PASS_LEN:
        raise ValueError(
            f"candidates of {length} bytes exceed this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")

    @jax.jit
    def step(base_digits, n_valid, salt, count, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        digest = phpass_digest_batch(cand, lens, salt, count)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_pertarget_wordlist_step(gen, word_batch: int, digest_fn,
                                 hit_capacity: int = 64):
    """Generic wordlist+rules step for per-target-sweep engines: the
    on-device scaffold (packed-wordlist slice -> rule expansion ->
    digest -> compare -> compact) with the engine's math injected as
    `digest_fn(cand, lens, *params)` — the same contract as
    parallel/sharded.make_sharded_pertarget_step, so an engine
    writes its filter once for both.  The LAST step argument is the
    target word vector: step(w0, n_valid_words, *params, target)."""
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, *args):
        *params, target = args
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        digest = digest_fn(cw, cl, *params)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_phpass_wordlist_step(gen, word_batch: int, hit_capacity: int = 64):
    if gen.max_len > MAX_PASS_LEN:
        raise ValueError(
            f"wordlist max_len {gen.max_len} exceeds this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")
    return make_pertarget_wordlist_step(gen, word_batch,
                                        phpass_digest_batch,
                                        hit_capacity)


def make_sharded_phpass_mask_step(gen, mesh, batch_per_device: int,
                                  hit_capacity: int = 64):
    """Multi-chip variant: the unified sharded runtime's per-target
    step driving phpass_digest_batch (salt, count params)."""
    from dprf_tpu.parallel.sharded import make_sharded_pertarget_step

    if gen.length > MAX_PASS_LEN:
        raise ValueError(
            f"candidates of {gen.length} bytes exceed this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")
    return make_sharded_pertarget_step(
        gen, mesh, batch_per_device, phpass_digest_batch, 2,
        hit_capacity)


class PerTargetSweepSetup:
    """Shared field setup for every per-target-sweep worker (phpass,
    crypt family, pbkdf2, netntlmv2, ...)."""

    def _setup_sweep(self, engine, gen, targets, hit_capacity, oracle):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle


class _PhpassWorkerBase(PerTargetSweepSetup):
    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int, hit_capacity: int, oracle):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self._targs = []
        for t in self.targets:
            self._targs.append((
                jnp.asarray(np.frombuffer(t.params["salt"], np.uint8)),
                jnp.int32(t.params["count"]),
                jnp.asarray(np.frombuffer(t.digest, dtype="<u4")
                            .astype(np.uint32))))

    def _rescan(self, start: int, end: int, ti: int) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        sub = WorkUnit(-1, start, end - start)
        hits = CpuWorker(self.oracle, self.gen,
                         [self.targets[ti]]).process(sub)
        return [Hit(ti, h.cand_index, h.plaintext) for h in hits]


class PhpassMaskWorker(_PhpassWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = 1 << 14,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.stride = batch
        self.step = make_phpass_mask_step(gen, batch, hit_capacity)

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            targ = self._targs[ti]
            queued = []
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart),
                                   dtype=jnp.int32)
                queued.append((bstart, self.step(
                    base, jnp.int32(n_valid), *targ)))
            for bstart, (cnt, lanes, _) in queued:
                cnt = int(cnt)
                if cnt == 0:
                    continue
                if cnt > self.hit_capacity:
                    hits.extend(self._rescan(
                        bstart, min(bstart + self.stride, unit.end), ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


class PhpassWordlistWorker(_PhpassWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = 1 << 14,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_phpass_wordlist_step(gen, self.word_batch,
                                              hit_capacity)

    def process(self, unit: WorkUnit) -> list[Hit]:
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            targ = self._targs[ti]
            queued = []
            for ws in range(w_start, w_end, self.word_batch):
                nw = min(self.word_batch, w_end - ws,
                         self.gen.n_words - ws)
                if nw <= 0:
                    break
                queued.append((ws, nw, self.step(
                    jnp.int32(ws), jnp.int32(nw), *targ)))
            for ws, nw, (cnt, lanes, _) in queued:
                cnt = int(cnt)
                if cnt == 0:
                    continue
                if cnt > self.hit_capacity:
                    start = max(unit.start, ws * R)
                    end = min(unit.end, (ws + nw) * R)
                    hits.extend(self._rescan(start, end, ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = wordlist_lane_to_gidx(int(lane), ws,
                                                 self.word_batch, R)
                    if not unit.start <= gidx < unit.end:
                        continue
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


class ShardedPhpassMaskWorker(PhpassMaskWorker):
    """Per-target sweep over the unified sharded runtime.  Submit-
    based: ALL (target, batch) dispatches enqueue up front with one
    device-accumulated flag, so the remote worker loop pipelines
    sharded per-target units exactly like the fast-hash paths."""

    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 13, hit_capacity: int = 64,
                 oracle=None):
        _PhpassWorkerBase.__init__(self, engine, gen, targets,
                                   mesh.devices.size * batch_per_device,
                                   hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch
        self.step = make_sharded_phpass_mask_step(
            gen, mesh, batch_per_device, hit_capacity)

    def submit(self, unit: WorkUnit):
        from dprf_tpu.runtime.worker import PendingUnit
        queued = []
        flag = None
        for ti in range(len(self.targets)):
            targ = self._targs[ti]
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart),
                                   dtype=jnp.int32)
                result = self.step(base, jnp.int32(n_valid), *targ)
                # device-accumulated unit flag (total is psum'd)
                f = result[0]
                flag = f if flag is None else flag + f
                queued.append(("pshard", (ti, bstart), result))
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        ti, bstart = start
        total, counts, lanes, _ = result
        if int(total) == 0:
            return []
        if (np.asarray(counts) > lanes.shape[-1]).any():
            return self._rescan(
                bstart, min(bstart + self.stride, unit.end), ti)
        hits: list[Hit] = []
        for lane in np.asarray(lanes).ravel():
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()


@register("phpass", device="jax")
class JaxPhpassEngine(PhpassEngine):
    """Device phpass: parsing/oracle from the CPU engine, fused
    iterated-MD5 workers for execution."""

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return PhpassMaskWorker(self, gen, targets,
                                batch=min(batch, 1 << 14),
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return PhpassWordlistWorker(self, gen, targets,
                                    batch=min(batch, 1 << 14),
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedPhpassMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, 1 << 13),
            hit_capacity=hit_capacity, oracle=oracle)
