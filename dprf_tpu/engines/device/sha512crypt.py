"""Device sha512crypt engine ($6$, the Linux shadow default;
hashcat 1800).

The scheme's setup phase hashes VARIABLE-length, multi-block inputs
(the bit-walked A context reaches ~300 bytes; the S-sequence source is
the salt repeated 16+A[0] times, up to ~4.3 KB), and the `rounds` loop
hashes one ~110-byte message per iteration.  TPU mapping:

- a generic multi-block SHA-512 over a fixed-width byte buffer: blocks
  are compressed in a static unroll with per-lane `where`-masked state
  updates, so lanes with fewer blocks simply stop advancing;
- the repeated-salt source is never materialized at its worst-case
  4.3 KB: each 128-byte block is generated on the fly as
  salt[(k*128 + j) mod salt_len] and fed to the chained compression;
- round messages are built at the byte level (clipped gathers +
  boundary masks over a 128-byte window, per-lane password lengths)
  exactly like the md5crypt kernel, under `lax.fori_loop` with
  `rounds` as a runtime argument -- one compiled step serves every
  target, salt, and rounds value.

Password cap: 64 + 2L + 16 <= 111 single-block bytes -> L <= 15 on the
device path (the CPU oracle handles longer).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Sha512cryptEngine
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.sha512 import (INIT512, init_state,
                                 sha512_compress_state)

#: device-path password cap
MAX_PASS_LEN = 15
#: worst-case bytes of the A context: L + S + L + 4 walk segments of
#: max(64, L) -> 15 + 16 + 15 + 256 = 302; padded fits 3 blocks
A_CTX_BLOCKS = 3
#: worst-case blocks of the repeated-salt S source:
#: (16 + 255) * 16 = 4336 bytes (+17 padding) -> 35 blocks
DS_BLOCKS = 35


def _be_words(msg: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, 128k] -> uint32[B, 32k] big-endian."""
    coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                dtype=np.uint32))
    grouped = msg.reshape(msg.shape[0], -1, 4).astype(jnp.uint32)
    return (grouped * coef).sum(axis=-1, dtype=jnp.uint32)


def _sha512_multiblock(msg: jnp.ndarray, lens: jnp.ndarray,
                       n_blocks_max: int) -> jnp.ndarray:
    """SHA-512 of per-lane `lens` bytes inside msg uint8[B, 128*max]
    (bytes beyond lens must be zero) -> uint32[B, 16] digest words."""
    B = msg.shape[0]
    pos = jnp.arange(msg.shape[1], dtype=jnp.int32)[None, :]
    msg = (msg + jnp.where(pos == lens[:, None], jnp.uint8(0x80),
                           jnp.uint8(0))).astype(jnp.uint8)
    words = _be_words(msg)
    n_blocks = (lens + 17 + 127) // 128
    # 128-bit big-endian length field: low 32 bits live in the last
    # word of the final block (lens <= ~4 KB, so higher bits are 0)
    widx = n_blocks * 32 - 1
    warange = jnp.arange(words.shape[1], dtype=jnp.int32)[None, :]
    words = jnp.where(warange == widx[:, None],
                      (lens[:, None].astype(jnp.uint32) * 8), words)
    state = init_state(INIT512, (B,))
    for k in range(n_blocks_max):
        new = sha512_compress_state(state, words[:, k * 32:(k + 1) * 32])
        state = jnp.where((k < n_blocks)[:, None], new, state)
    return state


def _digest_bytes(state: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, 16] interleaved words -> uint8[B, 64] digest bytes."""
    shifts = jnp.asarray(np.array([24, 16, 8, 0], np.uint32))
    b = (state[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return b.reshape(state.shape[0], 64).astype(jnp.uint8)


def _pad_to(x: jnp.ndarray, width: int) -> jnp.ndarray:
    B, w = x.shape
    return jnp.zeros((B, width), jnp.uint8).at[:, :w].set(x)


def _gat(src_pad, idx):
    return jnp.take_along_axis(src_pad,
                               jnp.clip(idx, 0, src_pad.shape[1] - 1),
                               axis=1)


def sha512crypt_digest_batch(cand: jnp.ndarray, lens: jnp.ndarray,
                             salt: jnp.ndarray, salt_len,
                             rounds) -> jnp.ndarray:
    """cand uint8[B, maxlen] (lens <= 15) + salt uint8[16]/salt_len +
    rounds -> uint32[B, 16] raw digest words."""
    B = cand.shape[0]
    L = lens[:, None]
    S = jnp.broadcast_to(salt_len, (B,))[:, None].astype(jnp.int32)
    Ls = lens
    Ss = S[:, 0]

    W1 = 128
    pos1 = jnp.arange(W1, dtype=jnp.int32)[None, :]
    pw1 = _pad_to(cand, W1)
    salt1 = jnp.broadcast_to(
        jnp.pad(salt, (0, W1 - salt.shape[0]))[None, :],
        (B, W1)).astype(jnp.uint8)

    # -- B_alt = sha512(pw + salt + pw) ---------------------------------
    msg = jnp.where(pos1 < L, _gat(pw1, pos1), 0)
    msg = jnp.where((pos1 >= L) & (pos1 < L + S),
                    _gat(salt1, pos1 - L), msg)
    msg = jnp.where((pos1 >= L + S) & (pos1 < 2 * L + S),
                    _gat(pw1, pos1 - L - S), msg).astype(jnp.uint8)
    B_alt = _sha512_multiblock(msg, 2 * Ls + Ss, 1)
    Bb = _digest_bytes(B_alt)

    # -- A context: pw + salt + B[:L] + bit-walk of full B/pw -----------
    WA = A_CTX_BLOCKS * 128
    posA = jnp.arange(WA, dtype=jnp.int32)[None, :]
    pwA = _pad_to(cand, WA)
    saltA = _pad_to(salt[None, :].astype(jnp.uint8), WA)
    saltA = jnp.broadcast_to(saltA, (B, WA))
    BbA = _pad_to(Bb, WA)
    msg = jnp.where(posA < L, _gat(pwA, posA), 0)
    msg = jnp.where((posA >= L) & (posA < L + S),
                    _gat(saltA, posA - L), msg)
    o = L + S
    msg = jnp.where((posA >= o) & (posA < o + L), _gat(BbA, posA - o),
                    msg)
    off = o + L
    for j in range(4):
        seg_present = (Ls >> j) > 0
        bit = ((Ls >> j) & 1) == 1
        seg_len = jnp.where(seg_present,
                            jnp.where(bit, 64, Ls), 0)[:, None]
        src = jnp.where(bit[:, None], _gat(BbA, posA - off),
                        _gat(pwA, posA - off))
        msg = jnp.where((posA >= off) & (posA < off + seg_len), src, msg)
        off = off + seg_len
    msg = msg.astype(jnp.uint8)
    A = _sha512_multiblock(msg, off[:, 0], A_CTX_BLOCKS)

    # -- P sequence: sha512(pw * L)[:L] ---------------------------------
    WP = 256        # 15 * 15 = 225 bytes max
    posP = jnp.arange(WP, dtype=jnp.int32)[None, :]
    Lsafe = jnp.maximum(Ls, 1)[:, None]
    rep = _gat(_pad_to(cand, WP), posP % Lsafe)
    msg = jnp.where(posP < L * L, rep, 0).astype(jnp.uint8)
    DP = _sha512_multiblock(msg, Ls * Ls, 2)
    Pb = _digest_bytes(DP)     # P = Pb[:L]

    # -- S sequence: sha512(salt * (16 + A[0]))[:salt_len] --------------
    # chained on the fly: block k's bytes are salt[(128k + j) % S]
    A0 = (A[:, 0] >> jnp.uint32(24)).astype(jnp.int32)   # first byte
    ds_len = (16 + A0) * Ss
    n_blocks = (ds_len + 17 + 127) // 128
    Ssafe = jnp.maximum(Ss, 1)[:, None]
    state0 = init_state(INIT512, (B,))

    def ds_block(k, state):
        gpos = k * 128 + pos1                    # [B, 128] global pos
        blk = _gat(salt1, gpos % Ssafe)
        blk = jnp.where(gpos < ds_len[:, None], blk, 0)
        blk = (blk + jnp.where(gpos == ds_len[:, None], jnp.uint8(0x80),
                               jnp.uint8(0))).astype(jnp.uint8)
        words = _be_words(blk)
        # the 128-bit length field lands in this block iff it is the
        # last one; low word = bits at local word index 31
        is_last = (n_blocks - 1) == k
        words = words.at[:, 31].set(
            jnp.where(is_last, ds_len.astype(jnp.uint32) * 8,
                      words[:, 31]))
        new = sha512_compress_state(state, words)
        return jnp.where((k < n_blocks)[:, None], new, state)

    DS = lax.fori_loop(0, DS_BLOCKS, ds_block, state0)
    Sb = _digest_bytes(DS)     # S = Sb[:salt_len]

    # -- rounds ----------------------------------------------------------
    P128 = _pad_to(Pb, W1)
    S128 = _pad_to(Sb, W1)

    def body(i, prev):
        odd = (i & 1) == 1
        s3 = (i % 3) != 0
        s7 = (i % 7) != 0
        d = _pad_to(_digest_bytes(prev), W1)
        l1 = jnp.where(odd, L, 64)
        l4 = jnp.where(odd, 64, L)
        c1 = l1
        c2 = c1 + jnp.where(s3, S, 0)
        c3 = c2 + jnp.where(s7, L, 0)
        total = (c3 + l4)[:, 0]
        src1 = jnp.where(odd, _gat(P128, pos1), _gat(d, pos1))
        src4 = jnp.where(odd, _gat(d, pos1 - c3), _gat(P128, pos1 - c3))
        msg = jnp.where(pos1 < c1, src1, 0)
        msg = jnp.where((pos1 >= c1) & (pos1 < c2),
                        _gat(S128, pos1 - c1), msg)
        msg = jnp.where((pos1 >= c2) & (pos1 < c3),
                        _gat(P128, pos1 - c2), msg)
        msg = jnp.where((pos1 >= c3) & (pos1 < total[:, None]), src4,
                        msg).astype(jnp.uint8)
        return _sha512_multiblock(msg, total, 1)

    return lax.fori_loop(0, rounds, body, A)


def make_sha512crypt_mask_step(gen, batch: int, hit_capacity: int = 64):
    """step(base_digits, n_valid, salt uint8[16], salt_len, rounds,
    target uint32[16]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    if length > MAX_PASS_LEN:
        raise ValueError(
            f"candidates of {length} bytes exceed this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, rounds, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        digest = sha512crypt_digest_batch(cand, lens, salt, salt_len,
                                          rounds)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_sha512crypt_wordlist_step(gen, word_batch: int,
                                   hit_capacity: int = 64):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    if gen.max_len > MAX_PASS_LEN:
        raise ValueError(
            f"wordlist max_len {gen.max_len} exceeds this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, rounds, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        digest = sha512crypt_digest_batch(cw, cl, salt, salt_len, rounds)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def _targs(targets):
    out = []
    for t in targets:
        s = t.params["salt"]
        buf = np.zeros((16,), np.uint8)
        buf[:len(s)] = np.frombuffer(s, np.uint8)
        out.append((jnp.asarray(buf), jnp.int32(len(s)),
                    jnp.int32(t.params["rounds"]),
                    jnp.asarray(np.frombuffer(t.digest, dtype=">u4")
                                .astype(np.uint32))))
    return out


# The per-target sweep bodies are the phpass workers' (they splat the
# (salt, salt_len, rounds, target) tuple _targs built); only the step
# factories differ.

class Sha512cryptMaskWorker(PhpassMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 12,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        self.step = make_sha512crypt_mask_step(gen, batch, hit_capacity)


class Sha512cryptWordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 12,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        self.step = make_sha512crypt_wordlist_step(gen, self.word_batch,
                                                   hit_capacity)


class ShardedSha512cryptMaskWorker(ShardedPhpassMaskWorker):
    """Multi-chip variant via the generic per-target sharded step;
    the sharded phpass worker's result decoding applies unchanged."""

    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 11, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _targs(self.targets)
        if gen.length > MAX_PASS_LEN:
            raise ValueError(
                f"candidates of {gen.length} bytes exceed this engine's "
                f"{MAX_PASS_LEN}-byte single-block budget")
        self.step = make_sharded_pertarget_step(
            gen, mesh, batch_per_device, sha512crypt_digest_batch, 3,
            hit_capacity)


@register("sha512crypt", device="jax")
class JaxSha512cryptEngine(Sha512cryptEngine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Sha512cryptMaskWorker(self, gen, targets,
                                     batch=min(batch, 1 << 12),
                                     hit_capacity=hit_capacity,
                                     oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Sha512cryptWordlistWorker(self, gen, targets,
                                         batch=min(batch, 1 << 12),
                                         hit_capacity=hit_capacity,
                                         oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedSha512cryptMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, 1 << 11),
            hit_capacity=hit_capacity, oracle=oracle)
