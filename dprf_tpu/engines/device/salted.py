"""Salted fast-hash engines: md5/sha1/sha256/sha512 over $pass.$salt
and $salt.$pass (hashcat modes 10/20, 110/120, 1410/1420, 1710/1720).

Target lines use the hashcat convention ``hexdigest:salt`` (the salt is
the literal bytes after the first colon; ``$HEX[..]`` decodes hex
salts).  Salted sweeps are inherently per-target -- each salt reshapes
the digest of every candidate -- so the workers sweep the keyspace once
per target, exactly like bcrypt's; unlike bcrypt, ONE compiled step
serves every target because the salt is a runtime argument (a fixed
buffer + length), not a trace-time constant.

On device the salt is appended (ps) or prepended (sp) to the candidate
with the same vectorized variable-shift select the combinator decode
uses, then flows through the engines' varlen packing -- no new hash
code at all; the compression functions are the ones every other path
shares.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import SALT_MAX, parse_salted_line
from dprf_tpu.engines.device.engines import (JaxMd5Engine, JaxSha1Engine,
                                             JaxSha256Engine,
                                             JaxSha512Engine)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.runtime.worker import (Hit, CpuWorker, word_cover_range,
                                     wordlist_lane_to_gidx)
from dprf_tpu.runtime.workunit import WorkUnit

def _salted_concat(cand, length: int, salt, salt_len, order: str,
                   batch: int, salt_width: int = SALT_MAX):
    """cand uint8[B, L] + salt uint8[salt_width] (salt_len valid) ->
    (bytes uint8[B, L + salt_width], lengths int32[B]).  `salt_width`
    is the engine's static salt-buffer width -- SALT_MAX for the
    generic hexdigest:salt modes, 4 for MSSQL's fixed salt, so widened
    candidates don't pay a 32-byte buffer reservation against the
    single-block limit."""
    width = length + salt_width
    pos = jnp.arange(width, dtype=jnp.int32)[None, :]
    if order == "ps":
        out = jnp.zeros((batch, width), jnp.uint8).at[:, :length].set(cand)
        sidx = jnp.clip(pos - length, 0, salt_width - 1)
        svals = jnp.broadcast_to(salt[None, :], (batch, salt_width))
        out = jnp.where(pos < length, out,
                        jnp.take_along_axis(svals, sidx, axis=1))
    else:
        cpad = jnp.zeros((batch, width), jnp.uint8).at[:, :length].set(cand)
        cidx = jnp.clip(pos - salt_len, 0, width - 1)
        cshift = jnp.take_along_axis(cpad, cidx, axis=1)
        svals = jnp.broadcast_to(
            jnp.pad(salt, (0, width - salt_width))[None, :], (batch, width))
        out = jnp.where(pos < salt_len, svals, cshift)
    return out, jnp.full((batch,), length, jnp.int32) + salt_len


def make_salted_mask_step(engine, gen, batch: int, order: str,
                          hit_capacity: int = 64):
    """step(base_digits, n_valid, salt uint8[SALT_MAX], salt_len int32,
    target uint32[W]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    pre = engine.pre_salt
    mult = engine.length_multiplier
    sw = engine.salt_width

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        if pre is not None:
            cand = pre(cand)
        byts, lengths = _salted_concat(cand, length * mult, salt,
                                       salt_len, order, batch, sw)
        words = engine.pack_varlen(byts, lengths)
        digest = engine.digest_packed(words)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_salted_wordlist_step(engine, gen, word_batch: int, order: str,
                              hit_capacity: int = 64):
    """Wordlist(+rules) variant; lanes are flat r*B + b indices."""
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    pre = engine.pre_salt
    mult = engine.length_multiplier
    sw = engine.salt_width

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        if pre is not None:
            cw = pre(cw)
            cl = cl * mult
        Le = L * mult
        RB = cw.shape[0]
        width = Le + sw
        pos = jnp.arange(width, dtype=jnp.int32)[None, :]
        if order == "ps":
            out = jnp.zeros((RB, width), jnp.uint8).at[:, :Le].set(cw)
            sidx = jnp.clip(pos - cl[:, None], 0, sw - 1)
            svals = jnp.broadcast_to(salt[None, :], (RB, sw))
            out = jnp.where(pos < cl[:, None], out,
                            jnp.take_along_axis(svals, sidx, axis=1))
        else:
            cpad = jnp.zeros((RB, width), jnp.uint8).at[:, :Le].set(cw)
            cidx = jnp.clip(pos - salt_len, 0, width - 1)
            out = jnp.where(
                pos < salt_len,
                jnp.broadcast_to(jnp.pad(salt, (0, width - sw))[None, :],
                                 (RB, width)),
                jnp.take_along_axis(cpad, cidx, axis=1))
        lengths = cl + salt_len
        words = engine.pack_varlen(out, lengths)
        digest = engine.digest_packed(words)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_sharded_salted_mask_step(engine, gen, mesh, batch_per_device: int,
                                  order: str, hit_capacity: int = 64):
    """Multi-chip salted mask step through the ONE sharded runtime:
    only the salt-concat digest math lives here."""
    from dprf_tpu.parallel.sharded import make_sharded_pertarget_step

    length = gen.length
    pre = engine.pre_salt
    mult = engine.length_multiplier
    sw = engine.salt_width

    def digest_fn(cand, lens, salt, salt_len):
        if pre is not None:
            cand = pre(cand)
        byts, lengths = _salted_concat(cand, length * mult, salt,
                                       salt_len, order, cand.shape[0],
                                       sw)
        return engine.digest_packed(engine.pack_varlen(byts, lengths))

    return make_sharded_pertarget_step(gen, mesh, batch_per_device,
                                       digest_fn, 2, hit_capacity)


class _SaltedWorkerBase:
    """Per-target sweep shared by the salted mask/wordlist workers."""

    #: device salt-buffer width; families whose step consumes a wider
    #: runtime salt (e.g. scrypt's 51-byte PBKDF2 buffer) override it
    SALT_WIDTH = SALT_MAX

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int, hit_capacity: int, oracle):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.batch = batch
        self._targs = self._prep_targets()

    def _prep_targets(self):
        """Per-target device state for _invoke: (salt buffer, salt len,
        digest words).  Families whose per-target state is something
        else entirely (zip2's per-target compiled steps over a 10-byte
        auth digest) override this alongside _invoke."""
        dt = "<u4" if self.engine.little_endian else ">u4"
        width = getattr(self.engine, "salt_width", self.SALT_WIDTH)
        targs = []
        for t in self.targets:
            salt = t.params["salt"]
            if len(salt) > width:
                raise ValueError(
                    f"{self.engine.name}: salt of {len(salt)} bytes "
                    f"exceeds the engine's {width}-byte buffer")
            buf = np.zeros((width,), np.uint8)
            buf[:len(salt)] = np.frombuffer(salt, np.uint8)
            targs.append((
                jnp.asarray(buf), jnp.int32(len(salt)),
                jnp.asarray(np.frombuffer(t.digest, dtype=dt)
                            .astype(np.uint32))))
        return targs

    def _rescan(self, start: int, end: int, ti: int) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        sub = WorkUnit(-1, start, end - start)
        hits = CpuWorker(self.oracle, self.gen,
                         [self.targets[ti]]).process(sub)
        return [Hit(ti, h.cand_index, h.plaintext) for h in hits]

    def _invoke(self, ti: int, base, n):
        """One step call for target ti -- the override point for worker
        families whose per-target state isn't a (salt, target) pair
        (e.g. JWT's per-target compiled steps)."""
        salt, salt_len, tgt = self._targs[ti]
        return self.step(base, n, salt, salt_len, tgt)

    #: wide fusion bounds (see runtime/worker.py MaskWorkerBase): a
    #: wide-capable subclass overrides _wide_invoke to rebuild its
    #: per-target step at inner*stride lanes -- one device program per
    #: ~100 batches instead of per batch, the same link-amortization
    #: the Pallas mask workers use (scan-wrapping is not an option on
    #: this backend; TPU_PROBE_LOG_r04.md finding 8).
    SUPER_CAP = 256
    SUPER_MIN = 8

    def _wide_invoke(self, ti: int, base, sbatch: int, n_valid):
        """Wide step call for target ti, or None when not wide-capable
        (the default: per-batch dispatch only)."""
        return None

    def _wide_inner(self, remaining_strides: int) -> int:
        # env flag + int32 cap are worker-lifetime invariants: resolve
        # once (this runs on every iteration of the per-batch sweep)
        cap = getattr(self, "_wide_cap", None)
        if cap is None:
            from dprf_tpu.ops.superstep import max_inner
            from dprf_tpu.utils import env as envreg
            cap = self._wide_cap = (
                0 if not envreg.get_bool("DPRF_SUPERSTEP")
                else max_inner(self.stride, self.SUPER_CAP))
        if getattr(self, "_wide_disabled", False) or \
                cap < self.SUPER_MIN or \
                remaining_strides < self.SUPER_MIN:
            return 0
        return min(cap, 1 << (remaining_strides.bit_length() - 1))

    def _batch_flag(self, result):
        """Scalar that is nonzero iff this batch needs host attention
        (hits or overflow); override with any extra buffers.  See
        runtime/worker.py MaskWorkerBase._batch_flag."""
        return result[0]

    def _accept(self, ti: int, gidx: int, plain: bytes) -> bool:
        """Final say on a device-reported lane.  Workers whose device
        compare is a narrow prefilter (e.g. zip2's 2-byte password
        verification value) override this with an oracle confirmation
        so ~1/2^16 false maybes never leave the worker."""
        return True


def per_target_setup(worker, engine, gen, targets, batch, hit_capacity,
                     oracle):
    """Shared field setup for worker families whose per-target state is
    a COMPILED STEP (JWT's signing input, office's salt+verifier
    blocks) rather than the (salt, digest words) rows
    _SaltedWorkerBase.__init__ prepares."""
    worker.engine = engine
    worker.gen = gen
    worker.targets = list(targets)
    worker.hit_capacity = hit_capacity
    worker.oracle = oracle
    worker.batch = batch


class PerTargetStepsMixin:
    """_invoke for workers holding one compiled step per target."""

    def _invoke(self, ti: int, base, n):
        return self._steps[ti](base, n)


class SaltedMaskWorker(_SaltedWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.stride = batch
        self.step = make_salted_mask_step(engine, gen, batch,
                                          engine.order, hit_capacity)

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            queued = []
            flag = None
            pos = unit.start
            while pos < unit.end:
                inner = self._wide_inner((unit.end - pos) // self.stride)
                window = inner * self.stride if inner >= 2 else 0
                base = jnp.asarray(self.gen.digits(pos), dtype=jnp.int32)
                result = None
                if window:
                    result = self._wide_invoke(ti, base, window,
                                               jnp.int32(window))
                if result is None:         # per-batch dispatch
                    window = min(self.stride, unit.end - pos)
                    result = self._invoke(ti, base, jnp.int32(window))
                # device-accumulated unit flag: one host readback per
                # (target, unit) when nothing hit -- see
                # runtime/worker.py MaskWorkerBase.process
                f = self._batch_flag(result)
                flag = f if flag is None else flag + f
                queued.append((pos, window, result))
                pos += window
            if flag is None or int(flag) == 0:
                continue
            for bstart, window, result in queued:
                hits.extend(self._entry_hits(ti, bstart, window, result,
                                             unit))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True

    def _entry_hits(self, ti: int, bstart: int, window: int, result,
                    unit: WorkUnit) -> list[Hit]:
        """Decode one dispatch's result; a wide window whose buffer
        overflowed re-drives through the per-batch device step so the
        exact host rescan stays one stride wide."""
        count, lanes, _ = result
        count = int(count)
        if count == 0:
            return []
        if count > lanes.shape[0]:     # the step's BUILT buffer size
            if window > self.stride:
                out: list[Hit] = []
                end = min(bstart + window, unit.end)
                for bs in range(bstart, end, self.stride):
                    nv = min(self.stride, end - bs)
                    base = jnp.asarray(self.gen.digits(bs),
                                       dtype=jnp.int32)
                    out.extend(self._entry_hits(
                        ti, bs, nv, self._invoke(ti, base, jnp.int32(nv)),
                        unit))
                return out
            return self._rescan(
                bstart, min(bstart + self.stride, unit.end), ti)
        hits: list[Hit] = []
        for lane in np.asarray(lanes):
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            plain = self.gen.candidate(gidx)
            if self._accept(ti, gidx, plain):
                hits.append(Hit(ti, gidx, plain))
        return hits


class SaltedWordlistWorker(_SaltedWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_salted_wordlist_step(engine, gen, self.word_batch,
                                              engine.order, hit_capacity)


    def process(self, unit: WorkUnit) -> list[Hit]:
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            queued = []
            flag = None
            for ws in range(w_start, w_end, self.word_batch):
                nw = min(self.word_batch, w_end - ws, self.gen.n_words - ws)
                if nw <= 0:
                    break
                result = self._invoke(ti, jnp.int32(ws), jnp.int32(nw))
                # device-accumulated unit flag (see mask worker above)
                f = self._batch_flag(result)
                flag = f if flag is None else flag + f
                queued.append((ws, nw, result))
            if flag is None or int(flag) == 0:
                continue
            for ws, nw, (count, lanes, _) in queued:
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    start = max(unit.start, ws * R)
                    end = min(unit.end, (ws + nw) * R)
                    hits.extend(self._rescan(start, end, ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = wordlist_lane_to_gidx(int(lane), ws,
                                                 self.word_batch, R)
                    if not unit.start <= gidx < unit.end:
                        continue
                    plain = self.gen.candidate(gidx)
                    if self._accept(ti, gidx, plain):
                        hits.append(Hit(ti, gidx, plain))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


class PallasSaltedMaskWorker(SaltedMaskWorker):
    """Salted mask sweep over the extended Pallas kernels
    (ops/pallas_ext.py): the whole decode -> concat-salt -> compress
    -> compare chain stays in VMEM, with the salt bytes and target
    digest as RUNTIME scalars -- one compiled kernel per distinct salt
    LENGTH serves the whole hashlist.  Per-target sweep loop, hit
    contract, rescan, and the unit flag all come from
    SaltedMaskWorker; only _invoke changes."""

    def __init__(self, engine, gen, targets, algo: str,
                 batch: int = 1 << 18, hit_capacity: int = 64,
                 oracle=None, interpret: bool = False):
        from dprf_tpu.ops import pallas_ext
        from dprf_tpu.ops.pallas_mask import SUB

        # NOT _SaltedWorkerBase.__init__: its _prep_targets builds
        # per-target (salt buffer, len, digest) device arrays this
        # worker never reads -- _kargs below is the kernel-format
        # equivalent
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        tile = SUB * 128
        batch = max(tile, (batch // tile) * tile)
        self.stride = self.batch = batch
        self._algo = algo
        self._interpret = interpret
        lens = sorted({len(t.params["salt"]) for t in self.targets})
        self._ksteps = {
            n: pallas_ext.make_salted_crack_step(
                algo, engine.order, gen, batch, n, hit_capacity,
                interpret=interpret)
            for n in lens}
        self._wide_ksteps: dict = {}
        # per-target runtime args: salt bytes as int32, target words
        # bit-cast to int32 (SMEM scalars)
        dt = "<u4" if engine.little_endian else ">u4"
        self._kargs = []
        for t in self.targets:
            salt = t.params["salt"]
            self._kargs.append((
                len(salt),
                jnp.asarray(np.frombuffer(salt, np.uint8)
                            .astype(np.int32)),
                jnp.asarray(np.frombuffer(t.digest, dtype=dt)
                            .astype(np.uint32).view(np.int32))))

    def warmup(self) -> None:
        """One launch per COMPILED KERNEL (distinct salt length), not
        per target -- warmup exists to surface compile failures, and a
        10k-target hashlist shares at most a handful of kernels."""
        from dprf_tpu.utils.sync import hard_sync
        base = jnp.asarray(self.gen.digits(0), dtype=jnp.int32)
        by_len = {n: (salt, tgt) for n, salt, tgt in self._kargs}
        for n, (salt, tgt) in by_len.items():
            hard_sync(self._ksteps[n](base, jnp.int32(0), salt, tgt))

    def _invoke(self, ti: int, base, n):
        slen, salt, tgt = self._kargs[ti]
        return self._ksteps[slen](base, n, salt, tgt)

    def _wide_invoke(self, ti: int, base, sbatch: int, n_valid):
        """Wide kernel step at sbatch lanes, cached per (salt length,
        sbatch) -- salt/target stay RUNTIME scalars, so one wide
        program per salt length serves the whole hashlist, exactly
        like the per-batch kernels.  A build failure degrades this
        worker to per-batch dispatch (never a scan wrapper)."""
        from dprf_tpu.ops import pallas_ext
        slen, salt, tgt = self._kargs[ti]
        key = (slen, sbatch)
        try:
            step = self._wide_ksteps.get(key)
            if step is None:
                scale = max(1, sbatch // self.batch)
                cap = max(self.hit_capacity,
                          min(self.hit_capacity * scale, 1024))
                step = self._wide_ksteps[key] = \
                    pallas_ext.make_salted_crack_step(
                        self._algo, self.engine.order, self.gen,
                        sbatch, slen, cap, interpret=self._interpret)
            # the CALL stays inside the try: jit/Mosaic compile
            # lazily, so a wide program that exceeds VMEM surfaces
            # HERE, not in the factory -- it must degrade this worker
            # to per-batch dispatch, not kill the WorkUnit
            return step(base, n_valid, salt, tgt)
        except Exception as e:  # noqa: BLE001 -- compiler errors
            from dprf_tpu.utils.logging import DEFAULT as log
            self._wide_disabled = True
            log.warn("wide salted kernel failed to build/compile; "
                     "falling back to per-batch dispatch",
                     sbatch=sbatch, error=str(e))
            return None


#: device base class -> kernel core algo for the extended salted
#: kernels (sha512 has no 32-bit core; engines with pre_salt
#: transforms or length multipliers pack differently)
_KERNEL_ALGOS = ((JaxMd5Engine, "md5"), (JaxSha1Engine, "sha1"),
                 (JaxSha256Engine, "sha256"))


def _kernel_algo(engine) -> str | None:
    if engine.pre_salt is not None or engine.length_multiplier != 1:
        return None
    for base, algo in _KERNEL_ALGOS:
        if isinstance(engine, base):
            return algo
    return None


def maybe_pallas_salted_worker(engine, gen, targets, batch: int,
                               hit_capacity: int, oracle):
    """PallasSaltedMaskWorker when the job is kernel-eligible (warmed,
    so compile failures surface here), else None -- the factory then
    builds the XLA-step worker.  Mirrors JaxEngineBase's pallas
    selection + fallback pattern."""
    from dprf_tpu.ops import pallas_ext
    from dprf_tpu.ops.pallas_mask import pallas_mode
    from dprf_tpu.utils.logging import DEFAULT as log

    mode = pallas_mode()
    if mode is None:
        return None
    algo = _kernel_algo(engine)
    lens = [len(t.params["salt"]) for t in targets]
    if algo is None or not pallas_ext.salted_eligible(
            algo, engine.order, gen, lens):
        log.info("salted pallas kernel not eligible for this job; "
                 "using the XLA pipeline", engine=engine.name,
                 targets=len(targets))
        return None
    try:
        worker = PallasSaltedMaskWorker(
            engine, gen, targets, algo, batch=batch,
            hit_capacity=hit_capacity, oracle=oracle,
            interpret=mode.get("interpret", False))
        worker.warmup()
        return worker
    except Exception as e:
        log.warn("salted pallas kernel failed to build/compile; "
                 "falling back to the XLA pipeline",
                 engine=engine.name,
                 error=f"{type(e).__name__}: {e}")
        return None


class ShardedSaltedMaskWorker(SaltedMaskWorker):
    """SaltedMaskWorker over a device mesh: super-batch strides, the
    per-shard overflow check, super-batch-global lanes."""

    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets,
                                   mesh.devices.size * batch_per_device,
                                   hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch
        self.step = make_sharded_salted_mask_step(
            engine, gen, mesh, batch_per_device, engine.order,
            hit_capacity)

    def submit(self, unit: WorkUnit):
        """Submit-based per-target sweep (unified sharded runtime):
        ALL (target, batch) dispatches enqueue up front with one
        device-accumulated flag, so the remote worker loop pipelines
        sharded salted units like the fast-hash paths."""
        from dprf_tpu.runtime.worker import PendingUnit
        queued = []
        flag = None
        for ti in range(len(self.targets)):
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart),
                                   dtype=jnp.int32)
                result = self._invoke(ti, base, jnp.int32(n_valid))
                # device-accumulated unit flag (total is psum'd)
                f = self._batch_flag(result)
                flag = f if flag is None else flag + f
                queued.append(("salt-shard", (ti, bstart), result))
        if flag is not None and hasattr(flag, "copy_to_host_async"):
            flag.copy_to_host_async()
        return PendingUnit(self, unit, queued, flag)

    def _decode_queued(self, kind: str, start, result,
                       unit: WorkUnit) -> list[Hit]:
        ti, bstart = start
        total, counts, lanes, _ = result
        if int(total) == 0:
            return []
        if (np.asarray(counts) > lanes.shape[-1]).any():
            return self._rescan(
                bstart, min(bstart + self.stride, unit.end), ti)
        hits: list[Hit] = []
        for lane in np.asarray(lanes).ravel():
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            plain = self.gen.candidate(gidx)
            if self._accept(ti, gidx, plain):
                hits.append(Hit(ti, gidx, plain))
        return hits

    def process(self, unit: WorkUnit) -> list[Hit]:
        return self.submit(unit).resolve()

    process._submit_based = True   # safe to pipeline via submit()


class _SaltedDeviceMixin:
    """Device engine for one (algo, order): the base engine's packing
    and digest with the salted worker factories."""

    salted = True
    order: str
    #: optional device transform of the candidate bytes BEFORE the salt
    #: is appended (mssql's UTF-16LE widening); uint8[B, L] ->
    #: uint8[B, length_multiplier * L] with every valid byte mapped to
    #: `length_multiplier` output bytes.
    pre_salt = None
    length_multiplier = 1
    #: static device salt-buffer width; engines with a fixed short salt
    #: (MSSQL: 4 bytes) narrow it so the buffer reservation doesn't
    #: count against the single-block limit.
    salt_width = SALT_MAX
    #: leave headroom for any parseable salt in the single block;
    #: the worker factories additionally check ACTUAL salts.  Set per
    #: class in _register_device from the base engine's block limit.
    max_candidate_len = 55 - SALT_MAX

    def parse_target(self, text: str) -> Target:
        digest, salt = parse_salted_line(text, self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        self._check_lengths(gen.length, targets)
        worker = maybe_pallas_salted_worker(self, gen, targets, batch,
                                            hit_capacity, oracle)
        if worker is not None:
            return worker
        return SaltedMaskWorker(self, gen, targets, batch=batch,
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        self._check_lengths(gen.max_len, targets)
        return SaltedWordlistWorker(self, gen, targets, batch=batch,
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        self._check_lengths(gen.length, targets)
        return ShardedSaltedMaskWorker(self, gen, targets, mesh,
                                       batch_per_device=batch_per_device,
                                       hit_capacity=hit_capacity,
                                       oracle=oracle)

    # the generic unsalted sharded wordlist step must NOT be inherited
    # (it would silently ignore the salt); shadow it so the CLI
    # degrades to the single-chip salted worker with a warning instead
    make_sharded_wordlist_worker = None

    # likewise the generic combinator worker compares unsalted digests
    make_combinator_worker = None
    make_sharded_combinator_worker = None

    def _check_lengths(self, cand_len: int, targets) -> None:
        worst = (cand_len * self.length_multiplier
                 + max(len(t.params["salt"]) for t in targets))
        if worst > self._block_limit:
            raise ValueError(
                f"candidate+salt can reach {worst} bytes, over the "
                f"{self._block_limit}-byte single-block limit; "
                "shorten the mask/words")


def _register_device(base_cls, algo: str):
    for order in ("ps", "sp"):
        name = f"{algo}-{order}"
        cls = type(f"Jax{algo.title()}{order.title()}Engine",
                   (_SaltedDeviceMixin, base_cls),
                   {"name": name, "order": order,
                    "__doc__": (f"Salted {algo}: "
                                + ("$pass.$salt" if order == "ps"
                                   else "$salt.$pass")
                                + " appended on device."),
                    "max_candidate_len":
                        base_cls._block_limit - SALT_MAX})
        register(name, device="jax")(cls)


_register_device(JaxMd5Engine, "md5")
_register_device(JaxSha1Engine, "sha1")
_register_device(JaxSha256Engine, "sha256")
_register_device(JaxSha512Engine, "sha512")


@register("postgres", device="jax")
@register("postgres-md5", device="jax")
class JaxPostgresEngine(_SaltedDeviceMixin, JaxMd5Engine):
    """PostgreSQL MD5 auth (hashcat 12): md5($pass.$username) -- the
    salted-md5 'ps' machinery with postgres's line format."""

    name = "postgres"
    order = "ps"

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import PostgresMd5Engine
        return PostgresMd5Engine().parse_target(text)


def _register_ldap_salted():
    """LDAP {SSHA}/{SSHA512}/{SMD5} (hashcat 111/1711): the salted
    'ps' device machinery with the LDAP base64 line format -- parsing
    delegates to the CPU engines (same pattern as postgres)."""
    from dprf_tpu.engines.cpu.engines import (LdapSmd5Engine,
                                              LdapSsha512Engine,
                                              LdapSshaEngine)

    for names, base_cls, cpu_cls in (
            (("ldap-ssha", "ssha"), JaxSha1Engine, LdapSshaEngine),
            (("ldap-ssha512", "ssha512"), JaxSha512Engine,
             LdapSsha512Engine),
            (("ldap-smd5",), JaxMd5Engine, LdapSmd5Engine)):
        def make_parse(cpu_cls):
            def parse_target(self, text: str):
                return cpu_cls().parse_target(text)
            return parse_target

        cls = type(f"Jax{cpu_cls.__name__}",
                   (_SaltedDeviceMixin, base_cls),
                   {"name": names[0], "order": "ps",
                    "__doc__": cpu_cls.__doc__ + " (device)",
                    "parse_target": make_parse(cpu_cls),
                    "max_candidate_len":
                        base_cls._block_limit - SALT_MAX})
        for n in names:
            register(n, device="jax")(cls)


_register_ldap_salted()


class _MssqlDeviceMixin(_SaltedDeviceMixin):
    """MSSQL family: the salted 'ps' machinery with a pre-salt
    UTF-16LE widening of the candidate (and an ASCII uppercase first
    for 2000's case-insensitive digest).  The 4-byte salt is appended
    to the WIDENED bytes, unwidened -- which is why this is a pre-salt
    transform, not the engines' widen_utf16 packing flag (that would
    widen the salt too)."""

    order = "ps"
    length_multiplier = 2
    #: MSSQL salts are exactly 4 bytes; a narrow buffer keeps the
    #: widened candidate + salt inside the single block (2*25+4 <= 55).
    salt_width = 4
    _upper = False

    def pre_salt(self, cand):
        from dprf_tpu.ops import pack as pack_ops
        if self._upper:
            cand = jnp.where((cand >= 97) & (cand <= 122),
                             cand - 32, cand).astype(jnp.uint8)
        return pack_ops.utf16le_widen(cand)


@register("mssql2000", device="jax")
class JaxMssql2000Engine(_MssqlDeviceMixin, JaxSha1Engine):
    """MSSQL 2000 (hashcat 131; device)."""

    name = "mssql2000"
    _upper = True
    max_candidate_len = (55 - 4) // 2

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import Mssql2000Engine
        return Mssql2000Engine().parse_target(text)


@register("mssql2005", device="jax")
class JaxMssql2005Engine(_MssqlDeviceMixin, JaxSha1Engine):
    """MSSQL 2005 (hashcat 132; device)."""

    name = "mssql2005"
    max_candidate_len = (55 - 4) // 2

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import Mssql2005Engine
        return Mssql2005Engine().parse_target(text)


@register("mssql2012", device="jax")
@register("mssql2014", device="jax")
class JaxMssql2012Engine(_MssqlDeviceMixin, JaxSha512Engine):
    """MSSQL 2012/2014 (hashcat 1731; device)."""

    name = "mssql2012"
    max_candidate_len = (111 - 4) // 2

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import Mssql2012Engine
        return Mssql2012Engine().parse_target(text)


@register("oracle11", device="jax")
@register("oracle-11g", device="jax")
class JaxOracle11Engine(_SaltedDeviceMixin, JaxSha1Engine):
    """Oracle 11g (hashcat 112): sha1($pass.$salt) -- the salted-sha1
    'ps' machinery with Oracle's S: line format."""

    name = "oracle11"
    order = "ps"
    #: fixed 10-byte salt -> narrow buffer, longer candidates (45)
    salt_width = 10
    max_candidate_len = 55 - 10

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import Oracle11Engine
        return Oracle11Engine().parse_target(text)
