"""Device RAR5 engine (hashcat 13000): the pbkdf2-sha256 workers with
a fold -- the 32-byte derived key's quarters XOR into RAR5's 8-byte
password check value, so the compare target is 2 words.  Iteration
counts (2^n + 32) and salts are runtime args; one compiled step serves
every target."""

from __future__ import annotations

import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Rar5Engine
from dprf_tpu.engines.device.pbkdf2 import (Pbkdf2MaskWorker,
                                            Pbkdf2WordlistWorker, _targs,
                                            make_pbkdf2_mask_step,
                                            make_pbkdf2_wordlist_step)


def _fold_pswcheck(dk):
    """uint32[B, 8] dk words -> uint32[B, 2] check words (byte-aligned
    XOR commutes with the big-endian word view)."""
    return jnp.stack([dk[:, 0] ^ dk[:, 2] ^ dk[:, 4] ^ dk[:, 6],
                      dk[:, 1] ^ dk[:, 3] ^ dk[:, 5] ^ dk[:, 7]],
                     axis=-1)


class Rar5MaskWorker(Pbkdf2MaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch=batch,
                         hit_capacity=hit_capacity, oracle=oracle)
        self.step = make_pbkdf2_mask_step(gen, batch, hit_capacity,
                                          fold=_fold_pswcheck)


class Rar5WordlistWorker(Pbkdf2WordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch=batch,
                         hit_capacity=hit_capacity, oracle=oracle)
        self.step = make_pbkdf2_wordlist_step(gen, self.word_batch,
                                              hit_capacity,
                                              fold=_fold_pswcheck)


@register("rar5", device="jax")
class JaxRar5Engine(Rar5Engine):
    """Device RAR5: PBKDF2-HMAC-SHA256 workers + the pswcheck fold."""

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Rar5MaskWorker(self, gen, targets,
                              batch=min(batch, 1 << 13),
                              hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Rar5WordlistWorker(self, gen, targets,
                                  batch=min(batch, 1 << 13),
                                  hit_capacity=hit_capacity,
                                  oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
