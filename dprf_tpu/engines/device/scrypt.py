"""Device scrypt engine: the HBM-scale memory-hard path.

ROMix pins V = N x 128r bytes per candidate in HBM (16 MB each at the
common 16384:8:1), so unlike every other engine the batch here is
bounded by device memory: worker construction clamps the batch to
DPRF_SCRYPT_MEM bytes of V (default 4 GiB) and logs when it does.
N, r, p are trace-time constants -- steps are compiled per distinct
parameter tuple and shared by every target using it; the salt stays a
runtime argument.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (PBKDF2_SALT_MAX as SALT_MAX,
                                          ScryptEngine)
from dprf_tpu.engines.device.salted import (SaltedMaskWorker,
                                            SaltedWordlistWorker,
                                            ShardedSaltedMaskWorker,
                                            _SaltedWorkerBase)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.hmac import pack_raw_varlen
from dprf_tpu.ops.scrypt import scrypt_dk
from dprf_tpu.utils import env as envreg
from dprf_tpu.utils.logging import DEFAULT as log


def _mem_cap() -> int:
    return envreg.get_int("DPRF_SCRYPT_MEM")


def _clamp_batch(batch: int, targets: Sequence, what: str) -> int:
    """Bound the batch so the largest target's V array fits the cap."""
    worst = max(128 * t.params["r"] * t.params["n"] for t in targets)
    cap = max(8, _mem_cap() // worst)
    if batch > cap:
        log.info(f"scrypt: clamping {what} to fit ROMix memory",
                 requested=batch, clamped=cap,
                 v_bytes_per_candidate=worst)
        return cap
    return batch


def make_scrypt_mask_step(gen, batch: int, n: int, r: int, p: int,
                          hit_capacity: int = 64):
    """step(base_digits, n_valid, salt, salt_len, target) ->
    (count, lanes, _) -- the salted-step contract."""
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        kw = pack_raw_varlen(cand, lengths, big_endian=True)
        dk = scrypt_dk(kw, salt, salt_len, n, r, p)
        found = cmp_ops.compare_single(dk, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_scrypt_wordlist_step(gen, word_batch: int, n: int, r: int,
                              p: int, hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        kw = pack_raw_varlen(cw, cl, big_endian=True)
        dk = scrypt_dk(kw, salt, salt_len, n, r, p)
        found = cmp_ops.compare_single(dk, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_sharded_scrypt_mask_step(gen, mesh, batch_per_device: int,
                                  n: int, r: int, p: int,
                                  hit_capacity: int = 64):
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map

    flat = gen.flat_charsets
    length = gen.length
    B = batch_per_device

    def shard_fn(base_digits, n_valid, salt, salt_len, target):
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * B).astype(jnp.int32)
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        lengths = jnp.full((B,), length, jnp.int32)
        kw = pack_raw_varlen(cand, lengths, big_endian=True)
        dk = scrypt_dk(kw, salt, salt_len, n, r, p)
        lane_global = offset + jnp.arange(B, dtype=jnp.int32)
        found = cmp_ops.compare_single(dk, target) & \
            (lane_global < n_valid)
        count, lanes, tpos = cmp_ops.compact_hits(
            found, jnp.zeros((B,), jnp.int32), hit_capacity)
        lanes = jnp.where(lanes >= 0, lanes + offset, lanes)
        total = lax.psum(count, SHARD_AXIS)
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    sharded = shard_map(
        shard_fn, mesh=mesh, in_specs=(P(),) * 5,
        out_specs=(P(), P(), P(), P()), check_vma=False)

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, target):
        total, counts, lanes, tpos = sharded(base_digits, n_valid, salt,
                                             salt_len, target)
        return total[0], counts, lanes, tpos

    step.super_batch = mesh.devices.size * B
    return step


class _ScryptStepsMixin:
    """Per-(N, r, p) compiled steps shared by targets with identical
    parameters; _invoke routes each target to its step."""

    SALT_WIDTH = SALT_MAX      # u1_block's 51-byte PBKDF2 salt buffer

    def _build_steps(self, factory):
        cache: dict = {}
        self._steps = []
        for t in self.targets:
            key = (t.params["n"], t.params["r"], t.params["p"])
            if key not in cache:
                cache[key] = factory(*key)
            self._steps.append(cache[key])

    def _invoke(self, ti: int, base, n):
        salt, salt_len, tgt = self._targs[ti]
        return self._steps[ti](base, n, salt, salt_len, tgt)


class ScryptMaskWorker(_ScryptStepsMixin, SaltedMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 10,
                 hit_capacity: int = 64, oracle=None):
        batch = _clamp_batch(batch, targets, "batch")
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.stride = batch
        self._build_steps(
            lambda n, r, p: make_scrypt_mask_step(gen, batch, n, r, p,
                                                  hit_capacity))


class ScryptWordlistWorker(_ScryptStepsMixin, SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 10,
                 hit_capacity: int = 64, oracle=None):
        # a dispatch materializes word_batch * n_rules candidates' V
        # arrays, so the clamp must bound that product, not the nominal
        # batch; a rule file bigger than the whole memory budget cannot
        # be subdivided (word_batch floors at 1) and is an error
        batch = _clamp_batch(batch, targets, "batch")
        if gen.n_rules > batch:
            raise ValueError(
                f"scrypt: {gen.n_rules} rules expand one word to more "
                f"candidates than the ROMix memory budget allows "
                f"({batch}; raise DPRF_SCRYPT_MEM or split the rules)")
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._build_steps(
            lambda n, r, p: make_scrypt_wordlist_step(
                gen, self.word_batch, n, r, p, hit_capacity))


class ShardedScryptMaskWorker(_ScryptStepsMixin, ShardedSaltedMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 10, hit_capacity: int = 64,
                 oracle=None):
        batch_per_device = _clamp_batch(batch_per_device, targets,
                                        "batch_per_device")
        _SaltedWorkerBase.__init__(self, engine, gen, targets,
                                   mesh.devices.size * batch_per_device,
                                   hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch
        self._build_steps(
            lambda n, r, p: make_sharded_scrypt_mask_step(
                gen, mesh, batch_per_device, n, r, p, hit_capacity))


@register("scrypt", device="jax")
class JaxScryptEngine(ScryptEngine):
    """Device scrypt.  Inherits parsing and the oracle hash_batch from
    the CPU engine; adds the ROMix device pipeline workers."""

    little_endian = False      # dk words are big-endian SHA-256 output
    digest_words = 8

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return ScryptMaskWorker(self, gen, targets, batch=batch,
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return ScryptWordlistWorker(self, gen, targets, batch=batch,
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedScryptMaskWorker(self, gen, targets, mesh,
                                       batch_per_device=batch_per_device,
                                       hit_capacity=hit_capacity,
                                       oracle=oracle)

    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
