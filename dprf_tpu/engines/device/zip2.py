"""Device WinZip AES engine ($zip2$, hashcat 13600).

Device work per candidate is ONE PBKDF2-HMAC-SHA1 output block (the
one holding the 2-byte password verification value): 2 key-pad
compressions + 1000 x 2 iteration compressions -- the archive salt is
a per-target trace-time constant, exactly the shape
ops/hmac_sha1.pbkdf2_sha1_block already implements for PMKID.  The
2-byte compare is a 1/2^16 prefilter, so every reported lane is
confirmed against the stored HMAC-SHA1 auth code with the CPU oracle
(the _accept hook) before it leaves the worker.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Zip2Engine
from dprf_tpu.engines.device.salted import (SaltedMaskWorker,
                                            SaltedWordlistWorker,
                                            _SaltedWorkerBase)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import hmac_key_states, pbkdf2_sha1_block


def make_zip2_mask_step(gen, target, batch: int, iterations: int,
                        hit_capacity: int = 64):
    """Per-target step: the verification value lives in PBKDF2 block
    T_{mode+1}, big-endian word {4-mode}, top 16 bits.
    step(base_digits, n_valid) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    mode = target.params["mode"]
    salt = target.params["salt"]
    pwv = int.from_bytes(target.params["verify"], "big")

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        istate, ostate = hmac_key_states(key)
        t = pbkdf2_sha1_block(istate, ostate, salt, mode + 1, iterations)
        found = (t[:, 4 - mode] >> 16) == jnp.uint32(pwv)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_zip2_wordlist_step(gen, target, word_batch: int, iterations: int,
                            hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.hmac import pack_raw_varlen
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    mode = target.params["mode"]
    salt = target.params["salt"]
    pwv = int.from_bytes(target.params["verify"], "big")

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        key = pack_raw_varlen(cw, cl, big_endian=True)
        istate, ostate = hmac_key_states(key)
        t = pbkdf2_sha1_block(istate, ostate, salt, mode + 1, iterations)
        found = ((t[:, 4 - mode] >> 16) == jnp.uint32(pwv)) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class _Zip2AcceptMixin:
    """Per-target compiled steps + oracle confirmation of every device
    maybe (2-byte prefilter -> full PBKDF2 + auth HMAC check on host)."""

    def _prep_targets(self):
        # per-target state is the compiled step, not (salt, digest
        # words) -- the 10-byte auth digest has no word form
        return None

    def _accept(self, ti: int, gidx: int, plain: bytes) -> bool:
        oracle = self.oracle or self.engine
        t = self.targets[ti]
        return oracle.hash_batch([plain], params=t.params)[0] == t.digest

    def _invoke(self, ti: int, base, n):
        return self._steps[ti](base, n)


class Zip2MaskWorker(_Zip2AcceptMixin, SaltedMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 16,
                 hit_capacity: int = 64, oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.stride = batch
        self._steps = [
            make_zip2_mask_step(gen, t, batch, engine.iterations,
                                hit_capacity)
            for t in self.targets]


class Zip2WordlistWorker(_Zip2AcceptMixin, SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 16,
                 hit_capacity: int = 64, oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._steps = [
            make_zip2_wordlist_step(gen, t, self.word_batch,
                                    engine.iterations, hit_capacity)
            for t in self.targets]


@register("zip2", device="jax")
@register("winzip", device="jax")
class JaxZip2Engine(Zip2Engine):
    """Device WinZip AES.  Parsing and the auth-code oracle come from
    the CPU engine; the device runs the PBKDF2 prefilter block."""

    little_endian = False
    digest_words = 5

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Zip2MaskWorker(self, gen, targets, batch=batch,
                              hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Zip2WordlistWorker(self, gen, targets, batch=batch,
                                  hit_capacity=hit_capacity, oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
