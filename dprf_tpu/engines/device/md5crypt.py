"""Device md5crypt engine ($1$; hashcat 500).

md5crypt's 1000 rounds compose each message from (prev digest,
password, salt) in an order cycling with i mod 2/3/7 -- data-dependent
LENGTHS, which are hostile to fixed-shape compilation.  The TPU answer:
every message is built at the BYTE level inside the round body with
clipped take_along_axis gathers and boundary masks over a 64-byte
window (per-lane password lengths included), then packed to words and
fed to the shared MD5 compression under `lax.fori_loop`.  The round
index only enters through three scalars (i&1, i%3!=0, i%7!=0), so one
compiled step serves every target; salt bytes/length are runtime
arguments.

Length budget: messages reach 16 + 2*len(pw) + len(salt) bytes and
must stay in one 55-byte block, so the device path caps passwords at
15 bytes (salt <= 8 per the format).  Longer passwords run on the CPU
oracle path.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Md5cryptEngine
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.md5 import md5_digest_words
from dprf_tpu.engines.device.phpass import (_le_words, PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)

#: device-path password cap (16 + 2L + 8 <= 55)
MAX_PASS_LEN = 15


def _gat(src_pad, idx):
    """Clipped per-lane gather over a [B, 64]-padded source."""
    return jnp.take_along_axis(src_pad, jnp.clip(idx, 0, 63), axis=1)


def _pad64(x):
    B, w = x.shape
    return jnp.zeros((B, 64), jnp.uint8).at[:, :w].set(x)


def _finish(msg, total):
    """Add the 0x80 marker + bit length, pack to words."""
    pos = jnp.arange(64, dtype=jnp.int32)[None, :]
    msg = (msg + jnp.where(pos == total[:, None], jnp.uint8(0x80),
                           jnp.uint8(0))).astype(jnp.uint8)
    words = _le_words(msg)
    return words.at[:, 14].set(total.astype(jnp.uint32) * 8)


def _digest_bytes(words):
    """MD5 digest words uint32[B, 4] -> bytes uint8[B, 16] (LE)."""
    shifts = jnp.asarray([0, 8, 16, 24], jnp.uint32)
    b = (words[:, :, None] >> shifts[None, None, :]) & jnp.uint32(0xFF)
    return b.reshape(words.shape[0], 16).astype(jnp.uint8)


def md5crypt_digest_batch(cand: jnp.ndarray, lens: jnp.ndarray,
                          salt: jnp.ndarray, salt_len,
                          magic_bytes: bytes = b"$1$") -> jnp.ndarray:
    """cand uint8[B, maxlen] (lens <= 15) + salt uint8[8]/salt_len ->
    raw digest words uint32[B, 4].  `magic_bytes` is a trace-time
    constant ($1$ for md5crypt, $apr1$ for Apache's variant; it only
    enters the initial context)."""
    B = cand.shape[0]
    pos = jnp.arange(64, dtype=jnp.int32)[None, :]
    pw = _pad64(cand)
    L = lens[:, None]
    S = jnp.broadcast_to(salt_len, (B,))[:, None].astype(jnp.int32)
    salt_pad = jnp.broadcast_to(
        jnp.pad(salt, (0, 64 - salt.shape[0]))[None, :], (B, 64)
    ).astype(jnp.uint8)

    # -- alt = md5(pw + salt + pw) ---------------------------------------
    msg = jnp.where(pos < L, _gat(pw, pos), 0)
    msg = jnp.where((pos >= L) & (pos < L + S), _gat(salt_pad, pos - L),
                    msg)
    msg = jnp.where((pos >= L + S) & (pos < 2 * L + S),
                    _gat(pw, pos - L - S), msg).astype(jnp.uint8)
    alt = md5_digest_words(_finish(msg, (2 * lens
                                         + S[:, 0]).astype(jnp.int32)))

    # -- initial context: pw + magic + salt + alt[:len(pw)] + bitwalk ----
    M = len(magic_bytes)
    magic = jnp.broadcast_to(
        jnp.pad(jnp.asarray(np.frombuffer(magic_bytes, np.uint8)),
                (0, 64 - M))[None, :], (B, 64)).astype(jnp.uint8)
    altb = _pad64(_digest_bytes(alt))
    # bit-walk bytes: for j while (L >> j) > 0: (L>>j)&1 ? 0 : pw[0]
    walk = jnp.stack(
        [jnp.where((lens >> j) & 1 == 1, jnp.uint8(0), cand[:, 0])
         for j in range(4)], axis=1).astype(jnp.uint8)
    wlen = sum(((lens >> j) > 0).astype(jnp.int32) for j in range(4))
    o1, o2 = L, L + M
    o3, o4 = L + M + S, 2 * L + M + S
    total = (o4 + wlen[:, None])[:, 0]
    msg = jnp.where(pos < o1, _gat(pw, pos), 0)
    msg = jnp.where((pos >= o1) & (pos < o2), _gat(magic, pos - o1), msg)
    msg = jnp.where((pos >= o2) & (pos < o3), _gat(salt_pad, pos - o2),
                    msg)
    msg = jnp.where((pos >= o3) & (pos < o4), _gat(altb, pos - o3), msg)
    msg = jnp.where((pos >= o4) & (pos < total[:, None]),
                    _gat(_pad64(walk), pos - o4), msg).astype(jnp.uint8)
    inter = md5_digest_words(_finish(msg, total))

    # -- 1000 rounds -----------------------------------------------------
    def body(i, inter):
        odd = (i & 1) == 1
        s3 = (i % 3) != 0
        s7 = (i % 7) != 0
        d = _pad64(_digest_bytes(inter))
        l1 = jnp.where(odd, L, 16)
        l4 = jnp.where(odd, 16, L)
        c1 = l1
        c2 = c1 + jnp.where(s3, S, 0)
        c3 = c2 + jnp.where(s7, L, 0)
        total = (c3 + l4)[:, 0]
        src1 = jnp.where(odd, _gat(pw, pos), _gat(d, pos))
        src4 = jnp.where(odd, _gat(d, pos - c3), _gat(pw, pos - c3))
        msg = jnp.where(pos < c1, src1, 0)
        msg = jnp.where((pos >= c1) & (pos < c2),
                        _gat(salt_pad, pos - c1), msg)
        msg = jnp.where((pos >= c2) & (pos < c3),
                        _gat(pw, pos - c2), msg)
        msg = jnp.where((pos >= c3) & (pos < total[:, None]), src4,
                        msg).astype(jnp.uint8)
        return md5_digest_words(_finish(msg, total))

    return lax.fori_loop(0, 1000, body, inter)


def make_md5crypt_mask_step(gen, batch: int, hit_capacity: int = 64,
                            magic: bytes = b"$1$"):
    """step(base_digits, n_valid, salt uint8[8], salt_len int32,
    target uint32[4]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    if length > MAX_PASS_LEN:
        raise ValueError(
            f"candidates of {length} bytes exceed this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        digest = md5crypt_digest_batch(cand, lens, salt, salt_len,
                                       magic)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_md5crypt_wordlist_step(gen, word_batch: int,
                                hit_capacity: int = 64,
                                magic: bytes = b"$1$"):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    if gen.max_len > MAX_PASS_LEN:
        raise ValueError(
            f"wordlist max_len {gen.max_len} exceeds this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        digest = md5crypt_digest_batch(cw, cl, salt, salt_len, magic)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_sharded_md5crypt_mask_step(gen, mesh, batch_per_device: int,
                                    hit_capacity: int = 64,
                                    magic: bytes = b"$1$"):
    """Multi-chip variant through the ONE sharded runtime."""
    from dprf_tpu.parallel.sharded import make_sharded_pertarget_step

    if gen.length > MAX_PASS_LEN:
        raise ValueError(
            f"candidates of {gen.length} bytes exceed this engine's "
            f"{MAX_PASS_LEN}-byte single-block budget")

    def digest_fn(cand, lens, salt, salt_len):
        return md5crypt_digest_batch(cand, lens, salt, salt_len, magic)

    return make_sharded_pertarget_step(gen, mesh, batch_per_device,
                                       digest_fn, 2, hit_capacity)


def _md5crypt_targs(targets):
    out = []
    for t in targets:
        s = t.params["salt"]
        buf = np.zeros((8,), np.uint8)
        buf[:len(s)] = np.frombuffer(s, np.uint8)
        out.append((jnp.asarray(buf), jnp.int32(len(s)),
                    jnp.asarray(np.frombuffer(t.digest, dtype="<u4")
                                .astype(np.uint32))))
    return out


class Md5cryptMaskWorker(PhpassMaskWorker):
    """Reuses the phpass per-target sweep (same step arity: two salt
    args + target); only the step factory and target args differ."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = self.stride = batch
        self._targs = _md5crypt_targs(self.targets)
        self.step = make_md5crypt_mask_step(
            gen, batch, hit_capacity, magic=engine.magic)


class Md5cryptWordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _md5crypt_targs(self.targets)
        self.step = make_md5crypt_wordlist_step(
            gen, self.word_batch, hit_capacity,
            magic=engine.magic)


class ShardedMd5cryptMaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 12, hit_capacity: int = 64,
                 oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _md5crypt_targs(self.targets)
        self.step = make_sharded_md5crypt_mask_step(
            gen, mesh, batch_per_device, hit_capacity,
            magic=engine.magic)


@register("md5crypt", device="jax")
class JaxMd5cryptEngine(Md5cryptEngine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Md5cryptMaskWorker(self, gen, targets,
                                  batch=min(batch, 1 << 13),
                                  hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Md5cryptWordlistWorker(self, gen, targets,
                                      batch=min(batch, 1 << 13),
                                      hit_capacity=hit_capacity,
                                      oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedMd5cryptMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, 1 << 12),
            hit_capacity=hit_capacity, oracle=oracle)

@register("apr1", device="jax")
@register("apache-md5", device="jax")
class JaxApr1Engine(JaxMd5cryptEngine):
    """Apache $apr1$ (htpasswd; hashcat 1600) on the md5crypt device
    pipeline: the magic is a trace-time constant of the step, so the
    only difference from $1$ is six context bytes instead of three.
    Parsing comes from the CPU Apr1Engine."""

    name = "apr1"
    magic = b"$apr1$"

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import Apr1Engine
        return Apr1Engine().parse_target(text)
