"""Device HMAC engines: hmac-md5/sha1/sha256 with key = $pass
(hashcat 50/150/1450) or key = $salt (60/160/1460), and JWT HS256
(16500).

Same per-target sweep shape as the salted fast modes (the salt -- here
the HMAC message or key -- is a runtime argument, so ONE compiled step
serves every target); the digest chain is ops/hmac.py's generalized
two-compression-keyed HMAC.  JWT differs: its message (the signing
input ``header.payload``) is a per-target constant that may span
several blocks, so JWT steps are compiled per target with the message
baked in as constant blocks.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import (SALT_MAX, JwtHs256Engine,
                                          parse_salted_line)
from dprf_tpu.engines.device.engines import (JaxMd5Engine, JaxSha1Engine,
                                             JaxSha256Engine)
from dprf_tpu.engines.device.salted import (PerTargetStepsMixin,
                                            SaltedMaskWorker,
                                            SaltedWordlistWorker,
                                            ShardedSaltedMaskWorker,
                                            _SaltedWorkerBase,
                                            per_target_setup)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac import (hmac_const_msg, hmac_one_block_msg,
                               key_states, md_pad_blocks,
                               msg_block_after_prefix, pack_raw_varlen)


def _hmac_digest(algo: str, key_is_pass: bool, cand, lengths,
                 salt, salt_len, big_endian: bool):
    """The shared digest chain: cand uint8[B, L] + per-lane lengths +
    runtime salt buffer -> HMAC digest uint32[B, W]."""
    if key_is_pass:
        kw = pack_raw_varlen(cand, lengths, big_endian)
        istate, ostate = key_states(algo, kw)
        msg = msg_block_after_prefix(salt[None, :], salt_len[None],
                                     big_endian)
        return hmac_one_block_msg(algo, istate, ostate, msg[0])
    salt64 = jnp.pad(salt, (0, 64 - SALT_MAX))
    kw = pack_ops._words_from_bytes(salt64[None, :], big_endian)
    istate, ostate = key_states(algo, kw)
    msg = msg_block_after_prefix(cand, lengths, big_endian)
    return hmac_one_block_msg(algo, istate, ostate, msg)


def make_hmac_mask_step(engine, gen, batch: int, hit_capacity: int = 64):
    """step(base_digits, n_valid, salt, salt_len, target) ->
    (count, lanes, _): the salted-step contract, HMAC digest chain."""
    flat = gen.flat_charsets
    length = gen.length
    algo, key_is_pass = engine._algo, engine._key_is_pass
    big_endian = not engine.little_endian

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        digest = _hmac_digest(algo, key_is_pass, cand, lengths,
                              salt, salt_len, big_endian)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_hmac_wordlist_step(engine, gen, word_batch: int,
                            hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    algo, key_is_pass = engine._algo, engine._key_is_pass
    big_endian = not engine.little_endian

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        digest = _hmac_digest(algo, key_is_pass, cw, cl,
                              salt, salt_len, big_endian)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_sharded_hmac_mask_step(engine, gen, mesh, batch_per_device: int,
                                hit_capacity: int = 64):
    """Multi-chip variant through the ONE sharded runtime."""
    from dprf_tpu.parallel.sharded import make_sharded_pertarget_step

    algo, key_is_pass = engine._algo, engine._key_is_pass
    big_endian = not engine.little_endian

    def digest_fn(cand, lens, salt, salt_len):
        return _hmac_digest(algo, key_is_pass, cand, lens, salt,
                            salt_len, big_endian)

    return make_sharded_pertarget_step(gen, mesh, batch_per_device,
                                       digest_fn, 2, hit_capacity)


class HmacMaskWorker(SaltedMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.stride = batch
        self.step = make_hmac_mask_step(engine, gen, batch, hit_capacity)


class HmacWordlistWorker(SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_hmac_wordlist_step(engine, gen, self.word_batch,
                                            hit_capacity)


class ShardedHmacMaskWorker(ShardedSaltedMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 18, hit_capacity: int = 64,
                 oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets,
                                   mesh.devices.size * batch_per_device,
                                   hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch
        self.step = make_sharded_hmac_mask_step(
            engine, gen, mesh, batch_per_device, hit_capacity)


class _HmacDeviceMixin:
    """Device engine for one (algo, key side): parsing from the CPU
    convention, workers over the runtime-salt HMAC steps."""

    salted = True
    _algo: str
    _key_is_pass: bool

    def parse_target(self, text: str) -> Target:
        digest, salt = parse_salted_line(text, self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        self._check_len(gen.length)
        return HmacMaskWorker(self, gen, targets, batch=batch,
                              hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        self._check_len(gen.max_len)
        return HmacWordlistWorker(self, gen, targets, batch=batch,
                                  hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        self._check_len(gen.length)
        return ShardedHmacMaskWorker(self, gen, targets, mesh,
                                     batch_per_device=batch_per_device,
                                     hit_capacity=hit_capacity,
                                     oracle=oracle)

    # message/key structure is keyed per candidate: the generic unsalted
    # workers would compare plain digests -- shadow them (CLI degrades
    # with a warning exactly as for the salted modes)
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None

    def _check_len(self, cand_len: int) -> None:
        if cand_len > self.max_candidate_len:
            raise ValueError(
                f"{self.name}: candidates up to {cand_len} bytes exceed "
                f"the {self.max_candidate_len}-byte limit "
                + ("(one HMAC key block)" if self._key_is_pass
                   else "(one message block)"))


def _register_hmac_device(base_cls, algo: str):
    for key_is_pass in (True, False):
        name = f"hmac-{algo}" + ("" if key_is_pass else "-salt")
        key, msg = (("$pass", "$salt") if key_is_pass
                    else ("$salt", "$pass"))
        cls = type(f"JaxHmac{algo.title()}"
                   f"{'Pass' if key_is_pass else 'Salt'}Engine",
                   (_HmacDeviceMixin, base_cls),
                   {"name": name, "_algo": algo,
                    "_key_is_pass": key_is_pass,
                    "__doc__": (f"Device HMAC-{algo.upper()} "
                                f"(key = {key}, message = {msg})."),
                    "max_candidate_len": 64 if key_is_pass else 55})
        register(name, device="jax")(cls)


_register_hmac_device(JaxMd5Engine, "md5")
_register_hmac_device(JaxSha1Engine, "sha1")
_register_hmac_device(JaxSha256Engine, "sha256")


# -- JWT HS256 ---------------------------------------------------------------

def make_jwt_mask_step(gen, msg: bytes, target_words: np.ndarray,
                       batch: int, hit_capacity: int = 64):
    """Per-target step: the signing input is baked in as constant
    blocks.  step(base_digits, n_valid) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    blocks = md_pad_blocks(msg, big_endian=True)
    target = jnp.asarray(target_words)

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        kw = pack_ops.pack_raw(cand, length, big_endian=True)
        istate, ostate = key_states("sha256", kw)
        digest = hmac_const_msg("sha256", istate, ostate, blocks)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_jwt_wordlist_step(gen, msg: bytes, target_words: np.ndarray,
                           word_batch: int, hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    blocks = md_pad_blocks(msg, big_endian=True)
    target = jnp.asarray(target_words)

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        kw = pack_raw_varlen(cw, cl, big_endian=True)
        istate, ostate = key_states("sha256", kw)
        digest = hmac_const_msg("sha256", istate, ostate, blocks)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def _jwt_twords(t) -> np.ndarray:
    return np.frombuffer(t.digest, dtype=">u4").astype(np.uint32)


class JwtMaskWorker(PerTargetStepsMixin, SaltedMaskWorker):
    """Per-target sweep with per-target compiled steps (the signing
    input is a trace-time constant); hit extraction is inherited from
    the salted worker via the _invoke override point."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        per_target_setup(self, engine, gen, targets, batch,
                         hit_capacity, oracle)
        self.stride = batch
        self._steps = [
            make_jwt_mask_step(gen, t.params["msg"], _jwt_twords(t),
                               batch, hit_capacity)
            for t in self.targets]


class JwtWordlistWorker(PerTargetStepsMixin, SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        per_target_setup(self, engine, gen, targets, batch,
                         hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._steps = [
            make_jwt_wordlist_step(gen, t.params["msg"], _jwt_twords(t),
                                   self.word_batch, hit_capacity)
            for t in self.targets]


@register("jwt-hs256", device="jax")
@register("jwt", device="jax")
class JaxJwtHs256Engine(JwtHs256Engine):
    """Device JWT HS256: per-target constant signing input, candidate
    secret as the HMAC key.  Inherits parsing and the oracle hash_batch
    from the CPU engine (the PMKID pattern -- one definition, so oracle
    and device can never silently diverge) and adds the device worker
    factories."""

    little_endian = False
    digest_words = 8

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return JwtMaskWorker(self, gen, targets, batch=batch,
                             hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return JwtWordlistWorker(self, gen, targets, batch=batch,
                                 hit_capacity=hit_capacity, oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
