"""Device MS Cache engines (DCC/DCC2, hashcat 1100/2100).

DCC1 is two chained MD4 blocks: the NTLM digest of the password, then
MD4 over (inner digest || UTF16LE(lower(user))) -- the username is a
runtime salt, so ONE compiled step serves every target.  DCC2 feeds
DCC1 through PBKDF2-HMAC-SHA1 with the same username salt and a
per-target iteration count (runtime scalar through the shared
pbkdf2_sha1_runtime_salt helper).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import MsCache2Engine, MsCacheEngine
from dprf_tpu.engines.device.pbkdf2_sha1 import pbkdf2_sha1_runtime_salt
from dprf_tpu.engines.device.salted import (SaltedMaskWorker,
                                            SaltedWordlistWorker,
                                            ShardedSaltedMaskWorker,
                                            _SaltedWorkerBase)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.md4 import INIT as MD4_INIT, md4_compress
from dprf_tpu.ops.scrypt import bswap32


def dcc1_words(cand: jnp.ndarray, lengths: jnp.ndarray,
               salt: jnp.ndarray, salt_len) -> jnp.ndarray:
    """Candidates uint8[B, L] (+ per-lane lengths) + runtime username
    salt -> DCC1 uint32[B, 4] (little-endian MD4 words)."""
    B = cand.shape[0]
    wide = pack_ops.utf16le_widen(cand)
    inner = md4_compress(
        jnp.broadcast_to(jnp.asarray(MD4_INIT), (B, 4)),
        pack_ops.pack_varlen(wide, lengths * 2, big_endian=False))
    # outer block bytes: inner digest (LE word bytes are already the
    # digest byte order) then the salt, marker, and bit length
    pos = jnp.arange(64, dtype=jnp.int32)
    salt64 = jnp.pad(salt, (0, 64 - salt.shape[0]))
    sbytes = jnp.broadcast_to(salt64[None, :], (B, 64))
    sidx = jnp.clip(pos - 16, 0, 63)
    buf = jnp.where((pos >= 16) & (pos < 16 + salt_len),
                    jnp.take_along_axis(sbytes, jnp.broadcast_to(
                        sidx[None, :], (B, 64)), axis=1), 0)
    buf = buf + jnp.where(pos == 16 + salt_len, jnp.uint8(0x80),
                          jnp.uint8(0))
    m = pack_ops._words_from_bytes(buf.astype(jnp.uint8),
                                   big_endian=False)
    m = m.at[:, 0:4].set(inner)
    m = m.at[:, 14].set(((16 + salt_len) * 8).astype(jnp.uint32))
    return md4_compress(
        jnp.broadcast_to(jnp.asarray(MD4_INIT), (B, 4)), m)


def _dcc2_words(cand, lengths, salt, salt_len, iterations):
    d1 = dcc1_words(cand, lengths, salt, salt_len)
    key = jnp.zeros((cand.shape[0], 16), jnp.uint32)
    key = key.at[:, 0:4].set(bswap32(d1))   # BE key-block packing
    return pbkdf2_sha1_runtime_salt(key, salt, salt_len, iterations, 4)


def _digest_fn(v2: bool):
    if v2:
        return lambda cand, lens, salt, slen, iters: _dcc2_words(
            cand, lens, salt, slen, iters)
    return lambda cand, lens, salt, slen, iters: dcc1_words(
        cand, lens, salt, slen)


def make_mscache_mask_step(gen, batch: int, v2: bool,
                           hit_capacity: int = 64):
    """step(base_digits, n_valid, salt, salt_len, iterations, target)
    -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    digest = _digest_fn(v2)

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, iterations, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        d = digest(cand, lengths, salt, salt_len, iterations)
        found = cmp_ops.compare_single(d, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_mscache_wordlist_step(gen, word_batch: int, v2: bool,
                               hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    if L > 27:
        raise ValueError(
            f"mscache candidates are UTF-16LE widened: wordlist "
            f"max_len {L} > 27 overflows the single MD4 block "
            "(set --max-len 27 or shorter)")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    digest = _digest_fn(v2)

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, iterations, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        pos = jnp.arange(cw.shape[1], dtype=jnp.int32)
        cw = jnp.where(pos[None, :] < cl[:, None], cw, 0)  # mask junk
        d = digest(cw, cl, salt, salt_len, iterations)
        found = cmp_ops.compare_single(d, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_sharded_mscache_mask_step(gen, mesh, batch_per_device: int,
                                   v2: bool, hit_capacity: int = 64):
    """Multi-chip variant through the ONE sharded runtime."""
    from dprf_tpu.parallel.sharded import make_sharded_pertarget_step

    return make_sharded_pertarget_step(gen, mesh, batch_per_device,
                                       _digest_fn(v2), 3, hit_capacity)


class _MsCacheInvokeMixin:
    """_targs rows gain the per-target iteration count (1 for DCC1)."""

    #: DCC2's u1_block consumes the 51-byte PBKDF2 salt buffer; DCC1
    #: only reads salt_len bytes, so the wide buffer serves both.
    SALT_WIDTH = 51

    def _prep_targets(self):
        base = super()._prep_targets()
        return [(salt, slen, tgt,
                 jnp.int32(t.params.get("iterations", 1)))
                for (salt, slen, tgt), t in zip(base, self.targets)]

    def _invoke(self, ti: int, base, n):
        salt, slen, tgt, iters = self._targs[ti]
        return self.step(base, n, salt, slen, iters, tgt)


class MsCacheMaskWorker(_MsCacheInvokeMixin, SaltedMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.stride = batch
        self.step = make_mscache_mask_step(gen, batch, engine._v2,
                                           hit_capacity)


class MsCacheWordlistWorker(_MsCacheInvokeMixin, SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets, batch,
                                   hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_mscache_wordlist_step(gen, self.word_batch,
                                               engine._v2, hit_capacity)


class ShardedMsCacheMaskWorker(_MsCacheInvokeMixin,
                               ShardedSaltedMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 16, hit_capacity: int = 64,
                 oracle=None):
        _SaltedWorkerBase.__init__(self, engine, gen, targets,
                                   mesh.devices.size * batch_per_device,
                                   hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch
        self.step = make_sharded_mscache_mask_step(
            gen, mesh, batch_per_device, engine._v2, hit_capacity)


class _MsCacheDeviceMixin:
    little_endian = True       # MD4 digest words
    digest_words = 4
    _v2 = False

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return MsCacheMaskWorker(self, gen, targets, batch=batch,
                                 hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return MsCacheWordlistWorker(self, gen, targets, batch=batch,
                                     hit_capacity=hit_capacity,
                                     oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedMsCacheMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)

    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None


@register("mscache", device="jax")
@register("dcc", device="jax")
class JaxMsCacheEngine(_MsCacheDeviceMixin, MsCacheEngine):
    """Device MS Cache v1: two chained MD4 blocks, username as a
    runtime salt."""


@register("mscache2", device="jax")
@register("dcc2", device="jax")
class JaxMsCache2Engine(_MsCacheDeviceMixin, MsCache2Engine):
    """Device MS Cache v2: DCC1 -> PBKDF2-HMAC-SHA1(username,
    per-target iterations)."""

    _v2 = True
    little_endian = False      # PBKDF2 dk bytes are BE SHA-1 words
