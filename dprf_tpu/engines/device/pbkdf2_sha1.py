"""Generic PBKDF2-HMAC-SHA1 engine (hashcat 12000:
``sha1:<iterations>:<b64 salt>:<b64 dk>``).

Same runtime-salt design as the pbkdf2-sha256 engine: the U1 block is
assembled on device from salt bytes, so one compiled step serves every
target and iteration count.  Derived keys of 4..40 bytes (multiples of
4) are supported; up to two output blocks are computed as needed and
the compare truncates to the target's dk width.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (PBKDF2_SALT_MAX as SALT_MAX,
                                          Pbkdf2Sha1Engine)
from dprf_tpu.engines.device.pbkdf2 import _targs, u1_block
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import _block20, hmac_key_states, hmac_sha1_20
from dprf_tpu.ops.sha1 import sha1_compress


def _pbkdf2_sha1_t(istate, ostate, salt, salt_len, block_index: int,
                   iterations):
    from jax import lax

    first = jnp.broadcast_to(
        u1_block(salt, salt_len, block_index)[None, :],
        istate.shape[:-1] + (16,))
    inner = sha1_compress(istate, first)
    u = sha1_compress(ostate, _block20(inner))

    def body(_, carry):
        u, t = carry
        u = hmac_sha1_20(istate, ostate, u)
        return u, t ^ u

    _, t = lax.fori_loop(1, iterations, body, (u, u))
    return t


def pbkdf2_sha1_runtime_salt(key_words, salt, salt_len, iterations,
                             dk_words: int):
    """PBKDF2-HMAC-SHA1 with runtime salt; dk_words (static, <= 10)
    output words -> uint32[B, dk_words]."""
    istate, ostate = hmac_key_states(key_words)
    t1 = _pbkdf2_sha1_t(istate, ostate, salt, salt_len, 1, iterations)
    if dk_words <= 5:
        return t1[:, :dk_words]
    t2 = _pbkdf2_sha1_t(istate, ostate, salt, salt_len, 2, iterations)
    return jnp.concatenate([t1, t2[:, :dk_words - 5]], axis=-1)


def make_pbkdf2_sha1_mask_step(gen, batch: int, dk_words: int,
                               hit_capacity: int = 64):
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, iterations, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        dk = pbkdf2_sha1_runtime_salt(key, salt, salt_len, iterations,
                                      dk_words)
        # per-target dk widths may differ: the target's (static) shape
        # drives the compare width; jit re-specializes per width
        found = cmp_ops.compare_single(dk[:, :target.shape[0]], target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_pbkdf2_sha1_wordlist_step(gen, word_batch: int, dk_words: int,
                                   hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, iterations, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        # raw (markerless) HMAC key block, masked to per-lane length
        pos = jnp.arange(64, dtype=jnp.int32)[None, :]
        raw = jnp.where(pos < cl[:, None],
                        jnp.zeros((cw.shape[0], 64),
                                  jnp.uint8).at[:, :Lw].set(cw), 0)
        coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                    dtype=np.uint32))
        key = (raw.reshape(cw.shape[0], 16, 4).astype(jnp.uint32)
               * coef).sum(axis=-1, dtype=jnp.uint32)
        dk = pbkdf2_sha1_runtime_salt(key, salt, salt_len, iterations,
                                      dk_words)
        found = cmp_ops.compare_single(dk[:, :target.shape[0]],
                                       target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class Pbkdf2Sha1WordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        dk_words = max(len(t.digest) // 4 for t in self.targets)
        self.step = make_pbkdf2_sha1_wordlist_step(
            gen, self.word_batch, dk_words, hit_capacity)


class Pbkdf2Sha1MaskWorker(PhpassMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        # dk widths can differ per target: the step computes the job
        # maximum and the compare truncates to each target's (static)
        # word count -- jit specializes per distinct width
        dk_words = max(len(t.digest) // 4 for t in self.targets)
        self.step = make_pbkdf2_sha1_mask_step(gen, batch, dk_words,
                                               hit_capacity)


@register("pbkdf2-sha1", device="jax")
class JaxPbkdf2Sha1Engine(Pbkdf2Sha1Engine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Pbkdf2Sha1MaskWorker(self, gen, targets,
                                    batch=min(batch, 1 << 13),
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Pbkdf2Sha1WordlistWorker(self, gen, targets,
                                        batch=min(batch, 1 << 13),
                                        hit_capacity=hit_capacity,
                                        oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedPbkdf2Sha1MaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, 1 << 12),
            hit_capacity=hit_capacity, oracle=oracle)


class ShardedPbkdf2Sha1MaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 12, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _targs(self.targets)
        widths = {len(t.digest) for t in self.targets}
        if len(widths) != 1:
            raise ValueError(
                "the sharded pbkdf2-sha1 path needs one dk width per "
                "job; split the hashlist or run single-chip")
        dk_words = widths.pop() // 4
        length = gen.length

        def digest_fn(cand, lens, salt, salt_len, iterations):
            key = pack_ops.pack_raw(cand, length, big_endian=True)
            return pbkdf2_sha1_runtime_salt(key, salt, salt_len,
                                            iterations, dk_words)

        self.step = make_sharded_pertarget_step(
            gen, mesh, batch_per_device, digest_fn, 3, hit_capacity)


@register("atlassian", device="jax")
@register("pkcs5s2", device="jax")
class JaxAtlassianEngine(JaxPbkdf2Sha1Engine):
    """Atlassian/Crowd {PKCS5S2} (hashcat 12001): the generic
    PBKDF2-HMAC-SHA1 device pipeline (2 output blocks for the 32-byte
    dk) with the {PKCS5S2} base64 line format."""

    name = "atlassian"

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import AtlassianEngine
        return AtlassianEngine().parse_target(text)
