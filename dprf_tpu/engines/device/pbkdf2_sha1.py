"""Generic PBKDF2-HMAC-SHA1 engine (hashcat 12000:
``sha1:<iterations>:<b64 salt>:<b64 dk>``).

Same runtime-salt design as the pbkdf2-sha256 engine: the U1 block is
assembled on device from salt bytes, so one compiled step serves every
target and iteration count.  Derived keys of 4..40 bytes (multiples of
4) are supported; up to two output blocks are computed as needed and
the compare truncates to the target's dk width.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (PBKDF2_SALT_MAX as SALT_MAX,
                                          Pbkdf2Sha1Engine)
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import _block20, hmac_key_states, hmac_sha1_20
from dprf_tpu.ops.sha1 import sha1_compress


def _u1_block_sha1(salt: jnp.ndarray, salt_len, block_index: int):
    """Runtime U1 message block: salt || INT32BE(i) padded as the
    second block of the inner hash; salt uint8[SALT_MAX] -> uint32[16].
    """
    buf = jnp.zeros((64,), jnp.uint8).at[:SALT_MAX].set(salt)
    pos = jnp.arange(64, dtype=jnp.int32)
    msg_len = salt_len + 4
    buf = jnp.where(pos < salt_len, buf, 0)
    buf = buf + jnp.where(pos == salt_len + 3, jnp.uint8(block_index),
                          jnp.uint8(0))
    buf = (buf + jnp.where(pos == msg_len, jnp.uint8(0x80),
                           jnp.uint8(0))).astype(jnp.uint8)
    coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                dtype=np.uint32))
    words = (buf.reshape(16, 4).astype(jnp.uint32) * coef).sum(
        axis=-1, dtype=jnp.uint32)
    return words.at[15].set(((64 + msg_len) * 8).astype(jnp.uint32))


def _pbkdf2_sha1_t(istate, ostate, salt, salt_len, block_index: int,
                   iterations):
    from jax import lax

    first = jnp.broadcast_to(
        _u1_block_sha1(salt, salt_len, block_index)[None, :],
        istate.shape[:-1] + (16,))
    inner = sha1_compress(istate, first)
    u = sha1_compress(ostate, _block20(inner))

    def body(_, carry):
        u, t = carry
        u = hmac_sha1_20(istate, ostate, u)
        return u, t ^ u

    _, t = lax.fori_loop(1, iterations, body, (u, u))
    return t


def pbkdf2_sha1_runtime_salt(key_words, salt, salt_len, iterations,
                             dk_words: int):
    """PBKDF2-HMAC-SHA1 with runtime salt; dk_words (static, <= 10)
    output words -> uint32[B, dk_words]."""
    istate, ostate = hmac_key_states(key_words)
    t1 = _pbkdf2_sha1_t(istate, ostate, salt, salt_len, 1, iterations)
    if dk_words <= 5:
        return t1[:, :dk_words]
    t2 = _pbkdf2_sha1_t(istate, ostate, salt, salt_len, 2, iterations)
    return jnp.concatenate([t1, t2[:, :dk_words - 5]], axis=-1)


def make_pbkdf2_sha1_mask_step(gen, batch: int, dk_words: int,
                               hit_capacity: int = 64):
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, iterations, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        dk = pbkdf2_sha1_runtime_salt(key, salt, salt_len, iterations,
                                      dk_words)
        # per-target dk widths may differ: the target's (static) shape
        # drives the compare width; jit re-specializes per width
        found = cmp_ops.compare_single(dk[:, :target.shape[0]], target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def _targs(targets):
    out = []
    for t in targets:
        s = t.params["salt"]
        buf = np.zeros((SALT_MAX,), np.uint8)
        buf[:len(s)] = np.frombuffer(s, np.uint8)
        out.append((jnp.asarray(buf), jnp.int32(len(s)),
                    jnp.int32(t.params["iterations"]),
                    jnp.asarray(np.frombuffer(t.digest, dtype=">u4")
                                .astype(np.uint32))))
    return out


def make_pbkdf2_sha1_wordlist_step(gen, word_batch: int, dk_words: int,
                                   hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, iterations, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        # raw (markerless) HMAC key block, masked to per-lane length
        pos = jnp.arange(64, dtype=jnp.int32)[None, :]
        raw = jnp.where(pos < cl[:, None],
                        jnp.zeros((cw.shape[0], 64),
                                  jnp.uint8).at[:, :Lw].set(cw), 0)
        coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                    dtype=np.uint32))
        key = (raw.reshape(cw.shape[0], 16, 4).astype(jnp.uint32)
               * coef).sum(axis=-1, dtype=jnp.uint32)
        dk = pbkdf2_sha1_runtime_salt(key, salt, salt_len, iterations,
                                      dk_words)
        found = cmp_ops.compare_single(dk[:, :target.shape[0]],
                                       target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class Pbkdf2Sha1WordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        dk_words = max(len(t.digest) // 4 for t in self.targets)
        self.step = make_pbkdf2_sha1_wordlist_step(
            gen, self.word_batch, dk_words, hit_capacity)


class Pbkdf2Sha1MaskWorker(PhpassMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        dk_words = max(len(t.digest) // 4 for t in self.targets)
        self.step = make_pbkdf2_sha1_mask_step(gen, batch, dk_words,
                                               hit_capacity)

    def process(self, unit):
        # dk widths can differ per target; compare_single truncates to
        # each target's word count because the TARGET drives the shape
        # (jit specializes per distinct width -- rare in practice)
        return super().process(unit)


@register("pbkdf2-sha1", device="jax")
class JaxPbkdf2Sha1Engine(Pbkdf2Sha1Engine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Pbkdf2Sha1MaskWorker(self, gen, targets,
                                    batch=min(batch, 1 << 13),
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Pbkdf2Sha1WordlistWorker(self, gen, targets,
                                        batch=min(batch, 1 << 13),
                                        hit_capacity=hit_capacity,
                                        oracle=oracle)
