"""PBKDF2-HMAC-SHA256 engine (Django's default hasher; hashcat 10900).

Accepted target lines:
  ``pbkdf2_sha256$<iterations>$<salt>$<base64 dk>``   (Django)
  ``sha256:<iterations>:<b64 salt>:<b64 dk>``         (hashcat 10900)

Unlike PMKID (one essid shared by a job), PBKDF2 dumps give every row
its own salt -- so the salt is a RUNTIME argument here (the U1 message
block is assembled on device from salt bytes + INT(1)), and one
compiled step serves every target and iteration count.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha256 import hmac256_key_states
from dprf_tpu.ops.sha256 import sha256_compress

from dprf_tpu.engines.cpu.engines import (PBKDF2_SALT_MAX as SALT_MAX,
                                           Cisco8Engine,
                                           Pbkdf2Sha256Engine)
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)


def u1_block(salt: jnp.ndarray, salt_len,
             block_index: int = 1) -> jnp.ndarray:
    """Runtime U1 message block for any 64-byte-block HMAC hash:
    salt || INT32BE(block_index), padded as the second block of the
    inner hash.  salt uint8[SALT_MAX] -> uint32[16] big-endian.
    Shared by the pbkdf2-sha256 and pbkdf2-sha1 engines."""
    buf = jnp.zeros((64,), jnp.uint8).at[:SALT_MAX].set(salt)
    pos = jnp.arange(64, dtype=jnp.int32)
    msg_len = salt_len + 4
    buf = jnp.where(pos < salt_len, buf, 0)
    buf = buf + jnp.where(pos == salt_len + 3, jnp.uint8(block_index),
                          jnp.uint8(0))
    buf = (buf + jnp.where(pos == msg_len, jnp.uint8(0x80),
                           jnp.uint8(0))).astype(jnp.uint8)
    coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                dtype=np.uint32))
    words = (buf.reshape(16, 4).astype(jnp.uint32) * coef).sum(
        axis=-1, dtype=jnp.uint32)
    return words.at[15].set(((64 + msg_len) * 8).astype(jnp.uint32))


def pbkdf2_sha256_runtime_salt(key_words: jnp.ndarray,
                               salt: jnp.ndarray, salt_len,
                               iterations) -> jnp.ndarray:
    """PBKDF2-HMAC-SHA256, 32-byte dk, with the salt as a runtime
    argument: uint32[B, 8]."""
    from jax import lax

    from dprf_tpu.ops.hmac_sha256 import _block32, hmac_sha256_32

    istate, ostate = hmac256_key_states(key_words)
    first = jnp.broadcast_to(u1_block(salt, salt_len)[None, :],
                             istate.shape[:-1] + (16,))
    inner = sha256_compress(istate, first)
    u = sha256_compress(ostate, _block32(inner))

    def body(_, carry):
        u, t = carry
        u = hmac_sha256_32(istate, ostate, u)
        return u, t ^ u

    _, t = lax.fori_loop(1, iterations, body, (u, u))
    return t


def make_pbkdf2_mask_step(gen, batch: int, hit_capacity: int = 64,
                          fold=None):
    """step(base_digits, n_valid, salt uint8[SALT_MAX], salt_len,
    iterations, target uint32[8]) -> (count, lanes, _).

    fold: optional dk-words transform before the compare (RAR5 xors
    the derived key's quarters into its 8-byte password check)."""
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, salt, salt_len, iterations, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        dk = pbkdf2_sha256_runtime_salt(key, salt, salt_len, iterations)
        if fold is not None:
            dk = fold(dk)
        found = cmp_ops.compare_single(dk, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_pbkdf2_wordlist_step(gen, word_batch: int,
                              hit_capacity: int = 64, fold=None):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt, salt_len, iterations, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        # HMAC key block: raw zero padding (NO MD marker/bit length),
        # masked per lane to the rule-expanded length
        pos = jnp.arange(64, dtype=jnp.int32)[None, :]
        raw = jnp.where(pos < cl[:, None],
                        jnp.zeros((cw.shape[0], 64),
                                  jnp.uint8).at[:, :Lw].set(cw), 0)
        coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                    dtype=np.uint32))
        key = (raw.reshape(cw.shape[0], 16, 4).astype(jnp.uint32)
               * coef).sum(axis=-1, dtype=jnp.uint32)
        dk = pbkdf2_sha256_runtime_salt(key, salt, salt_len, iterations)
        if fold is not None:
            dk = fold(dk)
        found = cmp_ops.compare_single(dk, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def _targs(targets):
    out = []
    for t in targets:
        s = t.params["salt"]
        buf = np.zeros((SALT_MAX,), np.uint8)
        buf[:len(s)] = np.frombuffer(s, np.uint8)
        out.append((jnp.asarray(buf), jnp.int32(len(s)),
                    jnp.int32(t.params["iterations"]),
                    jnp.asarray(np.frombuffer(t.digest, dtype=">u4")
                                .astype(np.uint32))))
    return out


# The per-target sweep bodies are the phpass workers' (they splat
# whatever per-target argument tuple _targs built); only the step
# factories and target args differ.

class Pbkdf2MaskWorker(PhpassMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        self.step = make_pbkdf2_mask_step(gen, batch, hit_capacity)


class Pbkdf2WordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        self.step = make_pbkdf2_wordlist_step(gen, self.word_batch,
                                              hit_capacity)


class ShardedPbkdf2MaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 12, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _targs(self.targets)
        length = gen.length

        def digest_fn(cand, lens, salt, salt_len, iterations):
            key = pack_ops.pack_raw(cand, length, big_endian=True)
            return pbkdf2_sha256_runtime_salt(key, salt, salt_len,
                                              iterations)

        self.step = make_sharded_pertarget_step(
            gen, mesh, batch_per_device, digest_fn, 3, hit_capacity)


@register("pbkdf2-sha256", device="jax")
class JaxPbkdf2Sha256Engine(Pbkdf2Sha256Engine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Pbkdf2MaskWorker(self, gen, targets,
                                batch=min(batch, 1 << 13),
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedPbkdf2MaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, 1 << 12),
            hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Pbkdf2WordlistWorker(self, gen, targets,
                                    batch=min(batch, 1 << 13),
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)


@register("cisco8", device="jax")
@register("cisco-ios-8", device="jax")
class JaxCisco8Engine(Cisco8Engine):
    """Cisco IOS type 8 on device: the pbkdf2-sha256 workers with the
    $8$ line format (same params shape: salt + iterations)."""

    make_mask_worker = JaxPbkdf2Sha256Engine.make_mask_worker
    make_wordlist_worker = JaxPbkdf2Sha256Engine.make_wordlist_worker
    make_sharded_mask_worker = \
        JaxPbkdf2Sha256Engine.make_sharded_mask_worker
