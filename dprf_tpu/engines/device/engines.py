"""JAX device engines: the TPU-native execution backends.

Each engine exposes (a) `digest_packed` -- the raw jit-traceable digest
over packed message words, used by the fused crack pipeline; and (b)
`hash_batch` -- the HashEngine-compatible host API (used by tests and
`--device=jax` verification paths), which round-trips bytes through the
device.

Digest word layouts match the CPU oracles bit-for-bit; tests/test_device_engines.py
checks every engine against the oracle over random candidate batches.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import DeviceHashEngine, HashEngine
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.md5 import md5_digest_words


class JaxEngineBase(DeviceHashEngine, HashEngine):
    """Shared packing + host-convenience layer for single-block engines."""

    #: digest words are little-endian uint32 (MD4/MD5 family) or
    #: big-endian (SHA family); drives target-table layout too.
    little_endian: bool = True
    max_candidate_len = 55

    # -- device path -----------------------------------------------------

    def pack(self, cand: jnp.ndarray, length: int) -> jnp.ndarray:
        """uint8[B, length] candidates -> uint32[B, 16] message words."""
        return pack_ops.pack_fixed(cand, length,
                                   big_endian=not self.little_endian)

    def pack_varlen(self, cand: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        return pack_ops.pack_varlen(cand, lengths,
                                    big_endian=not self.little_endian)

    # -- host-facing HashEngine API --------------------------------------

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        maxlen = max((len(c) for c in candidates), default=1) or 1
        if maxlen > self.max_candidate_len:
            raise ValueError(f"{self.name}: candidate longer than "
                             f"{self.max_candidate_len} bytes")
        batch = len(candidates)
        buf = np.zeros((batch, maxlen), dtype=np.uint8)
        lengths = np.zeros((batch,), dtype=np.int32)
        for i, c in enumerate(candidates):
            buf[i, :len(c)] = np.frombuffer(c, dtype=np.uint8)
            lengths[i] = len(c)
        words = self.pack_varlen(jnp.asarray(buf), jnp.asarray(lengths))
        digest = np.asarray(self.digest_packed(words))
        dt = "<u4" if self.little_endian else ">u4"
        return [digest[i].astype(dt).tobytes()[:self.digest_size]
                for i in range(batch)]


@register("md5", device="jax")
class JaxMd5Engine(JaxEngineBase):
    name = "md5"
    digest_size = 16
    digest_words = 4
    little_endian = True

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return md5_digest_words(blocks)
