"""JAX device engines: the TPU-native execution backends.

Each engine exposes (a) `digest_packed` -- the raw jit-traceable digest
over packed message words, used by the fused crack pipeline; and (b)
`hash_batch` -- the HashEngine-compatible host API (used by tests and
`--device=jax` verification paths), which round-trips bytes through the
device.

Digest word layouts match the CPU oracles bit-for-bit; tests/test_device_engines.py
checks every engine against the oracle over random candidate batches.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import DeviceHashEngine, HashEngine
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.md4 import md4_digest_words
from dprf_tpu.ops.md5 import md5_digest_words
from dprf_tpu.ops.sha1 import sha1_digest_words
from dprf_tpu.ops.sha256 import (sha224_digest_words,
                                 sha256_digest_words)
from dprf_tpu.ops.sha512 import sha384_digest_words, sha512_digest_words


class GenericWorkerFactories:
    """Combinator + multi-chip (keyspace DP over a 1-D mesh) worker
    factories over the generic fused steps.  Any engine exposing the
    digest_candidates hook can mix this in (JaxEngineBase and the
    keccak family both do); salted engines (bcrypt, PMKID) override
    with their own sharded pipelines, so every engine exposes the same
    multi-chip surface and `--devices N` never silently degrades to
    one chip."""

    def make_combinator_worker(self, gen, targets, batch: int,
                               hit_capacity: int, oracle=None):
        """Fused combinator/hybrid worker (left x right word tables)."""
        from dprf_tpu.runtime.worker import DeviceCombinatorWorker
        return DeviceCombinatorWorker(self, gen, targets, batch=batch,
                                      hit_capacity=hit_capacity,
                                      oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        """Sharded mask worker; kernel-capable jobs run the FUSED
        PALLAS KERNEL as the per-shard compute (parallel/sharded.
        make_sharded_kernel_mask_step) -- the single-chip
        make_mask_worker routing ladder at mesh scale, with the XLA
        sharded runtime as the not-eligible / build-failure fallback.
        Bulk lists (probe_eligible) stay on the XLA probe-table
        compute; the in-kernel blocked probe covers 2..MAX_TARGETS
        and needs an oracle to verify its sentinel survivors."""
        from dprf_tpu.ops.pallas_mask import kernel_eligible, pallas_mode
        from dprf_tpu.parallel.worker import ShardedMaskWorker
        from dprf_tpu.targets import probe as probe_mod
        from dprf_tpu.utils.logging import DEFAULT as log
        mode = pallas_mode()
        if mode is not None and probe_mod.probe_eligible(targets, self):
            log.info("bulk target list routes to the sharded "
                     "probe-table XLA pipeline", engine=self.name,
                     targets=len(targets))
        elif mode is not None and not kernel_eligible(self.name, gen,
                                                      len(targets)):
            log.info("pallas kernel not eligible for this sharded "
                     "job; using the XLA pipeline", engine=self.name,
                     targets=len(targets))
        elif mode is not None and len(targets) > 1 and oracle is None:
            log.info("sharded multi-target kernel needs an oracle to "
                     "verify probe survivors; using the XLA pipeline",
                     engine=self.name, targets=len(targets))
        elif mode is not None:
            try:
                worker = ShardedMaskWorker(
                    self, gen, targets, mesh,
                    batch_per_device=batch_per_device,
                    hit_capacity=hit_capacity, oracle=oracle,
                    kernel=dict(mode))
                worker.warmup()
                return worker
            except Exception as e:
                log.warn("sharded kernel compute failed to "
                         "build/compile; falling back to the XLA "
                         "pipeline", engine=self.name,
                         error=f"{type(e).__name__}: {e}")
        return ShardedMaskWorker(self, gen, targets, mesh,
                                 batch_per_device=batch_per_device,
                                 hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_wordlist_worker(self, gen, targets, mesh,
                                     word_batch_per_device: int,
                                     hit_capacity: int, oracle=None):
        from dprf_tpu.parallel.worker import ShardedWordlistWorker
        return ShardedWordlistWorker(
            self, gen, targets, mesh,
            word_batch_per_device=word_batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_combinator_worker(self, gen, targets, mesh,
                                       batch_per_device: int,
                                       hit_capacity: int, oracle=None):
        from dprf_tpu.parallel.worker import ShardedCombinatorWorker
        return ShardedCombinatorWorker(
            self, gen, targets, mesh,
            batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)


class JaxEngineBase(GenericWorkerFactories, DeviceHashEngine, HashEngine):
    """Shared packing + host-convenience layer for single-block engines."""

    #: digest words are little-endian uint32 (MD4/MD5 family) or
    #: big-endian (SHA family); drives target-table layout too.
    little_endian: bool = True
    max_candidate_len = 55
    #: single-block packing limit (55 for 64-byte blocks; 111 for the
    #: SHA-512 family's 128-byte blocks)
    _block_limit = 55
    #: kernel-profile phase mapping (ISSUE 15): substring patterns
    #: matched against device-op names in a jax.profiler capture,
    #: merged OVER telemetry/profiler.py's defaults -- how the
    #: analyzer splits a dispatch's device time into the
    #: generate/hash/compare sub-phases.  Engines whose compiled step
    #: carries distinctive op names (a Pallas custom-call, a
    #: scan-looped compress) refine this per class.
    PROFILE_PHASES: dict = {
        "generate": ("decode_batch", "mixed_radix"),
        "compare": ("compare_digests", "target_table", "bloom"),
        "hash": ("digest_packed", "pack_fixed", "pack_varlen"),
    }

    # -- device path -----------------------------------------------------

    def pack(self, cand: jnp.ndarray, length: int) -> jnp.ndarray:
        """uint8[B, length] candidates -> uint32[B, 16] message words."""
        return pack_ops.pack_fixed(cand, length,
                                   big_endian=not self.little_endian)

    def pack_varlen(self, cand: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
        return pack_ops.pack_varlen(cand, lengths,
                                    big_endian=not self.little_endian)

    def digest_candidates(self, cand: jnp.ndarray,
                          lengths) -> jnp.ndarray:
        """uint8[B, L] candidates + int32[B] lengths (or a python int
        for a fixed-length batch) -> digest words.  Default is the
        MD-style pack + compress; engines with non-MD framing (the
        keccak sponge family) override, so the generic sharded /
        combinator / rules factories serve every family through ONE
        hook instead of assuming the block packers."""
        if isinstance(lengths, int):
            words = self.pack(cand, lengths)
        else:
            words = self.pack_varlen(cand, lengths)
        return self.digest_packed(words)

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        """Build the fused-pipeline worker for a mask attack on this
        engine.  Engines with special pipelines (PMKID, bcrypt) override
        this -- it is the CLI's single entry into the device path.

        Kernel-capable engines route to the hand-written Pallas kernel
        when eligible (see ops/pallas_mask.pallas_mode): exact
        single-target compare, or the Bloom-prefilter multi-target path
        (which needs an oracle to verify maybes -- without one the job
        stays on the generic fused XLA pipeline).

        A kernel that fails to build or compile (a Mosaic lowering
        regression, an unexpected shape) must not abort the job: the
        construction + warmup compile is wrapped, and on failure the
        job degrades to the generic XLA pipeline with a loud warning.
        """
        from dprf_tpu.ops.pallas_mask import kernel_eligible, pallas_mode
        from dprf_tpu.targets import probe as probe_mod
        from dprf_tpu.utils.logging import DEFAULT as log
        mode = pallas_mode()
        if mode is not None and probe_mod.probe_eligible(targets, self):
            # bulk lists route to the probe-table worker: the Pallas
            # multi-target kernel replicates a per-set bitmap whose
            # cost grows with N, exactly what the probe table removes
            log.info("bulk target list routes to the probe-table XLA "
                     "pipeline", engine=self.name, targets=len(targets))
        elif mode is not None and not kernel_eligible(self.name, gen,
                                                      len(targets)):
            # weak-spot visibility: `--impl auto` users otherwise can't
            # tell which path ran without reading the result JSON
            log.info("pallas kernel not eligible for this job; "
                     "using the XLA pipeline", engine=self.name,
                     targets=len(targets))
        elif mode is not None and len(targets) > 1 and oracle is None:
            log.info("pallas multi-target kernel needs an oracle to "
                     "verify Bloom maybes; using the XLA pipeline",
                     engine=self.name, targets=len(targets))
        elif mode is not None:
            from dprf_tpu import tune as tune_mod
            from dprf_tpu.runtime.worker import PallasMaskWorker
            # tuned tile size (dprf tune --rungs sub): a cache miss
            # returns None and the kernel default stands
            sub = tune_mod.lookup_tuned_value(
                self.name, "sub", attack="mask",
                extras={"hit_cap": int(hit_capacity)})
            try:
                worker = PallasMaskWorker(self, gen, targets, batch=batch,
                                          hit_capacity=hit_capacity,
                                          oracle=oracle, sub=sub, **mode)
                worker.warmup()
                return worker
            except Exception as e:
                log.warn("pallas kernel failed to build/compile; "
                         "falling back to the XLA pipeline",
                         engine=self.name,
                         error=f"{type(e).__name__}: {e}")
        from dprf_tpu.runtime.worker import DeviceMaskWorker
        return DeviceMaskWorker(self, gen, targets, batch=batch,
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        """Fused wordlist+rules worker (config 3's on-device expansion).
        Single-target jobs whose rule set the in-VMEM interpreter
        kernel supports get the Pallas path (ops/pallas_rules.py),
        with the XLA pipeline as build-failure fallback."""
        from dprf_tpu.ops.pallas_mask import pallas_mode
        from dprf_tpu.ops.pallas_rules import kernel_rules_eligible
        from dprf_tpu.runtime.worker import DeviceWordlistWorker
        from dprf_tpu.utils.logging import DEFAULT as log
        mode = pallas_mode()
        if (mode is not None
                and kernel_rules_eligible(self.name, gen, len(targets))):
            from dprf_tpu.runtime.worker import PallasWordlistWorker
            try:
                worker = PallasWordlistWorker(
                    self, gen, targets, batch=batch,
                    hit_capacity=hit_capacity, oracle=oracle, **mode)
                worker.warmup()
                return worker
            except Exception as e:
                log.warn("rules kernel failed to build/compile; "
                         "falling back to the XLA pipeline",
                         engine=self.name,
                         error=f"{type(e).__name__}: {e}")
        elif mode is not None:
            log.info("rules kernel not eligible for this job; "
                     "using the XLA pipeline", engine=self.name,
                     targets=len(targets))
        return DeviceWordlistWorker(self, gen, targets, batch=batch,
                                    hit_capacity=hit_capacity, oracle=oracle)

    # -- host-facing HashEngine API --------------------------------------

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        maxlen = max((len(c) for c in candidates), default=1) or 1
        # _block_limit is the single-block packing limit; engine-specific
        # max_candidate_len (e.g. NTLM's 27 pre-widening chars) is
        # enforced by callers/overrides on the raw candidate.
        if maxlen > self._block_limit:
            raise ValueError(
                f"{self.name}: candidate longer than the "
                f"{self._block_limit}-byte single-block limit")
        batch = len(candidates)
        buf = np.zeros((batch, maxlen), dtype=np.uint8)
        lengths = np.zeros((batch,), dtype=np.int32)
        for i, c in enumerate(candidates):
            buf[i, :len(c)] = np.frombuffer(c, dtype=np.uint8)
            lengths[i] = len(c)
        words = self.pack_varlen(jnp.asarray(buf), jnp.asarray(lengths))
        digest = np.asarray(self.digest_packed(words))
        dt = "<u4" if self.little_endian else ">u4"
        return [digest[i].astype(dt).tobytes()[:self.digest_size]
                for i in range(batch)]


@register("md5", device="jax")
class JaxMd5Engine(JaxEngineBase):
    name = "md5"
    digest_size = 16
    digest_words = 4
    little_endian = True
    #: the md5 compress body fuses under names carrying the jitted
    #: scope ("md5") on TPU; the Pallas path shows as a custom-call
    PROFILE_PHASES = {
        **JaxEngineBase.PROFILE_PHASES,
        "hash": ("md5",) + JaxEngineBase.PROFILE_PHASES["hash"],
    }

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return md5_digest_words(blocks)


@register("sha1", device="jax")
@register("sha-1", device="jax")
class JaxSha1Engine(JaxEngineBase):
    name = "sha1"
    digest_size = 20
    digest_words = 5
    little_endian = False

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return sha1_digest_words(blocks)


@register("sha256", device="jax")
@register("sha-256", device="jax")
class JaxSha256Engine(JaxEngineBase):
    name = "sha256"
    digest_size = 32
    digest_words = 8
    little_endian = False

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return sha256_digest_words(blocks)


@register("sha224", device="jax")
class JaxSha224Engine(JaxEngineBase):
    """SHA-224: SHA-256 with its own IV, truncated to 28 bytes."""

    name = "sha224"
    digest_size = 28
    digest_words = 7
    little_endian = False

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return sha224_digest_words(blocks)


@register("sha512", device="jax")
@register("sha-512", device="jax")
class JaxSha512Engine(JaxEngineBase):
    """SHA-512 over 128-byte blocks; 64-bit words emulated as uint32
    (hi, lo) lane pairs (see ops/sha512.py)."""

    name = "sha512"
    digest_size = 64
    digest_words = 16
    little_endian = False
    max_candidate_len = 111
    _block_limit = 111

    def pack(self, cand: jnp.ndarray, length: int) -> jnp.ndarray:
        return pack_ops.pack_fixed_wide(cand, length)

    def pack_varlen(self, cand: jnp.ndarray,
                    lengths: jnp.ndarray) -> jnp.ndarray:
        return pack_ops.pack_varlen_wide(cand, lengths)

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return sha512_digest_words(blocks)


@register("sha384", device="jax")
@register("sha-384", device="jax")
class JaxSha384Engine(JaxSha512Engine):
    name = "sha384"
    digest_size = 48
    digest_words = 12

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return sha384_digest_words(blocks)


@register("ntlm", device="jax")
class JaxNtlmEngine(JaxEngineBase):
    """NTLM: MD4 over UTF-16LE.  The fused pipeline widens the latin-1
    candidate bytes to UTF-16LE on device (widen_utf16); the host
    hash_batch path widens here before packing."""

    name = "ntlm"
    digest_size = 16
    digest_words = 4
    little_endian = True
    widen_utf16 = True
    # 27 chars -> 54 UTF-16LE bytes: still one MD4 block.
    max_candidate_len = 27

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        return md4_digest_words(blocks)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if any(len(c) > self.max_candidate_len for c in candidates):
            raise ValueError("ntlm: candidate longer than 27 chars")
        widened = [bytes(b for ch in c for b in (ch, 0)) for c in candidates]
        return super().hash_batch(widened, params=params)


@register("ldap-sha", device="jax")
class JaxLdapShaEngine(JaxSha1Engine):
    """LDAP {SHA} (hashcat 101): the unsalted sha1 fast path (incl.
    multi-target compare) with the base64 line format."""

    name = "ldap-sha"

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import LdapShaEngine
        return LdapShaEngine().parse_target(text)


@register("ldap-md5", device="jax")
class JaxLdapMd5Engine(JaxMd5Engine):
    """LDAP {MD5}: the unsalted md5 fast path with the base64 line
    format."""

    name = "ldap-md5"

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import LdapMd5Engine
        return LdapMd5Engine().parse_target(text)


@register("mysql323", device="jax")
@register("mysql-old", device="jax")
class JaxMysql323Engine(JaxEngineBase):
    """MySQL pre-4.1 OLD_PASSWORD (hashcat 200): an add/xor/shift scan
    over the password bytes.  digest_packed recovers bytes and length
    from the standard big-endian single-block packing (bit count in
    word 15), so every generic pipeline -- mask, wordlist+rules,
    combinator, multi-target table, sharded -- applies unchanged."""

    name = "mysql323"
    digest_size = 8
    digest_words = 2
    little_endian = False

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        B = blocks.shape[0]
        lens = (blocks[:, 15] // 8).astype(jnp.int32)
        shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
        byts = ((blocks[:, :14, None] >> shifts) &
                jnp.uint32(0xFF)).reshape(B, 56)
        nr = jnp.full((B,), jnp.uint32(1345345333))
        nr2 = jnp.full((B,), jnp.uint32(0x12345671))
        add = jnp.full((B,), jnp.uint32(7))
        for i in range(55):
            c = byts[:, i]
            active = ((i < lens) & (c != 0x20) & (c != 0x09))
            nr_n = nr ^ ((((nr & 63) + add) * c) + (nr << 8))
            nr2_n = nr2 + ((nr2 << 8) ^ nr_n)
            add_n = add + c
            nr = jnp.where(active, nr_n, nr)
            nr2 = jnp.where(active, nr2_n, nr2)
            add = jnp.where(active, add_n, add)
        mask31 = jnp.uint32(0x7FFFFFFF)
        return jnp.stack([nr & mask31, nr2 & mask31], axis=1)

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import Mysql323Engine
        return Mysql323Engine().parse_target(text)
