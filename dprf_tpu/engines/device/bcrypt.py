"""Device bcrypt engine: the memory-hard / low-throughput path
(benchmark config 4).

bcrypt is salted with a per-target cost, so unlike the fast unsalted
engines one digest computation cannot serve a target list: the fused
step takes (salt_words, n_rounds, target_words) as *runtime* arguments
and the worker sweeps the keyspace once per target.  One compiled
program serves every bcrypt target of any cost.

The heavy state (4 KB of S-boxes per candidate lane) and the serial
EksBlowfish chains live in ops/blowfish.py; batches are kept small --
at cost 12 each candidate is ~4.3M Blowfish encryptions, so a batch is
seconds of device time and bigger batches only add latency, not
throughput.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import BcryptEngine
from dprf_tpu.ops import blowfish as bf_ops
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.rules_pipeline import expand_rules
from dprf_tpu.runtime.worker import (Hit, CpuWorker, word_cover_range,
                                     wordlist_lane_to_gidx)
from dprf_tpu.runtime.workunit import WorkUnit

#: default candidates per device step; bcrypt steps are seconds long
#: even at this size, and 4 KB of S-box state per lane caps usefully
#: large batches anyway (4096 lanes = 16 MB of mutating state).
DEFAULT_BATCH = 1 << 12


@register("bcrypt", device="jax")
class JaxBcryptEngine(BcryptEngine):
    """Device bcrypt.  Inherits hash parsing ($2a/$2b lines) from the
    CPU engine; hash_batch runs the EksBlowfish pipeline on device."""

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("bcrypt needs target params (salt, cost)")
        if any(len(c) > self.max_candidate_len for c in candidates):
            raise ValueError("bcrypt: candidate longer than 72 bytes")
        B = len(candidates)
        L = max(max((len(c) for c in candidates), default=1), 1)
        buf = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros((B,), dtype=np.int32)
        for i, c in enumerate(candidates):
            buf[i, :len(c)] = np.frombuffer(c, dtype=np.uint8)
            lens[i] = len(c)
        dw = _jit_bcrypt_batch(
            jnp.asarray(buf), jnp.asarray(lens),
            jnp.asarray(bf_ops.salt_to_words(params["salt"])),
            _n_rounds(params["cost"]))
        return bf_ops.words_to_digests(np.asarray(dw))

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return BcryptMaskWorker(self, gen, targets,
                                batch=min(batch, DEFAULT_BATCH),
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return BcryptWordlistWorker(self, gen, targets,
                                    batch=min(batch, DEFAULT_BATCH),
                                    hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedBcryptMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, DEFAULT_BATCH),
            hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_wordlist_worker(self, gen, targets, mesh,
                                     word_batch_per_device: int,
                                     hit_capacity: int, oracle=None):
        return ShardedBcryptWordlistWorker(
            self, gen, targets, mesh,
            word_batch_per_device=max(1, min(word_batch_per_device,
                                             DEFAULT_BATCH // gen.n_rules)),
            hit_capacity=hit_capacity, oracle=oracle)


_jit_bcrypt_batch = jax.jit(bf_ops.bcrypt_batch)


def _n_rounds(cost: int) -> jnp.ndarray:
    """2**cost as the device loop trip count.  Cost 31 (valid in the
    bcrypt format, ~2e9 rounds) would overflow the int32 loop bound --
    reject it with a pointer to the CPU path rather than wrapping to a
    zero-iteration loop that yields silent false negatives."""
    if not 4 <= cost <= 30:
        raise ValueError(
            f"bcrypt cost {cost} outside the device engine's range 4..30 "
            "(2**31 rounds exceeds the int32 loop bound; use --device=cpu)")
    return jnp.int32(1 << cost)


def _target_args(target: Target):
    """Target -> (salt_words, n_rounds, target_words) device args."""
    return (jnp.asarray(bf_ops.salt_to_words(target.params["salt"])),
            _n_rounds(target.params["cost"]),
            jnp.asarray(bf_ops.digest_to_words(target.digest)))


def make_bcrypt_mask_step(gen, batch: int, hit_capacity: int = 64):
    """step(base_digits int32[L], n_valid, salt_words uint32[4],
    n_rounds int32, target uint32[6]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, salt_words, n_rounds, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        dwords = bf_ops.bcrypt_batch(cand, lens, salt_words, n_rounds)
        found = bf_ops.compare_digest_words(dwords, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_sharded_bcrypt_mask_step(gen, mesh, batch_per_device: int,
                                  hit_capacity: int = 64):
    """Multi-chip bcrypt mask step (config 4 at pod scale): chip c owns
    lane slice [c*B, (c+1)*B) of the super-batch and runs the full
    EksBlowfish chain locally; only the scalar hit count psums over ICI.

    step(base_digits, n_valid, salt_words, n_rounds, target) ->
        (total, counts[n_dev], lanes[n_dev, cap] super-batch-global, _).
    """
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS

    flat = gen.flat_charsets
    length = gen.length
    B = batch_per_device

    def shard_fn(base_digits, n_valid, salt_words, n_rounds, target):
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * B).astype(jnp.int32)
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        lens = jnp.full((B,), length, jnp.int32)
        dwords = bf_ops.bcrypt_batch(cand, lens, salt_words, n_rounds)
        lane_global = offset + jnp.arange(B, dtype=jnp.int32)
        found = (bf_ops.compare_digest_words(dwords, target)
                 & (lane_global < n_valid))
        count, lanes, tpos = cmp_ops.compact_hits(
            found, jnp.zeros((B,), jnp.int32), hit_capacity)
        lanes = jnp.where(lanes >= 0, lanes + offset, lanes)
        total = lax.psum(count, SHARD_AXIS)
        # replicated hit buffers (see parallel/sharded.py)
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    sharded = jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(),) * 5,
        out_specs=(P(), P(), P(), P()),
        check_vma=False)

    @jax.jit
    def step(base_digits, n_valid, salt_words, n_rounds, target):
        total, counts, lanes, tpos = sharded(base_digits, n_valid,
                                             salt_words, n_rounds, target)
        return total[0], counts, lanes, tpos

    step.super_batch = mesh.devices.size * B
    return step


def make_sharded_bcrypt_wordlist_step(gen, mesh, word_batch: int,
                                      hit_capacity: int = 64):
    """Multi-chip bcrypt wordlist step: chip c expands+hashes words
    [w0 + c*B, w0 + (c+1)*B).  Lanes come back as super-batch flat
    indices r*(n_dev*B) + global word lane (the same convention as
    ops/rules_pipeline.make_sharded_wordlist_crack_step).
    """
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS

    n_dev = mesh.devices.size
    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(
        pad_to=n_dev * B, min_size=gen.n_words + n_dev * B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    def shard_fn(w0, n_valid_words, salt_words, n_rounds, target):
        dev = lax.axis_index(SHARD_AXIS)
        my_w0 = w0 + (dev * B).astype(jnp.int32)
        wslice = lax.dynamic_slice(words_dev, (my_w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (my_w0,), (B,))
        word_lane = (dev * B).astype(jnp.int32) + jnp.arange(
            B, dtype=jnp.int32)
        base_valid = word_lane < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        dwords = bf_ops.bcrypt_batch(cw, cl, salt_words, n_rounds)
        found = bf_ops.compare_digest_words(dwords, target) & cv
        count, lanes, tpos = cmp_ops.compact_hits(
            found, jnp.zeros_like(cl), hit_capacity)
        r = lanes // B
        b = lanes % B
        glanes = r * (n_dev * B) + dev * B + b
        lanes = jnp.where(lanes >= 0, glanes, lanes)
        total = lax.psum(count, SHARD_AXIS)
        # replicated hit buffers (see parallel/sharded.py)
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    sharded = jax.shard_map(
        shard_fn, mesh=mesh, in_specs=(P(),) * 5,
        out_specs=(P(), P(), P(), P()),
        check_vma=False)

    @jax.jit
    def step(w0, n_valid_words, salt_words, n_rounds, target):
        total, counts, lanes, tpos = sharded(w0, n_valid_words,
                                             salt_words, n_rounds, target)
        return total[0], counts, lanes, tpos

    step.super_words = n_dev * B
    return step


def make_bcrypt_wordlist_step(gen, word_batch: int, hit_capacity: int = 64):
    """Wordlist(+rules) variant; words are sliced from the HBM-resident
    packed table and expanded through the rule set on device, exactly
    like ops/rules_pipeline.py, then fed to EksBlowfish.

    step(w0, n_valid_words, salt_words, n_rounds, target) ->
        (count, lanes, _); lanes are flat r*B + b candidate indices.
    """
    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, salt_words, n_rounds, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        dwords = bf_ops.bcrypt_batch(cw, cl, salt_words, n_rounds)
        found = bf_ops.compare_digest_words(dwords, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl), hit_capacity)

    return step


class _BcryptWorkerBase:
    """Per-target keyspace sweep shared by the mask/wordlist workers."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int, hit_capacity: int, oracle):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.batch = batch
        self._targs = [_target_args(t) for t in self.targets]

    def _rescan(self, start: int, end: int, ti: int) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        sub = WorkUnit(-1, start, end - start)
        hits = CpuWorker(self.oracle, self.gen,
                         [self.targets[ti]]).process(sub)
        return [Hit(ti, h.cand_index, h.plaintext) for h in hits]


class BcryptMaskWorker(_BcryptWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = DEFAULT_BATCH,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.stride = batch
        self.step = make_bcrypt_mask_step(gen, batch, hit_capacity)

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            queued = []
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
                queued.append((bstart, self.step(
                    base, jnp.int32(n_valid), salt_w, n_rounds, tgt)))
            for bstart, (count, lanes, _) in queued:
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    hits.extend(self._rescan(
                        bstart, min(bstart + self.stride, unit.end), ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits


class ShardedBcryptMaskWorker(_BcryptWorkerBase):
    """Multi-chip bcrypt mask worker (keyspace DP over the mesh)."""

    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = DEFAULT_BATCH,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets,
                         mesh.devices.size * batch_per_device,
                         hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch          # one super-batch per step
        self.step = make_sharded_bcrypt_mask_step(
            gen, mesh, batch_per_device, hit_capacity)

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            queued = []
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
                queued.append((bstart, self.step(
                    base, jnp.int32(n_valid), salt_w, n_rounds, tgt)))
            for bstart, (total, counts, lanes, _) in queued:
                if int(total) == 0:
                    continue
                if (np.asarray(counts) > self.hit_capacity).any():
                    hits.extend(self._rescan(
                        bstart, min(bstart + self.stride, unit.end), ti))
                    continue
                for lane in np.asarray(lanes).ravel():
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits


class ShardedBcryptWordlistWorker(_BcryptWorkerBase):
    """Multi-chip bcrypt wordlist worker.  Super-batch lanes follow the
    sharded wordlist convention: lane = r * super_words + word lane."""

    def __init__(self, engine, gen, targets, mesh,
                 word_batch_per_device: int = 1 << 9,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets,
                         mesh.devices.size * word_batch_per_device
                         * gen.n_rules, hit_capacity, oracle)
        self.mesh = mesh
        self.step = make_sharded_bcrypt_wordlist_step(
            gen, mesh, word_batch_per_device, hit_capacity)
        self.super_words = self.step.super_words
        self.word_batch = self.super_words
        self.stride = self.super_words * gen.n_rules

    def process(self, unit: WorkUnit) -> list[Hit]:
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            queued = []
            for ws in range(w_start, w_end, self.super_words):
                nw = min(self.super_words, w_end - ws,
                         self.gen.n_words - ws)
                if nw <= 0:
                    break
                queued.append((ws, nw, self.step(
                    jnp.int32(ws), jnp.int32(nw), salt_w, n_rounds, tgt)))
            for ws, nw, (total, counts, lanes, _) in queued:
                if int(total) == 0:
                    continue
                if (np.asarray(counts) > self.hit_capacity).any():
                    start = max(unit.start, ws * R)
                    end = min(unit.end, (ws + nw) * R)
                    hits.extend(self._rescan(start, end, ti))
                    continue
                for lane in np.asarray(lanes).ravel():
                    if lane < 0:
                        continue
                    gidx = wordlist_lane_to_gidx(int(lane), ws,
                                                 self.super_words, R)
                    if not unit.start <= gidx < unit.end:
                        continue
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits


class BcryptWordlistWorker(_BcryptWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = DEFAULT_BATCH,
                 hit_capacity: int = 64, oracle=None):
        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.step = make_bcrypt_wordlist_step(gen, self.word_batch,
                                              hit_capacity)

    def process(self, unit: WorkUnit) -> list[Hit]:
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            queued = []
            for ws in range(w_start, w_end, self.word_batch):
                nw = min(self.word_batch, w_end - ws, self.gen.n_words - ws)
                if nw <= 0:
                    break
                queued.append((ws, nw, self.step(
                    jnp.int32(ws), jnp.int32(nw), salt_w, n_rounds, tgt)))
            for ws, nw, (count, lanes, _) in queued:
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    start = max(unit.start, ws * R)
                    end = min(unit.end, (ws + nw) * R)
                    hits.extend(self._rescan(start, end, ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = wordlist_lane_to_gidx(int(lane), ws,
                                                 self.word_batch, R)
                    if not unit.start <= gidx < unit.end:
                        continue
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
