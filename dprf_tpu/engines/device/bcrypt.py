"""Device bcrypt engine: the memory-hard / low-throughput path
(benchmark config 4).

bcrypt is salted with a per-target cost, so unlike the fast unsalted
engines one digest computation cannot serve a target list: the fused
step takes (salt_words, n_rounds, target_words) as *runtime* arguments
and the worker sweeps the keyspace once per target.  One compiled
program serves every bcrypt target of any cost.

The heavy state (4 KB of S-boxes per candidate lane) and the serial
EksBlowfish chains live in ops/blowfish.py; batches are kept small --
at cost 12 each candidate is ~4.3M Blowfish encryptions, so a batch is
seconds of device time and bigger batches only add latency, not
throughput.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import BcryptEngine
from dprf_tpu.ops import blowfish as bf_ops
from dprf_tpu.utils import env as envreg
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.rules_pipeline import expand_rules
from dprf_tpu.runtime.worker import (Hit, CpuWorker, word_cover_range,
                                     wordlist_lane_to_gidx)
from dprf_tpu.runtime.workunit import WorkUnit

#: default candidates per device step; bcrypt steps are seconds long
#: even at this size, and 4 KB of S-box state per lane caps usefully
#: large batches anyway (4096 lanes = 16 MB of mutating state).
DEFAULT_BATCH = 1 << 12


class RoutedCpuBcryptWorker(CpuWorker):
    """Returned by the bcrypt worker factories when the measured CPU
    oracle rate beats the device rate (VERDICT r3 #4: run bcrypt on
    the winner, don't silently lose on the accelerator)."""

    def __init__(self, oracle, gen, targets, chunk: int = 2048):
        super().__init__(oracle, gen, targets, chunk)
        self.stride = chunk

    def warmup(self) -> None:
        pass


def measure_eks_rates(oracle, batch: int, rounds: int = 16) -> dict:
    """Head-to-head candidate-rounds/second: the device advance (best
    available form) vs the CPU oracle, both over `rounds` EksBlowfish
    cost rounds.  Rounds scale linearly (measured r3/r4), so a 16-round
    micro-bench predicts any cost."""
    from dprf_tpu.ops.pallas_bcrypt import make_best_eks_advance
    from dprf_tpu.utils.sync import hard_sync

    rng = np.random.RandomState(1)
    cand = rng.randint(97, 123, (batch, 8), dtype=np.uint8)
    kw = bf_ops.key_words_from_candidates(
        jnp.asarray(cand), jnp.full((batch,), 8, jnp.int32))
    sw = jnp.asarray(np.frombuffer(bytes(range(16)), ">u4")
                     .astype(np.uint32))
    s18 = bf_ops.salt18_words(sw)
    advance = make_best_eks_advance(batch)
    P, S = bf_ops.eks_setup_begin(kw, sw)
    P, S = advance(P, S, kw, s18, jnp.int32(1))     # warm the compile
    hard_sync(S)
    t0 = time.perf_counter()
    P, S = advance(P, S, kw, s18, jnp.int32(rounds))
    hard_sync(S)
    device = batch * rounds / (time.perf_counter() - t0)

    n_cpu = 2
    cost4 = {"salt": bytes(range(16)), "cost": 4}
    t0 = time.perf_counter()
    oracle.hash_batch([bytes(cand[i]) for i in range(n_cpu)],
                      params=cost4)
    cpu = n_cpu * 16 / (time.perf_counter() - t0)
    return {"device_cand_rounds_s": device, "cpu_cand_rounds_s": cpu,
            "batch": batch, "rounds": rounds}


def _route_bcrypt(oracle, batch: int):
    """(use_cpu, rates) for a bcrypt job.  DPRF_BCRYPT_ROUTE forces
    'cpu' or 'device'; 'auto' measures on the TPU backend (off-TPU the
    device path is the test vehicle and always wins vs the pure-Python
    oracle anyway)."""
    from dprf_tpu.utils.logging import DEFAULT as log

    mode = envreg.get_str("DPRF_BCRYPT_ROUTE")
    if mode == "cpu" and oracle is None:
        log.warn("DPRF_BCRYPT_ROUTE=cpu but the job has no oracle "
                 "engine; staying on the device")
        return False, None
    if mode in ("cpu", "device"):
        log.info("bcrypt device routing forced", route=mode)
        return mode == "cpu", {"forced": mode}
    if oracle is None or jax.default_backend() != "tpu":
        return False, None
    rates = measure_eks_rates(oracle, batch)
    use_cpu = rates["cpu_cand_rounds_s"] > rates["device_cand_rounds_s"]
    log.info("bcrypt routed by measurement",
             winner="cpu" if use_cpu else "device",
             device_cand_rounds_s=f"{rates['device_cand_rounds_s']:.1f}",
             cpu_cand_rounds_s=f"{rates['cpu_cand_rounds_s']:.1f}")
    return use_cpu, rates


@register("bcrypt", device="jax")
class JaxBcryptEngine(BcryptEngine):
    """Device bcrypt.  Inherits hash parsing ($2a/$2b lines) from the
    CPU engine; hash_batch runs the EksBlowfish pipeline on device.
    Worker factories measure the device vs the CPU oracle at job start
    and route to the winner (_route_bcrypt)."""

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("bcrypt needs target params (salt, cost)")
        if any(len(c) > self.max_candidate_len for c in candidates):
            raise ValueError("bcrypt: candidate longer than 72 bytes")
        B = len(candidates)
        L = max(max((len(c) for c in candidates), default=1), 1)
        buf = np.zeros((B, L), dtype=np.uint8)
        lens = np.zeros((B,), dtype=np.int32)
        for i, c in enumerate(candidates):
            buf[i, :len(c)] = np.frombuffer(c, dtype=np.uint8)
            lens[i] = len(c)
        dw = _jit_bcrypt_batch(
            jnp.asarray(buf), jnp.asarray(lens),
            jnp.asarray(bf_ops.salt_to_words(params["salt"])),
            _n_rounds(params["cost"]))
        return bf_ops.words_to_digests(np.asarray(dw))

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        batch = min(batch, DEFAULT_BATCH)
        use_cpu, _ = _route_bcrypt(oracle, batch)
        if use_cpu:
            return RoutedCpuBcryptWorker(oracle, gen, targets)
        return BcryptMaskWorker(self, gen, targets, batch=batch,
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        batch = min(batch, DEFAULT_BATCH)
        # route at the ACTUAL chunked-state batch (words x rules), not
        # the nominal one -- the advance the worker runs is built for
        # word_batch * n_rules rows
        state_batch = max(1, batch // gen.n_rules) * gen.n_rules
        use_cpu, _ = _route_bcrypt(oracle, state_batch)
        if use_cpu:
            return RoutedCpuBcryptWorker(oracle, gen, targets)
        return BcryptWordlistWorker(self, gen, targets, batch=batch,
                                    hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedBcryptMaskWorker(
            self, gen, targets, mesh,
            batch_per_device=min(batch_per_device, DEFAULT_BATCH),
            hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_wordlist_worker(self, gen, targets, mesh,
                                     word_batch_per_device: int,
                                     hit_capacity: int, oracle=None):
        return ShardedBcryptWordlistWorker(
            self, gen, targets, mesh,
            word_batch_per_device=max(1, min(word_batch_per_device,
                                             DEFAULT_BATCH // gen.n_rules)),
            hit_capacity=hit_capacity, oracle=oracle)


_jit_bcrypt_batch = jax.jit(bf_ops.bcrypt_batch)

#: per-dispatch wall budget for the chunked cost loop.  The axon tunnel
#: enforces a hard ~60 s execution deadline per dispatch (a cost-12
#: batch in ONE dispatch tripped it and poisoned the backend,
#: TPU_PROBE_LOG_r03); a 20 s budget keeps 3x headroom while the
#: ~0.4 s/dispatch tunnel RTT stays <2% overhead.
DEFAULT_DISPATCH_S = envreg.get_float("DPRF_BCRYPT_DISPATCH_S")


class ChunkedEks:
    """Drives the EksBlowfish 2**cost main loop in deadline-bounded
    dispatches, carrying the (P, S) state on device between them.

    The very first dispatch is a single untimed round that absorbs the
    advance fn's JIT compile; the next chunk is small (16 rounds) to
    calibrate seconds/round for the current (batch, impl) without
    risking the deadline; later chunks grow toward `dispatch_s`, capped
    at 8x per step so one optimistic estimate cannot jump straight past
    the deadline.  Once calibrated, a total that fits one dispatch with
    headroom is issued sync-free so consecutive batches pipeline.
    State buffers are donated to the advance dispatch, so the 4 KB/lane
    S-boxes are updated in place rather than copied each chunk.
    """

    CALIBRATE_ROUNDS = 16
    GROWTH_CAP = 8

    def __init__(self, dispatch_s: float = None, advance=None):
        """`advance(P, S, key_words, salt18, n) -> (P, S)` defaults to
        the jitted single-chip eks_rounds; the sharded workers pass
        their shard_map'd equivalent."""
        self.dispatch_s = (DEFAULT_DISPATCH_S if dispatch_s is None
                           else dispatch_s)
        self._advance = (advance if advance is not None else
                         jax.jit(bf_ops.eks_rounds, donate_argnums=(0, 1)))
        self._per_round: Optional[float] = None   # EMA, seconds/round
        # Carried across run() calls: once calibrated, later batches
        # start at the budget-sized chunk instead of re-paying the
        # 8x ramp (a few tunnel RTTs per batch, thousands of batches).
        self._last_chunk = self.CALIBRATE_ROUNDS

    def _next_chunk(self, remaining: int, last_chunk: int) -> int:
        if self._per_round is None:
            return min(remaining, self.CALIBRATE_ROUNDS)
        want = max(1, int(self.dispatch_s / self._per_round))
        return min(remaining, want, last_chunk * self.GROWTH_CAP)

    def run(self, P, S, key_words, salt18, total_rounds: int,
            on_chunk=None):
        """Advance (P, S) by `total_rounds`; returns the final state.
        `on_chunk(done, total)` is called after each dispatch (progress
        / lease-renewal hook)."""
        from dprf_tpu.utils.sync import hard_sync

        done = 0
        if self._per_round is None and done < total_rounds:
            # warm the advance fn's compile with a 1-round dispatch so
            # the first EMA sample doesn't fold seconds of JIT time
            # into seconds/round and starve the ramp (ADVICE r3)
            P, S = self._advance(P, S, key_words, salt18, jnp.int32(1))
            hard_sync(S)
            done += 1
            if on_chunk is not None:
                on_chunk(done, total_rounds)
        elif (self._per_round is not None
              and (total_rounds - done) * self._per_round
              <= 0.75 * self.dispatch_s):
            # the whole remaining chain fits one calibrated dispatch
            # with deadline headroom: issue it WITHOUT a host sync so
            # batch N+1's begin/cost-loop can overlap batch N's finish
            # (the worker's hit readback is the natural per-batch sync
            # point).  No EMA update -- nothing was measured.
            P, S = self._advance(P, S, key_words, salt18,
                                 jnp.int32(total_rounds - done))
            if on_chunk is not None:
                on_chunk(total_rounds, total_rounds)
            return P, S
        while done < total_rounds:
            chunk = self._next_chunk(total_rounds - done,
                                     self._last_chunk)
            t0 = time.perf_counter()
            P, S = self._advance(P, S, key_words, salt18,
                                 jnp.int32(chunk))
            # hard_sync, NOT block_until_ready: over the axon tunnel
            # the latter returns at enqueue (utils/sync.py), which
            # would calibrate the EMA on enqueue time and grow chunks
            # straight past the ~60 s execution deadline
            hard_sync(S)
            dt = time.perf_counter() - t0
            per = dt / chunk
            self._per_round = (per if self._per_round is None
                               else 0.5 * self._per_round + 0.5 * per)
            done += chunk
            # remaining-clamped tails must not shrink the carried ramp
            self._last_chunk = max(self._last_chunk, chunk)
            if on_chunk is not None:
                on_chunk(done, total_rounds)
        return P, S


def make_bcrypt_mask_chunk_fns(gen, batch: int, hit_capacity: int = 64):
    """Chunked-variant device functions for the mask sweep:

    begin(base_digits, salt_words) -> (key_words, P, S)
    finish(P, S, n_valid, target) -> (count, lanes, _)

    The cost loop between them runs through ChunkedEks.run, so no
    single dispatch carries the whole 2**cost chain."""
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def begin(base_digits, salt_words):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        kw = bf_ops.key_words_from_candidates(cand, lens)
        P, S = bf_ops.eks_setup_begin(kw, salt_words)
        return kw, P, S

    @jax.jit
    def finish(P, S, n_valid, target):
        dwords = bf_ops.bcrypt_digest_words(P, S)
        found = bf_ops.compare_digest_words(dwords, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return begin, finish


def make_bcrypt_wordlist_chunk_fns(gen, word_batch: int,
                                   hit_capacity: int = 64):
    """Chunked-variant device functions for the wordlist(+rules) sweep:

    begin(w0, n_valid_words, salt_words) -> (key_words, valid, P, S)
    finish(P, S, valid, target) -> (count, lanes, _)
    """
    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def begin(w0, n_valid_words, salt_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        kw = bf_ops.key_words_from_candidates(cw, cl)
        P, S = bf_ops.eks_setup_begin(kw, salt_words)
        return kw, cv, P, S

    @jax.jit
    def finish(P, S, valid, target):
        dwords = bf_ops.bcrypt_digest_words(P, S)
        found = bf_ops.compare_digest_words(dwords, target) & valid
        n = valid.shape[0]
        return cmp_ops.compact_hits(found, jnp.zeros((n,), jnp.int32),
                                    hit_capacity)

    return begin, finish


def _n_rounds(cost: int) -> jnp.ndarray:
    """2**cost as the device loop trip count.  Cost 31 (valid in the
    bcrypt format, ~2e9 rounds) would overflow the int32 loop bound --
    reject it with a pointer to the CPU path rather than wrapping to a
    zero-iteration loop that yields silent false negatives."""
    if not 4 <= cost <= 30:
        raise ValueError(
            f"bcrypt cost {cost} outside the device engine's range 4..30 "
            "(2**31 rounds exceeds the int32 loop bound; use --device=cpu)")
    return jnp.int32(1 << cost)


def _target_args(target: Target):
    """Target -> (salt_words, n_rounds, target_words) device args."""
    return (jnp.asarray(bf_ops.salt_to_words(target.params["salt"])),
            _n_rounds(target.params["cost"]),
            jnp.asarray(bf_ops.digest_to_words(target.digest)))


def make_bcrypt_mask_step(gen, batch: int, hit_capacity: int = 64):
    """step(base_digits int32[L], n_valid, salt_words uint32[4],
    n_rounds int32, target uint32[6]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, salt_words, n_rounds, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        dwords = bf_ops.bcrypt_batch(cand, lens, salt_words, n_rounds)
        found = bf_ops.compare_digest_words(dwords, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def _make_sharded_eks_advance(mesh):
    """Shard_map'd ChunkedEks advance: each chip advances its own lane
    slice of the (key_words, P, S) state; no collectives -- the chains
    are per-lane serial.  State stays sharded on device between
    dispatches."""
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map

    sharded = shard_map(
        bf_ops.eks_rounds, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)), check_vma=False)
    return jax.jit(sharded, donate_argnums=(0, 1))


def make_sharded_bcrypt_mask_chunk_fns(gen, mesh, batch_per_device: int,
                                       hit_capacity: int = 64):
    """Multi-chip chunked bcrypt mask sweep (config 4 at pod scale):
    chip c owns lane slice [c*B, (c+1)*B) of the super-batch; the cost
    loop runs through ChunkedEks with the state sharded across chips,
    so no dispatch -- single- or multi-chip -- carries the whole
    2**cost chain (the shape that trips per-dispatch deadlines).

    begin(base_digits, salt_words) -> (key_words, P, S)   [sharded]
    finish(P, S, n_valid, target) ->
        (total, counts[n_dev], lanes[n_dev, cap] super-batch-global, _)
    """
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map

    flat = gen.flat_charsets
    length = gen.length
    B = batch_per_device

    def begin_fn(base_digits, salt_words):
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * B).astype(jnp.int32)
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        lens = jnp.full((B,), length, jnp.int32)
        kw = bf_ops.key_words_from_candidates(cand, lens)
        Pst, Sst = bf_ops.eks_setup_begin(kw, salt_words)
        return kw, Pst, Sst

    begin = jax.jit(shard_map(
        begin_fn, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(SHARD_AXIS),) * 3, check_vma=False))

    def finish_fn(Pst, Sst, n_valid, target):
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * B).astype(jnp.int32)
        dwords = bf_ops.bcrypt_digest_words(Pst, Sst)
        lane_global = offset + jnp.arange(B, dtype=jnp.int32)
        found = (bf_ops.compare_digest_words(dwords, target)
                 & (lane_global < n_valid))
        count, lanes, tpos = cmp_ops.compact_hits(
            found, jnp.zeros((B,), jnp.int32), hit_capacity)
        lanes = jnp.where(lanes >= 0, lanes + offset, lanes)
        total = lax.psum(count, SHARD_AXIS)
        # replicated hit buffers (see parallel/sharded.py)
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    finish_sm = shard_map(
        finish_fn, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(), P()),
        out_specs=(P(), P(), P(), P()), check_vma=False)

    @jax.jit
    def finish(Pst, Sst, n_valid, target):
        total, counts, lanes, tpos = finish_sm(Pst, Sst, n_valid, target)
        return total[0], counts, lanes, tpos

    begin.super_batch = mesh.devices.size * B
    return begin, finish


def make_sharded_bcrypt_wordlist_chunk_fns(gen, mesh, word_batch: int,
                                           hit_capacity: int = 64):
    """Multi-chip chunked bcrypt wordlist sweep: chip c expands+hashes
    words [w0 + c*B, w0 + (c+1)*B), cost loop chunked via ChunkedEks
    (state sharded).  Lanes come back as super-batch flat indices
    r*(n_dev*B) + global word lane (the same convention as
    ops/rules_pipeline.make_sharded_wordlist_crack_step).

    begin(w0, n_valid_words, salt_words) -> (key_words, valid, P, S)
    finish(P, S, valid, target) -> (total, counts, lanes, _)
    """
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map

    n_dev = mesh.devices.size
    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(
        pad_to=n_dev * B, min_size=gen.n_words + n_dev * B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    def begin_fn(w0, n_valid_words, salt_words):
        dev = lax.axis_index(SHARD_AXIS)
        my_w0 = w0 + (dev * B).astype(jnp.int32)
        wslice = lax.dynamic_slice(words_dev, (my_w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (my_w0,), (B,))
        word_lane = (dev * B).astype(jnp.int32) + jnp.arange(
            B, dtype=jnp.int32)
        base_valid = word_lane < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        kw = bf_ops.key_words_from_candidates(cw, cl)
        Pst, Sst = bf_ops.eks_setup_begin(kw, salt_words)
        return kw, cv, Pst, Sst

    begin = jax.jit(shard_map(
        begin_fn, mesh=mesh, in_specs=(P(), P(), P()),
        out_specs=(P(SHARD_AXIS),) * 4, check_vma=False))

    def finish_fn(Pst, Sst, valid, target):
        dev = lax.axis_index(SHARD_AXIS)
        dwords = bf_ops.bcrypt_digest_words(Pst, Sst)
        found = bf_ops.compare_digest_words(dwords, target) & valid
        n = valid.shape[0]
        count, lanes, tpos = cmp_ops.compact_hits(
            found, jnp.zeros((n,), jnp.int32), hit_capacity)
        r = lanes // B
        b = lanes % B
        glanes = r * (n_dev * B) + dev * B + b
        lanes = jnp.where(lanes >= 0, glanes, lanes)
        total = lax.psum(count, SHARD_AXIS)
        # replicated hit buffers (see parallel/sharded.py)
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS))

    finish_sm = shard_map(
        finish_fn, mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P()),
        out_specs=(P(), P(), P(), P()), check_vma=False)

    @jax.jit
    def finish(Pst, Sst, valid, target):
        total, counts, lanes, tpos = finish_sm(Pst, Sst, valid, target)
        return total[0], counts, lanes, tpos

    begin.super_words = n_dev * B
    return begin, finish


class _BcryptWorkerBase:
    """Per-target keyspace sweep shared by the mask/wordlist workers."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int, hit_capacity: int, oracle):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.batch = batch
        self._targs = [_target_args(t) for t in self.targets]

    def _rescan(self, start: int, end: int, ti: int) -> list[Hit]:
        if self.oracle is None:
            raise RuntimeError(
                f"hit buffer overflow (> {self.hit_capacity}) and no "
                "oracle engine to rescan with; raise hit_capacity")
        sub = WorkUnit(-1, start, end - start)
        hits = CpuWorker(self.oracle, self.gen,
                         [self.targets[ti]]).process(sub)
        return [Hit(ti, h.cand_index, h.plaintext) for h in hits]


class BcryptMaskWorker(_BcryptWorkerBase):
    """Single-chip mask sweep, chunked: the cost loop of every batch is
    split over deadline-bounded dispatches (ChunkedEks), so a cost-12
    batch no longer rides in one hour-long dispatch -- session3 proved
    that trips the tunnel's per-dispatch execution deadline and poisons
    the backend (TPU_PROBE_LOG_r03)."""

    def __init__(self, engine, gen, targets, batch: int = DEFAULT_BATCH,
                 hit_capacity: int = 64, oracle=None,
                 dispatch_s: float = None):
        from dprf_tpu.ops.pallas_bcrypt import make_best_eks_advance

        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.stride = batch
        self.begin, self.finish = make_bcrypt_mask_chunk_fns(
            gen, batch, hit_capacity)
        self.chunker = ChunkedEks(dispatch_s,
                                  advance=make_best_eks_advance(batch))

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            salt18 = bf_ops.salt18_words(salt_w)
            total = int(n_rounds)
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
                kw, P, S = self.begin(base, salt_w)
                P, S = self.chunker.run(P, S, kw, salt18, total)
                count, lanes, _ = self.finish(P, S, jnp.int32(n_valid), tgt)
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    hits.extend(self._rescan(
                        bstart, min(bstart + self.stride, unit.end), ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


class ShardedBcryptMaskWorker(_BcryptWorkerBase):
    """Multi-chip bcrypt mask worker (keyspace DP over the mesh),
    chunked: the cost loop runs in deadline-bounded dispatches with the
    EksBlowfish state sharded across chips (see BcryptMaskWorker)."""

    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = DEFAULT_BATCH,
                 hit_capacity: int = 64, oracle=None,
                 dispatch_s: float = None):
        super().__init__(engine, gen, targets,
                         mesh.devices.size * batch_per_device,
                         hit_capacity, oracle)
        self.mesh = mesh
        self.stride = self.batch          # one super-batch per sweep
        self.begin, self.finish = make_sharded_bcrypt_mask_chunk_fns(
            gen, mesh, batch_per_device, hit_capacity)
        self.chunker = ChunkedEks(dispatch_s,
                                  advance=_make_sharded_eks_advance(mesh))

    def process(self, unit: WorkUnit) -> list[Hit]:
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            salt18 = bf_ops.salt18_words(salt_w)
            total_rounds = int(n_rounds)
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
                kw, P, S = self.begin(base, salt_w)
                P, S = self.chunker.run(P, S, kw, salt18, total_rounds)
                total, counts, lanes, _ = self.finish(
                    P, S, jnp.int32(n_valid), tgt)
                if int(total) == 0:
                    continue
                if (np.asarray(counts) > self.hit_capacity).any():
                    hits.extend(self._rescan(
                        bstart, min(bstart + self.stride, unit.end), ti))
                    continue
                for lane in np.asarray(lanes).ravel():
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


class ShardedBcryptWordlistWorker(_BcryptWorkerBase):
    """Multi-chip bcrypt wordlist worker.  Super-batch lanes follow the
    sharded wordlist convention: lane = r * super_words + word lane."""

    def __init__(self, engine, gen, targets, mesh,
                 word_batch_per_device: int = 1 << 9,
                 hit_capacity: int = 64, oracle=None,
                 dispatch_s: float = None):
        super().__init__(engine, gen, targets,
                         mesh.devices.size * word_batch_per_device
                         * gen.n_rules, hit_capacity, oracle)
        self.mesh = mesh
        self.begin, self.finish = make_sharded_bcrypt_wordlist_chunk_fns(
            gen, mesh, word_batch_per_device, hit_capacity)
        self.chunker = ChunkedEks(dispatch_s,
                                  advance=_make_sharded_eks_advance(mesh))
        self.super_words = self.begin.super_words
        self.word_batch = self.super_words
        self.stride = self.super_words * gen.n_rules

    def process(self, unit: WorkUnit) -> list[Hit]:
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            salt18 = bf_ops.salt18_words(salt_w)
            total_rounds = int(n_rounds)
            for ws in range(w_start, w_end, self.super_words):
                nw = min(self.super_words, w_end - ws,
                         self.gen.n_words - ws)
                if nw <= 0:
                    break
                kw, cv, P, S = self.begin(jnp.int32(ws), jnp.int32(nw),
                                          salt_w)
                P, S = self.chunker.run(P, S, kw, salt18, total_rounds)
                total, counts, lanes, _ = self.finish(P, S, cv, tgt)
                if int(total) == 0:
                    continue
                if (np.asarray(counts) > self.hit_capacity).any():
                    start = max(unit.start, ws * R)
                    end = min(unit.end, (ws + nw) * R)
                    hits.extend(self._rescan(start, end, ti))
                    continue
                for lane in np.asarray(lanes).ravel():
                    if lane < 0:
                        continue
                    gidx = wordlist_lane_to_gidx(int(lane), ws,
                                                 self.super_words, R)
                    if not unit.start <= gidx < unit.end:
                        continue
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


class BcryptWordlistWorker(_BcryptWorkerBase):
    """Single-chip wordlist(+rules) sweep, chunked like the mask
    worker (see BcryptMaskWorker)."""

    def __init__(self, engine, gen, targets, batch: int = DEFAULT_BATCH,
                 hit_capacity: int = 64, oracle=None,
                 dispatch_s: float = None):
        from dprf_tpu.ops.pallas_bcrypt import make_best_eks_advance

        super().__init__(engine, gen, targets, batch, hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.begin, self.finish = make_bcrypt_wordlist_chunk_fns(
            gen, self.word_batch, hit_capacity)
        # the chunked state batch is rules x words (expand_rules rows)
        self.chunker = ChunkedEks(
            dispatch_s,
            advance=make_best_eks_advance(self.word_batch * gen.n_rules))

    def process(self, unit: WorkUnit) -> list[Hit]:
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        hits: list[Hit] = []
        for ti in range(len(self.targets)):
            salt_w, n_rounds, tgt = self._targs[ti]
            salt18 = bf_ops.salt18_words(salt_w)
            total = int(n_rounds)
            for ws in range(w_start, w_end, self.word_batch):
                nw = min(self.word_batch, w_end - ws, self.gen.n_words - ws)
                if nw <= 0:
                    break
                kw, cv, P, S = self.begin(jnp.int32(ws), jnp.int32(nw),
                                          salt_w)
                P, S = self.chunker.run(P, S, kw, salt18, total)
                count, lanes, _ = self.finish(P, S, cv, tgt)
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    start = max(unit.start, ws * R)
                    end = min(unit.end, (ws + nw) * R)
                    hits.extend(self._rescan(start, end, ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = wordlist_lane_to_gidx(int(lane), ws,
                                                 self.word_batch, R)
                    if not unit.start <= gidx < unit.end:
                        continue
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True
