"""Nested (double-hash) engines: outer(hex(inner(password))).

Covers hashcat's md5(md5($p)) 2600, sha1(sha1($p)) 4500, md5(sha1($p))
4400, sha1(md5($p)) 4700, sha256(md5($p)) 20800, sha256(sha1($p))
20700.  The outer hash consumes the lowercase-hex ASCII of the inner
digest (the convention those modes define), produced on device by a
vectorized nibble->char map -- no host round trip between the stages.

Because the whole chain is expressed as `digest_packed` over the
candidate's packed words, every existing execution path -- fused mask
pipeline, Pallas-ineligible fallback, wordlist+rules, combinator,
multi-target tables, sharded workers -- drives nested engines with no
new worker code.

Inner hex lengths must fit one outer block: md5 (32 hex bytes) and
sha1 (40) do; sha256's 64-byte hex would need two-block chaining, so
it is supported as an OUTER stage only.
"""

from __future__ import annotations

import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (NESTED_COMBOS,
                                          NESTED_DIGEST_SIZE)
from dprf_tpu.engines.device.engines import JaxEngineBase
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.md5 import md5_digest_words
from dprf_tpu.ops.sha1 import sha1_digest_words
from dprf_tpu.ops.sha256 import sha256_digest_words


def words_to_hex_bytes(words: jnp.ndarray,
                       little_endian: bool) -> jnp.ndarray:
    """Digest words uint32[B, W] -> lowercase hex uint8[B, 8W] in the
    digest's canonical byte order."""
    shifts = (0, 8, 16, 24) if little_endian else (24, 16, 8, 0)
    byts = jnp.stack([(words >> jnp.uint32(s)) & jnp.uint32(0xFF)
                      for s in shifts], axis=-1)
    byts = byts.reshape(words.shape[0], -1)          # [B, 4W]
    nibbles = jnp.stack([byts >> jnp.uint32(4),
                         byts & jnp.uint32(0xF)], axis=-1)
    nibbles = nibbles.reshape(words.shape[0], -1)    # [B, 8W]
    return (nibbles + jnp.where(nibbles < 10, jnp.uint32(ord("0")),
                                jnp.uint32(ord("a") - 10))
            ).astype(jnp.uint8)


_STAGES = {
    # algo -> (digest fn, words, little_endian)
    "md5": (md5_digest_words, 4, True),
    "sha1": (sha1_digest_words, 5, False),
    "sha256": (sha256_digest_words, 8, False),
}



class _NestedDeviceMixin(JaxEngineBase):
    _outer: str
    _inner: str

    # Candidate blocks feed the INNER hash, so packing follows the
    # inner algorithm's endianness; the class's little_endian attr
    # stays the OUTER digest layout (it drives target-table compare).

    def pack(self, cand: jnp.ndarray, length: int) -> jnp.ndarray:
        return pack_ops.pack_fixed(
            cand, length, big_endian=not _STAGES[self._inner][2])

    def pack_varlen(self, cand: jnp.ndarray,
                    lengths: jnp.ndarray) -> jnp.ndarray:
        return pack_ops.pack_varlen(
            cand, lengths, big_endian=not _STAGES[self._inner][2])

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        inner_fn, _, inner_le = _STAGES[self._inner]
        outer_fn, _, _ = _STAGES[self._outer]
        hexb = words_to_hex_bytes(inner_fn(blocks), inner_le)
        words2 = pack_ops.pack_fixed(hexb, 2 * NESTED_DIGEST_SIZE[self._inner],
                                     big_endian=not _STAGES[
                                         self._outer][2])
        return outer_fn(words2)


for outer, inner in NESTED_COMBOS:
    name = f"{outer}({inner})"
    cls = type(f"Jax{outer.title()}Of{inner.title()}Engine",
               (_NestedDeviceMixin,),
               {"name": name,
                "digest_size": NESTED_DIGEST_SIZE[outer],
                "digest_words": _STAGES[outer][1],
                "little_endian": _STAGES[outer][2],
                "__doc__": (f"Nested {outer}(hex({inner}(password))), "
                            "fused on device."),
                "_outer": outer, "_inner": inner})
    register(name, device="jax")(cls)


@register("mysql41", device="jax")
class JaxMysql41Engine(JaxEngineBase):
    """MySQL 4.1+ PASSWORD(): sha1(sha1(password)) over the RAW inner
    digest (no hex stage; hashcat 300).  Target lines are '*' + 40
    uppercase hex chars.

    Composition is free on device: SHA-1's big-endian digest words ARE
    the big-endian message words of the outer block, so the second
    stage is five word copies plus the padding constants.
    """

    name = "mysql41"
    digest_size = 20
    digest_words = 5
    little_endian = False

    def parse_target(self, text: str):
        from dprf_tpu.engines.cpu.engines import parse_mysql41
        return parse_mysql41(text)

    def digest_packed(self, blocks: jnp.ndarray,
                      lengths=None) -> jnp.ndarray:
        inner = sha1_digest_words(blocks)          # uint32[B, 5] BE
        B = inner.shape[0]
        block2 = jnp.zeros((B, 16), jnp.uint32)
        block2 = block2.at[:, :5].set(inner)
        block2 = block2.at[:, 5].set(jnp.uint32(0x80000000))
        block2 = block2.at[:, 15].set(jnp.uint32(160))   # 20 bytes
        return sha1_digest_words(block2)
