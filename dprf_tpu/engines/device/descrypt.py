"""Device descrypt engine (traditional crypt(3); hashcat 1500):
bitslice DES, like LM, but with crypt's two twists --

- the salt perturbs the E expansion.  In bitslice form E is a static
  row-take, so each DISTINCT salt is free re-wiring at trace time: the
  step groups targets by salt and unrolls one 25x16-round circuit per
  distinct salt, with every same-salt target folded into that circuit's
  compare at 64 ops apiece (the LM multi-target shape).  One compiled
  step, one keyspace sweep, serves the whole hashlist -- descrypt has
  only 4096 salts, so shadow files collide constantly.
- 25 chained encryptions of the zero block: the end-of-encryption
  half-swap feeds the next iteration (FP o IP cancels between
  iterations), so each circuit is one nested fori_loop over the single
  traced round body.

Key material is (password byte << 1) per crypt(3); candidates cap at
8 bytes so every reported plaintext hashes to the target exactly
(crypt's silent truncation never manufactures 'extra' cracks).
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import DescryptEngine
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.des import descrypt_bitslice
from dprf_tpu.engines.device.lm import (byte_planes, found_lanes,
                                        match_mask, target_bits)
from dprf_tpu.runtime.worker import (DeviceWordlistWorker,
                                     MaskWorkerBase)


def _key_bytes(cand: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, L<=8] candidate bytes -> uint8[B, 8] DES key bytes
    ((c << 1) & 0xFF, zero-padded)."""
    B, L = cand.shape
    key = jnp.zeros((B, 8), jnp.uint8)
    return key.at[:, :min(L, 8)].set(
        jnp.left_shift(cand[:, :8], 1))


#: distinct salts folded into ONE jitted step.  Each salt unrolls a
#: full 25x16-round bitslice circuit into the program, so XLA program
#: size and compile time grow linearly with salts-per-step; 8 keeps a
#: step's compile in the tens of seconds.  Workers build one step per
#: block of salts and sweep them in sequence (ADVICE r3).
MAX_SALTS_PER_STEP = 8

#: hard cap on distinct salts per job.  descrypt has 4096 possible
#: salts; a hashlist using hundreds means hundreds of compiled
#: circuits -- hours of compile for a sweep the CPU oracle finishes
#: faster.  Fail with direction instead of hanging.
MAX_DISTINCT_SALTS = 256


def _salt_groups(targets: Sequence[Target]):
    """[(salt, [(orig_ti, target_bits), ...]), ...] -- one bitslice
    circuit per distinct salt, all its targets folded into the
    compare."""
    groups: dict[int, list] = {}
    for ti, t in enumerate(targets):
        groups.setdefault(t.params["salt"], []).append(
            (ti, target_bits(t.digest)))
    if len(groups) > MAX_DISTINCT_SALTS:
        raise ValueError(
            f"descrypt hashlist has {len(groups)} distinct salts; the "
            f"device engine caps at {MAX_DISTINCT_SALTS} (each salt "
            "compiles a full bitslice circuit) -- split the hashlist "
            "or use --device=cpu")
    return sorted(groups.items())


def _salt_blocks(groups):
    """Split salt groups into blocks of MAX_SALTS_PER_STEP for one
    compiled step each."""
    return [groups[i:i + MAX_SALTS_PER_STEP]
            for i in range(0, len(groups), MAX_SALTS_PER_STEP)] or [[]]


def _block_tis(block) -> list:
    """Original target indices covered by one salt block."""
    return sorted({ti for _, members in block for ti, _ in members})


def _scoped_rescan(worker, tis, start: int, end: int) -> list:
    """Exact host rescan over ONLY the given targets, with hit target
    indices mapped back to the worker's original list."""
    from dprf_tpu.runtime.worker import CpuWorker

    if worker.oracle is None:
        raise RuntimeError(
            f"hit buffer overflow (> {worker.hit_capacity}) and no "
            "oracle engine to rescan with; raise hit_capacity")
    from dprf_tpu.runtime.workunit import WorkUnit as WU
    sub = WU(-1, start, end - start)
    hits = CpuWorker(worker.oracle, worker.gen,
                     [worker.targets[i] for i in tis]).process(sub)
    from dprf_tpu.runtime.worker import Hit as HitRec
    return [HitRec(tis[h.target_index], h.cand_index, h.plaintext)
            for h in hits]


def _fold_groups(kplanes, groups, n_lanes: int):
    """Run one circuit per salt group over the shared key planes and
    fold every target's compare; returns (found_any, tfirst) with
    tfirst carrying ORIGINAL target indices."""
    found_any = jnp.zeros((n_lanes,), jnp.bool_)
    tfirst = jnp.zeros((n_lanes,), jnp.int32)
    for salt, members in groups:
        cipher = descrypt_bitslice(kplanes, salt)
        for ti, tb in members:
            f = found_lanes(match_mask(cipher, tb), n_lanes)
            tfirst = jnp.where(f & ~found_any, jnp.int32(ti), tfirst)
            found_any = found_any | f
    return found_any, tfirst


def _make_mask_step_grouped(gen, groups, batch: int,
                            hit_capacity: int = 64):
    """One compiled step over ONE block of salt groups (<=
    MAX_SALTS_PER_STEP circuits); tpos carries ORIGINAL target
    indices (the LM step contract)."""
    if batch % 32:
        raise ValueError("bitslice batch must be a multiple of 32")
    if gen.length > 8:
        raise ValueError(f"descrypt candidates cap at 8 bytes; mask "
                         f"decodes to {gen.length}")
    flat = gen.flat_charsets

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        kplanes = byte_planes(_key_bytes(cand))
        found_any, tfirst = _fold_groups(kplanes, groups, batch)
        valid = jnp.arange(batch, dtype=jnp.int32) < n_valid
        return cmp_ops.compact_hits(found_any & valid, tfirst,
                                    hit_capacity)

    return step


def make_descrypt_mask_step(gen, targets: Sequence[Target], batch: int,
                            hit_capacity: int = 64):
    """Single-step factory (all salts in one program): only valid up
    to MAX_SALTS_PER_STEP distinct salts -- the workers block larger
    hashlists across several steps."""
    groups = _salt_groups(targets)
    if len(groups) > MAX_SALTS_PER_STEP:
        raise ValueError(
            f"{len(groups)} distinct salts exceed one step's "
            f"{MAX_SALTS_PER_STEP}-circuit budget; use the worker "
            "(it sweeps blocked steps)")
    return _make_mask_step_grouped(gen, groups, batch, hit_capacity)


def _make_wordlist_step_grouped(gen, groups, word_batch: int,
                                hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    if L > 8:
        raise ValueError("descrypt candidates cap at 8 bytes; set "
                         "--max-len 8")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        RB = cw.shape[0]
        pad = (-RB) % 32
        pos = jnp.arange(cw.shape[1], dtype=jnp.int32)
        cw = jnp.where(pos[None, :] < cl[:, None], cw, 0)
        cw = jnp.pad(cw, ((0, pad), (0, 0)))
        kplanes = byte_planes(_key_bytes(cw))
        found_any, tfirst = _fold_groups(kplanes, groups, RB + pad)
        found = found_any[:RB] & cv
        return cmp_ops.compact_hits(found, tfirst[:RB], hit_capacity)

    return step


def make_descrypt_wordlist_step(gen, targets: Sequence[Target],
                                word_batch: int, hit_capacity: int = 64):
    """Single-step factory; see make_descrypt_mask_step."""
    groups = _salt_groups(targets)
    if len(groups) > MAX_SALTS_PER_STEP:
        raise ValueError(
            f"{len(groups)} distinct salts exceed one step's "
            f"{MAX_SALTS_PER_STEP}-circuit budget; use the worker "
            "(it sweeps blocked steps)")
    return _make_wordlist_step_grouped(gen, groups, word_batch,
                                       hit_capacity)


class DescryptMaskWorker(MaskWorkerBase):
    """The LM worker shape -- tpos carries original target indices --
    except the hashlist's distinct salts are BLOCKED into steps of
    MAX_SALTS_PER_STEP circuits each, swept in sequence per unit, so
    a many-salt shadow file bounds each program's size/compile time
    instead of unrolling everything into one (ADVICE r3)."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 17,
                 hit_capacity: int = 64, oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.multi = len(self.targets) > 1
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        batch = max(32, (batch // 32) * 32)
        self.batch = self.stride = batch
        blocks = _salt_blocks(_salt_groups(self.targets))
        self._steps = [
            _make_mask_step_grouped(gen, block, batch, hit_capacity)
            for block in blocks]
        self._step_tis = [_block_tis(block) for block in blocks]
        self.step = self._steps[0]
        self._current_tis = self._step_tis[0]

    def warmup(self) -> None:
        for step in self._steps:
            self.step = step
            super().warmup()

    def process(self, unit):
        hits = []
        for step, tis in zip(self._steps, self._step_tis):
            self.step = step
            self._current_tis = tis
            hits.extend(super().process(unit))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True

    def _rescan(self, bstart, unit, window: int = 0):
        # scope the exact rescan to THIS block's targets: the base
        # rescan covers self.targets wholesale, which would double-
        # report other blocks' hits (their own sweeps find them too)
        return _scoped_rescan(self, self._current_tis, bstart,
                              min(bstart + (window or self.stride),
                                  unit.end))


class DescryptWordlistWorker(DeviceWordlistWorker):
    """DeviceWordlistWorker's machinery over the salt-grouped step
    (skips _setup_targets -- tpos already carries original indices)."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 17,
                 hit_capacity: int = 64, oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.multi = len(self.targets) > 1
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.batch = batch
        blocks = _salt_blocks(_salt_groups(self.targets))
        self._steps = [
            _make_wordlist_step_grouped(gen, block, self.word_batch,
                                        hit_capacity)
            for block in blocks]
        self._step_tis = [_block_tis(block) for block in blocks]
        self.step = self._steps[0]
        self._current_tis = self._step_tis[0]

    def warmup(self) -> None:
        from dprf_tpu.utils.sync import hard_sync
        for step in self._steps:
            hard_sync(step(jnp.int32(0), jnp.int32(0)))

    def process(self, unit):
        hits = []
        for step, tis in zip(self._steps, self._step_tis):
            self.step = step
            self._current_tis = tis
            hits.extend(super().process(unit))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True

    def _rescan_words(self, ws, nw, unit):
        # block-scoped exact rescan; see DescryptMaskWorker._rescan
        R = self.gen.n_rules
        start = max(unit.start, ws * R)
        end = min(unit.end, (ws + nw) * R)
        return _scoped_rescan(self, self._current_tis, start, end)


@register("descrypt", device="jax")
@register("des-crypt", device="jax")
@register("unix-crypt", device="jax")
class JaxDescryptEngine(DescryptEngine):
    """Device descrypt (see module docstring).  Parsing and the oracle
    come from the CPU engine."""

    little_endian = False
    digest_words = 2

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return DescryptMaskWorker(self, gen, targets, batch=batch,
                                  hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return DescryptWordlistWorker(self, gen, targets, batch=batch,
                                      hit_capacity=hit_capacity,
                                      oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
