"""Device MS Office 2007 engine (hashcat 9400).

Per candidate: 50,002 chained SHA-1 compressions on the word pipeline
(a lax.fori_loop -- the same iterated-KDF shape as PMKID), the
MS-OFFCRYPTO X1 key derivation, then a gather-based AES-128 decrypt of
the verifier blocks (ops/aes.py).  The AES gathers cost ~3% of the
SHA-1 spin, so the measured per-lane gather serialization that rules
out gather-heavy ciphers as hot loops is irrelevant here.  Salt and
verifier blocks are per-target trace-time constants (the JWT
per-target-step pattern).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Office2007Engine
from dprf_tpu.engines.device.salted import (PerTargetStepsMixin,
                                            SaltedMaskWorker,
                                            SaltedWordlistWorker,
                                            per_target_setup)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.aes import aes128_decrypt_blocks
from dprf_tpu.ops.scrypt import bswap32
from dprf_tpu.ops.sha1 import INIT as SHA1_INIT, sha1_compress


def _sha1_of_24(state_words, first_word):
    """SHA-1 of a 24-byte message (4-byte prefix + 20-byte digest):
    one compression on a padded block."""
    B = state_words.shape[0]
    m = jnp.zeros((B, 16), jnp.uint32)
    m = m.at[:, 0].set(first_word)
    m = m.at[:, 1:6].set(state_words)
    m = m.at[:, 6].set(jnp.uint32(0x80000000))
    m = m.at[:, 15].set(jnp.uint32(24 * 8))
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    return sha1_compress(init, m)


def office2007_key_words(cand: jnp.ndarray, lengths: jnp.ndarray,
                         salt: bytes, spin_count: int) -> jnp.ndarray:
    """Candidates uint8[B, L] -> AES key bytes uint8[B, 16] via the
    MS-OFFCRYPTO standard-encryption derivation."""
    B = cand.shape[0]
    wide = pack_ops.utf16le_widen(cand)
    # H0 = SHA1(salt || UTF16LE(pw)): salt is a 16-byte constant
    # prefix, so pack the widened password after it in one block
    width = 16 + wide.shape[1]
    buf = jnp.zeros((B, width), jnp.uint8)
    buf = buf.at[:, :16].set(jnp.broadcast_to(
        jnp.asarray(np.frombuffer(salt, np.uint8)), (B, 16)))
    buf = buf.at[:, 16:].set(wide)
    words = pack_ops.pack_varlen(buf, lengths * 2 + 16, big_endian=True)
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    h = sha1_compress(init, words)

    def body(i, h):
        # LE32(i) occupies the first 4 message bytes; as a big-endian
        # packed word that is bswap32(i)
        return _sha1_of_24(h, bswap32(jnp.uint32(i)))

    h = lax.fori_loop(0, spin_count, body, h)
    # Hfinal = SHA1(H || LE32(0))
    m = jnp.zeros((B, 16), jnp.uint32)
    m = m.at[:, 0:5].set(h)
    m = m.at[:, 6].set(jnp.uint32(0x80000000))   # marker at byte 24
    m = m.at[:, 15].set(jnp.uint32(24 * 8))
    hfinal = sha1_compress(init, m)
    # X1 = SHA1(0x36*64 with Hfinal xored into the first 20 bytes):
    # a full first block then a constant pad block
    pad36 = jnp.uint32(0x36363636)
    blk1 = jnp.full((B, 16), pad36, jnp.uint32)
    blk1 = blk1.at[:, 0:5].set(hfinal ^ pad36)
    state = sha1_compress(init, blk1)
    blk2 = np.zeros(16, np.uint32)
    blk2[0] = 0x80000000
    blk2[15] = 64 * 8
    x1 = sha1_compress(state, jnp.broadcast_to(jnp.asarray(blk2),
                                               (B, 16)))
    # first 16 key bytes from the big-endian X1 words
    key = jnp.zeros((B, 16), jnp.uint8)
    for j in range(16):
        key = key.at[:, j].set(
            (x1[:, j // 4] >> jnp.uint32(24 - 8 * (j % 4)))
            .astype(jnp.uint8))
    return key


def _office_found(cand, lengths, target, spin_count):
    salt = target.params["salt"]
    ev = target.params["verifier"]
    evh = target.params["verifier_hash"]
    blocks = np.stack([
        np.frombuffer(ev, np.uint8),
        np.frombuffer(evh[:16], np.uint8),
        np.frombuffer(evh[16:], np.uint8)])
    key = office2007_key_words(cand, lengths, salt, spin_count)
    plain = aes128_decrypt_blocks(key, blocks)
    verifier = plain[:, 0]                        # [B, 16]
    vhash = plain[:, 1:3].reshape(-1, 32)
    # SHA1(verifier): 16-byte message
    B = cand.shape[0]
    words = pack_ops.pack_fixed(verifier, 16, big_endian=True)
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    vh_words = sha1_compress(init, words)
    # decrypted hash bytes -> 5 big-endian words
    want = jnp.zeros((B, 5), jnp.uint32)
    for w in range(5):
        acc = jnp.zeros((B,), jnp.uint32)
        for b in range(4):
            acc = (acc << jnp.uint32(8)) | \
                vhash[:, 4 * w + b].astype(jnp.uint32)
        want = want.at[:, w].set(acc)
    return jnp.all(vh_words == want, axis=-1)


def make_office_mask_step(gen, target, batch: int, spin_count: int,
                          hit_capacity: int = 64):
    """Per-target step: step(base_digits, n_valid) -> (count, lanes, _)."""
    if gen.length > 19:
        raise ValueError(
            f"office2007 passwords cap at 19 chars (salt + UTF-16LE in "
            f"one SHA-1 block); mask decodes to {gen.length}")
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        found = _office_found(cand, lengths, target, spin_count)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_office_wordlist_step(gen, target, word_batch: int,
                              spin_count: int, hit_capacity: int = 64):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    if L > 19:
        raise ValueError("office2007 passwords cap at 19 chars; lower "
                         "--max-len")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        # pack_varlen masks bytes at positions >= length, so rule-edit
        # garbage beyond cl never reaches the hash
        found = _office_found(cw, cl, target, spin_count) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class OfficeMaskWorker(PerTargetStepsMixin, SaltedMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        per_target_setup(self, engine, gen, targets, batch,
                         hit_capacity, oracle)
        self.stride = batch
        self._steps = [
            make_office_mask_step(gen, t, batch, engine.spin_count,
                                  hit_capacity)
            for t in self.targets]


class OfficeWordlistWorker(PerTargetStepsMixin, SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        per_target_setup(self, engine, gen, targets, batch,
                         hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._steps = [
            make_office_wordlist_step(gen, t, self.word_batch,
                                      engine.spin_count, hit_capacity)
            for t in self.targets]


@register("office2007", device="jax")
@register("office", device="jax")
class JaxOffice2007Engine(Office2007Engine):
    """Device Office 2007: the SHA-1 spin on the word pipeline, AES
    verifier check via gather tables."""

    little_endian = False
    digest_words = 1

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        # 50k compressions/candidate: cap the batch like PMKID does
        return OfficeMaskWorker(self, gen, targets,
                                batch=min(batch, 1 << 13),
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return OfficeWordlistWorker(self, gen, targets,
                                    batch=min(batch, 1 << 13),
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
