"""Device MS Office 2007 engine (hashcat 9400).

Per candidate: 50,002 chained SHA-1 compressions on the word pipeline
(a lax.fori_loop -- the same iterated-KDF shape as PMKID), the
MS-OFFCRYPTO X1 key derivation, then a gather-based AES-128 decrypt of
the verifier blocks (ops/aes.py).  The AES gathers cost ~3% of the
SHA-1 spin, so the measured per-lane gather serialization that rules
out gather-heavy ciphers as hot loops is irrelevant here.  Salt and
verifier blocks are per-target trace-time constants (the JWT
per-target-step pattern).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (Office2007Engine,
                                          Office2010Engine,
                                          Office2013Engine)
from dprf_tpu.engines.device.salted import (PerTargetStepsMixin,
                                            SaltedMaskWorker,
                                            SaltedWordlistWorker,
                                            per_target_setup)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.aes import aes128_decrypt_blocks
from dprf_tpu.ops.scrypt import bswap32
from dprf_tpu.ops.sha1 import INIT as SHA1_INIT, sha1_compress


def _sha1_of_24(state_words, first_word):
    """SHA-1 of a 24-byte message (4-byte prefix + 20-byte digest):
    one compression on a padded block."""
    B = state_words.shape[0]
    m = jnp.zeros((B, 16), jnp.uint32)
    m = m.at[:, 0].set(first_word)
    m = m.at[:, 1:6].set(state_words)
    m = m.at[:, 6].set(jnp.uint32(0x80000000))
    m = m.at[:, 15].set(jnp.uint32(24 * 8))
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    return sha1_compress(init, m)


def _salted_pw_buf(cand, lengths, salt: bytes):
    """(salt || UTF16LE(pw)) byte buffer + lengths for H0."""
    B = cand.shape[0]
    wide = pack_ops.utf16le_widen(cand)
    buf = jnp.zeros((B, 16 + wide.shape[1]), jnp.uint8)
    buf = buf.at[:, :16].set(jnp.broadcast_to(
        jnp.asarray(np.frombuffer(salt, np.uint8)), (B, 16)))
    buf = buf.at[:, 16:].set(wide)
    return buf, lengths * 2 + 16


def sha1_spin(cand, lengths, salt: bytes, spin_count: int):
    """H0 = SHA1(salt||UTF16LE(pw)); H_i = SHA1(LE32(i)||H): the
    iterated core shared by 2007 standard and 2010 agile encryption."""
    B = cand.shape[0]
    buf, blens = _salted_pw_buf(cand, lengths, salt)
    words = pack_ops.pack_varlen(buf, blens, big_endian=True)
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    h = sha1_compress(init, words)

    def body(i, h):
        # LE32(i) occupies the first 4 message bytes; as a big-endian
        # packed word that is bswap32(i)
        return _sha1_of_24(h, bswap32(jnp.uint32(i)))

    return lax.fori_loop(0, spin_count, body, h)


def _key_bytes(words, n: int):
    """Big-endian digest words -> first n key bytes uint8[B, n]."""
    B = words.shape[0]
    key = jnp.zeros((B, n), jnp.uint8)
    for j in range(n):
        key = key.at[:, j].set(
            (words[:, j // 4] >> jnp.uint32(24 - 8 * (j % 4)))
            .astype(jnp.uint8))
    return key


def office2007_key_words(cand: jnp.ndarray, lengths: jnp.ndarray,
                         salt: bytes, spin_count: int) -> jnp.ndarray:
    """Candidates uint8[B, L] -> AES key bytes uint8[B, 16] via the
    MS-OFFCRYPTO standard-encryption derivation."""
    B = cand.shape[0]
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    h = sha1_spin(cand, lengths, salt, spin_count)
    # Hfinal = SHA1(H || LE32(0))
    m = jnp.zeros((B, 16), jnp.uint32)
    m = m.at[:, 0:5].set(h)
    m = m.at[:, 6].set(jnp.uint32(0x80000000))   # marker at byte 24
    m = m.at[:, 15].set(jnp.uint32(24 * 8))
    hfinal = sha1_compress(init, m)
    # X1 = SHA1(0x36*64 with Hfinal xored into the first 20 bytes):
    # a full first block then a constant pad block
    pad36 = jnp.uint32(0x36363636)
    blk1 = jnp.full((B, 16), pad36, jnp.uint32)
    blk1 = blk1.at[:, 0:5].set(hfinal ^ pad36)
    state = sha1_compress(init, blk1)
    blk2 = np.zeros(16, np.uint32)
    blk2[0] = 0x80000000
    blk2[15] = 64 * 8
    x1 = sha1_compress(state, jnp.broadcast_to(jnp.asarray(blk2),
                                               (B, 16)))
    return _key_bytes(x1, 16)


def _office_found(cand, lengths, target, spin_count):
    salt = target.params["salt"]
    ev = target.params["verifier"]
    evh = target.params["verifier_hash"]
    blocks = np.stack([
        np.frombuffer(ev, np.uint8),
        np.frombuffer(evh[:16], np.uint8),
        np.frombuffer(evh[16:], np.uint8)])
    key = office2007_key_words(cand, lengths, salt, spin_count)
    plain = aes128_decrypt_blocks(key, blocks)
    verifier = plain[:, 0]                        # [B, 16]
    vhash = plain[:, 1:3].reshape(-1, 32)
    # SHA1(verifier): 16-byte message
    B = cand.shape[0]
    words = pack_ops.pack_fixed(verifier, 16, big_endian=True)
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    vh_words = sha1_compress(init, words)
    # decrypted hash bytes -> 5 big-endian words
    want = jnp.zeros((B, 5), jnp.uint32)
    for w in range(5):
        acc = jnp.zeros((B,), jnp.uint32)
        for b in range(4):
            acc = (acc << jnp.uint32(8)) | \
                vhash[:, 4 * w + b].astype(jnp.uint32)
        want = want.at[:, w].set(acc)
    return jnp.all(vh_words == want, axis=-1)


# -- agile encryption (2010: SHA-1/AES-128; 2013: SHA-512/AES-256) ----------

def _sha1_agile_final(h, block_key: bytes):
    """SHA1(H || BK8): a 28-byte message, one compression."""
    B = h.shape[0]
    bk = np.frombuffer(block_key, ">u4").astype(np.uint32)
    m = jnp.zeros((B, 16), jnp.uint32)
    m = m.at[:, 0:5].set(h)
    m = m.at[:, 5].set(jnp.uint32(int(bk[0])))
    m = m.at[:, 6].set(jnp.uint32(int(bk[1])))
    m = m.at[:, 7].set(jnp.uint32(0x80000000))
    m = m.at[:, 15].set(jnp.uint32(28 * 8))
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    return sha1_compress(init, m)


def sha512_spin(cand, lengths, salt: bytes, spin_count: int):
    """The SHA-512 agile spin (Office 2013): 68-byte chain messages,
    one 128-byte block each; 64-bit words ride the uint32-pair core."""
    from dprf_tpu.ops.sha512 import sha512_digest_words

    B = cand.shape[0]
    buf, blens = _salted_pw_buf(cand, lengths, salt)
    h = sha512_digest_words(
        pack_ops.pack_varlen_wide(buf, blens))       # uint32[B, 16]

    def body(i, h):
        m = jnp.zeros((B, 32), jnp.uint32)
        m = m.at[:, 0].set(bswap32(jnp.uint32(i)))   # LE32(i) bytes 0-3
        m = m.at[:, 1:17].set(h)                     # digest at byte 4
        m = m.at[:, 17].set(jnp.uint32(0x80000000))
        m = m.at[:, 31].set(jnp.uint32(68 * 8))
        return sha512_digest_words(m)

    return lax.fori_loop(0, spin_count, body, h)


def _sha512_agile_final(h, block_key: bytes):
    """SHA512(H64 || BK8): a 72-byte message, one block."""
    from dprf_tpu.ops.sha512 import sha512_digest_words

    B = h.shape[0]
    bk = np.frombuffer(block_key, ">u4").astype(np.uint32)
    m = jnp.zeros((B, 32), jnp.uint32)
    m = m.at[:, 0:16].set(h)
    m = m.at[:, 16].set(jnp.uint32(int(bk[0])))
    m = m.at[:, 17].set(jnp.uint32(int(bk[1])))
    m = m.at[:, 18].set(jnp.uint32(0x80000000))
    m = m.at[:, 31].set(jnp.uint32(72 * 8))
    return sha512_digest_words(m)


def _agile_found(cand, lengths, target, spin_count: int, sha512: bool):
    from dprf_tpu.engines.cpu.engines import (OFFICE_BK_INPUT,
                                              OFFICE_BK_VALUE)
    from dprf_tpu.ops.aes import aes_decrypt_blocks
    from dprf_tpu.ops.sha512 import sha512_digest_words

    salt = target.params["salt"]
    ev = target.params["verifier"]
    evh = target.params["verifier_hash"]
    keylen = 32 if sha512 else 16
    B = cand.shape[0]
    if sha512:
        h = sha512_spin(cand, lengths, salt, spin_count)
        ki = _key_bytes(_sha512_agile_final(h, OFFICE_BK_INPUT), keylen)
        kv = _key_bytes(_sha512_agile_final(h, OFFICE_BK_VALUE), keylen)
    else:
        h = sha1_spin(cand, lengths, salt, spin_count)
        ki = _key_bytes(_sha1_agile_final(h, OFFICE_BK_INPUT), keylen)
        kv = _key_bytes(_sha1_agile_final(h, OFFICE_BK_VALUE), keylen)
    saltv = jnp.asarray(np.frombuffer(salt, np.uint8))
    inp = aes_decrypt_blocks(ki, np.frombuffer(ev, np.uint8)
                             .reshape(1, 16))[:, 0] ^ saltv
    vblocks = np.stack([np.frombuffer(evh[:16], np.uint8),
                        np.frombuffer(evh[16:], np.uint8)])
    val = aes_decrypt_blocks(kv, vblocks)
    v1 = val[:, 0] ^ saltv
    v2 = val[:, 1] ^ jnp.asarray(np.frombuffer(evh[:16], np.uint8))
    # H(decrypted input), compared over min(32, hash size) bytes
    if sha512:
        dwords = sha512_digest_words(pack_ops.pack_fixed_wide(inp, 16))
        n = 32
    else:
        init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
        dwords = sha1_compress(
            init, pack_ops.pack_fixed(inp, 16, big_endian=True))
        n = 20
    dbytes = _key_bytes(dwords, n)
    vbytes = jnp.concatenate([v1, v2], axis=1)[:, :n]
    return jnp.all(dbytes == vbytes, axis=-1)


def _make_mask_step(gen, batch: int, cap: int, found_fn,
                    hit_capacity: int = 64):
    """Shared office step shape: found_fn(cand, lengths) -> bool[B]."""
    if gen.length > cap:
        raise ValueError(
            f"office passwords cap at {cap} chars (salt + UTF-16LE in "
            f"one hash block); mask decodes to {gen.length}")
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        found = found_fn(cand, lengths)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def _make_wordlist_step(gen, word_batch: int, cap: int, found_fn,
                        hit_capacity: int = 64):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    if L > cap:
        raise ValueError(f"office passwords cap at {cap} chars; lower "
                         "--max-len")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        # pack_varlen masks bytes at positions >= length, so rule-edit
        # garbage beyond cl never reaches the hash
        found = found_fn(cw, cl) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def make_office_mask_step(gen, target, batch: int, spin_count: int,
                          hit_capacity: int = 64):
    return _make_mask_step(
        gen, batch, 19,
        lambda c, l: _office_found(c, l, target, spin_count),
        hit_capacity)


def make_office_wordlist_step(gen, target, word_batch: int,
                              spin_count: int, hit_capacity: int = 64):
    return _make_wordlist_step(
        gen, word_batch, 19,
        lambda c, l: _office_found(c, l, target, spin_count),
        hit_capacity)


def make_agile_mask_step(gen, target, batch: int, sha512: bool,
                         hit_capacity: int = 64):
    return _make_mask_step(
        gen, batch, 47 if sha512 else 19,
        lambda c, l: _agile_found(c, l, target, target.params["spin"],
                                  sha512),
        hit_capacity)


def make_agile_wordlist_step(gen, target, word_batch: int, sha512: bool,
                             hit_capacity: int = 64):
    return _make_wordlist_step(
        gen, word_batch, 47 if sha512 else 19,
        lambda c, l: _agile_found(c, l, target, target.params["spin"],
                                  sha512),
        hit_capacity)


class OfficeMaskWorker(PerTargetStepsMixin, SaltedMaskWorker):
    """Per-target compiled steps from a pluggable factory(gen, target,
    batch, hit_capacity) -- shared by the standard and agile engines."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None,
                 step_factory=None):
        per_target_setup(self, engine, gen, targets, batch,
                         hit_capacity, oracle)
        self.stride = batch
        self._steps = [step_factory(gen, t, batch, hit_capacity)
                       for t in self.targets]


class OfficeWordlistWorker(PerTargetStepsMixin, SaltedWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None,
                 step_factory=None):
        per_target_setup(self, engine, gen, targets, batch,
                         hit_capacity, oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._steps = [step_factory(gen, t, self.word_batch,
                                    hit_capacity)
                       for t in self.targets]


class _OfficeDeviceMixin:
    """Worker factories over the shared per-target-step office workers;
    subclasses provide the step factories (the 50k-compression spin
    caps the batch like PMKID's)."""

    little_endian = False
    digest_words = 1

    def _mask_factory(self, gen, t, batch, cap):
        raise NotImplementedError

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return OfficeMaskWorker(self, gen, targets,
                                batch=min(batch, 1 << 13),
                                hit_capacity=hit_capacity, oracle=oracle,
                                step_factory=self._mask_factory)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return OfficeWordlistWorker(self, gen, targets,
                                    batch=min(batch, 1 << 13),
                                    hit_capacity=hit_capacity,
                                    oracle=oracle,
                                    step_factory=self._wordlist_factory)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None


class _AgileDeviceMixin(_OfficeDeviceMixin):
    _sha512: bool

    def _mask_factory(self, gen, t, batch, cap):
        return make_agile_mask_step(gen, t, batch, self._sha512, cap)

    def _wordlist_factory(self, gen, t, wb, cap):
        return make_agile_wordlist_step(gen, t, wb, self._sha512, cap)


@register("office2010", device="jax")
class JaxOffice2010Engine(_AgileDeviceMixin, Office2010Engine):
    """Device Office 2010 agile: SHA-1 spin + AES-128 CBC verifier."""

    _sha512 = False


@register("office2013", device="jax")
class JaxOffice2013Engine(_AgileDeviceMixin, Office2013Engine):
    """Device Office 2013 agile: SHA-512 spin (uint32-pair core) +
    AES-256 CBC verifier."""

    _sha512 = True


@register("office2007", device="jax")
@register("office", device="jax")
class JaxOffice2007Engine(_OfficeDeviceMixin, Office2007Engine):
    """Device Office 2007: the SHA-1 spin on the word pipeline, AES
    verifier check via gather tables."""

    def _mask_factory(self, gen, t, batch, cap):
        return make_office_mask_step(gen, t, batch, self.spin_count, cap)

    def _wordlist_factory(self, gen, t, wb, cap):
        return make_office_wordlist_step(gen, t, wb, self.spin_count,
                                         cap)
