"""Device PDF RC4 engines (hashcat 10400 / 10500).

TPU mapping of the user-password check (cpu/pdf.py for the spec):

- The Algorithm-2 MD5 runs over pad32(password) || O || P || ID
  [|| -1]: only the first 32 bytes depend on the candidate, and O
  fills the rest of block 1 — so block 2 (P, ID, metadata flag, MD
  padding) is a TARGET-CONSTANT 16-word block precomputed on host,
  and block 1 is built on device from the candidate with the spec
  PAD string gathered in per length.
- R2: key = digest[:5]; the stored U is RC4(key, PAD), so the filter
  compares ONE keystream word against U[0:4] ^ PAD[0:4] (the
  coordinator oracle confirms the full 32 bytes).
- R3+: 50 chained MD5s (fori_loop), then the 20-pass RC4 cascade over
  MD5(PAD || ID) via ops/rc4.rc4_apply16; all 16 result bytes are
  compared (4 words), so device hits are already exact.

The RC4 passes ride the XLA rc4 ops (per-lane serial gathers — the
bcrypt/krb5 slow shape), so absolute rates are low; the pallas RC4
layout (ops/pallas_krb5.py) is the recorded upgrade path.  Workers
are per-target sweeps; mixed R2/R3 hashlists get per-target steps.
"""

from __future__ import annotations

from typing import Sequence

import hashlib
import struct

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.pdf import PAD, PdfEngine
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.md5 import INIT as MD5_INIT, md5_compress
from dprf_tpu.ops.rc4 import (rc4_apply16, rc4_keystream_bytes,
                              words_to_bytes)

_PAD_ARR = np.frombuffer(PAD, np.uint8).astype(np.int32)
_PAD_W0 = int.from_bytes(PAD[:4], "little")


def _le_words(data: bytes) -> np.ndarray:
    return np.frombuffer(data, "<u4").astype(np.uint32)


def _block2_words(p: dict) -> np.ndarray:
    """The target-constant second MD5 block of Algorithm 2."""
    tail = struct.pack("<i", p["p"]) + p["id"]
    if p["rev"] >= 4 and not p["enc_metadata"]:
        tail += b"\xff\xff\xff\xff"
    total = 64 + len(tail)
    padded = tail + b"\x80" + bytes(55 - len(tail)) + \
        struct.pack("<Q", total * 8)
    assert len(padded) == 64, "block-2 tail exceeds one block"
    return _le_words(padded)


def _padded_pw_words(cand, lens):
    """words 0..7 of block 1: candidate bytes then the spec PAD."""
    B, maxlen = cand.shape
    pad_dev = jnp.asarray(_PAD_ARR)
    words = []
    for w in range(8):
        acc = jnp.zeros((B,), jnp.uint32)
        for q in range(4):
            pos = 4 * w + q
            if pos < maxlen:
                from_pw = cand[:, pos].astype(jnp.uint32)
            else:
                from_pw = jnp.zeros((B,), jnp.uint32)
            pad_idx = jnp.clip(pos - lens, 0, 31)
            from_pad = jnp.take(pad_dev, pad_idx).astype(jnp.uint32)
            byte = jnp.where(pos < lens, from_pw, from_pad)
            acc = acc | (byte << jnp.uint32(8 * q))
        words.append(acc)
    return words


def pdf_key_words(cand, lens, o_words, b2_words, rev: int,
                  key_len: int):
    """Candidates -> Algorithm-2 digest words uint32[B, 4] (the
    50-fold R3+ stretch runs over digest[:key_len] — 5 for 40-bit
    keys, 16 for 128-bit)."""
    B = cand.shape[0]
    pw = _padded_pw_words(cand, lens)
    b1 = jnp.stack(pw + [jnp.broadcast_to(o_words[w], (B,))
                         for w in range(8)], axis=1)
    init = jnp.broadcast_to(jnp.asarray(MD5_INIT), (B, 4))
    state = md5_compress(init, b1)
    b2 = jnp.broadcast_to(b2_words[None, :], (B, 16))
    digest = md5_compress(state, b2)
    if rev >= 3:
        iter_pad = jnp.zeros((B, 16), jnp.uint32)
        iter_pad = iter_pad.at[:, key_len // 4].set(
            jnp.uint32(0x80 << (8 * (key_len % 4))))
        iter_pad = iter_pad.at[:, 14].set(jnp.uint32(key_len * 8))
        keep = jnp.uint32((1 << (8 * (key_len % 4))) - 1
                          if key_len % 4 else 0xFFFFFFFF)

        def body(_, d):
            block = iter_pad
            for w in range(key_len // 4):
                block = block.at[:, w].set(d[:, w])
            if key_len % 4:
                w = key_len // 4
                block = block.at[:, w].set(block[:, w]
                                           | (d[:, w] & keep))
            return md5_compress(init, block)

        digest = lax.fori_loop(0, 50, body, digest)
    return digest


def make_pdf2_filter(key_len: int):
    """R2: first keystream word of RC4(digest[:key_len], ...) as
    uint32[B, 1]; the step's target word is U[0:4] ^ PAD[0:4]."""
    def fb(cand, lens, o_words, b2_words):
        digest = pdf_key_words(cand, lens, o_words, b2_words, 2,
                               key_len)
        key = words_to_bytes(digest)[:, :key_len]
        return rc4_keystream_bytes(key, 1)
    return fb


def make_pdf3_u(key_len: int):
    """R3+: the full 16-byte recomputed U as uint32[B, 4]."""
    def fb(cand, lens, o_words, b2_words, x0_words):
        B = cand.shape[0]
        digest = pdf_key_words(cand, lens, o_words, b2_words, 3,
                               key_len)
        key = words_to_bytes(digest)[:, :key_len]
        u = jnp.broadcast_to(x0_words[None, :],
                             (B, 4)).astype(jnp.uint32)
        u = rc4_apply16(key, u)

        def body(i, u):
            return rc4_apply16(key ^ i, u)

        return lax.fori_loop(1, 20, body, u)
    return fb


def _target_args(t: Target):
    p = t.params
    o_words = jnp.asarray(_le_words(p["o"]))
    b2 = jnp.asarray(_block2_words(p))
    if p["rev"] == 2:
        tw = jnp.asarray(
            np.array([int.from_bytes(p["u"][:4], "little") ^ _PAD_W0],
                     np.uint32))
        return (o_words, b2), tw
    x0 = hashlib.md5(PAD + p["id"]).digest()
    return ((o_words, b2, jnp.asarray(_le_words(x0))),
            jnp.asarray(_le_words(p["u"][:16])))


def _filter_for(rev: int, key_len: int):
    return (make_pdf2_filter(key_len) if rev == 2
            else make_pdf3_u(key_len))


def _make_step(gen, batch: int, rev: int, key_len: int,
               hit_capacity: int):
    flat = gen.flat_charsets
    length = gen.length
    fb = _filter_for(rev, key_len)

    @jax.jit
    def step(base_digits, n_valid, *args):
        *params, target = args
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        word = fb(cand, lens, *params)
        found = cmp_ops.compare_single(word, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def _make_wordlist_step(gen, word_batch: int, rev: int,
                        key_len: int, hit_capacity: int):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    fb = _filter_for(rev, key_len)

    @jax.jit
    def step(w0, n_valid_words, *args):
        *params, target = args
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        word = fb(cw, cl, *params)
        found = cmp_ops.compare_single(word, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,  # noqa: E402
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)


class PdfMaskWorker(PhpassMaskWorker):
    """Per-target sweep with PER-REVISION compiled steps (a hashlist
    may mix R2 and R3 documents); the base sweep calls
    step(base, n, *targ), so _targs carries the target index and the
    dispatcher picks that target's step.

    On TPU, eligible kinds ride the fused Pallas kernel
    (ops/pallas_pdf.py — decode -> Algorithm-2 MD5 -> 50-fold stretch
    -> RC4 cascade in one program, the krb5 RC4 layout); others keep
    the XLA step."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 16,
                 hit_capacity: int = 64, oracle=None):
        from dprf_tpu.ops import pallas_krb5, pallas_pdf
        from dprf_tpu.ops.pallas_mask import pallas_mode
        from dprf_tpu.ops.pallas_pdf import target_scalars

        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        mode = pallas_mode()
        tile = pallas_krb5.SUBC * pallas_pdf.CHUNKS
        if mode is not None:
            batch = max(tile, (batch // tile) * tile)
        self.batch = self.stride = batch
        by_kind = {}
        self._kargs = []
        self.kernel_kinds = set()      # (rev, key_len) on the kernel
        for t in self.targets:
            kind = (2 if t.params["rev"] == 2 else 3,
                    t.params["key_len"])
            if kind not in by_kind:
                step = None
                interp = (mode or {}).get("interpret", False)
                if mode is not None and pallas_pdf.pdf_kernel_eligible(
                        gen, *kind, on_hardware=not interp):
                    from dprf_tpu.engines.device._kernel_util import \
                        kind_kernel_step
                    from dprf_tpu.utils.sync import hard_sync
                    scalars = target_scalars(t)
                    step = kind_kernel_step(
                        "pdf",
                        lambda: pallas_pdf.make_pdf_crack_step(
                            gen, batch, *kind,
                            hit_capacity=hit_capacity,
                            interpret=interp),
                        lambda s: hard_sync(s(
                            jnp.zeros((gen.length,), jnp.int32),
                            jnp.int32(0), *scalars)))
                if step is None:
                    step = _make_step(gen, batch, *kind, hit_capacity)
                    kernel = False
                else:
                    kernel = True
                    self.kernel_kinds.add(kind)
                by_kind[kind] = (step, kernel)
            step, kernel = by_kind[kind]
            if kernel:
                o, b2, x0, u = target_scalars(t)
                self._kargs.append((step, (o, b2, x0), u))
            else:
                params, tw = _target_args(t)
                self._kargs.append((step, params, tw))
        self._targs = [(ti,) for ti in range(len(self.targets))]

    def step(self, base, n_valid, ti: int):
        s, params, tw = self._kargs[ti]
        return s(base, n_valid, *params, tw)


class PdfWordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 16,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        by_kind = {}
        self._kargs = []
        for t in self.targets:
            kind = (2 if t.params["rev"] == 2 else 3,
                    t.params["key_len"])
            if kind not in by_kind:
                by_kind[kind] = _make_wordlist_step(
                    gen, self.word_batch, *kind, hit_capacity)
            params, tw = _target_args(t)
            self._kargs.append((by_kind[kind], params, tw))
        self._targs = [(ti,) for ti in range(len(self.targets))]

    def step(self, w0, n_valid, ti: int):
        s, params, tw = self._kargs[ti]
        return s(w0, n_valid, *params, tw)


class ShardedPdfMaskWorker(ShardedPhpassMaskWorker):
    """Multi-chip sweep on the generic per-target sharded step; built
    per revision (R2: 2 params + 1-word target, R3: 3 params +
    4-word target)."""

    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 14, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        by_kind = {}
        self._kargs = []
        for t in self.targets:
            rev = 2 if t.params["rev"] == 2 else 3
            kind = (rev, t.params["key_len"])
            if kind not in by_kind:
                by_kind[kind] = make_sharded_pertarget_step(
                    gen, mesh, batch_per_device, _filter_for(*kind),
                    2 if rev == 2 else 3, hit_capacity)
            params, tw = _target_args(t)
            self._kargs.append((by_kind[kind], params, tw))
        self._targs = [(ti,) for ti in range(len(self.targets))]

    def step(self, base, n_valid, ti: int):
        s, params, tw = self._kargs[ti]
        return s(base, n_valid, *params, tw)


@register("pdf", device="jax")
class JaxPdfEngine(PdfEngine):
    def make_mask_worker(self, gen, targets, batch: int,
                         hit_capacity: int, oracle=None):
        return PdfMaskWorker(self, gen, targets, batch=batch,
                             hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return PdfWordlistWorker(self, gen, targets, batch=batch,
                                 hit_capacity=hit_capacity,
                                 oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedPdfMaskWorker(
            self, gen, targets, mesh, batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)
