"""WPA2-PMKID device engine: the iterated-KDF path (benchmark config 5).

Unlike the fast unsalted engines, PMKID digests depend on per-target
parameters (essid as the PBKDF2 salt; AP/STA MACs in the PMKID
message).  The fused step exploits the job structure: the PMK depends
only on (passphrase, essid), so targets are grouped by essid and the
4096-iteration PBKDF2 runs once per unique essid per candidate; each
target then costs only one extra HMAC (4 compressions) and a 4-word
compare.

A typical PMKID job has one essid and a handful of targets, so the cost
is ~16.4k SHA-1 compressions per candidate -- the low-throughput path
by design.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import Pmkid2Engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import pbkdf2_sha1_pmk, pmkid_from_pmk
from dprf_tpu.runtime.worker import DeviceMaskWorker


@register("wpa2-pmkid", device="jax")
@register("pmkid", device="jax")
class JaxPmkidEngine(Pmkid2Engine):
    """Device PMKID engine.  Inherits the CPU engine's target parsing
    (hashcat 16800 lines), oracle hash_batch, and the `iterations`
    count (one shared definition, so oracle and device KDF can never
    silently diverge); adds the device batch computation and the
    fused-worker factories the CLI uses."""

    def pmk_packed(self, key_words: jnp.ndarray, essid: bytes) -> jnp.ndarray:
        """uint32[B, 16] zero-padded passphrase blocks -> uint32[B, 8] PMK."""
        return pbkdf2_sha1_pmk(key_words, essid, self.iterations)

    def pmkid_packed(self, pmk_words: jnp.ndarray,
                     target: Target) -> jnp.ndarray:
        return pmkid_from_pmk(pmk_words, target.params["mac_ap"],
                              target.params["mac_sta"])

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        # PBKDF2 is ~16k compressions/candidate; a huge batch only adds
        # latency per step, so cap it well below fast-hash batch sizes.
        worker = maybe_pallas_pmkid_worker(self, gen, targets,
                                           batch=min(batch, 1 << 15),
                                           hit_capacity=hit_capacity,
                                           oracle=oracle)
        if worker is not None:
            return worker
        return PmkidDeviceWorker(self, gen, targets,
                                 batch=min(batch, 1 << 14),
                                 hit_capacity=hit_capacity, oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        """Config 5's pod-scale path: keyspace DP over the mesh."""
        return ShardedPmkidWorker(self, gen, targets, mesh,
                                  batch_per_device=min(batch_per_device,
                                                       1 << 12),
                                  hit_capacity=hit_capacity, oracle=oracle)


def _group_targets(targets: Sequence[Target]):
    """(essid -> target indices, per-target uint32 digest words)."""
    by_essid: dict[bytes, list[int]] = {}
    for i, t in enumerate(targets):
        by_essid.setdefault(t.params["essid"], []).append(i)
    twords = [np.frombuffer(t.digest, dtype=">u4").astype(np.uint32)
              for t in targets]
    return by_essid, twords


def _pmkid_match(engine, targets, by_essid, twords, key, valid):
    """Per-lane match scan, memory FLAT in target count: accumulates a
    match count and the first matching target index per lane instead of
    a [T, B] mask (VERDICT r2 weak #4 -- a 1k-target list at batch 2^14
    must not build a 16M-lane buffer).

    A lane matching >= 2 targets (same passphrase cracking two captures)
    reports only its first target here; the worker resolves the rest
    with the oracle whenever n_multi > 0, so no crack is ever lost.

    Returns (nmatch int32[B], tfirst int32[B])."""
    nmatch = jnp.zeros(valid.shape, jnp.int32)
    tfirst = jnp.full(valid.shape, -1, jnp.int32)
    for essid, tidx in by_essid.items():
        pmk = engine.pmk_packed(key, essid)     # once per essid
        for i in tidx:
            pmkid = engine.pmkid_packed(pmk, targets[i])
            hit = jnp.all(pmkid == jnp.asarray(twords[i]), axis=-1) & valid
            tfirst = jnp.where(hit & (nmatch == 0), jnp.int32(i), tfirst)
            nmatch = nmatch + hit.astype(jnp.int32)
    return nmatch, tfirst


def make_pmkid_crack_step(engine: JaxPmkidEngine, gen: MaskGenerator,
                          targets: Sequence[Target], batch: int,
                          hit_capacity: int = 64):
    """Fused step: index -> passphrase -> PMK (per essid) -> PMKID (per
    target) -> hits.  tpos payload is the ORIGINAL (first-matching)
    target index; n_multi counts lanes matching >= 2 targets.

    step(base_digits, n_valid) -> (count, lanes, tpos, n_multi)."""
    flat = gen.flat_charsets
    length = gen.length
    by_essid, twords = _group_targets(targets)

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        cand = gen.decode_batch(base_digits, flat, batch)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        valid = jnp.arange(batch, dtype=jnp.int32) < n_valid
        nmatch, tfirst = _pmkid_match(engine, targets, by_essid, twords,
                                      key, valid)
        count, lanes, tpos = cmp_ops.compact_hits(nmatch > 0, tfirst,
                                                  hit_capacity)
        n_multi = jnp.sum((nmatch > 1).astype(jnp.int32))
        return count, lanes, tpos, n_multi

    return step


def make_sharded_pmkid_crack_step(engine: JaxPmkidEngine,
                                  gen: MaskGenerator,
                                  targets: Sequence[Target], mesh,
                                  batch_per_device: int,
                                  hit_capacity: int = 64):
    """Multi-chip PMKID step (config 5 is the pod-scale sweep): chip c
    owns the lane slice [c*B, (c+1)*B) of each super-batch, runs the
    whole PBKDF2->PMKID->compare chain locally, and psums only the
    scalar hit/multi counts over ICI.

    step(base_digits, n_valid) -> (total, counts[n_dev],
        lanes[n_dev, cap] super-batch-global, tpos[n_dev, cap],
        n_multi_total)."""
    import jax as _jax
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from dprf_tpu.parallel.mesh import SHARD_AXIS, shard_map

    flat = gen.flat_charsets
    length = gen.length
    by_essid, twords = _group_targets(targets)
    B = batch_per_device

    def shard_fn(base_digits, n_valid):
        dev = lax.axis_index(SHARD_AXIS)
        offset = (dev * B).astype(jnp.int32)
        cand = gen.decode_batch(base_digits, flat, B, lane_offset=offset)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        lane_global = offset + jnp.arange(B, dtype=jnp.int32)
        valid = lane_global < n_valid
        nmatch, tfirst = _pmkid_match(engine, targets, by_essid, twords,
                                      key, valid)
        count, lanes, tpos = cmp_ops.compact_hits(nmatch > 0, tfirst,
                                                  hit_capacity)
        lanes = jnp.where(lanes >= 0, lanes + offset, lanes)
        total = lax.psum(count, SHARD_AXIS)
        n_multi = lax.psum(jnp.sum((nmatch > 1).astype(jnp.int32)),
                           SHARD_AXIS)
        # replicated hit buffers (see parallel/sharded.py)
        return (total[None],
                lax.all_gather(count, SHARD_AXIS),
                lax.all_gather(lanes, SHARD_AXIS),
                lax.all_gather(tpos, SHARD_AXIS),
                n_multi[None])

    sharded = shard_map(
        shard_fn, mesh=mesh, in_specs=(P(), P()),
        out_specs=(P(), P(), P(), P(), P()),
        check_vma=False)

    @_jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        total, counts, lanes, tpos, n_multi = sharded(base_digits, n_valid)
        return total[0], counts, lanes, tpos, n_multi[0]

    step.super_batch = mesh.devices.size * B
    return step


class PallasPmkidWorker:
    """Per-target PMKID sweep over the fused Pallas PBKDF2 kernel
    (ops/pallas_pbkdf2.py) -- measured 156.5 kH/s at 4096 iterations
    on TPU v5 lite vs 17.4 kH/s through the XLA step (9x; ~2.56 G
    SHA-1 compressions/s, the sha1 kernel's rate).

    The kernel recomputes the PMK per target, so jobs where many
    targets share one ESSID (where the XLA step amortizes the KDF)
    route here only while the per-essid target count stays under the
    kernel's speedup factor -- see maybe_pallas_pmkid_worker."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 15, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.ops.pallas_pbkdf2 import (make_pmkid_kernel_step,
                                                target_kernel_args)

        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self._targs = [target_kernel_args(t) for t in self.targets]
        lens = sorted({a[0] for a in self._targs})
        self._steps = {n: make_pmkid_kernel_step(gen, batch, n,
                                                 hit_capacity)
                       for n in lens}
        self.batch = self.stride = next(iter(self._steps.values())).batch

    def warmup(self) -> None:
        from dprf_tpu.utils.sync import hard_sync
        base = jnp.asarray(self.gen.digits(0), dtype=jnp.int32)
        by_len = {a[0]: a for a in self._targs}
        for n, (el, essid, msg5, tgt) in by_len.items():
            hard_sync(self._steps[n](base, jnp.int32(0),
                                     jnp.int32(self.engine.iterations),
                                     essid, msg5, tgt))

    def process(self, unit) -> list:
        from dprf_tpu.runtime.worker import CpuWorker, Hit
        iters = jnp.int32(self.engine.iterations)
        hits: list = []
        for ti, (el, essid, msg5, tgt) in enumerate(self._targs):
            step = self._steps[el]
            queued = []
            flag = None
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart),
                                   dtype=jnp.int32)
                result = step(base, jnp.int32(n_valid), iters, essid,
                              msg5, tgt)
                # device-accumulated unit flag; one readback per
                # (target, unit) -- see MaskWorkerBase.process
                flag = result[0] if flag is None else flag + result[0]
                queued.append((bstart, result))
            if flag is None or int(flag) == 0:
                continue
            for bstart, (count, lanes, _) in queued:
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    if self.oracle is None:
                        raise RuntimeError(
                            "hit buffer overflow and no oracle to "
                            "rescan with; raise hit_capacity")
                    end = min(bstart + self.stride, unit.end)
                    sub = type(unit)(-1, bstart, end - bstart)
                    hits.extend(Hit(ti, h.cand_index, h.plaintext)
                                for h in CpuWorker(
                                    self.oracle, self.gen,
                                    [self.targets[ti]]).process(sub))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


def maybe_pallas_pmkid_worker(engine, gen, targets, batch: int,
                              hit_capacity: int, oracle):
    """PallasPmkidWorker when the kernel path wins, else None.

    The kernel is ~9x the XLA step per keyspace sweep but sweeps once
    per TARGET, while the XLA step shares each ESSID's PBKDF2 across
    its targets -- so route to the kernel only while the largest
    same-essid target group stays under the speedup factor."""
    from dprf_tpu.ops.pallas_mask import pallas_mode
    from dprf_tpu.ops.pallas_pbkdf2 import pmkid_kernel_eligible
    from dprf_tpu.utils.logging import DEFAULT as log

    if not targets:
        return None
    # evaluate the routing heuristic BEFORE the backend check so the
    # hermetic suite can exercise it (the mode gate would otherwise
    # shadow it off-TPU)
    lens = [len(t.params["essid"]) for t in targets]
    by_essid, _ = _group_targets(targets)
    max_per_essid = max(len(v) for v in by_essid.values())
    if max_per_essid > 8 or not pmkid_kernel_eligible(gen, lens):
        log.info("pmkid pallas kernel not chosen for this job; "
                 "using the XLA step", targets=len(targets),
                 max_per_essid=max_per_essid)
        return None
    mode = pallas_mode()
    if mode is None or mode.get("interpret", False):
        # TPU-only: the 14 statically-unrolled SHA-1 compressions
        # don't compile on XLA:CPU in reasonable time (the sha256
        # kernel rule); hardware proof in TPU_RESULTS_r04
        return None
    try:
        worker = PallasPmkidWorker(engine, gen, targets, batch=batch,
                                   hit_capacity=hit_capacity,
                                   oracle=oracle)
        worker.warmup()
        return worker
    except Exception as e:
        log.warn("pmkid pallas kernel failed to build/compile; "
                 "falling back to the XLA step",
                 error=f"{type(e).__name__}: {e}")
        return None


class PmkidDeviceWorker(DeviceMaskWorker):
    """Mask worker over the fused PMKID step (salted multi-target)."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 14, hit_capacity: int = 64,
                 oracle=None):
        self._setup_pmkid(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        self.step = make_pmkid_crack_step(engine, gen, self.targets, batch,
                                          hit_capacity)

    def _setup_pmkid(self, engine, gen, targets, hit_capacity, oracle):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        # tpos already carries original target indices: identity order.
        self.multi = True
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)

    def _resolve_all_targets(self, bstart: int, lanes_np) -> list:
        """Some lane matched >= 2 targets (n_multi > 0): re-check every
        reported lane against EVERY target so the non-first matches are
        not lost.  The expensive PBKDF2 runs once per (lane, essid) --
        the same grouping the device step exploits -- and each target
        then costs one host HMAC, so even 1k targets sharing an essid
        resolve with <= hit_capacity KDF computations."""
        import hashlib as _hl
        import hmac as _hmac

        from dprf_tpu.runtime.worker import Hit
        iters = (self.oracle or self.engine).iterations
        by_essid: dict[bytes, list[int]] = {}
        for i, t in enumerate(self.targets):
            by_essid.setdefault(t.params["essid"], []).append(i)
        hits = []
        for lane in lanes_np:
            if lane < 0:
                continue
            gidx = bstart + int(lane)
            plain = self.gen.candidate(gidx)
            for essid, tidx in by_essid.items():
                pmk = _hl.pbkdf2_hmac("sha1", plain, essid, iters, 32)
                for ti in tidx:
                    t = self.targets[ti]
                    msg = (b"PMK Name" + t.params["mac_ap"]
                           + t.params["mac_sta"])
                    if _hmac.new(pmk, msg, _hl.sha1).digest()[:16] == \
                            t.digest:
                        hits.append(Hit(ti, gidx, plain))
        return hits

    def _batch_hits(self, bstart: int, result, unit,
                    window: int = 0) -> list:
        count, lanes, tpos, n_multi = result
        count = int(count)
        if count == 0:
            return []
        if count > lanes.shape[0]:     # the step's built buffer size
            return self._rescan(bstart, unit, window)
        if int(n_multi):
            return self._resolve_all_targets(bstart, np.asarray(lanes))
        return self._decode_lanes(bstart, np.asarray(lanes),
                                  np.asarray(tpos))


class ShardedPmkidWorker(PmkidDeviceWorker):
    """Multi-chip PMKID worker: the keyspace-DP shard_map step with the
    same hit semantics as the single-chip worker."""

    def __init__(self, engine, gen, targets: Sequence[Target], mesh,
                 batch_per_device: int = 1 << 12, hit_capacity: int = 64,
                 oracle=None):
        self._setup_pmkid(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self.step = make_sharded_pmkid_crack_step(
            engine, gen, self.targets, mesh, batch_per_device, hit_capacity)

    def _batch_hits(self, bstart: int, result, unit,
                    window: int = 0) -> list:
        total, counts, lanes, tpos, n_multi = result
        if int(total) == 0:
            return []
        if (np.asarray(counts) > lanes.shape[-1]).any():
            return self._rescan(bstart, unit, window)
        lanes_np = np.asarray(lanes).ravel()
        if int(n_multi):
            return self._resolve_all_targets(bstart, lanes_np)
        return self._decode_lanes(bstart, lanes_np,
                                  np.asarray(tpos).ravel())
