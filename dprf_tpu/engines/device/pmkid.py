"""WPA2-PMKID device engine: the iterated-KDF path (benchmark config 5).

Unlike the fast unsalted engines, PMKID digests depend on per-target
parameters (essid as the PBKDF2 salt; AP/STA MACs in the PMKID
message).  The fused step exploits the job structure: the PMK depends
only on (passphrase, essid), so targets are grouped by essid and the
4096-iteration PBKDF2 runs once per unique essid per candidate; each
target then costs only one extra HMAC (4 compressions) and a 4-word
compare.

A typical PMKID job has one essid and a handful of targets, so the cost
is ~16.4k SHA-1 compressions per candidate -- the low-throughput path
by design.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import Pmkid2Engine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.hmac_sha1 import pbkdf2_sha1_pmk, pmkid_from_pmk
from dprf_tpu.runtime.worker import DeviceMaskWorker


@register("wpa2-pmkid", device="jax")
@register("pmkid", device="jax")
class JaxPmkidEngine(Pmkid2Engine):
    """Device PMKID engine.  Inherits the CPU engine's target parsing
    (hashcat 16800 lines) and oracle hash_batch; adds the device batch
    computation and a fused-worker factory the CLI uses."""

    iterations = 4096

    def pmk_packed(self, key_words: jnp.ndarray, essid: bytes) -> jnp.ndarray:
        """uint32[B, 16] zero-padded passphrase blocks -> uint32[B, 8] PMK."""
        return pbkdf2_sha1_pmk(key_words, essid, self.iterations)

    def pmkid_packed(self, pmk_words: jnp.ndarray,
                     target: Target) -> jnp.ndarray:
        return pmkid_from_pmk(pmk_words, target.params["mac_ap"],
                              target.params["mac_sta"])

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        # PBKDF2 is ~16k compressions/candidate; a huge batch only adds
        # latency per step, so cap it well below fast-hash batch sizes.
        return PmkidDeviceWorker(self, gen, targets,
                                 batch=min(batch, 1 << 14),
                                 hit_capacity=hit_capacity, oracle=oracle)


def make_pmkid_crack_step(engine: JaxPmkidEngine, gen: MaskGenerator,
                          targets: Sequence[Target], batch: int,
                          hit_capacity: int = 64):
    """Fused step: index -> passphrase -> PMK (per essid) -> PMKID (per
    target) -> hits.  tpos payload is the ORIGINAL target index."""
    flat = gen.flat_charsets
    length = gen.length
    by_essid: dict[bytes, list[int]] = {}
    for i, t in enumerate(targets):
        by_essid.setdefault(t.params["essid"], []).append(i)
    # uint32 target words per target (big-endian PMKID bytes).
    twords = [np.frombuffer(t.digest, dtype=">u4").astype(np.uint32)
              for t in targets]

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        cand = gen.decode_batch(base_digits, flat, batch)
        key = pack_ops.pack_raw(cand, length, big_endian=True)
        valid = jnp.arange(batch, dtype=jnp.int32) < n_valid
        # One candidate may match SEVERAL targets (same passphrase under
        # different essids), so hits are (target, lane) pairs: a [T*B]
        # found-mask compacted with the target index as payload.
        hit_rows = []
        tpos_rows = []
        for essid, tidx in by_essid.items():
            pmk = engine.pmk_packed(key, essid)
            for i in tidx:
                pmkid = engine.pmkid_packed(pmk, targets[i])
                hit = jnp.all(pmkid == jnp.asarray(twords[i]), axis=-1)
                hit_rows.append(hit & valid)
                tpos_rows.append(jnp.full((batch,), i, jnp.int32))
        found = jnp.concatenate(hit_rows)
        tpos = jnp.concatenate(tpos_rows)
        count, flat_idx, tpos = cmp_ops.compact_hits(found, tpos,
                                                     hit_capacity)
        lanes = jnp.where(flat_idx >= 0, flat_idx % batch, flat_idx)
        return count, lanes, tpos

    return step


class PmkidDeviceWorker(DeviceMaskWorker):
    """Mask worker over the fused PMKID step (salted multi-target)."""

    def __init__(self, engine, gen, targets: Sequence[Target],
                 batch: int = 1 << 14, hit_capacity: int = 64,
                 oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        # tpos already carries original target indices: identity order.
        self.multi = True
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        self.batch = self.stride = batch
        self.step = make_pmkid_crack_step(engine, gen, self.targets, batch,
                                          hit_capacity)
