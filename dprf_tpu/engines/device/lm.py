"""Device LM-hash engine: bitslice DES on the VPU (hashcat 3000).

Candidates are uppercased and transposed into 56 bit-planes (one int32
plane bit-column per candidate, 32 candidates per vector word), the
bitslice DES circuit (ops/des.py) encrypts the LM magic under every
key simultaneously, and target compare is 64 plane selects folded into
one match mask -- no gathers anywhere, which is what makes DES viable
on this hardware at all (compare bcrypt's measured gather
serialization).  Multi-target lists fold into the same pass at 64
ops per extra target.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.engines import LmEngine
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.des import (LM_MAGIC, const_planes, des_encrypt_bitslice,
                              key_planes_from_bytes7)
from dprf_tpu.runtime.worker import (DeviceWordlistWorker,
                                     MaskWorkerBase)


def _upper(cand: jnp.ndarray) -> jnp.ndarray:
    return jnp.where((cand >= 97) & (cand <= 122), cand - 32, cand)


def byte_planes(cand: jnp.ndarray) -> list:
    """uint8[B, K] (B a multiple of 32) -> 8K int32 planes, plane
    8k+bit = byte k's bit (MSB first), lane j of word v = candidate
    32v+j.  K is 7 for LM halves, 8 for descrypt keys."""
    B, K = cand.shape
    groups = cand.astype(jnp.int32).reshape(B // 32, 32, K)
    weights = jnp.left_shift(jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    planes = []
    for k in range(K):
        for bit in range(8):
            vals = (groups[:, :, k] >> (7 - bit)) & 1
            # distinct bits: sum == bitwise or, and int32 wrap on the
            # sign bit is exact
            planes.append((vals * weights).sum(axis=1, dtype=jnp.int32))
    return planes


def target_bits(digest: bytes) -> list[int]:
    return [(digest[i // 8] >> (7 - i % 8)) & 1 for i in range(64)]


def found_lanes(m, batch: int):
    """int32 word match-mask -> bool[batch] per-lane mask (lane j of
    word v = candidate 32v+j).  Shared by the LM and descrypt steps."""
    lanebit = jnp.left_shift(jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    return ((jnp.broadcast_to(m[:, None], (batch // 32, 32))
             & lanebit) != 0).reshape(batch)


def match_mask(cipher, tbits: list[int]):
    """Cipher planes + 64 target bits -> int32 word mask of matching
    lanes.  des_encrypt_bitslice always returns 64 real planes (the
    final FP reindexes a stacked array), so this is a plain 64-term
    select-and-AND chain."""
    m = cipher[0] if tbits[0] else ~cipher[0]
    for p, t in zip(cipher[1:], tbits[1:]):
        m = m & (p if t else ~p)
    return m


def make_lm_mask_step(gen, targets: Sequence[Target], batch: int,
                      hit_capacity: int = 64):
    """step(base_digits, n_valid) -> (count, lanes, tpos); tpos carries
    ORIGINAL target indices (first match per lane)."""
    if batch % 32:
        raise ValueError("bitslice batch must be a multiple of 32")
    if gen.length > 7:
        raise ValueError(
            f"an LM half is at most 7 characters; mask decodes to "
            f"{gen.length}")
    flat = gen.flat_charsets
    length = gen.length
    tbits = [target_bits(t.digest) for t in targets]

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        cand7 = jnp.zeros((batch, 7), jnp.uint8).at[:, :length].set(
            _upper(cand))
        cipher = des_encrypt_bitslice(
            key_planes_from_bytes7(byte_planes(cand7)),
            const_planes(LM_MAGIC))
        found_any = jnp.zeros((batch,), jnp.bool_)
        tfirst = jnp.zeros((batch,), jnp.int32)
        for ti, tb in enumerate(tbits):
            f = found_lanes(match_mask(cipher, tb), batch)
            tfirst = jnp.where(f & ~found_any, jnp.int32(ti), tfirst)
            found_any = found_any | f
        valid = jnp.arange(batch, dtype=jnp.int32) < n_valid
        return cmp_ops.compact_hits(found_any & valid, tfirst,
                                    hit_capacity)

    return step


def make_lm_wordlist_step(gen, targets: Sequence[Target],
                          word_batch: int, hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    if L > 7:
        raise ValueError("lm candidates cap at 7 bytes; set --max-len 7")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    tbits = [target_bits(t.digest) for t in targets]

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        RB = cw.shape[0]
        pad = (-RB) % 32
        cw = jnp.pad(cw, ((0, pad), (0, 0)))
        cv = jnp.pad(cv, (0, pad))
        pos = jnp.arange(cw.shape[1], dtype=jnp.int32)
        cw = jnp.where(pos[None, :] < jnp.pad(cl, (0, pad))[:, None],
                       cw, 0)
        cand7 = jnp.zeros((RB + pad, 7), jnp.uint8).at[:, :cw.shape[1]] \
            .set(_upper(cw))
        cipher = des_encrypt_bitslice(
            key_planes_from_bytes7(byte_planes(cand7)),
            const_planes(LM_MAGIC))
        found_any = jnp.zeros((RB + pad,), jnp.bool_)
        tfirst = jnp.zeros((RB + pad,), jnp.int32)
        for ti, tb in enumerate(tbits):
            f = found_lanes(match_mask(cipher, tb), RB + pad)
            tfirst = jnp.where(f & ~found_any, jnp.int32(ti), tfirst)
            found_any = found_any | f
        found = found_any[:RB] & cv[:RB]
        return cmp_ops.compact_hits(found, tfirst[:RB], hit_capacity)

    return step


class LmMaskWorker(MaskWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.multi = len(self.targets) > 1
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        batch = max(32, (batch // 32) * 32)
        self.batch = self.stride = batch
        self.step = make_lm_mask_step(gen, self.targets, batch,
                                      hit_capacity)


class LmWordlistWorker(DeviceWordlistWorker):
    """DeviceWordlistWorker's process/hit-decode/rescan machinery over
    the bitslice step (its own __init__ skips _setup_targets -- LM's
    tpos already carries original target indices)."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.multi = len(self.targets) > 1
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.batch = batch
        self.step = make_lm_wordlist_step(gen, self.targets,
                                          self.word_batch, hit_capacity)


@register("lm", device="jax")
class JaxLmEngine(LmEngine):
    """Device LM: bitslice DES (see module docstring).  Parsing and
    the oracle come from the CPU engine."""

    little_endian = False
    digest_words = 2

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return LmMaskWorker(self, gen, targets, batch=batch,
                            hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return LmWordlistWorker(self, gen, targets, batch=batch,
                                hit_capacity=hit_capacity, oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
