"""Device Kerberos etype-23 engines (krb5tgs 13100 / krb5asrep 18200).

Full RFC 4757 verification needs RC4 over the WHOLE multi-KB ticket
plus HMAC-MD5 over the plaintext — per candidate.  The device path
avoids all of it: the plaintext is confounder(8 random bytes) || DER
ticket, and the DER header at offset 8 ([APPLICATION n] + length +
SEQUENCE + length) is DETERMINISTIC given len(edata2) - 8, so the
filter is

    NTLM -> K1 -> K3 (two constant-message HMAC-MD5s, shared with
    netntlmv2) -> RC4 KSA + 12 keystream bytes (ops/rc4.py) ->
    (keystream[8:12] ^ edata2[8:12]) & mask == expected

an exact masked 32-bit compare.  False-positive odds are ~2^-32 per
candidate per target (~2^-30 for AS-REP's relaxed tag byte); the
coordinator's CPU-oracle verification (runtime/coordinator.py) is the
authoritative RFC check on every reported hit, exactly the Bloom
prefilter contract of the 1000-target path.

A non-DER (BER long-form) encoder would defeat the header prediction —
MIT krb5 and Windows KDCs emit DER; the CPU engine remains the
fallback for exotic encoders (`--device=cpu`).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.krb5 import Krb5AsRepEngine, Krb5TgsEngine
from dprf_tpu.engines.device.netntlmv2 import (_hmac_md5_const_msg,
                                               hmac_msg_blocks)
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.md4 import md4_digest_words
from dprf_tpu.ops.rc4 import rc4_keystream_words

#: RFC 4757 prepends 8 random confounder bytes before the DER ticket;
#: the predictable header lives at plaintext offset CONF.
CONF = 8


def der_filter_words(edata_len: int, msg_type: int) -> tuple[int, int]:
    """(expected, mask) little-endian uint32 over plaintext bytes
    [8, 12) — the DER header right after the confounder.

    DER framing of the decrypted ticket: [APPLICATION n] tag, outer
    length of C = (edata_len - 8) - header, then SEQUENCE (0x30) and
    its length.  DER's definite minimal-length rule fixes the outer
    form from C alone, and the inner SEQUENCE fills the window:

      C < 0x80:        [tag,   C, 0x30, C-2]   (inner short form too)
      C <= 0xFF:       [tag, 0x81,   C, 0x30]
      C <= 0xFFFF:     [tag, 0x82, C>>8, C&0xFF]
      C <= 0xFFFFFF:   [tag, 0x83, C>>16, (C>>8)&0xFF]  (PAC-bloated)

    TGS plaintext is EncTicketPart [APPLICATION 3] = 0x63 (exact);
    AS-REP is EncASRepPart [APPLICATION 25] = 0x79, but some KDCs tag
    it EncTGSRepPart 0x7A, so its tag byte matches 0x78-0x7B
    (mask 0xFC)."""
    from dprf_tpu.engines.cpu.krb5 import TGS_MSG_TYPE
    if msg_type == TGS_MSG_TYPE:
        tag_exp, tag_mask = 0x63, 0xFF
    else:
        tag_exp, tag_mask = 0x78, 0xFC
    L = edata_len - CONF            # DER blob length
    if L - 2 < 0x80:
        exp = [tag_exp, L - 2, 0x30, L - 4]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    elif L - 3 <= 0xFF:
        exp = [tag_exp, 0x81, L - 3, 0x30]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    elif L - 4 <= 0xFFFF:
        C = L - 4
        exp = [tag_exp, 0x82, (C >> 8) & 0xFF, C & 0xFF]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    elif L - 5 <= 0xFFFFFF:
        C = L - 5
        exp = [tag_exp, 0x83, (C >> 16) & 0xFF, (C >> 8) & 0xFF]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    else:
        # a >16 MB ticket is not a ticket; a silent filter miss would
        # be a false NEGATIVE, so refuse loudly (--device=cpu works)
        raise ValueError(f"DER blob of {L} bytes (edata2 minus "
                         "confounder) exceeds the header forms the "
                         "device filter predicts")
    pack = lambda bs: sum(b << (8 * t) for t, b in enumerate(bs))
    return pack(exp) & pack(msk), pack(msk)


def krb5_filter_batch(cand: jnp.ndarray, lens: jnp.ndarray,
                      type_blocks, type_n, chk_blocks, chk_n,
                      cipher4, mask) -> jnp.ndarray:
    """Candidates -> masked plaintext-bytes-[8,12) word uint32[B, 1].

    cipher4: uint32[1] — edata2 bytes [8, 12) (LE), past the
    confounder; mask: uint32[1].  The step's target word is the DER
    expectation from `der_filter_words`, already masked."""
    wide = pack_ops.utf16le_widen(cand)
    nt = md4_digest_words(pack_ops.pack_varlen(wide, lens * 2,
                                               big_endian=False))
    k1 = _hmac_md5_const_msg(nt, type_blocks, type_n)
    k3 = _hmac_md5_const_msg(k1, chk_blocks, chk_n)
    ks = rc4_keystream_words(k3, (CONF + 4) // 4)
    plain4 = ks[:, CONF // 4] ^ cipher4[0]
    return (plain4 & mask[0])[:, None]


#: krb5_filter_batch's per-target argument count (everything between
#: `lens` and the target word) — the sharded pertarget step needs it.
N_PARAMS = 6


def make_krb5_mask_step(gen, batch: int, hit_capacity: int = 64):
    """step(base_digits, n_valid, *target_params, expected) ->
    (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    if length > 27:
        raise ValueError("krb5 etype-23 passwords cap at 27 chars "
                         "(single-block UTF-16LE NTLM)")

    @jax.jit
    def step(base_digits, n_valid, type_blocks, type_n, chk_blocks,
             chk_n, cipher4, mask, expected):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        word = krb5_filter_batch(cand, lens, type_blocks, type_n,
                                 chk_blocks, chk_n, cipher4, mask)
        found = cmp_ops.compare_single(word, expected)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_krb5_wordlist_step(gen, word_batch: int, hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    if Lw > 27:
        raise ValueError("krb5 etype-23 passwords cap at 27 chars")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, type_blocks, type_n, chk_blocks,
             chk_n, cipher4, mask, expected):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        word = krb5_filter_batch(cw, cl, type_blocks, type_n,
                                 chk_blocks, chk_n, cipher4, mask)
        found = cmp_ops.compare_single(word, expected) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def _targs(targets: Sequence[Target]):
    out = []
    for t in targets:
        p = t.params
        tw, tn = hmac_msg_blocks(
            p["msg_type"].to_bytes(4, "little"), 1, what="msg_type")
        cw, cn = hmac_msg_blocks(p["checksum"], 1, what="checksum")
        expected, mask = der_filter_words(len(p["edata"]),
                                          p["msg_type"])
        cipher4 = int.from_bytes(p["edata"][CONF:CONF + 4], "little")
        out.append((jnp.asarray(tw), jnp.int32(tn),
                    jnp.asarray(cw), jnp.int32(cn),
                    jnp.asarray([cipher4], jnp.uint32),
                    jnp.asarray([mask], jnp.uint32),
                    jnp.asarray([expected], jnp.uint32)))
    return out


class Krb5MaskWorker(PhpassMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        self.step = make_krb5_mask_step(gen, batch, hit_capacity)


class PallasKrb5MaskWorker(PhpassMaskWorker):
    """Mask sweep over the RC4 prefilter KERNEL (ops/pallas_krb5.py):
    the XLA step's per-lane serial RC4 swaps measured 21 kH/s on chip
    (TPU_RESULTS_r04 krb5-20); the kernel's sublane layout makes them
    vector ops.  Target scalars are runtime, so one compiled kernel
    serves the whole hashlist (both msg types).  Sweep loop, rescan,
    and the hit contract come from PhpassMaskWorker."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None,
                 interpret: bool = False):
        from dprf_tpu.ops import pallas_krb5

        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        tile = pallas_krb5.SUBC * pallas_krb5.CHUNKS
        batch = max(tile, (batch // tile) * tile)
        self.batch = self.stride = batch
        self._targs = [pallas_krb5.target_scalars(t) for t in targets]
        self.step = pallas_krb5.make_krb5_crack_step(
            gen, batch, hit_capacity, interpret=interpret)

    def warmup(self) -> None:
        """One launch so Mosaic compile failures surface in the
        factory (which then falls back to the XLA step), not mid-job."""
        import jax.numpy as jnp

        from dprf_tpu.utils.sync import hard_sync
        base = jnp.asarray(self.gen.digits(0), dtype=jnp.int32)
        hard_sync(self.step(base, jnp.int32(0), *self._targs[0]))


def maybe_pallas_krb5_worker(engine, gen, targets, batch: int,
                             hit_capacity: int, oracle):
    """PallasKrb5MaskWorker when the job is kernel-eligible (warmed so
    compile failures surface here), else None -> XLA-step worker."""
    from dprf_tpu.ops import pallas_krb5
    from dprf_tpu.ops.pallas_mask import pallas_mode
    from dprf_tpu.utils.logging import DEFAULT as log

    mode = pallas_mode()
    if mode is None or not pallas_krb5.krb5_kernel_eligible(gen):
        return None
    try:
        worker = PallasKrb5MaskWorker(
            engine, gen, targets, batch=batch,
            hit_capacity=hit_capacity, oracle=oracle,
            interpret=mode.get("interpret", False))
        worker.warmup()
        return worker
    except Exception as e:  # noqa: BLE001 -- compiler errors
        log.warn("krb5 kernel failed to build/compile; using the "
                 "XLA step", engine=engine.name, error=str(e))
        return None


class Krb5WordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        self.step = make_krb5_wordlist_step(gen, self.word_batch,
                                            hit_capacity)


class ShardedKrb5MaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 16, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _targs(self.targets)
        if gen.length > 27:
            raise ValueError("krb5 etype-23 passwords cap at 27 chars")
        self.step = make_sharded_pertarget_step(
            gen, mesh, batch_per_device, krb5_filter_batch, N_PARAMS,
            hit_capacity)


class _JaxKrb5Mixin:
    def make_mask_worker(self, gen, targets, batch: int,
                         hit_capacity: int, oracle=None):
        worker = maybe_pallas_krb5_worker(self, gen, targets, batch,
                                          hit_capacity, oracle)
        if worker is not None:
            return worker
        return Krb5MaskWorker(self, gen, targets, batch=batch,
                              hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Krb5WordlistWorker(self, gen, targets, batch=batch,
                                  hit_capacity=hit_capacity,
                                  oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedKrb5MaskWorker(
            self, gen, targets, mesh, batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)


@register("krb5tgs", device="jax")
class JaxKrb5TgsEngine(_JaxKrb5Mixin, Krb5TgsEngine):
    pass


@register("krb5asrep", device="jax")
class JaxKrb5AsRepEngine(_JaxKrb5Mixin, Krb5AsRepEngine):
    pass
