"""Device NetNTLMv1 engine (hashcat 5500): NTLM digest -> bitslice
triple-DES-split of the server challenge.

The MD4 digest words transpose into 168 key bit-planes (21 bytes =
three 7-byte DES keys, the last padded with constant-zero planes); the
bitslice DES circuit (ops/des.py) then encrypts the per-target
challenge under thirds of every candidate's NTLM hash simultaneously.
The challenge is a trace-time constant, so steps compile per target
(the JWT pattern) -- v1 captures come one challenge at a time.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import NetNtlmV1Engine
from dprf_tpu.engines.device.lm import match_mask, target_bits
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.des import (const_planes, des_encrypt_bitslice,
                              key_planes_from_bytes7)
from dprf_tpu.ops.md4 import md4_digest_words
from dprf_tpu.runtime.worker import DeviceWordlistWorker, MaskWorkerBase


def _digest_byte_planes(nt_words: jnp.ndarray) -> list:
    """MD4 digest uint32[B, 4] (LE words) -> 128 bit-planes in byte
    stream order (byte k = word k//4 >> 8*(k%4)), 32 candidates per
    int32 word."""
    B = nt_words.shape[0]
    groups = nt_words.astype(jnp.uint32).reshape(B // 32, 32, 4)
    weights = jnp.left_shift(jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    planes = []
    for k in range(16):
        byte = (groups[:, :, k // 4] >> jnp.uint32(8 * (k % 4))) \
            & jnp.uint32(0xFF)
        byte = byte.astype(jnp.int32)
        for bit in range(8):
            vals = (byte >> (7 - bit)) & 1
            planes.append((vals * weights).sum(axis=1, dtype=jnp.int32))
    return planes


def _nt_responses(nt_words: jnp.ndarray, challenge: bytes):
    """NTLM digests -> three cipher plane lists (the 24-byte NT
    response in bitslice form)."""
    dplanes = _digest_byte_planes(nt_words) + [0] * 40   # 5 zero bytes
    chal = const_planes(challenge)
    out = []
    for i in range(3):
        seven = dplanes[56 * i:56 * i + 56]
        out.append(des_encrypt_bitslice(
            key_planes_from_bytes7(seven), chal))
    return out


def _match(ciphers, digest: bytes, batch: int):
    """Three cipher plane lists vs the 24-byte response -> bool[B]."""
    lanebit = jnp.left_shift(jnp.int32(1), jnp.arange(32, dtype=jnp.int32))
    m = None
    for i in range(3):
        part = match_mask(ciphers[i], target_bits(digest[8 * i:8 * i + 8]))
        m = part if m is None else (m & part)
    return ((jnp.broadcast_to(m[:, None], (batch // 32, 32))
             & lanebit) != 0).reshape(batch)


def make_netntlmv1_mask_step(gen, target, batch: int,
                             hit_capacity: int = 64):
    """Per-target step: step(base_digits, n_valid) -> (count, lanes, _)."""
    if batch % 32:
        raise ValueError("bitslice batch must be a multiple of 32")
    if gen.length > 27:
        raise ValueError(f"netntlmv1 passwords cap at 27 chars "
                         f"(UTF-16LE widening); mask decodes to "
                         f"{gen.length}")
    flat = gen.flat_charsets
    length = gen.length
    challenge = target.params["challenge"]
    digest = target.digest

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        wide = pack_ops.utf16le_widen(cand)
        nt = md4_digest_words(
            pack_ops.pack_fixed(wide, 2 * length, big_endian=False))
        found = _match(_nt_responses(nt, challenge), digest, batch)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_netntlmv1_wordlist_step(gen, target, word_batch: int,
                                 hit_capacity: int = 64,
                                 word_tables=None):
    """word_tables: optional pre-uploaded (words_dev, lens_dev) so the
    per-target step factories share ONE device copy of the wordlist."""
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    if L > 27:
        raise ValueError("ntlm candidates cap at 27 chars; lower "
                         "--max-len")
    if word_tables is None:
        words_np, lens_np = gen.packed_words(pad_to=B,
                                             min_size=gen.n_words + B - 1)
        word_tables = (jnp.asarray(words_np), jnp.asarray(lens_np))
    words_dev, lens_dev = word_tables
    rules = gen.rules
    challenge = target.params["challenge"]
    digest = target.digest

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        RB = cw.shape[0]
        pad = (-RB) % 32
        cw = jnp.pad(cw, ((0, pad), (0, 0)))
        cl_p = jnp.pad(cl, (0, pad))
        wide = pack_ops.utf16le_widen(cw)
        nt = md4_digest_words(
            pack_ops.pack_varlen(wide, cl_p * 2, big_endian=False))
        found = _match(_nt_responses(nt, challenge), digest, RB + pad)
        found = found[:RB] & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class NetNtlmV1MaskWorker(MaskWorkerBase):
    """Per-target compiled steps (trace-time challenge), single-target
    hit decode per sweep."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.multi = len(self.targets) > 1
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        batch = max(32, (batch // 32) * 32)
        self.batch = self.stride = batch
        self._steps = [make_netntlmv1_mask_step(gen, t, batch,
                                                hit_capacity)
                       for t in self.targets]

    def process(self, unit):
        from dprf_tpu.runtime.worker import Hit
        hits: list = []
        for ti, step in enumerate(self._steps):
            queued = []
            for bstart in range(unit.start, unit.end, self.stride):
                n_valid = min(self.stride, unit.end - bstart)
                base = jnp.asarray(self.gen.digits(bstart),
                                   dtype=jnp.int32)
                queued.append((bstart, step(base, jnp.int32(n_valid))))
            for bstart, (count, lanes, _) in queued:
                count = int(count)
                if count == 0:
                    continue
                if count > self.hit_capacity:
                    # CpuWorker over the single target reports index 0
                    hits.extend(Hit(ti, h.cand_index, h.plaintext)
                                for h in self._rescan_one(bstart, unit,
                                                          ti))
                    continue
                for lane in np.asarray(lanes):
                    if lane < 0:
                        continue
                    gidx = bstart + int(lane)
                    hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True

    def _rescan_one(self, bstart: int, unit, ti: int):
        from dprf_tpu.runtime.worker import CpuWorker
        from dprf_tpu.runtime.workunit import WorkUnit
        if self.oracle is None:
            raise RuntimeError("hit buffer overflow and no oracle")
        end = min(bstart + self.stride, unit.end)
        sub = WorkUnit(-1, bstart, end - bstart)
        return CpuWorker(self.oracle, self.gen,
                         [self.targets[ti]]).process(sub)


class NetNtlmV1WordlistWorker(DeviceWordlistWorker):
    """DeviceWordlistWorker machinery over per-target bitslice steps;
    sweeps the word range once per target."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        self.multi = len(self.targets) > 1
        self._order = np.arange(max(1, len(self.targets)), dtype=np.int64)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.batch = batch
        words_np, lens_np = gen.packed_words(
            pad_to=self.word_batch,
            min_size=gen.n_words + self.word_batch - 1)
        tables = (jnp.asarray(words_np), jnp.asarray(lens_np))
        self._steps = [
            make_netntlmv1_wordlist_step(gen, t, self.word_batch,
                                         hit_capacity,
                                         word_tables=tables)
            for t in self.targets]

    def process(self, unit):
        from dprf_tpu.runtime.worker import Hit

        hits = []
        all_targets = self.targets
        try:
            for ti, step in enumerate(self._steps):
                # single-target view so the inherited hit decode AND
                # the overflow rescan both see exactly this target;
                # their index-0 hits rebind to ti
                self.step = step
                self.targets = [all_targets[ti]]
                self.multi = False
                hits.extend(Hit(ti, h.cand_index, h.plaintext)
                            for h in super().process(unit))
        finally:
            self.targets = all_targets
            self.multi = len(all_targets) > 1
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True


@register("netntlmv1", device="jax")
class JaxNetNtlmV1Engine(NetNtlmV1Engine):
    """Device NetNTLMv1: NTLM on the word pipeline, response via three
    bitslice DES encryptions of the challenge."""

    little_endian = True
    digest_words = 6

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return NetNtlmV1MaskWorker(self, gen, targets, batch=batch,
                                   hit_capacity=hit_capacity,
                                   oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return NetNtlmV1WordlistWorker(self, gen, targets, batch=batch,
                                       hit_capacity=hit_capacity,
                                       oracle=oracle)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None
