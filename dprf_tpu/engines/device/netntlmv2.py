"""NetNTLMv2 engine (challenge-response; hashcat 5600).

Line format: ``USER::DOMAIN:challenge:NTproofstr:blob`` (hex fields).
Algorithm: nt = MD4(UTF16LE(pw)); key2 = HMAC-MD5(nt,
UTF16LE(upper(USER) + DOMAIN)); proof = HMAC-MD5(key2,
challenge || blob); match proof against NTproofstr.

TPU mapping: both HMAC messages are per-TARGET constants, so they are
pre-padded into MD5 blocks on the host and shipped as RUNTIME
arguments (uint32[MAXB, 16] + block count) -- the device just chains
`md5_compress` over them per candidate under a masked static unroll.
Only the 16-byte keys vary per candidate, so the HMAC pads are single
xors.  One compiled step serves every target.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (NetNtlmV2Engine,
                                           parse_netntlmv2)
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.ops.md4 import md4_digest_words
from dprf_tpu.ops.md5 import INIT as MD5_INIT, md5_compress

#: static cap on pre-padded HMAC message blocks (challenge+blob; blobs
#: carry timestamps/target-info lists and are typically 100-400 bytes)
MAX_MSG_BLOCKS = 20

_IPAD = np.uint32(0x36363636)
_OPAD = np.uint32(0x5C5C5C5C)


def _hmac_padded(msg: bytes) -> bytes:
    """MD5 padding for a message that follows the 64-byte key block."""
    total = 64 + len(msg)
    padded = msg + b"\x80"
    padded += b"\x00" * ((56 - len(padded) % 64) % 64)
    return padded + (total * 8).to_bytes(8, "little")


def blocks_needed(msg: bytes) -> int:
    return len(_hmac_padded(msg)) // 64


def hmac_msg_blocks(msg: bytes, width_blocks: int,
                    what: str = "message") -> tuple:
    """Pre-pad an HMAC message (which follows the 64-byte key block)
    into MD5 blocks: (uint32[width_blocks, 16] LE words, n_blocks).
    `width_blocks` is the JOB-wide static width (max over targets), so
    the compiled unroll never exceeds the job's real block count."""
    padded = _hmac_padded(msg)
    n_blocks = len(padded) // 64
    if n_blocks > width_blocks:
        raise ValueError(
            f"{what} needs {n_blocks} HMAC blocks, cap {width_blocks}")
    buf = np.zeros((width_blocks, 64), np.uint8)
    buf[:n_blocks] = np.frombuffer(padded, np.uint8).reshape(n_blocks, 64)
    words = buf.reshape(width_blocks, 16, 4).astype(np.uint32) @ \
        np.array([1, 1 << 8, 1 << 16, 1 << 24], dtype=np.uint32)
    return words, n_blocks


def _hmac_md5_const_msg(key4: jnp.ndarray, msg_blocks: jnp.ndarray,
                        n_blocks) -> jnp.ndarray:
    """HMAC-MD5 with per-candidate 16-byte keys (uint32[B, 4]) over a
    constant pre-padded message (uint32[MAXB, 16], n_blocks valid) ->
    uint32[B, 4]."""
    B = key4.shape[0]
    key_block = jnp.zeros((B, 16), jnp.uint32).at[:, :4].set(key4)
    init = jnp.broadcast_to(jnp.asarray(MD5_INIT), (B, 4))
    istate = md5_compress(init, key_block ^ _IPAD)
    ostate = md5_compress(init, key_block ^ _OPAD)
    state = istate
    for k in range(msg_blocks.shape[0]):
        blk = jnp.broadcast_to(msg_blocks[k][None, :], (B, 16))
        new = md5_compress(state, blk)
        state = jnp.where(k < n_blocks, new, state)
    # outer: 16-byte inner digest, padded (64 key + 16 msg)
    inner_block = jnp.zeros((B, 16), jnp.uint32)
    inner_block = inner_block.at[:, :4].set(state)
    inner_block = inner_block.at[:, 4].set(jnp.uint32(0x80))
    inner_block = inner_block.at[:, 14].set(jnp.uint32((64 + 16) * 8))
    return md5_compress(ostate, inner_block)


def netntlmv2_digest_batch(cand: jnp.ndarray, lens: jnp.ndarray,
                           ident_blocks, ident_n, msg_blocks,
                           msg_n) -> jnp.ndarray:
    """Candidates -> NetNTLMv2 proof words uint32[B, 4]."""
    wide = pack_ops.utf16le_widen(cand)
    nt = md4_digest_words(pack_ops.pack_varlen(wide, lens * 2,
                                               big_endian=False))
    key2 = _hmac_md5_const_msg(nt, ident_blocks, ident_n)
    return _hmac_md5_const_msg(key2, msg_blocks, msg_n)


def make_netntlmv2_mask_step(gen, batch: int, hit_capacity: int = 64):
    """step(base_digits, n_valid, ident_blocks, ident_n, msg_blocks,
    msg_n, target uint32[4]) -> (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length
    if length > 27:
        raise ValueError("netntlmv2 passwords cap at 27 chars "
                         "(single-block UTF-16LE NTLM)")

    @jax.jit
    def step(base_digits, n_valid, ident_blocks, ident_n, msg_blocks,
             msg_n, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        digest = netntlmv2_digest_batch(cand, lens, ident_blocks,
                                        ident_n, msg_blocks, msg_n)
        found = cmp_ops.compare_single(digest, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_netntlmv2_wordlist_step(gen, word_batch: int,
                                 hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    if Lw > 27:
        raise ValueError("netntlmv2 passwords cap at 27 chars")
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, ident_blocks, ident_n, msg_blocks,
             msg_n, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        digest = netntlmv2_digest_batch(cw, cl, ident_blocks, ident_n,
                                        msg_blocks, msg_n)
        found = cmp_ops.compare_single(digest, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def _targs(targets):
    """Per-target step args, with the block-array widths sized to the
    JOB maximum (not the format cap) so the compiled unroll pays only
    for blocks some target actually uses."""
    idents, msgs = [], []
    for t in targets:
        p = t.params
        idents.append((p["user"].upper() + p["domain"]).encode("utf-16-le"))
        msgs.append(p["challenge"] + p["blob"])
    ident_w = max(blocks_needed(i) for i in idents)
    msg_w = max(blocks_needed(m) for m in msgs)
    if msg_w > MAX_MSG_BLOCKS:
        raise ValueError(f"a blob needs {msg_w} HMAC blocks "
                         f"(cap {MAX_MSG_BLOCKS})")
    out = []
    for t, ident, msg in zip(targets, idents, msgs):
        iw, inb = hmac_msg_blocks(ident, ident_w, what="user+domain")
        mw, mnb = hmac_msg_blocks(msg, msg_w, what="challenge+blob")
        out.append((jnp.asarray(iw), jnp.int32(inb),
                    jnp.asarray(mw), jnp.int32(mnb),
                    jnp.asarray(np.frombuffer(t.digest, dtype="<u4")
                                .astype(np.uint32))))
    return out


class NetNtlmV2MaskWorker(PhpassMaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 16,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = self.stride = batch
        self._targs = _targs(self.targets)
        self.step = make_netntlmv2_mask_step(gen, batch, hit_capacity)


class NetNtlmV2WordlistWorker(PhpassWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 16,
                 hit_capacity: int = 64, oracle=None):
        self.engine, self.gen = engine, gen
        self.targets = list(targets)
        self.hit_capacity, self.oracle = hit_capacity, oracle
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._targs = _targs(self.targets)
        self.step = make_netntlmv2_wordlist_step(gen, self.word_batch,
                                                 hit_capacity)


class ShardedNetNtlmV2MaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 14, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._targs = _targs(self.targets)
        if gen.length > 27:
            raise ValueError("netntlmv2 passwords cap at 27 chars")
        self.step = make_sharded_pertarget_step(
            gen, mesh, batch_per_device, netntlmv2_digest_batch, 4,
            hit_capacity)


@register("netntlmv2", device="jax")
class JaxNetNtlmV2Engine(NetNtlmV2Engine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return NetNtlmV2MaskWorker(self, gen, targets, batch=batch,
                                   hit_capacity=hit_capacity,
                                   oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return NetNtlmV2WordlistWorker(self, gen, targets, batch=batch,
                                       hit_capacity=hit_capacity,
                                       oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        return ShardedNetNtlmV2MaskWorker(
            self, gen, targets, mesh, batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)
