"""Device Ethereum keystore engines (hashcat 15600/15700).

KDF rides the existing PBKDF2-SHA256 or scrypt pipelines; the wallet
MAC is one single-block Keccak-256 (ops/keccak.py, uint32 lane pairs)
over dk[16:32] || ciphertext.  Salt, parameters, and ciphertext are
per-target trace-time constants, so steps compile per target through
the shared office-style step_factory workers; scrypt batches clamp to
the ROMix memory budget."""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import (EthereumPbkdf2Engine,
                                          EthereumScryptEngine)
from dprf_tpu.engines.device.office import (OfficeMaskWorker,
                                            OfficeWordlistWorker)
from dprf_tpu.engines.device.scrypt import _clamp_batch
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.hmac import pack_raw_varlen
from dprf_tpu.ops.keccak import keccak256_words


def _mac_found(dk_words, target):
    """dk uint32[B, 8] -> keccak MAC compare vs the target's stored
    mac."""
    ct = target.params["ct"]
    B = dk_words.shape[0]
    width = 16 + len(ct)
    msg = jnp.zeros((B, width), jnp.uint8)
    for j in range(16):
        msg = msg.at[:, j].set(
            (dk_words[:, 4 + j // 4] >> jnp.uint32(24 - 8 * (j % 4)))
            .astype(jnp.uint8))
    msg = msg.at[:, 16:].set(jnp.broadcast_to(
        jnp.asarray(np.frombuffer(ct, np.uint8)), (B, len(ct))))
    mac = keccak256_words(msg, jnp.full((B,), width, jnp.int32))
    want = jnp.asarray(np.frombuffer(target.digest, ">u4")
                       .astype(np.uint32))
    return cmp_ops.compare_single(mac, want)


def _dk_fn(target):
    """Per-target derived-key function over packed candidates."""
    from dprf_tpu.engines.cpu.engines import PBKDF2_SALT_MAX

    salt = target.params["salt"]
    sbuf = np.zeros(PBKDF2_SALT_MAX, np.uint8)
    sbuf[:len(salt)] = np.frombuffer(salt, np.uint8)
    sdev = jnp.asarray(sbuf)
    slen = jnp.int32(len(salt))
    if "iterations" in target.params:
        from dprf_tpu.engines.device.pbkdf2 import \
            pbkdf2_sha256_runtime_salt
        iters = jnp.int32(target.params["iterations"])

        def dk(cand, lengths):
            key = pack_raw_varlen(cand, lengths, big_endian=True)
            return pbkdf2_sha256_runtime_salt(key, sdev, slen, iters)
    else:
        from dprf_tpu.ops.scrypt import scrypt_dk
        n, r, p = (target.params[k] for k in ("n", "r", "p"))

        def dk(cand, lengths):
            key = pack_raw_varlen(cand, lengths, big_endian=True)
            return scrypt_dk(key, sdev, slen, n, r, p)
    return dk


def make_ethereum_mask_step(gen, target, batch: int,
                            hit_capacity: int = 64):
    flat = gen.flat_charsets
    length = gen.length
    dk = _dk_fn(target)

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        found = _mac_found(dk(cand, lengths), target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_ethereum_wordlist_step(gen, target, word_batch: int,
                                hit_capacity: int = 64):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    dk = _dk_fn(target)

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        found = _mac_found(dk(cw, cl), target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


class _EthereumDeviceMixin:
    little_endian = False
    digest_words = 8

    def _cap_batch(self, targets, batch: int) -> int:
        if any("n" in t.params for t in targets):
            return _clamp_batch(min(batch, 1 << 13), targets, "batch")
        return min(batch, 1 << 13)

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return OfficeMaskWorker(
            self, gen, targets, batch=self._cap_batch(targets, batch),
            hit_capacity=hit_capacity, oracle=oracle,
            step_factory=make_ethereum_mask_step)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return OfficeWordlistWorker(
            self, gen, targets, batch=self._cap_batch(targets, batch),
            hit_capacity=hit_capacity, oracle=oracle,
            step_factory=make_ethereum_wordlist_step)

    make_sharded_mask_worker = None
    make_sharded_wordlist_worker = None
    make_combinator_worker = None
    make_sharded_combinator_worker = None


@register("ethereum-pbkdf2", device="jax")
class JaxEthereumPbkdf2Engine(_EthereumDeviceMixin, EthereumPbkdf2Engine):
    """Device Ethereum keystore (PBKDF2 KDF) with the Keccak MAC."""


@register("ethereum-scrypt", device="jax")
class JaxEthereumScryptEngine(_EthereumDeviceMixin, EthereumScryptEngine):
    """Device Ethereum keystore (scrypt KDF) with the Keccak MAC."""
