"""Shared per-kind Pallas-step fallback for the per-target-sweep
workers (pdf, 7z): build the kernel step AND force its compile inside
one try, so both trace-time errors and Mosaic compile failures (the
SIGABRT/HTTP-500 class — engines.py wraps worker.warmup() for exactly
this reason) degrade to the XLA step instead of aborting mid-job.
Silent compile HANGS (TPU_PROBE_LOG_r04 finding 8 / r05 finding 12)
cannot be caught client-side; risky shapes stay gated off by their
eligibility predicates until measured."""

from __future__ import annotations


def kind_kernel_step(name: str, build, warmup):
    """build() -> lazily-jitted step; warmup(step) must invoke it once
    (hard_sync'd) to force the device compile.  Returns the warmed
    step, or None for the caller's XLA fallback."""
    try:
        step = build()
        warmup(step)
        return step
    except Exception as e:  # noqa: BLE001 -- any compiler/runtime error
        from dprf_tpu.utils.logging import DEFAULT as log
        log.warn(f"{name} kernel failed to build/compile; using the "
                 "XLA step", error=str(e))
        return None
