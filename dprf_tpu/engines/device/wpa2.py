"""Device WPA2 handshake-MIC engine (hc22000 WPA*02; hashcat 22000).

All heavy lifting reuses existing device ops: the PMK is the
runtime-salt PBKDF2-HMAC-SHA1 (one compiled step serves every essid);
the PRF-512 block and the EAPOL MIC are HMACs whose MESSAGES are
per-target constants -- pre-padded on the host and chained through the
shared compressions with only the (per-candidate) keys varying, the
same trick as the NetNTLMv2 engine.  Key version 2 MICs use HMAC-SHA1,
key version 1 uses HMAC-MD5; the worker picks the compiled step per
target's key version.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Wpa2EapolEngine
from dprf_tpu.engines.cpu.wpa2 import PRF_LABEL, ptk_data
from dprf_tpu.engines.device.netntlmv2 import (_hmac_md5_const_msg,
                                               hmac_msg_blocks)
from dprf_tpu.engines.device.pbkdf2_sha1 import pbkdf2_sha1_runtime_salt
from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,
                                            PhpassWordlistWorker)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.sha1 import INIT as SHA1_INIT, sha1_compress
from dprf_tpu.ops.hmac_sha1 import _block20

_IPAD = np.uint32(0x36363636)
_OPAD = np.uint32(0x5C5C5C5C)

#: static caps on pre-padded HMAC message blocks
PRF_BLOCKS = 2      # 22+1+76+1 = 100 bytes (+9 pad) -> 2 x 64
EAPOL_BLOCKS = 8    # EAPOL frames up to ~440 bytes


def sha1_msg_blocks(msg: bytes, width_blocks: int, what: str) -> tuple:
    """Pre-pad an HMAC-SHA1 message (after the 64-byte key block) into
    big-endian blocks: (uint32[width, 16], n_blocks)."""
    total = 64 + len(msg)
    padded = msg + b"\x80"
    padded += b"\x00" * ((56 - len(padded) % 64) % 64)
    padded += (total * 8).to_bytes(8, "big")
    n_blocks = len(padded) // 64
    if n_blocks > width_blocks:
        raise ValueError(f"{what} needs {n_blocks} HMAC blocks, "
                         f"cap {width_blocks}")
    buf = np.zeros((width_blocks, 64), np.uint8)
    buf[:n_blocks] = np.frombuffer(padded, np.uint8).reshape(n_blocks, 64)
    words = buf.reshape(width_blocks, 16, 4).astype(np.uint32) @ \
        np.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32)
    return words, n_blocks


def _hmac_sha1_const_msg(key_words: jnp.ndarray, n_key_words: int,
                         msg_blocks: jnp.ndarray,
                         n_blocks) -> jnp.ndarray:
    """HMAC-SHA1 with per-candidate keys (uint32[B, n_key_words],
    <= 16) over a constant pre-padded big-endian message ->
    uint32[B, 5]."""
    B = key_words.shape[0]
    key_block = jnp.zeros((B, 16),
                          jnp.uint32).at[:, :n_key_words].set(key_words)
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT), (B, 5))
    istate = sha1_compress(init, key_block ^ _IPAD)
    ostate = sha1_compress(init, key_block ^ _OPAD)
    state = istate
    for k in range(msg_blocks.shape[0]):
        blk = jnp.broadcast_to(msg_blocks[k][None, :], (B, 16))
        new = sha1_compress(state, blk)
        state = jnp.where(k < n_blocks, new, state)
    return sha1_compress(ostate, _block20(state))


def wpa2_mic_batch(cand, lens, essid, essid_len, iterations,
                   prf_blocks, prf_n, eapol_blocks, eapol_n,
                   keyver: int) -> jnp.ndarray:
    """Candidates -> MIC words uint32[B, 4] (keyver static: 1 = MD5
    MIC, 2 = SHA-1 MIC truncated to 16 bytes)."""
    # HMAC key = raw zero-padded passphrase block, per-lane lengths
    pos = jnp.arange(64, dtype=jnp.int32)[None, :]
    raw = jnp.where(pos < lens[:, None],
                    jnp.zeros((cand.shape[0], 64),
                              jnp.uint8).at[:, :cand.shape[1]].set(cand),
                    0)
    coef = jnp.asarray(np.array([1 << 24, 1 << 16, 1 << 8, 1],
                                dtype=np.uint32))
    key = (raw.reshape(cand.shape[0], 16, 4).astype(jnp.uint32)
           * coef).sum(axis=-1, dtype=jnp.uint32)
    pmk = pbkdf2_sha1_runtime_salt(key, essid, essid_len, iterations, 8)
    kck5 = _hmac_sha1_const_msg(pmk, 8, prf_blocks, prf_n)
    kck = kck5[:, :4]                 # first 16 bytes of PRF-512
    if keyver == 1:
        # HMAC-MD5 keys/messages are little-endian words: byte-swap the
        # big-endian KCK words
        kck_le = ((kck >> jnp.uint32(24))
                  | ((kck >> jnp.uint32(8)) & jnp.uint32(0xFF00))
                  | ((kck << jnp.uint32(8)) & jnp.uint32(0xFF0000))
                  | (kck << jnp.uint32(24)))
        return _hmac_md5_const_msg(kck_le, eapol_blocks, eapol_n)
    return _hmac_sha1_const_msg(kck, 4, eapol_blocks, eapol_n)[:, :4]


def make_wpa2_mask_step(gen, batch: int, keyver: int,
                        hit_capacity: int = 64):
    """step(base_digits, n_valid, essid, essid_len, iterations,
    prf_blocks, prf_n, eapol_blocks, eapol_n, target) ->
    (count, lanes, _)."""
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, essid, essid_len, iterations,
             prf_blocks, prf_n, eapol_blocks, eapol_n, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        mic = wpa2_mic_batch(cand, lens, essid, essid_len, iterations,
                             prf_blocks, prf_n, eapol_blocks, eapol_n,
                             keyver)
        found = cmp_ops.compare_single(mic, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def make_wpa2_wordlist_step(gen, word_batch: int, keyver: int,
                            hit_capacity: int = 64):
    from jax import lax

    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, Lw = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules

    @jax.jit
    def step(w0, n_valid_words, essid, essid_len, iterations,
             prf_blocks, prf_n, eapol_blocks, eapol_n, target):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, Lw))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, Lw)
        mic = wpa2_mic_batch(cw, cl, essid, essid_len, iterations,
                             prf_blocks, prf_n, eapol_blocks, eapol_n,
                             keyver)
        found = cmp_ops.compare_single(mic, target) & cv
        return cmp_ops.compact_hits(found, jnp.zeros_like(cl),
                                    hit_capacity)

    return step


def _wpa2_targs(targets, iterations: int):
    """Per-target (essid, essid_len, iterations, prf blocks/count,
    eapol blocks/count, mic words, keyver)."""
    out = []
    for t in targets:
        p = t.params
        ebuf = np.zeros((51,), np.uint8)     # pbkdf2 SALT_MAX width
        ebuf[:len(p["essid"])] = np.frombuffer(p["essid"], np.uint8)
        snonce = p["eapol"][17:49]
        prf_msg = (PRF_LABEL + b"\x00"
                   + ptk_data(p["mac_ap"], p["mac_sta"], p["anonce"],
                              snonce) + b"\x00")
        pw, pn = sha1_msg_blocks(prf_msg, PRF_BLOCKS, "PRF data")
        if p["keyver"] == 1:
            ew, en = hmac_msg_blocks(p["eapol"], EAPOL_BLOCKS,
                                     what="EAPOL frame")
        else:
            ew, en = sha1_msg_blocks(p["eapol"], EAPOL_BLOCKS,
                                     "EAPOL frame")
        dt = "<u4" if p["keyver"] == 1 else ">u4"
        out.append(((jnp.asarray(ebuf), jnp.int32(len(p["essid"])),
                     jnp.int32(iterations), jnp.asarray(pw),
                     jnp.int32(pn), jnp.asarray(ew), jnp.int32(en),
                     jnp.asarray(np.frombuffer(t.digest, dtype=dt)
                                 .astype(np.uint32))),
                    p["keyver"]))
    return out


class Wpa2MaskWorker(PhpassMaskWorker):
    """Per-target sweep with a per-keyver compiled step."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = self.stride = batch
        pairs = _wpa2_targs(self.targets, engine.iterations)
        self._targs = [targ for targ, _ in pairs]
        self._keyvers = [kv for _, kv in pairs]
        self._steps = {kv: make_wpa2_mask_step(gen, batch, kv,
                                               hit_capacity)
                       for kv in set(self._keyvers)}

    def process(self, unit):
        hits = []
        for ti in range(len(self.targets)):
            self.step = self._steps[self._keyvers[ti]]
            hits.extend(self._sweep_one(unit, ti))
        return hits
    # this sweep overlaps internally (queue-then-decode); an
    # inherited submit() would bypass the override
    process._serial_only = True

    def _sweep_one(self, unit, ti):
        from dprf_tpu.runtime.worker import Hit
        targ = self._targs[ti]
        hits = []
        queued = []
        for bstart in range(unit.start, unit.end, self.stride):
            n_valid = min(self.stride, unit.end - bstart)
            base = jnp.asarray(self.gen.digits(bstart), dtype=jnp.int32)
            queued.append((bstart, self.step(
                base, jnp.int32(n_valid), *targ)))
        for bstart, (cnt, lanes, _) in queued:
            cnt = int(cnt)
            if cnt == 0:
                continue
            if cnt > self.hit_capacity:
                hits.extend(self._rescan(
                    bstart, min(bstart + self.stride, unit.end), ti))
                continue
            for lane in np.asarray(lanes):
                if lane < 0:
                    continue
                gidx = bstart + int(lane)
                hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits


class Wpa2WordlistWorker(Wpa2MaskWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        pairs = _wpa2_targs(self.targets, engine.iterations)
        self._targs = [targ for targ, _ in pairs]
        self._keyvers = [kv for _, kv in pairs]
        self._steps = {kv: make_wpa2_wordlist_step(
            gen, self.word_batch, kv, hit_capacity)
            for kv in set(self._keyvers)}

    def _sweep_one(self, unit, ti):
        from dprf_tpu.runtime.worker import (Hit, word_cover_range,
                                             wordlist_lane_to_gidx)
        R = self.gen.n_rules
        w_start, w_end = word_cover_range(unit, R)
        targ = self._targs[ti]
        hits = []
        queued = []
        for ws in range(w_start, w_end, self.word_batch):
            nw = min(self.word_batch, w_end - ws, self.gen.n_words - ws)
            if nw <= 0:
                break
            queued.append((ws, nw, self.step(
                jnp.int32(ws), jnp.int32(nw), *targ)))
        for ws, nw, (cnt, lanes, _) in queued:
            cnt = int(cnt)
            if cnt == 0:
                continue
            if cnt > self.hit_capacity:
                start = max(unit.start, ws * R)
                end = min(unit.end, (ws + nw) * R)
                hits.extend(self._rescan(start, end, ti))
                continue
            for lane in np.asarray(lanes):
                if lane < 0:
                    continue
                gidx = wordlist_lane_to_gidx(int(lane), ws,
                                             self.word_batch, R)
                if not unit.start <= gidx < unit.end:
                    continue
                hits.append(Hit(ti, gidx, self.gen.candidate(gidx)))
        return hits


@register("wpa2-eapol", device="jax")
@register("wpa2", device="jax")
class JaxWpa2EapolEngine(Wpa2EapolEngine):
    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        return Wpa2MaskWorker(self, gen, targets,
                              batch=min(batch, 1 << 13),
                              hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return Wpa2WordlistWorker(self, gen, targets,
                                  batch=min(batch, 1 << 13),
                                  hit_capacity=hit_capacity,
                                  oracle=oracle)
