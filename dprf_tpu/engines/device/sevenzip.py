"""Device 7-Zip engine (hashcat 11600): fully-fused stored-entry check.

The whole verification runs in one jitted step per target:

- **KDF**: SHA-256 over the 2^cycles concatenated counter units.  The
  stream layout (salt || UTF-16LE pw || LE64 counter, repeating) is
  STATIC for a fixed mask length, so the step walks it in
  lcm(64, unit)-byte groups — each group is a whole number of both
  64-byte SHA blocks and counter units, so every byte's source
  (salt const / candidate column / counter shift) is compile-time
  wiring and the group loop is a `lax.fori_loop` of
  `sha256_compress` calls with zero gathers.
- **AES-256-CBC**: ops/aes.aes_decrypt_blocks (ciphertext and IV are
  target constants, so the CBC xor chain is constant wiring too).
- **CRC32**: vectorized table walk over the decrypted bytes; the
  found-mask compares the full 32-bit CRC, so device hits are exact.

Throughput is KDF-bound (~2^19 * unit/64 SHA-256 compressions per
candidate at the standard cycles=19).  Wordlist attacks fall back to
the CPU oracle (the stream layout is length-dependent, and hashlib's
C loop is genuinely competitive for this shape); mask + sharded mask
are the device paths.
"""

from __future__ import annotations

import math
import struct

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.base import Target
from dprf_tpu.engines.cpu.sevenzip import SevenZipEngine
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.utils import env as envreg
from dprf_tpu.ops.aes import aes_decrypt_blocks
from dprf_tpu.ops.sha256 import INIT as SHA256_INIT, sha256_compress

#: device-path cap on the encrypted payload: the AES block loop and
#: CRC walk are part of one jitted step, so a multi-KB stored file
#: would explode the trace (aes_decrypt_blocks unrolls 14 rounds per
#: block).  Targets above the cap run on the CPU oracle instead --
#: correct either way, and the KDF (not the payload) dominates cost.
DEVICE_DATA_CAP = envreg.get_int("DPRF_7Z_DEVICE_DATA_CAP")

#: CRC-32 (IEEE 802.3, the zlib polynomial) byte-step table.
_CRC_TABLE = np.zeros(256, np.uint32)
for _i in range(256):
    _c = _i
    for _ in range(8):
        _c = (0xEDB88320 ^ (_c >> 1)) if _c & 1 else _c >> 1
    _CRC_TABLE[_i] = _c


def crc32_batch(data: jnp.ndarray, nbytes: int) -> jnp.ndarray:
    """CRC32 over the first nbytes of uint8[B, N] rows, vectorized:
    a fori_loop of one 256-entry table gather per byte (the loop is
    rolled so the trace stays small whatever the payload size)."""
    tbl = jnp.asarray(_CRC_TABLE)
    c0 = jnp.full((data.shape[0],), 0xFFFFFFFF, jnp.uint32)

    def body(q, c):
        byte = lax.dynamic_slice_in_dim(data, q, 1,
                                        axis=1)[:, 0].astype(jnp.uint32)
        idx = ((c ^ byte) & jnp.uint32(0xFF)).astype(jnp.int32)
        return jnp.take(tbl, idx) ^ (c >> jnp.uint32(8))

    return lax.fori_loop(0, nbytes, body, c0) ^ jnp.uint32(0xFFFFFFFF)


def sevenzip_key_words(cand, length: int, salt: bytes, cycles: int):
    """Candidates uint32[B, length] -> SHA-256 key state uint32[B, 8].

    Walks the counter stream in lcm(64, unit)-byte groups; see module
    docstring.  cycles <= 24 keeps the counter in 32 bits."""
    B = cand.shape[0]
    sl = len(salt)
    unit = sl + 2 * length + 8
    g = math.gcd(64, unit)
    bpg, upg = unit // g, 64 // g          # blocks / units per group
    n_units = 1 << cycles
    if n_units % upg:
        raise ValueError(f"cycles {cycles} stream does not align to "
                         f"the {upg}-unit group")
    n_groups = n_units // upg

    def byte_at(q: int, grp):
        """Stream byte at group offset q as uint32[B] (grp traced)."""
        u, off = divmod(q, unit)
        if off < sl:
            return jnp.full((B,), np.uint32(salt[off]))
        off -= sl
        if off < 2 * length:
            if off % 2:
                return jnp.zeros((B,), jnp.uint32)   # UTF-16LE high
            return cand[:, off // 2].astype(jnp.uint32)
        cb = off - 2 * length                        # LE64 counter
        if cb >= 4:
            return jnp.zeros((B,), jnp.uint32)       # cycles <= 24
        counter = (grp * upg + u).astype(jnp.uint32)
        return jnp.broadcast_to(
            (counter >> jnp.uint32(8 * cb)) & jnp.uint32(0xFF), (B,))

    def group(grp, state):
        grp32 = grp.astype(jnp.int32)
        for b in range(bpg):
            words = []
            for w in range(16):
                q = 64 * b + 4 * w
                words.append(
                    (byte_at(q, grp32) << jnp.uint32(24))
                    | (byte_at(q + 1, grp32) << jnp.uint32(16))
                    | (byte_at(q + 2, grp32) << jnp.uint32(8))
                    | byte_at(q + 3, grp32))
            state = sha256_compress(state, jnp.stack(words, axis=1))
        return state

    state = jnp.broadcast_to(
        jnp.asarray(SHA256_INIT, jnp.uint32), (B, 8))
    state = lax.fori_loop(0, n_groups, group, state)

    # final padding block: the stream ends exactly on a group
    # boundary, so it is 0x80 + zeros + the 64-bit big-endian bitlen
    bitlen = n_units * unit * 8
    pad = np.zeros(16, np.uint32)
    pad[0] = 0x80000000
    pad[14] = (bitlen >> 32) & 0xFFFFFFFF
    pad[15] = bitlen & 0xFFFFFFFF
    return sha256_compress(state, jnp.broadcast_to(
        jnp.asarray(pad), (B, 16)))


def make_state_check(params: dict):
    """uint32[B, 8] SHA-256 key states -> uint32[B, 1] recomputed
    CRC32 (exact); shared by the XLA KDF path and the Pallas KDF
    kernel (ops/pallas_7z.py)."""
    data, iv = params["data"], params["iv"]
    unpacked = params["unpacked_len"]
    blocks = np.frombuffer(data, np.uint8).reshape(-1, 16)
    prev = np.concatenate(
        [np.frombuffer((iv + bytes(16))[:16], np.uint8)[None],
         blocks[:-1]], axis=0)           # CBC xor chain, all constant

    def check(state):
        # key bytes: big-endian serialization of the 8 state words
        B = state.shape[0]
        shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
        keys = ((state[:, :, None] >> shifts[None, None, :])
                & jnp.uint32(0xFF)).reshape(B, 32).astype(jnp.uint8)
        plain = aes_decrypt_blocks(keys, blocks) ^ \
            jnp.asarray(prev)[None]
        flat = plain.reshape(B, -1)
        return crc32_batch(flat, unpacked)[:, None]

    return check


def make_7z_filter(length: int, params: dict):
    """fb(cand, lens) -> uint32[B, 1] recomputed CRC32 (exact)."""
    salt, cycles = params["salt"], params["cycles"]
    check = make_state_check(params)

    def fb(cand, lens):
        return check(sevenzip_key_words(cand, length, salt, cycles))

    return fb


def _make_step(gen, batch: int, params: dict, hit_capacity: int):
    flat = gen.flat_charsets
    length = gen.length
    fb = make_7z_filter(length, params)

    @jax.jit
    def step(base_digits, n_valid, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        word = fb(cand, lens)
        found = cmp_ops.compare_single(word, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


def _make_kernel_step(gen, batch: int, params: dict,
                      hit_capacity: int, interpret: bool):
    """KDF on the Pallas kernel (ops/pallas_7z.py), AES+CRC verdict
    in XLA -- the KDF is ~99.9% of the work at production cycles."""
    from dprf_tpu.ops.pallas_7z import make_7z_kdf_pallas_fn

    check = make_state_check(params)
    kdf = make_7z_kdf_pallas_fn(gen, batch, params["salt"],
                                params["cycles"], interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid, target):
        word = check(kdf(base_digits))
        found = cmp_ops.compare_single(word, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,  # noqa: E402
                                            ShardedPhpassMaskWorker)


def _crc_word(t: Target) -> jnp.ndarray:
    return jnp.asarray(
        np.array([struct.unpack("<I", t.digest)[0]], np.uint32))


class SevenZipMaskWorker(PhpassMaskWorker):
    """Per-target sweep; every target's stream layout/data are static,
    so each target owns a compiled step."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 12,
                 hit_capacity: int = 64, oracle=None):
        from dprf_tpu.ops.pallas_7z import sevenzip_kernel_eligible
        from dprf_tpu.ops.pallas_mask import TILE, pallas_mode

        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        mode = pallas_mode()
        if mode is not None:
            batch = max(TILE, (batch // TILE) * TILE)
        self.batch = self.stride = batch
        self._steps = []
        for t in self.targets:
            step = None
            if mode is not None and sevenzip_kernel_eligible(
                    gen, t.params["cycles"], len(t.params["salt"])):
                from dprf_tpu.engines.device._kernel_util import \
                    kind_kernel_step
                from dprf_tpu.utils.sync import hard_sync
                tw = _crc_word(t)
                step = kind_kernel_step(
                    "7z KDF",
                    lambda t=t: _make_kernel_step(
                        gen, batch, t.params, hit_capacity,
                        interpret=mode.get("interpret", False)),
                    lambda s, tw=tw: hard_sync(s(
                        jnp.zeros((gen.length,), jnp.int32),
                        jnp.int32(0), tw)))
            if step is None:
                step = _make_step(gen, batch, t.params, hit_capacity)
            self._steps.append(step)
        self._targs = [(ti, _crc_word(t))
                       for ti, t in enumerate(self.targets)]

    def step(self, base, n_valid, ti: int, target):
        return self._steps[ti](base, n_valid, target)


class ShardedSevenZipMaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 10, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._steps = [make_sharded_pertarget_step(
            gen, mesh, batch_per_device,
            make_7z_filter(gen.length, t.params), 0, hit_capacity)
            for t in self.targets]
        self._targs = [(ti, _crc_word(t))
                       for ti, t in enumerate(self.targets)]

    def step(self, base, n_valid, ti: int, target):
        return self._steps[ti](base, n_valid, target)


def _over_cap(targets) -> bool:
    big = max(len(t.params["data"]) for t in targets)
    if big <= DEVICE_DATA_CAP:
        return False
    from dprf_tpu.utils.logging import DEFAULT as log
    log.warn("7z stored entry exceeds the device payload cap; "
             "running on the CPU oracle",
             data_bytes=big, cap=DEVICE_DATA_CAP)
    return True


@register("7z", device="jax")
@register("sevenzip", device="jax")
class JaxSevenZipEngine(SevenZipEngine):
    def make_mask_worker(self, gen, targets, batch: int,
                         hit_capacity: int, oracle=None):
        if _over_cap(targets):
            from dprf_tpu.runtime.worker import CpuWorker
            return CpuWorker(oracle or self, gen, targets)
        return SevenZipMaskWorker(self, gen, targets, batch=batch,
                                  hit_capacity=hit_capacity,
                                  oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        if _over_cap(targets):
            from dprf_tpu.runtime.worker import CpuWorker
            return CpuWorker(oracle or self, gen, targets)
        return ShardedSevenZipMaskWorker(
            self, gen, targets, mesh, batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)
