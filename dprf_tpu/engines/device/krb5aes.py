"""Device Kerberos AES etype-17/18 engines (hashcat 19600/19700,
19800/19900, 32100): fused PBKDF2 -> DK -> CBC-prefilter check.

TPU mapping of the RFC 3962 check (cpu/krb5aes.py for the spec and
the full oracle):

- **PBKDF2-HMAC-SHA1** (4096 iterations, 1 block for AES-128 / 2 for
  AES-256) dominates the cost — the same fused XLA chain config 5's
  PMKID engine rides (`ops/hmac_sha1.pbkdf2_sha1_block`).
- **DK derivations** (string-to-key's "kerberos" fold, then the
  usage||0xAA encryption subkey) are 1-2 batched AES encryptions each
  with per-candidate keys (`ops/aes.aes_encrypt_block_batch`); the
  n-fold constants are host bytes.
- **Prefilter**: decrypt ONE ciphertext block with Ke and check the
  DER header right after the 16-byte confounder — plaintext bytes
  [16, 20) are deterministic given len(edata2) exactly like the
  etype-23 filter (engines/device/krb5.der_filter_words, CONF=8
  there / 16 here).  Block 2 is plain CBC as long as it is not in
  the CTS stolen pair, so the device path requires edata2 >= 64
  bytes (always true for real TGS/AS-REP tickets; short Pre-Auth
  timestamps fall back to the CPU oracle).
- Device hits are *maybes*: the masked DER window is 32 bits for
  long-form tickets but only 24 bits for short-form ones (the
  short-form branch masks byte 4 out, so expect a 2^-24 false-maybe
  rate there, 2^-32 otherwise); the coordinator oracle-verifies each
  with the full CTS + HMAC-SHA1-96 chain, mirroring the etype-23
  design.

Mask, wordlist+rules, and sharded mask all run on device (variable
candidate lengths flow through pack_raw_varlen into the HMAC key
block); jobs fall back to the CPU oracle only when a target's edata2
sits below the CTS-safe floor, its salt (realm+user) exceeds the
one-block PBKDF2 salt budget (51 bytes), or a wordlist exceeds the
one-block HMAC key budget (55 bytes).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.krb5aes import (Krb5AsRepAesEngine,
                                          Krb5PaAesEngine,
                                          Krb5TgsAesEngine,
                                          USAGE_AS_REP,
                                          USAGE_PA_TIMESTAMP,
                                          USAGE_TGS_REP_TICKET, nfold)
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.aes import aes_decrypt_blocks, aes_encrypt_block_batch
from dprf_tpu.ops.hmac_sha1 import hmac_key_states, pbkdf2_sha1_block

#: confounder prefix of the decrypted plaintext (one AES block).
CONF = 16

#: smallest edata2 the device prefilter covers: the DER window block
#: (index 1) must sit outside the CTS stolen pair in every layout.
MIN_DEVICE_EDATA = 64

#: largest salt (realm+user) the fused PBKDF2 path packs: salt + the
#: 4-byte block index + 0x80 marker + 8-byte length must fit one
#: 64-byte SHA-1 block (ops/hmac_sha1.salt_block).  Long AD realms or
#: service-account principals above this run on the CPU oracle --
#: demoted at routing time, NOT discovered as a ValueError at the
#: first step() (ADVICE.md round-5 medium).
MAX_DEVICE_SALT = 51


def der_filter_words_aes(edata_len: int, usage: int) -> tuple[int, int]:
    """(expected, mask) little-endian uint32 over plaintext bytes
    [16, 20) — the DER header right after the confounder.  Same
    definite-minimal-length reasoning as the etype-23 filter
    (engines/device/krb5.der_filter_words), with the AES confounder
    width and per-usage application tags:

    TGS-REP ticket enc-part is EncTicketPart [APPLICATION 3] = 0x63
    (exact); AS-REP is EncASRepPart 0x79 with 0x7A KDC variance
    (match 0x78-0x7B, mask 0xFC); the Pre-Auth timestamp is a bare
    SEQUENCE 0x30."""
    if usage == USAGE_TGS_REP_TICKET:
        tag_exp, tag_mask = 0x63, 0xFF
    elif usage == USAGE_AS_REP:
        tag_exp, tag_mask = 0x78, 0xFC
    else:
        tag_exp, tag_mask = 0x30, 0xFF
    L = edata_len - CONF            # DER blob length (CTS: no padding)
    # first content byte after the length: inner SEQUENCE 0x30, or the
    # [0] context tag 0xA0 of a PA-ENC-TS-ENC (same for BOTH length
    # forms -- the long-form branches below must not assume 0x30, or a
    # large Pre-Auth blob's true password would be prefilter-rejected:
    # a silent missed-crack, ADVICE.md round-5 low)
    inner = 0xA0 if usage == USAGE_PA_TIMESTAMP else 0x30
    if L - 2 < 0x80:
        # short-form length; the third window byte is the first
        # content byte; byte 4 varies, so the window is 24 bits here
        exp = [tag_exp, L - 2, inner, 0x00]
        msk = [tag_mask, 0xFF, 0xFF, 0x00]
    elif L - 3 <= 0xFF:
        exp = [tag_exp, 0x81, L - 3, inner]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    elif L - 4 <= 0xFFFF:
        C = L - 4
        exp = [tag_exp, 0x82, (C >> 8) & 0xFF, C & 0xFF]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    elif L - 5 <= 0xFFFFFF:
        C = L - 5
        exp = [tag_exp, 0x83, (C >> 16) & 0xFF, (C >> 8) & 0xFF]
        msk = [tag_mask, 0xFF, 0xFF, 0xFF]
    else:
        raise ValueError("edata2 above 16 MB is not a ticket; use "
                         "--device=cpu")
    exp_w = sum(e << (8 * i) for i, e in enumerate(exp))
    msk_w = sum(m << (8 * i) for i, m in enumerate(msk))
    return exp_w & msk_w, msk_w


def _words_to_bytes_be(words: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, W] big-endian words -> uint8[B, 4W] (SHA-1/PBKDF2
    output serialization)."""
    B, W = words.shape
    shifts = jnp.asarray([24, 16, 8, 0], jnp.uint32)
    return ((words[:, :, None] >> shifts[None, None, :])
            & jnp.uint32(0xFF)).reshape(B, 4 * W).astype(jnp.uint8)


def _dk_batch(base: jnp.ndarray, constant: bytes) -> jnp.ndarray:
    """RFC 3961 DK with per-candidate base keys uint8[B, 16|32]:
    chain ECB encryptions of the n-folded constant until key-length
    bytes exist (1 block for AES-128, 2 for AES-256)."""
    B, kl = base.shape
    nf = nfold(constant, 16) if len(constant) != 16 else constant
    block = jnp.broadcast_to(
        jnp.asarray(np.frombuffer(nf, np.uint8)), (B, 16))
    out = aes_encrypt_block_batch(base, block)
    if kl == 16:
        return out
    out2 = aes_encrypt_block_batch(base, out)
    return jnp.concatenate([out, out2], axis=1)


def make_krb5aes_check(params: dict):
    """check(base uint8[B, key_len] PBKDF2 output) -> uint32[B, 1]
    MASKED DER window: the cheap tail (DK derivations + one-block CBC
    decrypt) shared by the XLA filter and the Pallas KDF-kernel step
    (the 7z pattern: heavy KDF on the kernel, verdict in XLA)."""
    usage, edata = params["usage"], params["edata"]
    _, mask_w = der_filter_words_aes(len(edata), usage)
    c1 = np.frombuffer(edata[:16], np.uint8)
    c2 = np.frombuffer(edata[16:32], np.uint8).reshape(1, 16)
    usage_const = usage.to_bytes(4, "big") + b"\xaa"

    def check(base):
        kkey = _dk_batch(base, b"kerberos")
        ke = _dk_batch(kkey, usage_const)
        p2 = aes_decrypt_blocks(ke, c2)[:, 0] ^ jnp.asarray(c1)
        word = (p2[:, 0].astype(jnp.uint32)
                | (p2[:, 1].astype(jnp.uint32) << 8)
                | (p2[:, 2].astype(jnp.uint32) << 16)
                | (p2[:, 3].astype(jnp.uint32) << 24))
        return (word & jnp.uint32(mask_w))[:, None]

    return check


def make_krb5aes_filter(params: dict, iterations: int = 4096):
    """fb(cand, lens) -> uint32[B, 1] MASKED DER window (compare
    against the masked expectation from der_filter_words_aes);
    candidate lengths arrive at trace time via `lens` (varlen HMAC
    keys), so the filter serves mask, wordlist, and sharded steps
    alike."""
    salt, key_len = params["salt"], params["key_len"]
    check = make_krb5aes_check(params)

    def fb(cand, lens):
        from dprf_tpu.ops.hmac import pack_raw_varlen
        key_words = pack_raw_varlen(cand, lens, big_endian=True)
        istate, ostate = hmac_key_states(key_words)
        t1 = pbkdf2_sha1_block(istate, ostate, salt, 1, iterations)
        if key_len == 16:
            base = _words_to_bytes_be(t1)[:, :16]
        else:
            t2 = pbkdf2_sha1_block(istate, ostate, salt, 2, iterations)
            base = _words_to_bytes_be(
                jnp.concatenate([t1, t2[:, :3]], axis=1))
        return check(base)

    return fb


def _expected_word(t) -> jnp.ndarray:
    exp_w, _ = der_filter_words_aes(len(t.params["edata"]),
                                    t.params["usage"])
    return jnp.asarray(np.array([exp_w], np.uint32))


from dprf_tpu.engines.device.phpass import (PhpassMaskWorker,  # noqa: E402
                                            PhpassWordlistWorker,
                                            ShardedPhpassMaskWorker)


def kdf_kernel_enabled(interpret: bool) -> bool:
    """The PBKDF2 kernel route is DEFAULT-OFF on real hardware until a
    recorded planted-crack run exists (DPRF_KRB5AES_KERNEL=1 enables
    it for the measuring session): the shape matches the
    hardware-proven PMKID kernel, but this repo records first compiles
    of new kernel variants before trusting them (TPU_PROBE_LOG_r05
    finding 12's lesson).  Interpret mode (tests) is ungated."""
    from dprf_tpu.utils import env as envreg
    return interpret or envreg.get_bool("DPRF_KRB5AES_KERNEL")


def _make_kdf_kernel_step(gen, batch: int, params: dict,
                          hit_capacity: int, interpret: bool,
                          iterations: int = 4096, kdf=None):
    """Mask step with PBKDF2 on the Pallas kernel
    (ops/pallas_pbkdf2.make_pbkdf2_kdf_pallas_fn) and the DK + CBC
    verdict in XLA — the KDF is ~99% of the work at 4096 iterations.
    The salt bytes and iteration count are runtime SMEM scalars, so
    callers share one compiled `kdf` per (mask, salt_len, key_len)
    across targets (the worker passes its cache entry)."""
    from dprf_tpu.ops.pallas_pbkdf2 import make_pbkdf2_kdf_pallas_fn

    salt, key_len = params["salt"], params["key_len"]
    check = make_krb5aes_check(params)
    if kdf is None:
        kdf = make_pbkdf2_kdf_pallas_fn(gen, batch, len(salt),
                                        key_len // 4,
                                        interpret=interpret)
    salt_dev = jnp.asarray(np.frombuffer(salt, np.uint8)
                           .astype(np.int32))

    @jax.jit
    def step(base_digits, n_valid, target):
        words = kdf(base_digits, jnp.int32(iterations), salt_dev)
        word = check(_words_to_bytes_be(words))
        found = cmp_ops.compare_single(word, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step, kdf


class Krb5AesMaskWorker(PhpassMaskWorker):
    """Per-target sweep (salt/etype/edata are per-target constants,
    so each target owns a compiled step).  A target outside the device
    envelope (edata2 below the CTS-safe floor, or salt above the
    one-block PBKDF2 budget) gets a HOST pseudo-step (full oracle over
    the unit) instead of demoting the whole job: mixed hashlists keep
    every eligible target on the device path.  On TPU the PBKDF2 runs
    on the fused Pallas kernel (warmup-gated, XLA fallback)."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        from dprf_tpu.engines.device._kernel_util import kind_kernel_step
        from dprf_tpu.ops.pallas_mask import TILE, pallas_mode
        from dprf_tpu.utils.sync import hard_sync

        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        mode = pallas_mode()
        if mode is not None:
            batch = max(TILE, (batch // TILE) * TILE)
        self.batch = self.stride = batch
        self._steps = []
        self.kernel_targets = set()    # target indices on the kernel
        kdf_cache = {}    # one compiled KDF per (salt_len, key_len)
        for ti, t in enumerate(self.targets):
            # below-floor edata2 OR over-budget salt: host pseudo-step
            # for THIS target only (the rest of the hashlist keeps its
            # compiled device steps)
            if not _target_device_ok(t):
                self._steps.append(self._host_step(ti))
                continue
            step = None
            interp = (mode or {}).get("interpret", False)
            if mode is not None and kdf_kernel_enabled(interp):
                tw = _expected_word(t)
                kind = (len(t.params["salt"]), t.params["key_len"])
                built = {}

                def build(t=t, kind=kind):
                    s, kdf = _make_kdf_kernel_step(
                        gen, batch, t.params, hit_capacity,
                        interpret=interp,
                        iterations=getattr(engine, "iterations", 4096),
                        kdf=kdf_cache.get(kind))
                    built["kdf"] = kdf
                    return s

                step = kind_kernel_step(
                    "krb5aes pbkdf2", build,
                    lambda s, tw=tw: hard_sync(s(
                        jnp.zeros((gen.length,), jnp.int32),
                        jnp.int32(0), tw)))
                if step is not None and "kdf" in built:
                    kdf_cache[kind] = built["kdf"]
            if step is None:
                fb = make_krb5aes_filter(
                    t.params, getattr(engine, "iterations", 4096))
                step = _make_step(gen, batch, fb, hit_capacity)
            else:
                self.kernel_targets.add(ti)
            self._steps.append(step)
        self._targs = [(ti, _expected_word(t))
                       for ti, t in enumerate(self.targets)]

    def _rescan(self, start, end, ti):
        # the device engine IS a full CPU-capable oracle (subclass of
        # the cpu engine), so an overflow without an explicit oracle
        # still rescans exactly instead of raising
        if self.oracle is None:
            from dprf_tpu.runtime.worker import CpuWorker, Hit
            from dprf_tpu.runtime.workunit import WorkUnit
            sub = WorkUnit(-1, start, end - start)
            hits = CpuWorker(self.engine, self.gen,
                             [self.targets[ti]]).process(sub)
            return [Hit(ti, h.cand_index, h.plaintext) for h in hits]
        return super()._rescan(start, end, ti)

    def _host_step(self, ti: int):
        """Oracle scan with the jitted-step output contract; the base
        sweep's int()/np.asarray() reads work on plain numpy."""
        t = self.targets[ti]
        oracle = self.oracle or self.engine

        def step(base_digits, n_valid, target):
            digits = [int(d) for d in np.asarray(base_digits)]
            start = 0
            for d, r in zip(digits, self.gen.radices):
                start = start * r + d
            n = int(n_valid)
            lanes = [i for i in range(n)
                     if oracle.verify(self.gen.candidate(start + i), t)]
            buf = np.full((self.hit_capacity,), -1, np.int32)
            buf[:len(lanes)] = lanes[:self.hit_capacity]
            return (np.int32(len(lanes)), buf,
                    np.zeros_like(buf))

        return step

    def step(self, base, n_valid, ti: int, target):
        return self._steps[ti](base, n_valid, target)


def _make_step(gen, batch: int, fb, hit_capacity: int):
    flat = gen.flat_charsets
    length = gen.length

    @jax.jit
    def step(base_digits, n_valid, target):
        cand = gen.decode_batch(base_digits, flat, batch)
        lens = jnp.full((batch,), length, jnp.int32)
        word = fb(cand, lens)
        found = cmp_ops.compare_single(word, target)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, jnp.zeros((batch,), jnp.int32),
                                    hit_capacity)

    return step


class Krb5AesWordlistWorker(PhpassWordlistWorker):
    """Wordlist+rules on device — the realistic Kerberoasting attack
    shape; per-target compiled steps (the shared scaffold of
    phpass.make_pertarget_wordlist_step with this engine's filter;
    variable candidate lengths flow into pack_raw_varlen)."""

    def __init__(self, engine, gen, targets, batch: int = 1 << 13,
                 hit_capacity: int = 64, oracle=None):
        from dprf_tpu.engines.device.phpass import \
            make_pertarget_wordlist_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.batch = batch
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self._steps = [
            make_pertarget_wordlist_step(
                gen, self.word_batch,
                make_krb5aes_filter(t.params,
                                    getattr(engine, "iterations", 4096)),
                hit_capacity)
            for t in self.targets]
        self._targs = [(ti, _expected_word(t))
                       for ti, t in enumerate(self.targets)]

    def step(self, w0, n_valid, ti: int, target):
        return self._steps[ti](w0, n_valid, target)


class ShardedKrb5AesMaskWorker(ShardedPhpassMaskWorker):
    def __init__(self, engine, gen, targets, mesh,
                 batch_per_device: int = 1 << 11, hit_capacity: int = 64,
                 oracle=None):
        from dprf_tpu.parallel.sharded import \
            make_sharded_pertarget_step
        self._setup_sweep(engine, gen, targets, hit_capacity, oracle)
        self.mesh = mesh
        self.batch = self.stride = mesh.devices.size * batch_per_device
        self._steps = [make_sharded_pertarget_step(
            gen, mesh, batch_per_device,
            make_krb5aes_filter(t.params,
                                getattr(engine, "iterations", 4096)),
            0, hit_capacity)
            for t in self.targets]
        self._targs = [(ti, _expected_word(t))
                       for ti, t in enumerate(self.targets)]

    def step(self, base, n_valid, ti: int, target):
        return self._steps[ti](base, n_valid, target)


def _target_device_ok(t) -> bool:
    """One target's eligibility for the fused device path: edata2 at
    or above the CTS-safe floor AND a salt that fits the one-block
    PBKDF2 layout.  The salt check matters: without it a long AD
    realm/principal crashes the job at the first step() with
    'salt too long for one block' instead of demoting to the oracle."""
    return (len(t.params["edata"]) >= MIN_DEVICE_EDATA
            and len(t.params["salt"]) <= MAX_DEVICE_SALT)


def _device_ok(targets, any_ok: bool = False) -> bool:
    """False when the job must demote to the CPU oracle.  With
    any_ok (the mask sweep, which routes ineligible targets to host
    pseudo-steps per target), one device-eligible target keeps the
    device worker; the wordlist/sharded scaffolds demote on any
    ineligible target (below-floor edata2 or over-budget salt)."""
    eligible = [_target_device_ok(t) for t in targets]
    ok = any(eligible) if any_ok else all(eligible)
    if not ok:
        from dprf_tpu.utils.logging import DEFAULT as log
        log.warn("krb5 AES target outside the device envelope (edata2 "
                 "below the CTS-safe floor, or salt above the "
                 "one-block budget); running on the CPU oracle",
                 edata_bytes=min(len(t.params["edata"]) for t in targets),
                 floor=MIN_DEVICE_EDATA,
                 salt_bytes=max(len(t.params["salt"]) for t in targets),
                 salt_cap=MAX_DEVICE_SALT)
    return ok


class _JaxKrb5AesMixin:
    def make_mask_worker(self, gen, targets, batch: int,
                         hit_capacity: int, oracle=None):
        if not _device_ok(targets, any_ok=True):
            from dprf_tpu.runtime.worker import CpuWorker
            return CpuWorker(oracle or self, gen, targets)
        return Krb5AesMaskWorker(self, gen, targets, batch=batch,
                                 hit_capacity=hit_capacity,
                                 oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        if not _device_ok(targets) or gen.max_len > 55:
            from dprf_tpu.runtime.worker import CpuWorker
            return CpuWorker(oracle or self, gen, targets)
        return Krb5AesWordlistWorker(self, gen, targets, batch=batch,
                                     hit_capacity=hit_capacity,
                                     oracle=oracle)

    def make_sharded_mask_worker(self, gen, targets, mesh,
                                 batch_per_device: int, hit_capacity: int,
                                 oracle=None):
        if not _device_ok(targets):
            from dprf_tpu.runtime.worker import CpuWorker
            return CpuWorker(oracle or self, gen, targets)
        return ShardedKrb5AesMaskWorker(
            self, gen, targets, mesh, batch_per_device=batch_per_device,
            hit_capacity=hit_capacity, oracle=oracle)


@register("krb5tgs17", device="jax")
@register("krb5tgs18", device="jax")
@register("krb5tgs-aes", device="jax")
class JaxKrb5TgsAesEngine(_JaxKrb5AesMixin, Krb5TgsAesEngine):
    pass


@register("krb5pa17", device="jax")
@register("krb5pa18", device="jax")
@register("krb5pa", device="jax")
class JaxKrb5PaAesEngine(_JaxKrb5AesMixin, Krb5PaAesEngine):
    pass


@register("krb5asrep17", device="jax")
@register("krb5asrep18", device="jax")
@register("krb5asrep-aes", device="jax")
class JaxKrb5AsRepAesEngine(_JaxKrb5AesMixin, Krb5AsRepAesEngine):
    pass
