"""Device SHA3 / Keccak family engines (hashcat 17300-18000):
sha3-224/256/384/512 and raw keccak-224/256/384/512, one generalized
single-block sponge with (rate, pad byte, digest width) per variant.

Keccak's sponge padding is its own thing, so these engines do not ride
the Merkle-Damgard packers: the fused step decodes candidates and
feeds raw bytes plus per-lane lengths straight into
ops/keccak.keccak_words (which pads in-kernel).  Multi-target lists
reuse the sorted-table compare the fast MD engines use."""

from __future__ import annotations

from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines import register
from dprf_tpu.engines.cpu.engines import Keccak256Engine, Sha3_256Engine
from dprf_tpu.engines.device.engines import GenericWorkerFactories
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops.keccak import keccak_words
from dprf_tpu.runtime.worker import (DeviceWordlistWorker,
                                     MaskWorkerBase)


def make_keccak_mask_step(gen, tgt, batch: int, pad_byte: int,
                          hit_capacity: int = 64, rate: int = 136,
                          out_bytes: int = 32):
    """tgt: single-target words uint32[out_bytes//4] (7 for the 224
    variants, 16 for 512) or a multi-target sorted table from
    cmp_ops.make_target_table."""
    flat = gen.flat_charsets
    length = gen.length
    multi = isinstance(tgt, cmp_ops.TargetTable)

    @jax.jit
    def step(base_digits, n_valid):
        cand = gen.decode_batch(base_digits, flat, batch)
        lengths = jnp.full((batch,), length, jnp.int32)
        digest = keccak_words(cand, lengths, pad_byte=pad_byte,
                              rate=rate, out_bytes=out_bytes)
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, tgt)
        else:
            found = cmp_ops.compare_single(digest, jnp.asarray(tgt))
            tpos = jnp.zeros((batch,), jnp.int32)
        found = found & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        return cmp_ops.compact_hits(found, tpos, hit_capacity)

    return step


def make_keccak_wordlist_step(gen, tgt, word_batch: int, pad_byte: int,
                              hit_capacity: int = 64, rate: int = 136,
                              out_bytes: int = 32):
    from dprf_tpu.ops.rules_pipeline import expand_rules

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    multi = isinstance(tgt, cmp_ops.TargetTable)

    @jax.jit
    def step(w0, n_valid_words):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, L)
        pos = jnp.arange(cw.shape[1], dtype=jnp.int32)
        cw = jnp.where(pos[None, :] < cl[:, None], cw, 0)  # mask junk
        digest = keccak_words(cw, cl, pad_byte=pad_byte, rate=rate,
                              out_bytes=out_bytes)
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, tgt)
        else:
            found = cmp_ops.compare_single(digest, jnp.asarray(tgt))
            tpos = jnp.zeros_like(cl)
        return cmp_ops.compact_hits(found & cv, tpos, hit_capacity)

    return step


class _KeccakTargetsMixin:
    """Single- or multi-target setup with the sorted-table compare."""

    def _setup_keccak(self, engine, gen, targets, hit_capacity, oracle):
        self.engine = engine
        self.gen = gen
        self.targets = list(targets)
        self.hit_capacity = hit_capacity
        self.oracle = oracle
        digests = [t.digest for t in self.targets]
        self.multi = len(digests) > 1
        if self.multi:
            table = cmp_ops.make_target_table(digests,
                                              little_endian=False)
            self._order = table.order
            return table
        self._order = np.zeros(1, dtype=np.int64)
        return np.frombuffer(digests[0], ">u4").astype(np.uint32)


class KeccakMaskWorker(_KeccakTargetsMixin, MaskWorkerBase):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        tgt = self._setup_keccak(engine, gen, targets, hit_capacity,
                                 oracle)
        self.batch = self.stride = batch
        self.step = make_keccak_mask_step(
            gen, tgt, batch, engine._pad_byte, hit_capacity,
            rate=engine._rate, out_bytes=engine.digest_size)


class PallasKeccakMaskWorker(_KeccakTargetsMixin, MaskWorkerBase):
    """Single-target mask worker over the fused Keccak kernel
    (ops/pallas_keccak.py): the whole decode->sponge->compare chain
    stays in VMEM.  Wide-step capable like the MD kernels."""

    SUPER_MODE = "wide"

    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None,
                 interpret: bool = False):
        from dprf_tpu.ops.pallas_keccak import SUBK

        tgt = self._setup_keccak(engine, gen, targets, hit_capacity,
                                 oracle)
        if self.multi:
            raise ValueError("keccak kernel is single-target")
        tile = SUBK * 128
        batch = max(tile, (batch // tile) * tile)
        self.batch = self.stride = batch
        self._tgt_words = np.asarray(tgt)
        self._interpret = interpret
        self.step = self._make_step(batch)

    def _make_step(self, batch: int):
        from dprf_tpu.ops.pallas_keccak import (
            make_pallas_keccak_crack_step)
        scale = max(1, batch // self.batch)
        cap = max(self.hit_capacity,
                  min(self.hit_capacity * scale, 1024))
        e = self.engine
        return make_pallas_keccak_crack_step(
            self.gen, self._tgt_words, batch, e._pad_byte,
            e._rate, e.digest_size, cap, interpret=self._interpret)


class KeccakWordlistWorker(_KeccakTargetsMixin, DeviceWordlistWorker):
    def __init__(self, engine, gen, targets, batch: int = 1 << 18,
                 hit_capacity: int = 64, oracle=None):
        tgt = self._setup_keccak(engine, gen, targets, hit_capacity,
                                 oracle)
        self.word_batch = max(1, batch // gen.n_rules)
        self.stride = self.word_batch * gen.n_rules
        self.batch = batch
        self.step = make_keccak_wordlist_step(
            gen, tgt, self.word_batch, engine._pad_byte, hit_capacity,
            rate=engine._rate, out_bytes=engine.digest_size)


class _KeccakDeviceMixin(GenericWorkerFactories):
    little_endian = False
    digest_words = 8
    _pad_byte: int
    _rate = 136

    def digest_candidates(self, cand, lengths):
        """The generic-factory hook (JaxEngineBase.digest_candidates):
        sponge framing instead of MD packing, so the sharded and
        combinator factories serve this family unchanged."""
        if isinstance(lengths, int):
            lengths = jnp.full((cand.shape[0],), lengths, jnp.int32)
        return keccak_words(cand, lengths, pad_byte=self._pad_byte,
                            rate=self._rate, out_bytes=self.digest_size)

    def make_mask_worker(self, gen, targets, batch: int, hit_capacity: int,
                         oracle=None):
        from dprf_tpu.ops.pallas_keccak import keccak_kernel_eligible
        from dprf_tpu.ops.pallas_mask import pallas_mode
        from dprf_tpu.utils.logging import DEFAULT as log
        mode = pallas_mode()
        if mode is not None and not keccak_kernel_eligible(
                gen, len(targets), self._rate):
            # weak-spot visibility, as in engines.py: --impl auto users
            # should be able to tell which path ran without reading
            # result JSON
            log.info("keccak kernel not eligible for this job; "
                     "using the XLA pipeline", engine=self.name,
                     targets=len(targets))
        elif mode is not None:
            try:
                w = PallasKeccakMaskWorker(self, gen, targets,
                                           batch=batch,
                                           hit_capacity=hit_capacity,
                                           oracle=oracle, **mode)
                w.warmup()
                return w
            except Exception as e:   # build/compile failure -> XLA
                log.warn("keccak kernel failed to build/compile; "
                         "falling back to the XLA pipeline",
                         engine=self.name,
                         error=f"{type(e).__name__}: {e}")
        return KeccakMaskWorker(self, gen, targets, batch=batch,
                                hit_capacity=hit_capacity, oracle=oracle)

    def make_wordlist_worker(self, gen, targets, batch: int,
                             hit_capacity: int, oracle=None):
        return KeccakWordlistWorker(self, gen, targets, batch=batch,
                                    hit_capacity=hit_capacity,
                                    oracle=oracle)

    # the generic multi-chip / combinator workers (inherited from
    # GenericWorkerFactories) ride the digest_candidates hook
    # (round 4b: previously None -- --devices N and -a combinator on
    # this family errored out)


@register("sha3-256", device="jax")
@register("sha3", device="jax")
class JaxSha3_256Engine(_KeccakDeviceMixin, Sha3_256Engine):
    """Device SHA3-256 (NIST 0x06 padding)."""

    _pad_byte = 0x06


@register("keccak-256", device="jax")
@register("keccak256", device="jax")
class JaxKeccak256Engine(_KeccakDeviceMixin, Keccak256Engine):
    """Device original Keccak-256 (0x01 padding; Ethereum)."""

    _pad_byte = 0x01


def _register_keccak_device_family():
    """Device sha3-224/384/512 and keccak-224/384/512 on the
    generalized sponge (hashcat 17300/17500/17600/17700/17900/18000);
    the 256 variants are the explicit classes above."""
    from dprf_tpu.engines.cpu.engines import KECCAK_SIZES
    from dprf_tpu.engines import engine_class

    for bits, rate in KECCAK_SIZES:
        for kind, pad in (("sha3", 0x06), ("keccak", 0x01)):
            name = f"{kind}-{bits}"
            cpu_cls = engine_class(name, device="cpu")
            cls = type(f"Jax{kind.title()}{bits}Engine",
                       (_KeccakDeviceMixin, cpu_cls),
                       {"__doc__": cpu_cls.__doc__ + " (device)",
                        "_pad_byte": pad, "_rate": rate,
                        "digest_words": bits // 32})
            register(name, device="jax")(cls)
            if kind == "keccak":
                register(f"keccak{bits}", device="jax")(cls)


_register_keccak_device_family()
