"""Engine registry: (name, device) -> HashEngine class.

Engines self-register at import time via the @register decorator, the
same plugin pattern the reference's `--engine=<algo>` flag implies.
Devices: "cpu" (oracle / reference path) and "jax" (TPU-native fused
path; also runs on the CPU backend of XLA for tests).
"""

from __future__ import annotations

from typing import Dict, Tuple, Type

from dprf_tpu.engines.base import HashEngine, DeviceHashEngine, Target  # noqa: F401

_REGISTRY: Dict[Tuple[str, str], type] = {}


def register(name: str, device: str = "cpu"):
    def deco(cls):
        key = (name.lower(), device)
        if key in _REGISTRY and _REGISTRY[key] is not cls:
            raise ValueError(f"duplicate engine registration: {key}")
        _REGISTRY[key] = cls
        return cls
    return deco


def get_engine(name: str, device: str = "cpu", **kwargs):
    _ensure_imported(device)
    key = (name.lower(), device)
    if key not in _REGISTRY:
        have = sorted(n for n, d in _REGISTRY if d == device)
        raise KeyError(f"no engine {name!r} for device {device!r}; "
                       f"available: {have}")
    return _REGISTRY[key](**kwargs)


def engine_names(device: str = "cpu") -> list[str]:
    _ensure_imported(device)
    return sorted(n for n, d in _REGISTRY if d == device)


def engine_class(name: str, device: str = "cpu") -> type:
    """The registered class without instantiating it (for listings)."""
    _ensure_imported(device)
    return _REGISTRY[(name.lower(), device)]


def _ensure_imported(device: str) -> None:
    # Import engine modules lazily so `import dprf_tpu` stays light and the
    # CPU oracle path never pulls in jax.
    if device == "cpu":
        import dprf_tpu.engines.cpu.engines  # noqa: F401
        import dprf_tpu.engines.cpu.krb5     # noqa: F401
        import dprf_tpu.engines.cpu.krb5aes  # noqa: F401
        import dprf_tpu.engines.cpu.pdf      # noqa: F401
        import dprf_tpu.engines.cpu.sevenzip  # noqa: F401
    elif device == "jax":
        try:
            import dprf_tpu.engines.device.engines  # noqa: F401
            import dprf_tpu.engines.device.pmkid    # noqa: F401
            import dprf_tpu.engines.device.bcrypt   # noqa: F401
            import dprf_tpu.engines.device.salted   # noqa: F401
            import dprf_tpu.engines.device.nested   # noqa: F401
            import dprf_tpu.engines.device.phpass   # noqa: F401
            import dprf_tpu.engines.device.md5crypt  # noqa: F401
            import dprf_tpu.engines.device.sha512crypt  # noqa: F401
            import dprf_tpu.engines.device.sha256crypt  # noqa: F401
            import dprf_tpu.engines.device.pbkdf2   # noqa: F401
            import dprf_tpu.engines.device.netntlmv2  # noqa: F401
            import dprf_tpu.engines.device.pbkdf2_sha1  # noqa: F401
            import dprf_tpu.engines.device.wpa2     # noqa: F401
            import dprf_tpu.engines.device.hmac     # noqa: F401
            import dprf_tpu.engines.device.scrypt   # noqa: F401
            import dprf_tpu.engines.device.zip2     # noqa: F401
            import dprf_tpu.engines.device.mscache  # noqa: F401
            import dprf_tpu.engines.device.lm       # noqa: F401
            import dprf_tpu.engines.device.netntlmv1  # noqa: F401
            import dprf_tpu.engines.device.office   # noqa: F401
            import dprf_tpu.engines.device.rar5     # noqa: F401
            import dprf_tpu.engines.device.ethereum  # noqa: F401
            import dprf_tpu.engines.device.sha3     # noqa: F401
            import dprf_tpu.engines.device.descrypt  # noqa: F401
            import dprf_tpu.engines.device.krb5     # noqa: F401
            import dprf_tpu.engines.device.krb5aes  # noqa: F401
            import dprf_tpu.engines.device.pdf      # noqa: F401
            import dprf_tpu.engines.device.sevenzip  # noqa: F401
        except ModuleNotFoundError as e:
            # Translate only a missing engines.device package into a friendly
            # error; import failures *inside* it should surface as-is.
            if e.name and e.name.startswith("dprf_tpu.engines.device"):
                raise KeyError("jax device engines are not available in this "
                               "build (dprf_tpu.engines.device missing)") from e
            raise
    else:
        raise KeyError(f"unknown device {device!r} (expected 'cpu' or 'jax')")
