"""sha512crypt ($6$ modular crypt, the Linux shadow default;
hashcat 1800) reference implementation, following the public
crypt(3)/glibc algorithm description.

Structure: an alternate digest B = sha512(pw+salt+pw); a bit-walked
initial digest A; the P and S byte sequences derived from digests of
repeated password/salt; then `rounds` (default 5000) iterations whose
message composition cycles with i mod 2/3/7.  The emitted base64 text
permutes digest bytes in 21 rotating (i, i+21, i+42) triplets.
"""

from __future__ import annotations

import hashlib

from dprf_tpu.engines.cpu.phpass import decode64, encode64

MAX_SALT_LEN = 16
DEFAULT_ROUNDS = 5000
MIN_ROUNDS, MAX_ROUNDS = 1000, 999999999


def _perm_rows():
    rows = []
    a, b, c = 0, 21, 42
    for _ in range(21):
        rows.append((a, b, c))
        a, b, c = b + 1, c + 1, a + 1
    return rows


#: digest byte order fed to the shared little-endian encode64: glibc
#: emits (d[a]<<16 | d[b]<<8 | d[c]) per rotating triplet, so each
#: triplet is listed reversed; d[63] rides alone in the final group.
_PERM = [i for (a, b, c) in _perm_rows() for i in (c, b, a)] + [63]


def sha512crypt_raw(password: bytes, salt: bytes,
                    rounds: int = DEFAULT_ROUNDS) -> bytes:
    """The raw (unpermuted) 64-byte digest."""
    sha = lambda d: hashlib.sha512(d).digest()  # noqa: E731
    B = sha(password + salt + password)
    ctx = password + salt
    # append B cycled to len(password) bytes
    for i in range(len(password)):
        ctx += B[i % 64:i % 64 + 1]
    # bit-walk: FULL B or FULL password per bit of len(password)
    cnt = len(password)
    while cnt > 0:
        ctx += B if cnt & 1 else password
        cnt >>= 1
    A = sha(ctx)
    # P sequence: digest of password repeated len(password) times,
    # cycled out to len(password) bytes
    DP = sha(password * len(password))
    P = bytes(DP[i % 64] for i in range(len(password)))
    # S sequence: digest of salt repeated (16 + A[0]) times, cycled to
    # len(salt) bytes
    DS = sha(salt * (16 + A[0]))
    S = bytes(DS[i % 64] for i in range(len(salt)))
    prev = A
    for i in range(rounds):
        msg = P if i & 1 else prev
        if i % 3:
            msg += S
        if i % 7:
            msg += P
        msg += prev if i & 1 else P
        prev = sha(msg)
    return prev


def encode_digest(digest: bytes) -> str:
    return encode64(bytes(digest[p] for p in _PERM))


def decode_digest(text: str) -> bytes:
    permuted = decode64(text, 64)
    out = bytearray(64)
    for where, src in zip(_PERM, permuted):
        out[where] = src
    return bytes(out)


def parse_sha512crypt(text: str):
    """'$6$[rounds=N$]salt$hash' -> (rounds, salt bytes, raw digest)."""
    t = text.strip()
    if not t.startswith("$6$"):
        raise ValueError(f"not a sha512crypt hash: {text!r}")
    rest = t[3:]
    rounds = DEFAULT_ROUNDS
    if rest.startswith("rounds="):
        spec, sep, rest = rest.partition("$")
        if not sep:
            raise ValueError(f"malformed sha512crypt hash: {text!r}")
        rounds = int(spec[len("rounds="):])
        if not MIN_ROUNDS <= rounds <= MAX_ROUNDS:
            raise ValueError(f"sha512crypt rounds out of range: {rounds}")
    salt_text, sep, digest_text = rest.partition("$")
    if not sep or len(digest_text) != 86:
        raise ValueError(f"malformed sha512crypt hash: {text!r}")
    salt = salt_text.encode("latin-1")[:MAX_SALT_LEN]
    return rounds, salt, decode_digest(digest_text)


def sha512crypt_hash(password: bytes, salt: bytes,
                     rounds: int = DEFAULT_ROUNDS) -> str:
    prefix = "$6$"
    if rounds != DEFAULT_ROUNDS:
        prefix += f"rounds={rounds}$"
    return (prefix + salt.decode("latin-1") + "$"
            + encode_digest(sha512crypt_raw(password, salt, rounds)))
