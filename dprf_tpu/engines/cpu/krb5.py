"""Kerberos 5 etype-23 (RC4-HMAC) engines: TGS-REP and AS-REP tickets.

Kerberoasting / AS-REP-roasting — the hashcat 13100 / 18200 modes a
hashcat-class framework is expected to carry (SURVEY.md §A fixes only
the five acceptance engines; these extend the same HashEngine plugin
surface, reference file:line citations impossible — empty mount).

RFC 4757 (the RC4-HMAC Kerberos encryption type):

    K  = NTLM(password) = MD4(UTF-16LE(password))
    K1 = HMAC-MD5(K, msg_type)      msg_type: 4-byte LE, 2=TGS, 8=AS-REP
    K3 = HMAC-MD5(K1, checksum)
    plaintext = RC4(K3, edata2)
    valid  <=>  HMAC-MD5(K1, plaintext) == checksum

The oracle computes the full RFC chain; `hash_batch` returns the
recomputed checksum so `digest == target.digest` is the standard
compare.  The device path (engines/device/krb5.py) instead checks the
DER header of the decrypted ticket — deterministic given len(edata2) —
and relies on coordinator oracle verification for the final say.
"""

from __future__ import annotations

import hmac as _hmac
from typing import Optional, Sequence

from dprf_tpu.engines import register
from dprf_tpu.engines.base import HashEngine, Target

#: RFC 4757 message-type constants (4-byte little-endian HMAC input).
TGS_MSG_TYPE = 2
ASREP_MSG_TYPE = 8

#: edata2 must at least hold the 8-byte confounder + a DER header +
#: HMAC'able content.
MIN_EDATA = 24


def rc4(key: bytes, data: bytes) -> bytes:
    """Plain RC4 (KSA + PRGA) — the oracle-side stream cipher."""
    S = list(range(256))
    j = 0
    for i in range(256):
        j = (j + S[i] + key[i % len(key)]) & 0xFF
        S[i], S[j] = S[j], S[i]
    out = bytearray(len(data))
    i = j = 0
    for t, c in enumerate(data):
        i = (i + 1) & 0xFF
        j = (j + S[i]) & 0xFF
        S[i], S[j] = S[j], S[i]
        out[t] = c ^ S[(S[i] + S[j]) & 0xFF]
    return bytes(out)


def krb5_rc4_checksum(password: bytes, msg_type: int, checksum: bytes,
                      edata: bytes) -> bytes:
    """Recompute the ticket checksum for one candidate (RFC 4757)."""
    from dprf_tpu.engines.cpu.engines import _md4_utf16
    nt = _md4_utf16(password)
    k1 = _hmac.new(nt, msg_type.to_bytes(4, "little"), "md5").digest()
    k3 = _hmac.new(k1, checksum, "md5").digest()
    plain = rc4(k3, edata)
    return _hmac.new(k1, plain, "md5").digest()


def _checksum_edata(fields: list[str], what: str) -> tuple[bytes, bytes]:
    """Decode the trailing checksum/edata2 hex fields of a krb5 line."""
    chk_hex, edata_hex = fields
    checksum = bytes.fromhex(chk_hex)
    edata = bytes.fromhex(edata_hex)
    if len(checksum) != 16:
        raise ValueError(f"{what}: checksum must be 16 bytes, "
                         f"got {len(checksum)}")
    if len(edata) < MIN_EDATA:
        raise ValueError(f"{what}: edata2 is {len(edata)} bytes "
                         f"(< {MIN_EDATA}) — truncated line?")
    return checksum, edata


def parse_krb5tgs(text: str) -> tuple[bytes, bytes]:
    """``$krb5tgs$23$*user$realm$spn*$checksum$edata2`` (the starred
    account metadata is optional) -> (checksum, edata2)."""
    t = text.strip()
    if not t.startswith("$krb5tgs$23$"):
        if t.startswith(("$krb5tgs$17$", "$krb5tgs$18$")):
            raise ValueError("etype-17/18 ticket: use --engine "
                             "krb5tgs-aes (AES modes), not the "
                             "etype-23 RC4 engine")
        raise ValueError(f"not a $krb5tgs$23$ line: {text[:40]!r}")
    rest = t[len("$krb5tgs$23$"):]
    if rest.startswith("*"):
        meta, sep, rest = rest[1:].partition("*$")
        if not sep:
            raise ValueError(f"unterminated account metadata: {text[:60]!r}")
    fields = rest.split("$")
    if len(fields) != 2:
        raise ValueError(f"expected checksum$edata2, got "
                         f"{len(fields)} fields: {text[:60]!r}")
    return _checksum_edata(fields, "krb5tgs")


def parse_krb5asrep(text: str) -> tuple[bytes, bytes]:
    """``$krb5asrep$23$user@realm:checksum$edata2`` (the account part
    before ':' is optional) -> (checksum, edata2)."""
    t = text.strip()
    if not t.startswith("$krb5asrep$"):
        raise ValueError(f"not a $krb5asrep$ line: {text[:40]!r}")
    rest = t[len("$krb5asrep$"):]
    etype, sep, after = rest.partition("$")
    # an etype field is 1-2 digits; a 32-hex checksum that happens to
    # be all-decimal must not be mistaken for one
    if sep and etype.isdigit() and len(etype) <= 2:
        # explicit etype field: only RC4-HMAC (23) is this engine
        if etype != "23":
            raise ValueError(f"$krb5asrep$ etype {etype} is not "
                             "RC4-HMAC (23) — AES etypes need a "
                             "different engine")
        rest = after
    head, _, edata_hex = rest.rpartition("$")
    _, _, chk_hex = head.rpartition(":")
    return _checksum_edata([chk_hex, edata_hex], "krb5asrep")


class _Krb5Rc4Engine(HashEngine):
    """Shared RFC 4757 oracle; subclasses fix msg_type + line format."""

    digest_size = 16
    salted = True
    max_candidate_len = 27      # NTLM single-block UTF-16LE limit
    _msg_type: int = 0

    def _parse(self, text: str) -> tuple[bytes, bytes]:
        raise NotImplementedError

    def parse_target(self, text: str) -> Target:
        checksum, edata = self._parse(text)
        return Target(raw=text.strip(), digest=checksum,
                      params={"checksum": checksum, "edata": edata,
                              "msg_type": self._msg_type})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError(f"{self.name} needs target params "
                             "(checksum + edata2)")
        return [krb5_rc4_checksum(c, params["msg_type"],
                                  params["checksum"], params["edata"])
                for c in candidates]


@register("krb5tgs")
class Krb5TgsEngine(_Krb5Rc4Engine):
    """Kerberos 5 TGS-REP etype 23, 'Kerberoasting' (hashcat 13100)."""

    name = "krb5tgs"
    _msg_type = TGS_MSG_TYPE

    def _parse(self, text: str) -> tuple[bytes, bytes]:
        return parse_krb5tgs(text)


@register("krb5asrep")
class Krb5AsRepEngine(_Krb5Rc4Engine):
    """Kerberos 5 AS-REP etype 23, 'AS-REP roasting' (hashcat 18200)."""

    name = "krb5asrep"
    _msg_type = ASREP_MSG_TYPE

    def _parse(self, text: str) -> tuple[bytes, bytes]:
        return parse_krb5asrep(text)
