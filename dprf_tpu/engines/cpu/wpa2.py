"""WPA2 4-way-handshake MIC verification (hashcat 22000, WPA*02 lines).

Chain: PMK = PBKDF2-HMAC-SHA1(passphrase, essid, 4096, 32);
KCK = PRF-512(PMK, "Pairwise key expansion",
              min(MACs)||max(MACs)||min(nonces)||max(nonces))[:16]
      (802.11i PRF: HMAC-SHA1(PMK, label || 0x00 || data || counter));
MIC = HMAC-SHA1(KCK, eapol_frame_with_zeroed_mic)[:16]  (key version 2)
   or HMAC-MD5(KCK, eapol)                               (key version 1).

hc22000 WPA*02 fields: WPA*02*mic*mac_ap*mac_sta*essid*anonce*eapol*mp;
the SNonce lives inside the stored EAPOL frame (key-nonce field at
offset 17), and the stored frame already has its MIC field zeroed.
"""

from __future__ import annotations

import hashlib
import hmac

PRF_LABEL = b"Pairwise key expansion"


def prf512_block0(pmk: bytes, data: bytes) -> bytes:
    """First 20 bytes of the 802.11i PRF-512 (enough for the KCK)."""
    return hmac.new(pmk, PRF_LABEL + b"\x00" + data + b"\x00",
                    hashlib.sha1).digest()


def ptk_data(mac_ap: bytes, mac_sta: bytes, anonce: bytes,
             snonce: bytes) -> bytes:
    return (min(mac_ap, mac_sta) + max(mac_ap, mac_sta)
            + min(anonce, snonce) + max(anonce, snonce))


def wpa2_mic(passphrase: bytes, essid: bytes, mac_ap: bytes,
             mac_sta: bytes, anonce: bytes, eapol: bytes,
             keyver: int, iterations: int = 4096) -> bytes:
    """CPU reference: the 16-byte MIC for one candidate."""
    pmk = hashlib.pbkdf2_hmac("sha1", passphrase, essid, iterations, 32)
    snonce = eapol[17:49]
    kck = prf512_block0(pmk, ptk_data(mac_ap, mac_sta, anonce,
                                      snonce))[:16]
    if keyver == 1:
        return hmac.new(kck, eapol, hashlib.md5).digest()
    return hmac.new(kck, eapol, hashlib.sha1).digest()[:16]


def parse_wpa02(text: str):
    """'WPA*02*mic*ap*sta*essid*anonce*eapol*mp' -> dict of fields."""
    t = text.strip()
    parts = t.split("*")
    if len(parts) < 8 or parts[0] != "WPA" or parts[1] != "02":
        raise ValueError(f"not a WPA*02 (EAPOL) line: {text!r}")
    mic = bytes.fromhex(parts[2])
    mac_ap = bytes.fromhex(parts[3])
    mac_sta = bytes.fromhex(parts[4])
    essid = bytes.fromhex(parts[5])
    anonce = bytes.fromhex(parts[6])
    eapol = bytes.fromhex(parts[7])
    if len(mic) != 16 or len(mac_ap) != 6 or len(mac_sta) != 6:
        raise ValueError(f"bad field lengths in {text!r}")
    if len(anonce) != 32 or len(eapol) < 95:
        raise ValueError(f"bad anonce/eapol in {text!r}")
    key_info = int.from_bytes(eapol[5:7], "big")
    keyver = key_info & 0x7
    if keyver not in (1, 2):
        raise ValueError(f"unsupported EAPOL key version {keyver} "
                         f"in {text!r}")
    return {"mic": mic, "mac_ap": mac_ap, "mac_sta": mac_sta,
            "essid": essid, "anonce": anonce, "eapol": eapol,
            "keyver": keyver}


def make_wpa02_line(passphrase: bytes, essid: bytes, mac_ap: bytes,
                    mac_sta: bytes, anonce: bytes, snonce: bytes,
                    keyver: int = 2, iterations: int = 4096) -> str:
    """Synthesize a WPA*02 line with a minimal message-2 EAPOL frame
    (test helper)."""
    key_info = 0x0100 | keyver        # MIC bit + key version
    body = (bytes([1]) +                     # key descriptor type
            key_info.to_bytes(2, "big") +
            (16).to_bytes(2, "big") +        # key length
            b"\x00" * 8 +                    # replay counter
            snonce +                         # key nonce (offset 17)
            b"\x00" * 16 +                   # key IV
            b"\x00" * 8 +                    # key RSC
            b"\x00" * 8 +                    # key ID
            b"\x00" * 16 +                   # MIC (zeroed in storage)
            (0).to_bytes(2, "big"))          # key data length
    eapol = bytes([2, 3]) + len(body).to_bytes(2, "big") + body
    mic = wpa2_mic(passphrase, essid, mac_ap, mac_sta, anonce, eapol,
                   keyver, iterations)
    return "*".join(["WPA", "02", mic.hex(), mac_ap.hex(),
                     mac_sta.hex(), essid.hex(), anonce.hex(),
                     eapol.hex(), "02"])
