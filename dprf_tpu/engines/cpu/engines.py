"""CPU reference HashEngines -- the bit-exact oracles.

These fill the role BASELINE.json config 1 calls the "CPU reference
HashEngine": every device engine must match them exactly, and they are
the `--device=cpu` execution path of the CLI.
"""

from __future__ import annotations

import hashlib
import hmac
import struct
from typing import Optional, Sequence

from dprf_tpu.engines import register
from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.engines.cpu.md4 import md4
from dprf_tpu.engines.cpu import bcrypt as _bcrypt


class _HashlibEngine(HashEngine):
    _algo: str

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        algo = self._algo
        return [hashlib.new(algo, c).digest() for c in candidates]


@register("md5")
class Md5Engine(_HashlibEngine):
    name = "md5"
    digest_size = 16
    _algo = "md5"


@register("sha1")
class Sha1Engine(_HashlibEngine):
    name = "sha1"
    digest_size = 20
    _algo = "sha1"


@register("sha256")
class Sha256Engine(_HashlibEngine):
    name = "sha256"
    digest_size = 32
    _algo = "sha256"


@register("sha512")
@register("sha-512")      # alias tables are device-symmetric (VERDICT r3)
class Sha512Engine(_HashlibEngine):
    name = "sha512"
    digest_size = 64
    max_candidate_len = 111    # single-block limit of the device engine
    _algo = "sha512"


@register("sha384")
@register("sha-384")
class Sha384Engine(_HashlibEngine):
    name = "sha384"
    digest_size = 48
    max_candidate_len = 111
    _algo = "sha384"


@register("sha224")
class Sha224Engine(_HashlibEngine):
    name = "sha224"
    digest_size = 28
    _algo = "sha224"


#: fixed device salt buffer width; also bounds parseable salt length
SALT_MAX = 32

_SALT_HEX_RE = None


def parse_salted_line(text: str, digest_size: int):
    """hashcat-convention 'hexdigest:salt' -> (digest, salt bytes);
    '$HEX[..]' decodes hex salts.  Shared by CPU and device engines."""
    import re
    global _SALT_HEX_RE
    if _SALT_HEX_RE is None:
        _SALT_HEX_RE = re.compile(r"^\$HEX\[([0-9a-fA-F]*)\]$")
    digest_hex, sep, salt_text = text.strip().partition(":")
    if not sep:
        raise ValueError(f"expected 'digest:salt', got {text!r}")
    digest = bytes.fromhex(digest_hex)
    if len(digest) != digest_size:
        raise ValueError(f"expected {digest_size}-byte digest in {text!r}")
    m = _SALT_HEX_RE.match(salt_text)
    salt = bytes.fromhex(m.group(1)) if m else salt_text.encode("latin-1")
    if len(salt) > SALT_MAX:
        raise ValueError(f"salt longer than {SALT_MAX} bytes in {text!r}")
    return digest, salt


class _SaltedCpuMixin(HashEngine):
    """CPU oracle for the salted fast modes: md5/sha1/sha256 over
    $pass.$salt ('ps', hashcat 10/110/1410) and $salt.$pass ('sp',
    hashcat 20/120/1420)."""

    salted = True
    _order: str

    def parse_target(self, text: str) -> Target:
        digest, salt = parse_salted_line(text, self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError(f"{self.name} needs target params (salt)")
        salt = params["salt"]
        if self._order == "ps":
            return [hashlib.new(self._algo, c + salt).digest()
                    for c in candidates]
        return [hashlib.new(self._algo, salt + c).digest()
                for c in candidates]


def _register_salted_cpu(algo: str, digest_size: int,
                         block_limit: int = 55):
    for order in ("ps", "sp"):
        name = f"{algo}-{order}"
        cls = type(f"{algo.title()}{order.title()}Engine",
                   (_SaltedCpuMixin,),
                   {"name": name, "digest_size": digest_size,
                    "_algo": algo, "_order": order,
                    "__doc__": (f"Salted {algo}: "
                                + ("$pass.$salt" if order == "ps"
                                   else "$salt.$pass")
                                + " ('hexdigest:salt' lines)."),
                    # leave headroom for any parseable salt in the
                    # single block
                    "max_candidate_len": block_limit - SALT_MAX})
        register(name, device="cpu")(cls)


_register_salted_cpu("md5", 16)
_register_salted_cpu("sha1", 20)
_register_salted_cpu("sha256", 32)
_register_salted_cpu("sha512", 64, block_limit=111)


def parse_ldap_line(text: str, scheme: str, digest_size: int):
    """LDAP userPassword line '{SCHEME}base64(digest + salt)' ->
    (digest, salt).  The salt is whatever follows the digest in the
    decoded blob (typically 4-8 bytes; empty for the unsalted {SHA}/
    {MD5} schemes)."""
    import base64

    t = text.strip()
    tag = "{" + scheme + "}"
    if not t[:len(tag)].upper() == tag:
        raise ValueError(f"not an LDAP {tag} line: {text!r}")
    try:
        blob = base64.b64decode(t[len(tag):], validate=True)
    except Exception as e:
        raise ValueError(f"bad base64 in LDAP line {text!r}: {e}")
    if len(blob) < digest_size:
        raise ValueError(f"LDAP {tag} blob shorter than the "
                         f"{digest_size}-byte digest: {text!r}")
    digest, salt = blob[:digest_size], blob[digest_size:]
    if len(salt) > SALT_MAX:
        raise ValueError(f"salt longer than {SALT_MAX} bytes in {text!r}")
    return digest, salt


class _LdapSaltedEngine(_SaltedCpuMixin):
    """LDAP {SSHA}-style schemes: digest(pass + salt), digest and salt
    packed together in one base64 blob -- the salted 'ps' computation
    with LDAP's line format."""

    _order = "ps"
    _scheme: str

    def parse_target(self, text: str) -> Target:
        digest, salt = parse_ldap_line(text, self._scheme,
                                       self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})


@register("ldap-ssha")
@register("ssha")
class LdapSshaEngine(_LdapSaltedEngine):
    """LDAP {SSHA} (hashcat 111): sha1($pass.$salt), base64 blob."""

    name = "ldap-ssha"
    digest_size = 20
    _algo = "sha1"
    _scheme = "SSHA"
    max_candidate_len = 55 - SALT_MAX


@register("ldap-ssha512")
@register("ssha512")
class LdapSsha512Engine(_LdapSaltedEngine):
    """LDAP {SSHA512} (hashcat 1711): sha512($pass.$salt)."""

    name = "ldap-ssha512"
    digest_size = 64
    _algo = "sha512"
    _scheme = "SSHA512"
    max_candidate_len = 111 - SALT_MAX


@register("ldap-smd5")
class LdapSmd5Engine(_LdapSaltedEngine):
    """LDAP {SMD5}: md5($pass.$salt), base64 blob."""

    name = "ldap-smd5"
    digest_size = 16
    _algo = "md5"
    _scheme = "SMD5"
    max_candidate_len = 55 - SALT_MAX


class _LdapPlainMixin(HashEngine):
    """Unsalted LDAP schemes ({SHA}, {MD5}): the plain fast hash with
    the base64 line format, so the multi-target fast path applies."""

    _scheme: str

    def parse_target(self, text: str) -> Target:
        digest, salt = parse_ldap_line(text, self._scheme,
                                       self.digest_size)
        if salt:
            raise ValueError(f"unexpected salt bytes after the digest "
                             f"in unsalted {{{self._scheme}}} line: "
                             f"{text!r}")
        return Target(raw=text.strip(), digest=digest)


@register("ldap-sha")
class LdapShaEngine(_LdapPlainMixin, Sha1Engine):
    """LDAP {SHA} (hashcat 101): raw sha1, base64 line format."""

    name = "ldap-sha"
    _scheme = "SHA"


@register("ldap-md5")
class LdapMd5Engine(_LdapPlainMixin, Md5Engine):
    """LDAP {MD5}: raw md5, base64 line format."""

    name = "ldap-md5"
    _scheme = "MD5"


@register("oracle11")
@register("oracle-11g")
class Oracle11Engine(_SaltedCpuMixin):
    """Oracle 11g (hashcat 112): sha1($pass.$salt) with a 10-byte
    salt.  Accepts Oracle's native 'S:<40-hex digest><20-hex salt>'
    and hashcat's 'hexdigest:salt' lines."""

    name = "oracle11"
    digest_size = 20
    _algo = "sha1"
    _order = "ps"
    #: the 11g salt is fixed at 10 raw bytes, so candidates get the
    #: rest of the single block (cf. the generic 55 - SALT_MAX cap)
    max_candidate_len = 55 - 10

    def parse_target(self, text: str) -> Target:
        t = text.strip()
        if t[:2].upper() == "S:" and len(t) == 62:
            try:
                digest = bytes.fromhex(t[2:42])
                salt = bytes.fromhex(t[42:])
            except ValueError:
                raise ValueError(f"bad hex in oracle11 line: {text!r}")
            return Target(raw=t, digest=digest, params={"salt": salt})
        tgt = super().parse_target(text)
        salt = tgt.params["salt"]
        # hashcat -m 112 lines carry the salt HEX-ENCODED (ST_HEX):
        # a 20-hex-char field is the 10-byte salt, not literal bytes
        if len(salt) == 20:
            try:
                salt = bytes.fromhex(salt.decode("ascii"))
            except (ValueError, UnicodeDecodeError):
                pass
        if len(salt) != 10:
            raise ValueError(
                f"oracle11 salts are exactly 10 bytes (20 hex chars); "
                f"got {len(salt)} in {text!r}")
        return Target(raw=tgt.raw, digest=tgt.digest,
                      params={"salt": salt})


def mysql323_words(password: bytes) -> tuple:
    """MySQL pre-4.1 OLD_PASSWORD(): two 31-bit words from an
    add/xor/shift scan over the password bytes (space and tab are
    skipped, as the server does).  All arithmetic is u32."""
    M = 0xFFFFFFFF
    nr, nr2, add = 1345345333, 0x12345671, 7
    for c in password:
        if c in (0x20, 0x09):
            continue
        nr ^= ((((nr & 63) + add) * c) + ((nr << 8) & M)) & M
        nr2 = (nr2 + (((nr2 << 8) & M) ^ nr)) & M
        add = (add + c) & M
    return nr & 0x7FFFFFFF, nr2 & 0x7FFFFFFF


@register("mysql323")
@register("mysql-old")
class Mysql323Engine(HashEngine):
    """MySQL pre-4.1 OLD_PASSWORD (hashcat 200): 16 hex chars = two
    big-endian 31-bit words."""

    name = "mysql323"
    digest_size = 8
    max_candidate_len = 55

    def parse_target(self, text: str) -> Target:
        t = text.strip()
        digest = bytes.fromhex(t)
        if len(digest) != 8:
            raise ValueError(f"mysql323 wants 16 hex chars: {text!r}")
        return Target(raw=t, digest=digest)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        out = []
        for c in candidates:
            a, b = mysql323_words(c)
            out.append(a.to_bytes(4, "big") + b.to_bytes(4, "big"))
        return out


def parse_mssql_line(text: str, version_tag: str, digest_hex: int):
    """MSSQL '0x<ver><8-hex salt><hex digest[s]>' -> (salt, digests).
    2000 lines carry TWO 40-hex sha1 digests (case-sensitive then
    upper-cased); 2005 carry one 40-hex; 2012/2014 one 128-hex."""
    t = text.strip()
    if not t.lower().startswith("0x" + version_tag):
        raise ValueError(f"not an MSSQL 0x{version_tag} line: {text!r}")
    body = t[2 + len(version_tag):]
    if len(body) < 8 + digest_hex or (len(body) - 8) % digest_hex:
        raise ValueError(f"malformed MSSQL line (want 8-hex salt + "
                         f"k x {digest_hex}-hex digest): {text!r}")
    try:
        salt = bytes.fromhex(body[:8])
        digests = [bytes.fromhex(body[8 + i * digest_hex:
                                      8 + (i + 1) * digest_hex])
                   for i in range((len(body) - 8) // digest_hex)]
    except ValueError:
        raise ValueError(f"bad hex in MSSQL line: {text!r}")
    return salt, digests


class _MssqlCpuBase(HashEngine):
    """sha-family over utf16le($pass) . $salt (4-byte salt)."""

    salted = True
    _algo: str
    _tag: str
    _upper = False
    #: digests per line: 2000 stores [case-sensitive, upper-cased],
    #: 2005/2012 exactly one.  Enforced so a 2000-format line fed to
    #: the 2005 engine (or vice versa) is rejected instead of silently
    #: cracking against the wrong digest.
    _ndigests = 1

    def parse_target(self, text: str) -> Target:
        salt, digests = parse_mssql_line(text, self._tag,
                                         2 * self.digest_size)
        if len(digests) != self._ndigests:
            raise ValueError(
                f"{self.name} wants {self._ndigests} digest(s) per "
                f"line, got {len(digests)} -- wrong MSSQL version? "
                f"{text!r}")
        # 2000 lines: [case-sensitive, upper]; crack the LAST digest
        # (the case-insensitive one).
        return Target(raw=text.strip(), digest=digests[-1],
                      params={"salt": salt})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError(f"{self.name} needs target params (salt)")
        salt = params["salt"]
        out = []
        for c in candidates:
            if self._upper:
                c = c.upper()          # ASCII-only, like the device path
            wide = bytes(b for ch in c for b in (ch, 0))
            out.append(hashlib.new(self._algo, wide + salt).digest())
        return out


@register("mssql2000")
class Mssql2000Engine(_MssqlCpuBase):
    """MSSQL 2000 (hashcat 131): sha1(utf16le(upper($pass)) . $salt) --
    the case-insensitive second digest of the 0x0100 line."""

    name = "mssql2000"
    digest_size = 20
    _algo = "sha1"
    _tag = "0100"
    _upper = True
    _ndigests = 2
    max_candidate_len = (55 - 4) // 2


@register("mssql2005")
class Mssql2005Engine(_MssqlCpuBase):
    """MSSQL 2005 (hashcat 132): sha1(utf16le($pass) . $salt)."""

    name = "mssql2005"
    digest_size = 20
    _algo = "sha1"
    _tag = "0100"
    max_candidate_len = (55 - 4) // 2


def descrypt_encode(digest8: bytes) -> str:
    """8-byte descrypt ciphertext -> the 11 itoa64 chars of a crypt(3)
    line: the 64 bits MSB-first in 6-bit groups (NOT phpass's
    little-endian packing), 2 zero bits appended."""
    from dprf_tpu.engines.cpu.phpass import ITOA64
    bits = [(digest8[i // 8] >> (7 - i % 8)) & 1 for i in range(64)]
    bits += [0, 0]
    out = []
    for g in range(11):
        v = 0
        for b in bits[6 * g:6 * g + 6]:
            v = (v << 1) | b
        out.append(ITOA64[v])
    return "".join(out)


def descrypt_decode(text11: str) -> bytes:
    """11 itoa64 chars -> the 8-byte ciphertext (inverse of
    descrypt_encode)."""
    from dprf_tpu.engines.cpu.phpass import ITOA64
    bits = []
    for ch in text11:
        v = ITOA64.index(ch)
        bits += [(v >> k) & 1 for k in range(5, -1, -1)]
    if bits[64] or bits[65]:
        raise ValueError("descrypt digest has nonzero trailing bits")
    return bytes(sum(bits[8 * k + j] << (7 - j) for j in range(8))
                 for k in range(8))


@register("descrypt")
@register("des-crypt")
@register("unix-crypt")
class DescryptEngine(HashEngine):
    """Traditional DES crypt(3) (hashcat 1500): 25 chained DES
    encryptions of the zero block, E expansion perturbed by the 12-bit
    salt, key = low 7 bits of the first 8 password bytes.  Validated
    against the system crypt()."""

    name = "descrypt"
    digest_size = 8
    salted = True
    #: crypt(3) silently truncates at 8; the workers cap candidates so
    #: every reported plaintext hashes to the target as-is
    max_candidate_len = 8

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.phpass import ITOA64
        t = text.strip()
        if len(t) != 13:
            raise ValueError(f"descrypt wants 13-char salt+digest "
                             f"lines, got {len(t)}: {text!r}")
        try:
            salt = ITOA64.index(t[0]) | (ITOA64.index(t[1]) << 6)
            digest = descrypt_decode(t[2:])
        except ValueError as e:
            raise ValueError(f"bad descrypt line {text!r}: {e}")
        return Target(raw=t, digest=digest,
                      params={"salt": salt, "salt_text": t[:2]})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.ops.des import des_crypt25, descrypt_key8
        if params is None or "salt" not in params:
            raise ValueError("descrypt needs target params (salt)")
        salt = params["salt"]
        return [des_crypt25(descrypt_key8(c), salt) for c in candidates]


@register("mssql2012")
@register("mssql2014")
class Mssql2012Engine(_MssqlCpuBase):
    """MSSQL 2012/2014 (hashcat 1731): sha512(utf16le($pass) . $salt),
    0x0200 lines."""

    name = "mssql2012"
    digest_size = 64
    _algo = "sha512"
    _tag = "0200"
    max_candidate_len = (111 - 4) // 2


#: nested double-hash combinations (outer, inner) with their hashcat
#: modes -- the ONE list device/nested.py and the oracles share (this
#: module stays jax-free, so it is the importable-everywhere home)
NESTED_COMBOS = [
    ("md5", "md5"),        # 2600
    ("sha1", "sha1"),      # 4500
    ("md5", "sha1"),       # 4400
    ("sha1", "md5"),       # 4700
    ("sha256", "md5"),     # 20800
    ("sha256", "sha1"),    # 20700
]
NESTED_DIGEST_SIZE = {"md5": 16, "sha1": 20, "sha256": 32}


class _NestedCpuMixin(HashEngine):
    """CPU oracle for nested modes: outer(hex(inner(password)))."""

    _outer: str
    _inner: str

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        return [hashlib.new(
            self._outer,
            hashlib.new(self._inner, c).hexdigest().encode()).digest()
            for c in candidates]


def _register_nested_cpu():
    for outer, inner in NESTED_COMBOS:
        name = f"{outer}({inner})"
        cls = type(f"{outer.title()}Of{inner.title()}Engine",
                   (_NestedCpuMixin,),
                   {"name": name,
                    "digest_size": NESTED_DIGEST_SIZE[outer],
                    "__doc__": f"Nested {outer}(hex({inner}(password))).",
                    "_outer": outer, "_inner": inner})
        register(name, device="cpu")(cls)


_register_nested_cpu()


def parse_mysql41(text: str) -> Target:
    """MySQL 4.1+ hash line: '*' + 40 uppercase hex chars (the '*' is
    part of the stored format; bare hex is accepted too)."""
    t = text.strip()
    hexpart = t[1:] if t.startswith("*") else t
    digest = bytes.fromhex(hexpart)
    if len(digest) != 20:
        raise ValueError(f"mysql41 wants 20 digest bytes, got {text!r}")
    return Target(raw=t, digest=digest)


@register("mysql41")
class Mysql41Engine(HashEngine):
    """MySQL 4.1+ PASSWORD() = sha1(sha1(password)), raw inner digest."""

    name = "mysql41"
    digest_size = 20

    def parse_target(self, text: str) -> Target:
        return parse_mysql41(text)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        return [hashlib.sha1(hashlib.sha1(c).digest()).digest()
                for c in candidates]


def _md4_utf16(password: bytes) -> bytes:
    return md4(password.decode("latin-1").encode("utf-16-le"))


def netntlmv2_proof(password: bytes, user: str, domain: str,
                    challenge: bytes, blob: bytes) -> bytes:
    """NetNTLMv2 reference: nt = MD4(UTF16LE(pw)); key2 = HMAC-MD5(nt,
    UTF16LE(upper(user)+domain)); proof = HMAC-MD5(key2, chal+blob)."""
    nt = _md4_utf16(password)
    ident = (user.upper() + domain).encode("utf-16-le")
    key2 = hmac.new(nt, ident, "md5").digest()
    return hmac.new(key2, challenge + blob, "md5").digest()


def parse_netntlmv2(text: str):
    """'USER::DOMAIN:chal:proof:blob' (hex fields) ->
    (user, domain, challenge, proof, blob)."""
    t = text.strip()
    user, sep, rest = t.partition("::")
    if not sep:
        raise ValueError(f"not a NetNTLMv2 line (no '::'): {text!r}")
    parts = rest.split(":")
    if len(parts) != 4:
        raise ValueError(f"malformed NetNTLMv2 line: {text!r}")
    domain, chal_hex, proof_hex, blob_hex = parts
    challenge = bytes.fromhex(chal_hex)
    proof = bytes.fromhex(proof_hex)
    blob = bytes.fromhex(blob_hex)
    if len(challenge) != 8 or len(proof) != 16:
        raise ValueError(f"bad challenge/proof length in {text!r}")
    return user, domain, challenge, proof, blob


@register("netntlmv2")
class NetNtlmV2Engine(HashEngine):
    """NetNTLMv2 challenge-response (hashcat 5600)."""

    name = "netntlmv2"
    digest_size = 16
    salted = True
    max_candidate_len = 27     # NTLM single-block UTF-16LE limit

    def parse_target(self, text: str) -> Target:
        user, domain, challenge, proof, blob = parse_netntlmv2(text)
        return Target(raw=text.strip(), digest=proof,
                      params={"user": user, "domain": domain,
                              "challenge": challenge, "blob": blob})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("netntlmv2 needs target params")
        return [netntlmv2_proof(c, params["user"], params["domain"],
                                params["challenge"], params["blob"])
                for c in candidates]


@register("ntlm")
class NtlmEngine(HashEngine):
    """NTLM: MD4 over the UTF-16LE encoding of the password."""

    name = "ntlm"
    digest_size = 16
    # 27 chars -> 54 UTF-16LE bytes, still a single MD4 block after padding.
    max_candidate_len = 27

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        out = []
        for c in candidates:
            # Candidates are raw bytes; treat them as latin-1 text so the
            # UTF-16LE widening is the byte-interleave NTLM expects for
            # the ASCII masks (?l/?u/?d/?s/?a) used by the benchmarks.
            out.append(md4(c.decode("latin-1").encode("utf-16-le")))
        return out


@register("bcrypt")
class BcryptEngine(HashEngine):
    """bcrypt (EksBlowfish).  Salted: digests are per-(candidate, target)."""

    name = "bcrypt"
    digest_size = 23
    salted = True
    max_candidate_len = 72

    def parse_target(self, text: str) -> Target:
        variant, cost, salt, digest = _bcrypt.parse_hash(text)
        if not 4 <= cost <= 31:
            raise ValueError(f"bcrypt cost out of range 4..31: {cost}")
        return Target(raw=text.strip(), digest=digest,
                      params={"variant": variant, "cost": cost, "salt": salt})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("bcrypt needs target params (salt, cost)")
        salt, cost = params["salt"], params["cost"]
        return [_bcrypt.bcrypt_raw(c, salt, cost) for c in candidates]


@register("md5crypt")
class Md5cryptEngine(HashEngine):
    """$1$ modular crypt (FreeBSD md5crypt; hashcat 500)."""

    name = "md5crypt"
    digest_size = 16
    salted = True
    max_candidate_len = 15    # device single-block budget: 16+2L+8 <= 55

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.md5crypt import parse_md5crypt
        salt, digest = parse_md5crypt(text)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.engines.cpu.md5crypt import md5crypt_raw
        if not params:
            raise ValueError("md5crypt needs target params (salt)")
        return [md5crypt_raw(c, params["salt"], self.magic)
                for c in candidates]

    #: scheme tag in the initial md5 context; subclasses override.
    magic = b"$1$"


@register("apr1")
@register("apache-md5")
class Apr1Engine(Md5cryptEngine):
    """Apache $apr1$ (htpasswd MD5; hashcat 1600): md5crypt with a
    6-byte magic -- same 1000-round scheme otherwise."""

    name = "apr1"
    magic = b"$apr1$"

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.md5crypt import parse_md5crypt
        salt, digest = parse_md5crypt(text, prefix="$apr1$")
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})


@register("sha512crypt")
class Sha512cryptEngine(HashEngine):
    """$6$ modular crypt (Linux shadow default; hashcat 1800)."""

    name = "sha512crypt"
    digest_size = 64
    salted = True
    max_candidate_len = 15    # device budget: 64 + 2L + 16 <= 111

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.sha512crypt import parse_sha512crypt
        rounds, salt, digest = parse_sha512crypt(text)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt, "rounds": rounds})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.engines.cpu.sha512crypt import sha512crypt_raw
        if not params:
            raise ValueError("sha512crypt needs target params "
                             "(salt, rounds)")
        return [sha512crypt_raw(c, params["salt"], params["rounds"])
                for c in candidates]


@register("sha256crypt")
class Sha256cryptEngine(HashEngine):
    """$5$ modular crypt (hashcat 7400)."""

    name = "sha256crypt"
    digest_size = 32
    salted = True
    max_candidate_len = 15

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.sha256crypt import parse_sha256crypt
        rounds, salt, digest = parse_sha256crypt(text)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt, "rounds": rounds})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.engines.cpu.sha256crypt import sha256crypt_raw
        if not params:
            raise ValueError("sha256crypt needs target params "
                             "(salt, rounds)")
        return [sha256crypt_raw(c, params["salt"], params["rounds"])
                for c in candidates]


#: pbkdf2 salt + INT(4) + 0x80 + length must fit the U1 block
PBKDF2_SALT_MAX = 51


def parse_pbkdf2_sha256(text: str):
    """-> (iterations, salt bytes, dk bytes).  Accepts Django's
    'pbkdf2_sha256$iter$salt$b64' and hashcat 10900's
    'sha256:iter:b64salt:b64dk'."""
    import base64
    t = text.strip()
    if t.startswith("pbkdf2_sha256$"):
        parts = t.split("$")
        if len(parts) != 4:
            raise ValueError(f"malformed Django pbkdf2 line: {text!r}")
        iters = int(parts[1])
        salt = parts[2].encode("latin-1")
        dk = base64.b64decode(parts[3])
    elif t.startswith("sha256:"):
        parts = t.split(":")
        if len(parts) != 4:
            raise ValueError(f"malformed pbkdf2 line: {text!r}")
        iters = int(parts[1])
        salt = base64.b64decode(parts[2])
        dk = base64.b64decode(parts[3])
    else:
        raise ValueError(f"not a pbkdf2-sha256 line: {text!r}")
    if not 1 <= iters <= (1 << 31) - 1:
        raise ValueError(f"iterations out of range in {text!r}")
    if len(salt) > PBKDF2_SALT_MAX:
        raise ValueError(f"salt longer than {PBKDF2_SALT_MAX} bytes: "
                         f"{text!r}")
    if len(dk) != 32:
        raise ValueError(f"expected a 32-byte derived key: {text!r}")
    return iters, salt, dk


@register("pbkdf2-sha256")
class Pbkdf2Sha256Engine(HashEngine):
    """PBKDF2-HMAC-SHA256 (Django default hasher; hashcat 10900)."""

    name = "pbkdf2-sha256"
    digest_size = 32
    salted = True
    max_candidate_len = 64    # single-block HMAC key

    def parse_target(self, text: str) -> Target:
        iters, salt, dk = parse_pbkdf2_sha256(text)
        return Target(raw=text.strip(), digest=dk,
                      params={"salt": salt, "iterations": iters})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("pbkdf2-sha256 needs target params")
        return [hashlib.pbkdf2_hmac("sha256", c, params["salt"],
                                    params["iterations"], 32)
                for c in candidates]


_CISCO_ITOA64 = ("./0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ"
                 "abcdefghijklmnopqrstuvwxyz")
_STD_B64 = ("ABCDEFGHIJKLMNOPQRSTUVWXYZ"
            "abcdefghijklmnopqrstuvwxyz0123456789+/")
_TO_STD = str.maketrans(_CISCO_ITOA64, _STD_B64)
_FROM_STD = str.maketrans(_STD_B64, _CISCO_ITOA64)


def cisco8_encode(dk: bytes) -> str:
    """Cisco type 8 digest text: standard base64 bit order, itoa64
    alphabet, no padding (verified against the published mode-9200
    example hash)."""
    import base64
    return base64.b64encode(dk).decode().rstrip("=").translate(_FROM_STD)


def cisco8_decode(text: str) -> bytes:
    import base64
    std = text.translate(_TO_STD)
    # validate=True: a char outside the itoa64 alphabet must raise, not
    # silently decode into a wrong digest
    return base64.b64decode(std + "=" * (-len(std) % 4), validate=True)


@register("cisco8")
@register("cisco-ios-8")
class Cisco8Engine(HashEngine):
    """Cisco IOS type 8 ($8$salt$hash): PBKDF2-HMAC-SHA256, 20000
    iterations, 32-byte dk (hashcat 9200).  Execution is the
    pbkdf2-sha256 path; only the line format differs."""

    name = "cisco8"
    digest_size = 32
    salted = True
    max_candidate_len = 64

    def parse_target(self, text: str) -> Target:
        t = text.strip()
        parts = t.split("$")
        if len(parts) != 4 or parts[0] != "" or parts[1] != "8":
            raise ValueError(f"not a Cisco type 8 hash: {text!r}")
        salt = parts[2].encode("latin-1")
        if not salt or len(salt) > PBKDF2_SALT_MAX:
            raise ValueError(f"bad Cisco type 8 salt in {text!r}")
        dk = cisco8_decode(parts[3])
        if len(dk) != 32:
            raise ValueError(f"Cisco type 8 wants a 32-byte dk: {text!r}")
        return Target(raw=t, digest=dk,
                      params={"salt": salt, "iterations": 20000})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("cisco8 needs target params")
        return [hashlib.pbkdf2_hmac("sha256", c, params["salt"],
                                    params["iterations"], 32)
                for c in candidates]


@register("pbkdf2-sha1")
class Pbkdf2Sha1Engine(HashEngine):
    """Generic PBKDF2-HMAC-SHA1 (hashcat 12000:
    'sha1:iter:b64salt:b64dk', dk 4..40 bytes in 4-byte steps)."""

    name = "pbkdf2-sha1"
    digest_size = 20           # nominal; per-target dk width may differ
    salted = True
    max_candidate_len = 64

    def parse_target(self, text: str) -> Target:
        import base64
        t = text.strip()
        parts = t.split(":")
        if len(parts) != 4 or parts[0] != "sha1":
            raise ValueError(f"not a pbkdf2-sha1 line: {text!r}")
        iters = int(parts[1])
        salt = base64.b64decode(parts[2])
        dk = base64.b64decode(parts[3])
        if not 1 <= iters <= (1 << 31) - 1:
            raise ValueError(f"iterations out of range in {text!r}")
        if len(salt) > PBKDF2_SALT_MAX:
            raise ValueError(f"salt longer than {PBKDF2_SALT_MAX}: "
                             f"{text!r}")
        if not 4 <= len(dk) <= 40 or len(dk) % 4:
            raise ValueError("derived key must be 4..40 bytes in 4-byte "
                             f"steps: {text!r}")
        return Target(raw=t, digest=dk,
                      params={"salt": salt, "iterations": iters,
                              "dklen": len(dk)})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("pbkdf2-sha1 needs target params")
        return [hashlib.pbkdf2_hmac("sha1", c, params["salt"],
                                    params["iterations"],
                                    params.get("dklen", 20))
                for c in candidates]


@register("atlassian")
@register("pkcs5s2")
class AtlassianEngine(Pbkdf2Sha1Engine):
    """Atlassian/Crowd {PKCS5S2} (hashcat 12001): PBKDF2-HMAC-SHA1,
    10000 iterations, base64(16-byte salt + 32-byte dk)."""

    name = "atlassian"

    def parse_target(self, text: str) -> Target:
        import base64
        t = text.strip()
        tag = "{PKCS5S2}"
        if not t.startswith(tag):
            raise ValueError(f"not a {tag} line: {text!r}")
        try:
            blob = base64.b64decode(t[len(tag):], validate=True)
        except Exception as e:
            raise ValueError(f"bad base64 in {text!r}: {e}")
        if len(blob) != 48:
            raise ValueError(f"{tag} blob must be 48 bytes "
                             f"(16 salt + 32 dk): {text!r}")
        return Target(raw=t, digest=blob[16:],
                      params={"salt": blob[:16], "iterations": 10000,
                              "dklen": 32})


@register("phpass")
class PhpassEngine(HashEngine):
    """phpass portable hashes ($P$/$H$, WordPress/phpBB; hashcat 400):
    h = md5(salt+pass), then count x h = md5(h+pass)."""

    name = "phpass"
    digest_size = 16
    salted = True

    from dprf_tpu.engines.cpu.phpass import MAX_PASS_LEN as \
        max_candidate_len  # noqa: F401  (39: digest+pass in one block)

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.phpass import parse_phpass
        count, salt, digest = parse_phpass(text)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt, "count": count})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.engines.cpu.phpass import phpass_raw
        if not params:
            raise ValueError("phpass needs target params (salt, count)")
        return [phpass_raw(c, params["salt"], params["count"])
                for c in candidates]


@register("wpa2-eapol")
@register("wpa2")
class Wpa2EapolEngine(HashEngine):
    """WPA2 4-way-handshake MIC (hc22000 WPA*02 lines; hashcat 22000).
    Same PBKDF2 cost as PMKID plus PRF-512 and the EAPOL HMAC."""

    name = "wpa2-eapol"
    digest_size = 16
    salted = True
    max_candidate_len = 63    # WPA passphrase limit
    iterations = 4096         # PBKDF2 rounds; tests lower it

    def parse_target(self, text: str) -> Target:
        from dprf_tpu.engines.cpu.wpa2 import parse_wpa02
        f = parse_wpa02(text)
        return Target(raw=text.strip(), digest=f.pop("mic"), params=f)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.engines.cpu.wpa2 import wpa2_mic
        if not params:
            raise ValueError("wpa2-eapol needs target params")
        return [wpa2_mic(c, params["essid"], params["mac_ap"],
                         params["mac_sta"], params["anonce"],
                         params["eapol"], params["keyver"],
                         self.iterations)
                for c in candidates]


@register("wpa2-pmkid")
class Pmkid2Engine(HashEngine):
    """WPA2-PMKID: PMK = PBKDF2-HMAC-SHA1(pass, essid, 4096, 32);
    PMKID = HMAC-SHA1(PMK, "PMK Name" | MAC_AP | MAC_STA)[:16].

    Target lines use the hashcat 16800 format:
    ``pmkid*mac_ap*mac_sta*essid_hex`` (macs as 12 hex chars, no colons).
    """

    name = "wpa2-pmkid"
    digest_size = 16
    salted = True
    max_candidate_len = 63    # WPA passphrase limit
    iterations = 4096         # PBKDF2 rounds; tests lower it for speed

    def parse_target(self, text: str) -> Target:
        parts = text.strip().split("*")
        if len(parts) != 4:
            raise ValueError(f"expected pmkid*mac_ap*mac_sta*essid, got {text!r}")
        pmkid, mac_ap, mac_sta, essid_hex = parts
        digest = bytes.fromhex(pmkid)
        ap, sta = bytes.fromhex(mac_ap), bytes.fromhex(mac_sta)
        if len(digest) != self.digest_size:
            raise ValueError(f"PMKID must be {self.digest_size} bytes, "
                             f"got {len(digest)} from {text!r}")
        if len(ap) != 6 or len(sta) != 6:
            raise ValueError(f"MACs must be 6 bytes each in {text!r}")
        return Target(
            raw=text.strip(),
            digest=digest,
            params={"essid": bytes.fromhex(essid_hex),
                    "mac_ap": ap, "mac_sta": sta})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("wpa2-pmkid needs target params (essid, macs)")
        message = b"PMK Name" + params["mac_ap"] + params["mac_sta"]
        out = []
        for c in candidates:
            pmk = hashlib.pbkdf2_hmac("sha1", c, params["essid"],
                                      self.iterations, 32)
            out.append(hmac.new(pmk, message, hashlib.sha1).digest()[:16])
        return out


# Convenience aliases matching common reference spellings.
register("pmkid")(Pmkid2Engine)
register("sha-1")(Sha1Engine)
register("sha-256")(Sha256Engine)


class _HmacCpuMixin(HashEngine):
    """CPU oracle for the HMAC fast modes over ``hexdigest:salt`` lines:
    key = $pass, message = $salt (hashcat 50/150/1450) or key = $salt,
    message = $pass (60/160/1460)."""

    salted = True
    _algo: str
    _key_is_pass: bool

    def parse_target(self, text: str) -> Target:
        digest, salt = parse_salted_line(text, self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError(f"{self.name} needs target params (salt)")
        salt = params["salt"]
        if self._key_is_pass:
            return [hmac.new(c, salt, self._algo).digest()
                    for c in candidates]
        return [hmac.new(salt, c, self._algo).digest()
                for c in candidates]


def _register_hmac_cpu(algo: str, digest_size: int):
    for key_is_pass in (True, False):
        name = f"hmac-{algo}" + ("" if key_is_pass else "-salt")
        key, msg = (("$pass", "$salt") if key_is_pass
                    else ("$salt", "$pass"))
        cls = type(f"Hmac{algo.title()}{'Pass' if key_is_pass else 'Salt'}"
                   "Engine", (_HmacCpuMixin,),
                   {"name": name, "digest_size": digest_size,
                    "_algo": algo, "_key_is_pass": key_is_pass,
                    "__doc__": (f"HMAC-{algo.upper()} (key = {key}, "
                                f"message = {msg}); 'hexdigest:salt' "
                                "lines."),
                    # key = $pass: candidate must fit one key block;
                    # key = $salt: candidate is a one-block message.
                    "max_candidate_len": 64 if key_is_pass else 55})
        register(name, device="cpu")(cls)


_register_hmac_cpu("md5", 16)
_register_hmac_cpu("sha1", 20)
_register_hmac_cpu("sha256", 32)


@register("jwt-hs256")
@register("jwt")
class JwtHs256Engine(HashEngine):
    """JWT HS256 (hashcat 16500): HMAC-SHA256(secret, signing input)
    where a target line is the full ``header.payload.signature`` token
    (base64url) and the signing input ``header.payload`` is a per-target
    message constant."""

    name = "jwt-hs256"
    digest_size = 32
    salted = True
    max_candidate_len = 64

    @staticmethod
    def _b64url(text: str) -> bytes:
        import base64
        pad = "=" * (-len(text) % 4)
        return base64.urlsafe_b64decode(text + pad)

    def parse_target(self, text: str) -> Target:
        parts = text.strip().split(".")
        if len(parts) != 3:
            raise ValueError(f"expected header.payload.signature JWT, "
                             f"got {text!r}")
        sig = self._b64url(parts[2])
        if len(sig) != self.digest_size:
            raise ValueError(
                f"JWT signature must be {self.digest_size} bytes "
                f"(HS256), got {len(sig)} from {text!r}")
        msg = (parts[0] + "." + parts[1]).encode("ascii")
        return Target(raw=text.strip(), digest=sig, params={"msg": msg})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("jwt-hs256 needs target params (msg)")
        return [hmac.new(c, params["msg"], hashlib.sha256).digest()
                for c in candidates]


@register("scrypt")
class ScryptEngine(HashEngine):
    """scrypt (RFC 7914; hashcat 8900): memory-hard KDF with
    ``SCRYPT:N:r:p:<b64 salt>:<b64 dk>`` target lines.  N, r, p are
    per-target parameters; the derived key is 32 bytes."""

    name = "scrypt"
    digest_size = 32
    salted = True
    max_candidate_len = 64     # one HMAC-SHA256 key block

    def parse_target(self, text: str) -> Target:
        import base64
        parts = text.strip().split(":")
        if len(parts) != 6 or parts[0].upper() != "SCRYPT":
            raise ValueError(
                f"expected SCRYPT:N:r:p:salt:dk, got {text!r}")
        n, r, p = (int(x) for x in parts[1:4])
        if n < 2 or n & (n - 1):
            raise ValueError(f"scrypt N must be a power of two: {n}")
        if n > 1 << 24:
            # V alone would be 128*r*N bytes per candidate; an absurd N
            # in one hostile line must not OOM the process
            raise ValueError(f"scrypt N={n} over the 2^24 limit")
        if not (1 <= r <= 32 and 1 <= p <= 16) or p * 4 * r > 255:
            raise ValueError(f"unsupported scrypt r={r} p={p}")
        salt = base64.b64decode(parts[4])
        digest = base64.b64decode(parts[5])
        if len(digest) != self.digest_size:
            raise ValueError(
                f"scrypt dk must be {self.digest_size} bytes, got "
                f"{len(digest)}")
        if len(salt) > PBKDF2_SALT_MAX:
            raise ValueError(
                f"salt longer than {PBKDF2_SALT_MAX} bytes")
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt, "n": n, "r": r, "p": p})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("scrypt needs target params (salt, n, r, p)")
        n, r, p = params["n"], params["r"], params["p"]
        # maxmem: V alone is 128*r*N bytes; give the libcrypto check
        # ample headroom.
        mem = 128 * r * n * max(1, p) * 2 + (1 << 20)
        return [hashlib.scrypt(c, salt=params["salt"], n=n, r=r, p=p,
                               dklen=self.digest_size, maxmem=mem)
                for c in candidates]


@register("zip2")
@register("winzip")
class Zip2Engine(HashEngine):
    """WinZip AES (hashcat 13600): ``$zip2$*0*M*0*salt*verify*dlen*
    data*auth*$/zip2$`` where M selects AES-128/192/256 (keylen
    16/24/32, salt 8/12/16).  DK = PBKDF2-HMAC-SHA1(pass, salt, 1000,
    2*keylen+2); the last 2 DK bytes are the password verification
    value (a 1/2^16 prefilter) and the stored auth code is
    HMAC-SHA1(DK[keylen:2*keylen], data)[:10] -- the digest this
    engine compares."""

    name = "zip2"
    digest_size = 10
    salted = True
    max_candidate_len = 64
    iterations = 1000

    _KEYLEN = {1: 16, 2: 24, 3: 32}

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        if not (body.startswith("$zip2$*") and body.endswith("*$/zip2$")):
            raise ValueError(f"expected $zip2$*...*$/zip2$ line, "
                             f"got {text[:40]!r}")
        parts = body[len("$zip2$*"):-len("*$/zip2$")].split("*")
        if len(parts) != 8:
            raise ValueError(f"expected 8 '*' fields in {text[:40]!r}")
        type_, mode, magic, salt_hex, verify_hex, dlen_hex, data_hex, \
            auth_hex = parts
        if type_ != "0" or magic != "0":
            # hashcat 13600 fixes both fields to 0 (AE-2); anything
            # else is a format we would crack under wrong semantics
            raise ValueError(
                f"unsupported zip2 version/magic {type_}/{magic}")
        mode = int(mode)
        if mode not in self._KEYLEN:
            raise ValueError(f"zip2 mode must be 1/2/3, got {mode}")
        salt = bytes.fromhex(salt_hex)
        if len(salt) != 4 + 4 * mode:
            raise ValueError(f"zip2 mode {mode} needs a "
                             f"{4 + 4 * mode}-byte salt")
        verify = bytes.fromhex(verify_hex)
        if len(verify) != 2:
            raise ValueError("zip2 verify value must be 2 bytes")
        data = bytes.fromhex(data_hex)
        if int(dlen_hex, 16) != len(data):
            raise ValueError("zip2 data length field disagrees with data")
        auth = bytes.fromhex(auth_hex)
        if len(auth) != self.digest_size:
            raise ValueError("zip2 auth code must be 10 bytes")
        return Target(raw=body, digest=auth,
                      params={"salt": salt, "mode": mode,
                              "verify": verify, "data": data})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("zip2 needs target params (salt, mode, data)")
        kl = self._KEYLEN[params["mode"]]
        out = []
        for c in candidates:
            dk = hashlib.pbkdf2_hmac("sha1", c, params["salt"],
                                     self.iterations, 2 * kl + 2)
            out.append(hmac.new(dk[kl:2 * kl], params["data"],
                                hashlib.sha1).digest()[:self.digest_size])
        return out


def _utf16_lower_user(user: str) -> bytes:
    return user.lower().encode("utf-16-le")


#: DCC outer-block budget: 16 digest bytes + salt + 0x80 + 8-byte
#: length must fit one 64-byte MD4 block -> salt <= 39 bytes; an even
#: byte count (UTF-16LE) makes that 38 bytes = 19 characters (Windows
#: caps sAMAccountName at 20, so 19 covers all but the edge).
DCC_USER_MAX = 19


def _parse_user_digest(text_digest_hex: str, user: str,
                       digest_size: int):
    """Shared mscache/mscache2 field validation -> (digest, salt)."""
    digest = bytes.fromhex(text_digest_hex)
    if len(digest) != digest_size:
        raise ValueError(f"expected {digest_size}-byte digest, "
                         f"got {len(digest)}")
    if not user:
        raise ValueError("empty username")
    if len(user) > DCC_USER_MAX:
        raise ValueError(f"username longer than {DCC_USER_MAX} chars")
    return digest, _utf16_lower_user(user)


def _dcc1(password: bytes, user_salt: bytes) -> bytes:
    """MS Cache v1: MD4(MD4(UTF16LE(pw)) || UTF16LE(lower(user)))."""
    inner = md4(password.decode("latin-1").encode("utf-16-le"))
    return md4(inner + user_salt)


@register("mscache")
@register("dcc")
class MsCacheEngine(HashEngine):
    """MS Cache v1 / Domain Cached Credentials (hashcat 1100):
    ``hexdigest:username`` lines; digest = MD4(MD4(UTF16LE(pw)) ||
    UTF16LE(lower(user)))."""

    name = "mscache"
    digest_size = 16
    salted = True
    max_candidate_len = 27     # UTF-16LE widening: one MD4 block

    def parse_target(self, text: str) -> Target:
        digest_hex, sep, user = text.strip().partition(":")
        if not sep or not user:
            raise ValueError(f"expected 'digest:username', got {text!r}")
        digest, salt = _parse_user_digest(digest_hex, user,
                                          self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt, "user": user})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("mscache needs target params (user)")
        return [_dcc1(c, params["salt"]) for c in candidates]


@register("mscache2")
@register("dcc2")
class MsCache2Engine(HashEngine):
    """MS Cache v2 / DCC2 (hashcat 2100): ``$DCC2$<iter>#<user>#<hex>``
    lines; digest = PBKDF2-HMAC-SHA1(DCC1, UTF16LE(lower(user)),
    iterations, 16)."""

    name = "mscache2"
    digest_size = 16
    salted = True
    max_candidate_len = 27

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        if not body.startswith("$DCC2$"):
            raise ValueError(f"expected $DCC2$iter#user#hash, got {text!r}")
        parts = body[len("$DCC2$"):].split("#")
        if len(parts) != 3:
            raise ValueError(f"expected 3 '#' fields in {text!r}")
        iterations = int(parts[0])
        if not 1 <= iterations <= (1 << 24):
            raise ValueError(f"unreasonable DCC2 iterations {iterations}")
        user = parts[1]
        digest, salt = _parse_user_digest(parts[2], user,
                                          self.digest_size)
        return Target(raw=body, digest=digest,
                      params={"salt": salt, "user": user,
                              "iterations": iterations})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("mscache2 needs target params (user, iters)")
        return [hashlib.pbkdf2_hmac("sha1", _dcc1(c, params["salt"]),
                                    params["salt"],
                                    params["iterations"], 16)
                for c in candidates]


@register("lm")
class LmEngine(HashEngine):
    """LM hash, one half (hashcat 3000): DES_{str_to_key(upper(pw))}
    ("KGS!@#$%") over a <= 7-char half.  A full 16-byte LM hash is two
    independent halves -- split it into two lines.  Candidates are
    uppercased here (LM is case-insensitive), so lowercase masks and
    wordlists work unchanged."""

    name = "lm"
    digest_size = 8
    max_candidate_len = 7

    def parse_target(self, text: str) -> Target:
        t = text.strip()
        digest = bytes.fromhex(t)
        if len(digest) == 16:
            raise ValueError(
                "full 16-byte LM hash: split it into its two 8-byte "
                "halves (one line each); each half cracks independently")
        if len(digest) != self.digest_size:
            raise ValueError(f"lm wants 8 digest bytes, got {text!r}")
        return Target(raw=t, digest=digest)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.ops.des import lm_half
        # a candidate longer than 7 bytes can never BE an LM half:
        # an empty digest compares unequal to every 8-byte target
        # (rule expansions may legitimately overshoot; truncating
        # instead would report plaintexts that don't hash to the
        # target)
        return [lm_half(c) if len(c) <= 7 else b"" for c in candidates]


def netntlmv1_response(password: bytes, challenge: bytes) -> bytes:
    """NetNTLMv1 NT response: the 16-byte NTLM hash zero-padded to 21
    bytes makes three DES keys; each encrypts the 8-byte challenge."""
    from dprf_tpu.ops.des import des_encrypt, str_to_key
    key21 = _md4_utf16(password) + bytes(5)
    return b"".join(des_encrypt(str_to_key(key21[7 * i:7 * i + 7]),
                                challenge) for i in range(3))


@register("netntlmv1")
class NetNtlmV1Engine(HashEngine):
    """NetNTLMv1 challenge-response (hashcat 5500):
    ``user::domain:lmresp(48 hex):ntresp(48 hex):challenge(16 hex)``
    lines; the NT response (24 bytes) is the digest."""

    name = "netntlmv1"
    digest_size = 24
    salted = True
    max_candidate_len = 27

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        parts = body.split(":")
        if len(parts) != 6 or parts[1]:
            raise ValueError(
                f"expected user::domain:lm:nt:challenge, got {text[:40]!r}")
        lmresp = bytes.fromhex(parts[3])
        ntresp = bytes.fromhex(parts[4])
        challenge = bytes.fromhex(parts[5])
        if len(ntresp) != self.digest_size:
            raise ValueError("NT response must be 24 bytes")
        if len(challenge) != 8:
            raise ValueError("server challenge must be 8 bytes")
        if len(lmresp) == 24 and lmresp[8:] == bytes(16) \
                and lmresp[:8] != bytes(8):
            # NTLMv1-ESS / SSP: the LM field carries the CLIENT
            # challenge and the DES input is MD5(server||client)[:8];
            # checking against the raw server challenge would silently
            # never match such captures
            challenge = hashlib.md5(challenge + lmresp[:8]).digest()[:8]
        return Target(raw=body, digest=ntresp,
                      params={"challenge": challenge, "user": parts[0],
                              "domain": parts[2]})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("netntlmv1 needs target params (challenge)")
        return [netntlmv1_response(c, params["challenge"])
                for c in candidates]


@register("office2007")
@register("office")
class Office2007Engine(HashEngine):
    """MS Office 2007 standard encryption (hashcat 9400):
    ``$office$*2007*20*128*16*<salt>*<encVerifier>*<encVerifierHash>``.
    Key = 50,002-round SHA-1 spin of (salt, UTF-16LE password) through
    the MS-OFFCRYPTO derivation; a candidate matches when
    SHA1(AES128dec(key, verifier)) equals the decrypted verifier hash.
    The comparable digest is a 1-byte match marker (the check is a
    decrypt-and-compare, not a digest equality)."""

    name = "office2007"
    digest_size = 1
    salted = True
    max_candidate_len = 19     # salt(16) + UTF-16LE pw in one SHA-1 block
    spin_count = 50000         # tests lower it for speed

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        parts = body.split("*")
        if len(parts) != 8 or parts[0] != "$office$" or \
                parts[1] != "2007":
            raise ValueError(
                f"expected $office$*2007*...*... line, got {text[:40]!r}")
        vsize, ksize, ssize = int(parts[2]), int(parts[3]), int(parts[4])
        if (vsize, ksize, ssize) != (20, 128, 16):
            raise ValueError(
                f"unsupported office2007 parameters {vsize}/{ksize}/"
                f"{ssize} (SHA-1 + AES-128 only)")
        salt = bytes.fromhex(parts[5])
        ev = bytes.fromhex(parts[6])
        evh = bytes.fromhex(parts[7])
        if len(salt) != 16 or len(ev) != 16 or len(evh) != 32:
            raise ValueError("bad office2007 field lengths")
        return Target(raw=body, digest=b"\x01",
                      params={"salt": salt, "verifier": ev,
                              "verifier_hash": evh})

    def _derive_key(self, password: bytes, salt: bytes) -> bytes:
        h = hashlib.sha1(
            salt + password.decode("latin-1").encode("utf-16-le")).digest()
        for i in range(self.spin_count):
            h = hashlib.sha1(i.to_bytes(4, "little") + h).digest()
        h = hashlib.sha1(h + (0).to_bytes(4, "little")).digest()
        buf = bytearray(b"\x36" * 64)
        for i, b in enumerate(h):
            buf[i] ^= b
        return hashlib.sha1(bytes(buf)).digest()[:16]

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("office2007 needs target params")
        from dprf_tpu.ops.aes import aes128_decrypt_block
        ev, evh = params["verifier"], params["verifier_hash"]
        out = []
        for c in candidates:
            key = self._derive_key(c, params["salt"])
            verifier = aes128_decrypt_block(key, ev)
            vhash = (aes128_decrypt_block(key, evh[:16])
                     + aes128_decrypt_block(key, evh[16:]))
            ok = hashlib.sha1(verifier).digest() == vhash[:20]
            out.append(b"\x01" if ok else b"\x00")
        return out


#: MS-OFFCRYPTO agile block keys (specification constants): the two
#: purposes of the password key encryptor's verifier.
OFFICE_BK_INPUT = bytes((0xFE, 0xA7, 0xD2, 0x76, 0x3B, 0x4B, 0x9E, 0x79))
OFFICE_BK_VALUE = bytes((0xD7, 0xAA, 0x0F, 0x6D, 0x30, 0x61, 0x34, 0x4E))


class _OfficeAgileEngine(HashEngine):
    """MS Office agile encryption (2010: SHA-1 + AES-128, hashcat
    9500; 2013: SHA-512 + AES-256, 9600):
    ``$office$*<ver>*<spin>*<keybits>*16*salt*encVerifier*encVerifierHash``.
    Match = H(CBCdec(key_input, verifier)) vs CBCdec(key_value,
    verifierHash) over the stored prefix."""

    digest_size = 1
    salted = True
    _version: str
    _hash: str
    _keybits: int

    @property
    def max_candidate_len(self):
        # salt(16) + UTF-16LE pw in one hash block
        return 19 if self._hash == "sha1" else 47

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        parts = body.split("*")
        if len(parts) != 8 or parts[0] != "$office$" or \
                parts[1] != self._version:
            raise ValueError(f"expected $office$*{self._version}*... "
                             f"line, got {text[:40]!r}")
        spin = int(parts[2])
        if not 1 <= spin <= (1 << 24):
            raise ValueError(f"unreasonable spin count {spin}")
        if int(parts[3]) != self._keybits or int(parts[4]) != 16:
            raise ValueError(
                f"office{self._version} expects {self._keybits}-bit "
                "keys and 16-byte salts")
        salt = bytes.fromhex(parts[5])
        ev = bytes.fromhex(parts[6])
        evh = bytes.fromhex(parts[7])
        if len(salt) != 16 or len(ev) != 16 or len(evh) != 32:
            raise ValueError("bad office agile field lengths")
        return Target(raw=body, digest=b"\x01",
                      params={"salt": salt, "verifier": ev,
                              "verifier_hash": evh, "spin": spin})

    def _agile_spin(self, password: bytes, salt: bytes,
                    spin: int) -> bytes:
        H = getattr(hashlib, self._hash)    # no name lookup per round
        h = H(salt
              + password.decode("latin-1").encode("utf-16-le")).digest()
        for i in range(spin):
            h = H(i.to_bytes(4, "little") + h).digest()
        return h

    def _agile_final(self, h: bytes, block_key: bytes) -> bytes:
        return hashlib.new(self._hash,
                           h + block_key).digest()[:self._keybits // 8]

    def _agile_key(self, password: bytes, salt: bytes, spin: int,
                   block_key: bytes) -> bytes:
        return self._agile_final(self._agile_spin(password, salt, spin),
                                 block_key)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError(f"{self.name} needs target params")
        from dprf_tpu.ops.aes import aes_decrypt_block
        salt, spin = params["salt"], params["spin"]
        ev, evh = params["verifier"], params["verifier_hash"]
        out = []
        for c in candidates:
            # ONE spin per candidate; the two block-key finals share it
            h = self._agile_spin(c, salt, spin)
            ki = self._agile_final(h, OFFICE_BK_INPUT)
            kv = self._agile_final(h, OFFICE_BK_VALUE)
            inp = bytes(a ^ b for a, b in
                        zip(aes_decrypt_block(ki, ev), salt))
            v1 = bytes(a ^ b for a, b in
                       zip(aes_decrypt_block(kv, evh[:16]), salt))
            v2 = bytes(a ^ b for a, b in
                       zip(aes_decrypt_block(kv, evh[16:]), evh[:16]))
            want = hashlib.new(self._hash, inp).digest()
            # the stored value holds min(32, hash size) comparable
            # bytes (sha1's 20-byte digest is padded in the file; the
            # pad bytes are not part of the check)
            n = min(32, len(want))
            out.append(b"\x01" if (v1 + v2)[:n] == want[:n]
                       else b"\x00")
        return out


@register("office2010")
class Office2010Engine(_OfficeAgileEngine):
    name = "office2010"
    _version = "2010"
    _hash = "sha1"
    _keybits = 128


@register("office2013")
class Office2013Engine(_OfficeAgileEngine):
    name = "office2013"
    _version = "2013"
    _hash = "sha512"
    _keybits = 256


def rar5_pswcheck(dk32: bytes) -> bytes:
    """RAR5 password check value: XOR of the 8-byte quarters of the
    32-byte derived key computed at iterations + 32."""
    q = [dk32[8 * i:8 * i + 8] for i in range(4)]
    return bytes(a ^ b ^ c ^ d for a, b, c, d in zip(*q))


@register("rar5")
class Rar5Engine(HashEngine):
    """RAR5 (hashcat 13000): ``$rar5$16$<salt>$<log2 iter>$<iv>$8$
    <pswcheck>``.  Key = PBKDF2-HMAC-SHA256(pass, salt, 2^n + 32);
    the stored 8-byte check is the XOR of the dk's quarters."""

    name = "rar5"
    digest_size = 8
    salted = True
    max_candidate_len = 64

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        parts = body.split("$")
        if len(parts) != 8 or parts[0] or parts[1] != "rar5":
            raise ValueError(
                f"expected $rar5$16$salt$n$iv$8$check, got {text[:40]!r}")
        if int(parts[2]) != 16 or int(parts[6]) != 8:
            raise ValueError("rar5 expects 16-byte salts and 8-byte "
                             "check values")
        salt = bytes.fromhex(parts[3])
        n = int(parts[4])
        if not 1 <= n <= 24:
            raise ValueError(f"unreasonable rar5 iteration exponent {n}")
        check = bytes.fromhex(parts[7])
        if len(salt) != 16 or len(check) != 8:
            raise ValueError("bad rar5 field lengths")
        return Target(raw=body, digest=check,
                      params={"salt": salt, "iterations": (1 << n) + 32})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("rar5 needs target params (salt, iters)")
        return [rar5_pswcheck(hashlib.pbkdf2_hmac(
                    "sha256", c, params["salt"], params["iterations"], 32))
                for c in candidates]


class _EthereumEngineBase(HashEngine):
    """Ethereum keystore (v3) wallets: MAC = Keccak-256(dk[16:32] ||
    ciphertext) compared against the stored mac."""

    digest_size = 32
    salted = True
    max_candidate_len = 64

    def _mac(self, dk: bytes, params: dict) -> bytes:
        from dprf_tpu.ops.keccak import keccak256
        return keccak256(dk[16:32] + params["ct"])

    @staticmethod
    def _check_fields(salt: bytes, ct: bytes, mac: bytes) -> None:
        if len(mac) != 32:
            raise ValueError("ethereum mac must be 32 bytes")
        if len(salt) > PBKDF2_SALT_MAX:
            raise ValueError(f"salt longer than {PBKDF2_SALT_MAX} bytes")
        if len(ct) > 119:
            raise ValueError("ciphertext too long for the single-block "
                             "keccak MAC path (>119 bytes)")


@register("ethereum-pbkdf2")
class EthereumPbkdf2Engine(_EthereumEngineBase):
    """Ethereum keystore, PBKDF2 KDF (hashcat 15600):
    ``$ethereum$p*<iter>*<salt hex>*<ct hex>*<mac hex>``."""

    name = "ethereum-pbkdf2"

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        parts = body.split("*")
        if len(parts) != 5 or parts[0] != "$ethereum$p":
            raise ValueError(
                f"expected $ethereum$p*iter*salt*ct*mac, got {text[:40]!r}")
        iterations = int(parts[1])
        if not 1 <= iterations <= (1 << 24):
            raise ValueError(f"unreasonable iteration count {iterations}")
        salt = bytes.fromhex(parts[2])
        ct = bytes.fromhex(parts[3])
        mac = bytes.fromhex(parts[4])
        self._check_fields(salt, ct, mac)
        return Target(raw=body, digest=mac,
                      params={"salt": salt, "iterations": iterations,
                              "ct": ct})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("ethereum-pbkdf2 needs target params")
        return [self._mac(hashlib.pbkdf2_hmac(
                    "sha256", c, params["salt"], params["iterations"], 32),
                          params)
                for c in candidates]


@register("ethereum-scrypt")
class EthereumScryptEngine(_EthereumEngineBase):
    """Ethereum keystore, scrypt KDF (hashcat 15700):
    ``$ethereum$s*<N>*<r>*<p>*<salt hex>*<ct hex>*<mac hex>``."""

    name = "ethereum-scrypt"

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        parts = body.split("*")
        if len(parts) != 7 or parts[0] != "$ethereum$s":
            raise ValueError(
                f"expected $ethereum$s*N*r*p*salt*ct*mac, "
                f"got {text[:40]!r}")
        n, r, p = (int(x) for x in parts[1:4])
        if n < 2 or n & (n - 1) or n > (1 << 24):
            raise ValueError(f"scrypt N must be a power of two <= 2^24, "
                             f"got {n}")
        if not (1 <= r <= 32 and 1 <= p <= 16) or p * 4 * r > 255:
            raise ValueError(f"unsupported scrypt r={r} p={p}")
        salt = bytes.fromhex(parts[4])
        ct = bytes.fromhex(parts[5])
        mac = bytes.fromhex(parts[6])
        self._check_fields(salt, ct, mac)
        return Target(raw=body, digest=mac,
                      params={"salt": salt, "n": n, "r": r, "p": p,
                              "ct": ct})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("ethereum-scrypt needs target params")
        n, r, p = params["n"], params["r"], params["p"]
        mem = 128 * r * n * max(1, p) * 2 + (1 << 20)
        return [self._mac(hashlib.scrypt(c, salt=params["salt"], n=n,
                                         r=r, p=p, dklen=32, maxmem=mem),
                          params)
                for c in candidates]


@register("sha3-256")
@register("sha3")
class Sha3_256Engine(HashEngine):
    """SHA3-256 (hashcat 17400): bare 64-hex-digest lines."""

    name = "sha3-256"
    digest_size = 32
    max_candidate_len = 55

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        return [hashlib.sha3_256(c).digest() for c in candidates]


@register("keccak-256")
@register("keccak256")
class Keccak256Engine(HashEngine):
    """Original Keccak-256 (hashcat 17800; Ethereum's hash): bare
    64-hex-digest lines.  Differs from SHA3-256 only in the 0x01
    padding byte."""

    name = "keccak-256"
    digest_size = 32
    max_candidate_len = 55

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        from dprf_tpu.ops.keccak import keccak256
        return [keccak256(c) for c in candidates]


#: (bits, sponge rate) for the SHA3/Keccak family; rate = 200 - bits/4
KECCAK_SIZES = [(224, 144), (384, 104), (512, 72)]


def _register_keccak_family():
    """sha3-224/384/512 (hashcat 17300/17500/17600; hashlib oracles)
    and keccak-224/384/512 (17700/17900/18000; scalar sponge oracle).
    256 variants are the explicit classes above."""
    from dprf_tpu.ops.keccak import keccak_digest

    for bits, rate in KECCAK_SIZES:
        def make_sha3_hash(bits):
            def hash_batch(self, candidates, params=None):
                return [hashlib.new(f"sha3_{bits}", c).digest()
                        for c in candidates]
            return hash_batch

        def make_keccak_hash(bits, rate):
            def hash_batch(self, candidates, params=None):
                return [keccak_digest(c, 0x01, rate, bits // 8)
                        for c in candidates]
            return hash_batch

        cls = type(f"Sha3_{bits}Engine", (HashEngine,),
                   {"name": f"sha3-{bits}", "digest_size": bits // 8,
                    "max_candidate_len": rate - 1,
                    "__doc__": f"SHA3-{bits}: bare hex-digest lines.",
                    "hash_batch": make_sha3_hash(bits)})
        register(f"sha3-{bits}", device="cpu")(cls)
        kcls = type(f"Keccak{bits}Engine", (HashEngine,),
                    {"name": f"keccak-{bits}", "digest_size": bits // 8,
                     "max_candidate_len": rate - 1,
                     "__doc__": (f"Original Keccak-{bits} (0x01 "
                                 "padding): bare hex-digest lines."),
                     "hash_batch": make_keccak_hash(bits, rate)})
        register(f"keccak-{bits}", device="cpu")(kcls)
        register(f"keccak{bits}", device="cpu")(kcls)


_register_keccak_family()


@register("postgres")
@register("postgres-md5")
class PostgresMd5Engine(_SaltedCpuMixin):
    """PostgreSQL MD5 auth hashes (hashcat 12): stored as
    ``md5<hex(md5(password || username))>``; target lines are
    ``md5<hex>:username`` or ``<hex>:username`` (``$HEX[..]`` decodes
    non-latin-1 usernames, the shared salted-line convention).  The
    hash itself is the salted-md5 'ps' oracle with the username as
    the salt."""

    name = "postgres"
    digest_size = 16
    _algo = "md5"
    _order = "ps"
    max_candidate_len = 55 - SALT_MAX

    def parse_target(self, text: str) -> Target:
        body = text.strip()
        if body.startswith("md5"):
            body = body[3:]
        digest, salt = parse_salted_line(body, self.digest_size)
        return Target(raw=text.strip(), digest=digest,
                      params={"salt": salt,
                              "user": salt.decode("latin-1")})
