"""7-Zip AES-256 engine (hashcat 11600), stored-coder entries.

The 7z password check (AES-256 + iterated SHA-256 KDF):

  key = SHA-256( concat_{i=0}^{2^cycles - 1} (salt || UTF-16LE(pw)
                                              || LE64(i)) )
  plaintext = AES-256-CBC-decrypt(key, iv, data)
  valid <=> CRC32(plaintext[:unpacked_len]) == stored crc

Line format (the 7z2hashcat one):
  $7z$p$cycles$salt_len$salt$iv_len$iv$crc$data_len$unpacked_len$data
p = 0 means the encrypted stream holds the STORED (uncompressed)
file, which this engine verifies end-to-end.  p != 0 entries need the
archive's LZMA coder chain to check the CRC; they are rejected loudly
at parse time rather than half-checked.
"""

from __future__ import annotations

import hashlib
import struct
import zlib
from typing import Optional, Sequence

from dprf_tpu.engines import register
from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.ops.aes import aes_decrypt_block


def sevenzip_key(password: bytes, salt: bytes, cycles: int) -> bytes:
    """The iterated-SHA-256 file key (UTF-16LE password)."""
    pw = password.decode("latin-1").encode("utf-16-le")
    unit = salt + pw
    h = hashlib.sha256()
    # stream the 2^cycles counter units in chunks (2^19 units is
    # ~12 MB for an 8-char password -- hashlib eats it in ~10 ms)
    step = 4096
    n = 1 << cycles
    for start in range(0, n, step):
        h.update(b"".join(unit + struct.pack("<Q", i)
                          for i in range(start, min(start + step, n))))
    return h.digest()


def sevenzip_decrypt(key: bytes, iv: bytes, data: bytes) -> bytes:
    """AES-256-CBC (iv zero-padded to 16 bytes, the 7z convention)."""
    iv = (iv + bytes(16))[:16]
    out = bytearray()
    prev = iv
    for off in range(0, len(data), 16):
        block = data[off:off + 16]
        plain = aes_decrypt_block(key, block)
        out += bytes(p ^ v for p, v in zip(plain, prev))
        prev = block
    return bytes(out)


def parse_7z(text: str) -> dict:
    t = text.strip()
    if not t.startswith("$7z$"):
        raise ValueError(f"not a $7z$ line: {text[:40]!r}")
    f = t[len("$7z$"):].split("$")
    if len(f) != 10:
        raise ValueError(f"malformed $7z$ line ({len(f)} fields, "
                         "expected 10)")
    p, cycles = int(f[0]), int(f[1])
    salt_len, salt = int(f[2]), bytes.fromhex(f[3])
    iv_len, iv = int(f[4]), bytes.fromhex(f[5])
    crc = int(f[6]) & 0xFFFFFFFF
    data_len, unpacked_len = int(f[7]), int(f[8])
    data = bytes.fromhex(f[9])
    if p != 0:
        raise ValueError(
            f"$7z$ coder type {p} is compressed; only stored (type 0) "
            "entries are verifiable without the archive's LZMA chain")
    # 7z2hashcat zero-pads the IV hex field to 16 bytes while iv_len
    # records the true length (p7zip commonly uses 8-byte IVs): accept
    # the padded field and keep the true prefix (decrypt re-pads).
    if len(iv) < iv_len:
        raise ValueError("IV field shorter than iv_len in $7z$ line")
    iv = iv[:iv_len]
    if len(salt) != salt_len or len(data) != data_len:
        raise ValueError("field length mismatch in $7z$ line")
    if not 0 < cycles <= 24:
        raise ValueError(f"unsupported cycles power {cycles}")
    if data_len % 16 or not 0 < unpacked_len <= data_len:
        raise ValueError("$7z$ data must be 16-byte blocks covering "
                         "unpacked_len")
    return {"cycles": cycles, "salt": salt, "iv": iv, "crc": crc,
            "unpacked_len": unpacked_len, "data": data}


@register("7z")
@register("sevenzip")
class SevenZipEngine(HashEngine):
    """7-Zip stored-entry password check (hashcat 11600)."""

    name = "7z"
    digest_size = 4            # the CRC32 is the compared value
    salted = True
    max_candidate_len = 27

    def parse_target(self, text: str) -> Target:
        params = parse_7z(text)
        return Target(raw=text.strip(),
                      digest=struct.pack("<I", params["crc"]),
                      params=params)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("7z needs target params ($7z$ fields)")
        out = []
        for c in candidates:
            key = sevenzip_key(c, params["salt"], params["cycles"])
            plain = sevenzip_decrypt(key, params["iv"], params["data"])
            out.append(struct.pack(
                "<I", zlib.crc32(plain[:params["unpacked_len"]])
                & 0xFFFFFFFF))
        return out
