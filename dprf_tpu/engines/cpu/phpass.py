"""phpass "portable" hashes (WordPress/phpBB; hashcat mode 400).

Format: ``$P$`` or ``$H$`` + one itoa64 char encoding log2(count) +
8-char salt + 22 itoa64 chars encoding the 16-byte digest.

Algorithm: h = md5(salt + password); repeat count times:
h = md5(h + password).  Pure Python here (the oracle); the device
engine runs the same chain as a fori_loop over the shared MD5
compression (engines/device/phpass.py).
"""

from __future__ import annotations

import hashlib

ITOA64 = "./0123456789ABCDEFGHIJKLMNOPQRSTUVWXYZ" \
    "abcdefghijklmnopqrstuvwxyz"
_ITOA64_INV = {c: i for i, c in enumerate(ITOA64)}

#: password length cap so digest(16) + password stays one MD5 block
MAX_PASS_LEN = 55 - 16


def encode64(data: bytes) -> str:
    """phpass itoa64 encoding: 3-byte little-endian groups -> 4 chars,
    6 bits each, LSB first (matches PHP's encode64)."""
    out = []
    i = 0
    while i < len(data):
        value = data[i]
        i += 1
        out.append(ITOA64[value & 0x3F])
        if i < len(data):
            value |= data[i] << 8
        out.append(ITOA64[(value >> 6) & 0x3F])
        if i >= len(data):
            break
        i += 1
        if i < len(data):
            value |= data[i] << 16
        out.append(ITOA64[(value >> 12) & 0x3F])
        if i >= len(data):
            break
        i += 1
        out.append(ITOA64[(value >> 18) & 0x3F])
    return "".join(out)


def decode64(text: str, n_bytes: int) -> bytes:
    """Inverse of encode64 for a known byte count."""
    out = bytearray()
    i = 0
    while len(out) < n_bytes:
        chunk = text[i:i + 4]
        i += 4
        value = 0
        for j, c in enumerate(chunk):
            if c not in _ITOA64_INV:
                raise ValueError(f"bad itoa64 char {c!r}")
            value |= _ITOA64_INV[c] << (6 * j)
        out.append(value & 0xFF)
        if len(out) < n_bytes and len(chunk) > 2:
            out.append((value >> 8) & 0xFF)
        if len(out) < n_bytes and len(chunk) > 3:
            out.append((value >> 16) & 0xFF)
    return bytes(out)


def parse_phpass(text: str):
    """'$P$Bsalt8chr...' -> (count, salt bytes, digest bytes)."""
    text = text.strip()
    if len(text) != 34 or text[:3] not in ("$P$", "$H$"):
        raise ValueError(f"not a phpass hash: {text!r}")
    log2count = _ITOA64_INV.get(text[3])
    if log2count is None or not 7 <= log2count <= 30:
        raise ValueError(f"bad phpass cost char {text[3]!r}")
    salt = text[4:12].encode("latin-1")
    digest = decode64(text[12:34], 16)
    return 1 << log2count, salt, digest


def phpass_raw(password: bytes, salt: bytes, count: int) -> bytes:
    h = hashlib.md5(salt + password).digest()
    for _ in range(count):
        h = hashlib.md5(h + password).digest()
    return h


def phpass_hash(password: bytes, salt: bytes, log2count: int,
                tag: str = "$P$") -> str:
    """Full crypt string (test helper)."""
    digest = phpass_raw(password, salt, 1 << log2count)
    return (tag + ITOA64[log2count] + salt.decode("latin-1")
            + encode64(digest))
