"""Pure-Python bcrypt (EksBlowfish), written from the Provos & Mazieres
"A Future-Adaptable Password Scheme" construction.

The Blowfish initial state comes from tools/gen_blowfish_constants.py
(hex digits of pi via the BBP series), so no published table was copied.
Validated against the classic John-the-Ripper/OpenBSD test vectors in
tests/test_cpu_engines.py.

This oracle is slow by nature (pure Python); use low cost factors in
tests.  The throughput path is the JAX engine in engines/device.
"""

from __future__ import annotations

import re
import struct

from dprf_tpu.engines.cpu._blowfish_tables import P_INIT, S_INIT

_MASK = 0xFFFFFFFF
_MAGIC = b"OrpheanBeholderScryDoubt"   # 3 x 64-bit ECB blocks
_B64_ALPHABET = "./ABCDEFGHIJKLMNOPQRSTUVWXYZabcdefghijklmnopqrstuvwxyz0123456789"
_B64_INDEX = {c: i for i, c in enumerate(_B64_ALPHABET)}
# Only variants with $2a/$2b key semantics (NUL-terminated key, unsigned
# bytes): $2$ and $2x$ differ in key handling and would silently produce
# false negatives, so they are rejected at parse time.
_HASH_RE = re.compile(r"^\$(2[aby])\$(\d{2})\$([./A-Za-z0-9]{22})([./A-Za-z0-9]{31})$")


class _Blowfish:
    __slots__ = ("p", "s")

    def __init__(self):
        self.p = list(P_INIT)
        self.s = [list(box) for box in S_INIT]

    def _encrypt(self, left: int, right: int) -> tuple:
        p = self.p
        s0, s1, s2, s3 = self.s
        for i in range(0, 16, 2):
            left ^= p[i]
            right ^= (((s0[left >> 24] + s1[(left >> 16) & 0xFF]) & _MASK
                       ^ s2[(left >> 8) & 0xFF]) + s3[left & 0xFF]) & _MASK
            right ^= p[i + 1]
            left ^= (((s0[right >> 24] + s1[(right >> 16) & 0xFF]) & _MASK
                      ^ s2[(right >> 8) & 0xFF]) + s3[right & 0xFF]) & _MASK
        return right ^ self.p[17], left ^ self.p[16]

    def expand_key(self, key: bytes, salt_words=None) -> None:
        # XOR the cyclically-extended key (big-endian 32-bit reads over the
        # byte stream) into the P-array, then regenerate P and S by chained
        # encryption; with a salt, successive encryptions are XOR-perturbed
        # by the alternating 64-bit salt halves.
        klen = len(key)
        j = 0
        for i in range(18):
            word = 0
            for _ in range(4):
                word = ((word << 8) | key[j]) & _MASK
                j = (j + 1) % klen
            self.p[i] ^= word

        left = right = 0
        n = 0
        for i in range(0, 18, 2):
            if salt_words is not None:
                left ^= salt_words[(2 * n) % 4]
                right ^= salt_words[(2 * n + 1) % 4]
            left, right = self._encrypt(left, right)
            n += 1
            self.p[i], self.p[i + 1] = left, right
        for box in self.s:
            for i in range(0, 256, 2):
                if salt_words is not None:
                    left ^= salt_words[(2 * n) % 4]
                    right ^= salt_words[(2 * n + 1) % 4]
                left, right = self._encrypt(left, right)
                n += 1
                box[i], box[i + 1] = left, right


def _eks_setup(password: bytes, salt: bytes, cost: int) -> _Blowfish:
    if not 4 <= cost <= 31:
        raise ValueError(f"bcrypt cost out of range: {cost}")
    if len(salt) != 16:
        raise ValueError("bcrypt salt must be 16 bytes")
    # $2a/$2b semantics: NUL-terminate, then truncate to 72 bytes.
    key = (password + b"\x00")[:72]
    salt_words = struct.unpack(">4I", salt)
    bf = _Blowfish()
    bf.expand_key(key, salt_words)
    for _ in range(1 << cost):
        bf.expand_key(key)
        bf.expand_key(salt)
    return bf


def bcrypt_raw(password: bytes, salt: bytes, cost: int) -> bytes:
    """23-byte bcrypt digest (the 24th ciphertext byte is discarded)."""
    bf = _eks_setup(password, salt, cost)
    words = list(struct.unpack(">6I", _MAGIC))
    for b in range(0, 6, 2):
        left, right = words[b], words[b + 1]
        for _ in range(64):
            left, right = bf._encrypt(left, right)
        words[b], words[b + 1] = left, right
    return struct.pack(">6I", *words)[:23]


def b64_encode(data: bytes) -> str:
    out = []
    for i in range(0, len(data), 3):
        chunk = data[i:i + 3]
        acc = int.from_bytes(chunk, "big") << (8 * (3 - len(chunk)))
        for k in range(len(chunk) + 1):
            out.append(_B64_ALPHABET[(acc >> (18 - 6 * k)) & 0x3F])
    return "".join(out)


def b64_decode(text: str, nbytes: int) -> bytes:
    acc = 0
    for c in text:
        acc = (acc << 6) | _B64_INDEX[c]
    acc >>= (6 * len(text)) - 8 * nbytes
    return acc.to_bytes(nbytes, "big")


def parse_hash(text: str) -> tuple:
    """'$2b$12$<salt22><hash31>' -> (variant, cost, salt16, digest23)."""
    m = _HASH_RE.match(text.strip())
    if not m:
        raise ValueError(f"not a bcrypt hash: {text!r}")
    variant, cost, salt_s, hash_s = m.groups()
    return variant, int(cost), b64_decode(salt_s, 16), b64_decode(hash_s, 23)


def bcrypt_hash(password: bytes, salt: bytes, cost: int,
                variant: str = "2b") -> str:
    digest = bcrypt_raw(password, salt, cost)
    return f"${variant}${cost:02d}${b64_encode(salt)[:22]}{b64_encode(digest)[:31]}"
