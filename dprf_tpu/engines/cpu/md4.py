"""Pure-Python MD4 (RFC 1320).

hashlib's OpenSSL backend no longer ships md4, but NTLM is MD4 over the
UTF-16LE password, so the oracle needs its own implementation.  Written
directly from the RFC's round structure; validated against the RFC 1320
appendix test vectors in tests/test_cpu_engines.py.
"""

from __future__ import annotations

import struct

_MASK = 0xFFFFFFFF

# Per-round message-word orders and rotation schedules (RFC 1320 section 3.4).
_R1_ORDER = tuple(range(16))
_R2_ORDER = (0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15)
_R3_ORDER = (0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15)
_R1_SHIFTS = (3, 7, 11, 19)
_R2_SHIFTS = (3, 5, 9, 13)
_R3_SHIFTS = (3, 9, 11, 15)


def _rotl(x: int, n: int) -> int:
    return ((x << n) | (x >> (32 - n))) & _MASK


def _compress(state: tuple, block: bytes) -> tuple:
    x = struct.unpack("<16I", block)
    a, b, c, d = state

    for i, k in enumerate(_R1_ORDER):
        f = (b & c) | (~b & d)
        a = _rotl((a + f + x[k]) & _MASK, _R1_SHIFTS[i % 4])
        a, b, c, d = d, a, b, c
    for i, k in enumerate(_R2_ORDER):
        g = (b & c) | (b & d) | (c & d)
        a = _rotl((a + g + x[k] + 0x5A827999) & _MASK, _R2_SHIFTS[i % 4])
        a, b, c, d = d, a, b, c
    for i, k in enumerate(_R3_ORDER):
        h = b ^ c ^ d
        a = _rotl((a + h + x[k] + 0x6ED9EBA1) & _MASK, _R3_SHIFTS[i % 4])
        a, b, c, d = d, a, b, c

    return ((state[0] + a) & _MASK, (state[1] + b) & _MASK,
            (state[2] + c) & _MASK, (state[3] + d) & _MASK)


def md4(data: bytes) -> bytes:
    state = (0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476)
    msg = data + b"\x80"
    msg += b"\x00" * ((56 - len(msg)) % 64)
    msg += struct.pack("<Q", (len(data) * 8) & 0xFFFFFFFFFFFFFFFF)
    for off in range(0, len(msg), 64):
        state = _compress(state, msg[off:off + 64])
    return struct.pack("<4I", *state)


def md4_hex(data: bytes) -> str:
    return md4(data).hex()
