"""md5crypt ($1$ modular crypt; hashcat 500) reference implementation.

The classic FreeBSD-derived scheme: an "alternate" digest
md5(pw+salt+pw), a bit-walked initial context, then 1000 rounds whose
message composition cycles with i mod 2/3/7.  Digest bytes are emitted
in the scheme's permuted base64 order; decoding recovers the raw
16-byte digest so engines can compare in digest space.
"""

from __future__ import annotations

import hashlib

from dprf_tpu.engines.cpu.phpass import ITOA64, decode64, encode64

#: device path packs pw+salt+pw and the round messages in one MD5
#: block; pw <= 15 with salt <= 8 keeps every message <= 55 bytes
MAX_SALT_LEN = 8

#: byte order in which md5crypt emits the digest through itoa64.
#: crypt feeds to64 24-bit groups (d[a]<<16 | d[b]<<8 | d[c]) over the
#: index triplets (0,6,12)(1,7,13)(2,8,14)(3,9,15)(4,10,5) + d[11];
#: our shared encode64 packs groups little-endian, so each triplet is
#: listed reversed here.
_PERM = [12, 6, 0, 13, 7, 1, 14, 8, 2, 15, 9, 3, 5, 10, 4, 11]


def md5crypt_raw(password: bytes, salt: bytes,
                 magic: bytes = b"$1$") -> bytes:
    """The raw (unpermuted) 16-byte md5crypt digest.  `magic` is the
    scheme tag mixed into the initial context -- b"$1$" for FreeBSD
    md5crypt, b"$apr1$" for Apache's apr1 variant (identical scheme
    otherwise)."""
    alt = hashlib.md5(password + salt + password).digest()
    ctx = password + magic + salt
    # alt CYCLES for passwords longer than one digest (glibc appends it
    # per 16-byte block of the password length)
    ctx += (alt * (len(password) // 16 + 1))[:len(password)]
    i = len(password)
    while i > 0:
        ctx += b"\0" if i & 1 else password[:1]
        i >>= 1
    inter = hashlib.md5(ctx).digest()
    for i in range(1000):
        msg = password if i & 1 else inter
        if i % 3:
            msg += salt
        if i % 7:
            msg += password
        msg += inter if i & 1 else password
        inter = hashlib.md5(msg).digest()
    return inter


def encode_digest(digest: bytes) -> str:
    """Raw digest -> the 22-char itoa64 text of a $1$ line."""
    return encode64(bytes(digest[p] for p in _PERM))


def decode_digest(text: str) -> bytes:
    """22-char itoa64 text -> raw 16-byte digest."""
    permuted = decode64(text, 16)
    out = bytearray(16)
    for where, src in zip(_PERM, permuted):
        out[where] = src
    return bytes(out)


def parse_md5crypt(text: str, prefix: str = "$1$"):
    """'$1$salt$hash' (or '$apr1$salt$hash') -> (salt, raw digest)."""
    t = text.strip()
    if not t.startswith(prefix):
        raise ValueError(f"not a {prefix} hash: {text!r}")
    rest = t[len(prefix):]
    salt_text, sep, digest_text = rest.partition("$")
    if not sep or len(digest_text) != 22:
        raise ValueError(f"malformed md5crypt hash: {text!r}")
    salt = salt_text.encode("latin-1")
    if len(salt) > MAX_SALT_LEN:
        raise ValueError(f"md5crypt salt longer than {MAX_SALT_LEN}: "
                         f"{text!r}")
    return salt, decode_digest(digest_text)


def md5crypt_hash(password: bytes, salt: bytes) -> str:
    """Full '$1$salt$...' string (test helper)."""
    return ("$1$" + salt.decode("latin-1") + "$"
            + encode_digest(md5crypt_raw(password, salt)))
