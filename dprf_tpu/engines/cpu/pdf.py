"""PDF standard-security-handler engines (hashcat 10400 / 10500).

The classic PDF encryption user-password check (PDF 1.1-1.6, RC4):

  key = MD5( pad32(password) || O || P_le32 || ID
             [|| 0xFFFFFFFF if R >= 4 and metadata unencrypted] )
  R2 (40-bit):   key = digest[:5];   U = RC4(key, PAD)
  R3+ (128-bit): 50 x digest = MD5(digest[:n]); key = digest[:n]
                 U = RC4(key, MD5(PAD || ID)), then 19 more passes
                 with key bytes xored by the pass number; compare
                 the first 16 bytes.

Line format (the hashcat one):
  $pdf$V*R*bits*P*enc_metadata*id_len*id*u_len*u*o_len*o

The oracle recomputes U; `Target.digest` is the stored U prefix that
the comparison uses (32 bytes for R2, 16 for R3+).  Offline note: no
official vector file ships in this image, so tests validate the
forward construction plus round-trips built by this same algorithm;
the algorithm follows the published PDF spec (ISO 32000 7.6.3).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Optional, Sequence

from dprf_tpu.engines import register
from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.engines.cpu.krb5 import rc4

#: the 32-byte password padding string from the PDF spec (7.6.3.3).
PAD = bytes([
    0x28, 0xBF, 0x4E, 0x5E, 0x4E, 0x75, 0x8A, 0x41,
    0x64, 0x00, 0x4E, 0x56, 0xFF, 0xFA, 0x01, 0x08,
    0x2E, 0x2E, 0x00, 0xB6, 0xD0, 0x68, 0x3E, 0x80,
    0x2F, 0x0C, 0xA9, 0xFE, 0x64, 0x53, 0x69, 0x7A])


def pdf_key(password: bytes, o: bytes, p: int, doc_id: bytes,
            rev: int, key_len: int, enc_metadata: bool = True) -> bytes:
    """Algorithm 2: the RC4 file-encryption key for one candidate."""
    msg = (password + PAD)[:32] + o[:32] + \
        struct.pack("<i", p) + doc_id
    if rev >= 4 and not enc_metadata:
        msg += b"\xff\xff\xff\xff"
    digest = hashlib.md5(msg).digest()
    if rev >= 3:
        for _ in range(50):
            digest = hashlib.md5(digest[:key_len]).digest()
    return digest[:key_len]


def pdf_user_check(password: bytes, o: bytes, p: int, doc_id: bytes,
                   rev: int, key_len: int,
                   enc_metadata: bool = True) -> bytes:
    """Algorithms 4/5: the recomputed U value (32 bytes R2, 16 R3+)."""
    key = pdf_key(password, o, p, doc_id, rev, key_len, enc_metadata)
    if rev == 2:
        return rc4(key, PAD)
    u = rc4(key, hashlib.md5(PAD + doc_id).digest())
    for i in range(1, 20):
        u = rc4(bytes(b ^ i for b in key), u)
    return u


def parse_pdf(text: str) -> dict:
    """hashcat $pdf$ line -> params dict."""
    t = text.strip()
    if not t.startswith("$pdf$"):
        raise ValueError(f"not a $pdf$ line: {text[:40]!r}")
    f = t[len("$pdf$"):].split("*")
    if len(f) < 10:
        raise ValueError(f"malformed $pdf$ line ({len(f)} fields)")
    ver, rev, bits, p = int(f[0]), int(f[1]), int(f[2]), int(f[3])
    enc_metadata = f[4] not in ("0", "false")
    id_len, doc_id = int(f[5]), bytes.fromhex(f[6])
    u_len, u = int(f[7]), bytes.fromhex(f[8])
    o_len, o = int(f[9]), bytes.fromhex(f[10]) if len(f) > 10 else b""
    if len(doc_id) != id_len or len(u) != u_len or len(o) != o_len:
        raise ValueError("field length mismatch in $pdf$ line")
    if rev not in (2, 3, 4):
        raise ValueError(f"unsupported $pdf$ revision {rev} (R2-R4 "
                         "RC4 only; R5/R6 are SHA-based AES)")
    if bits not in (40, 128):
        raise ValueError(f"unsupported key size {bits}")
    if rev == 2 and bits != 40:
        raise ValueError("R2 implies 40-bit keys (spec 7.6.3.2)")
    if len(o) != 32 or len(u) < 16:
        raise ValueError("$pdf$ O must be 32 bytes, U at least 16")
    return {"ver": ver, "rev": rev, "key_len": bits // 8, "p": p,
            "enc_metadata": enc_metadata, "id": doc_id, "u": u,
            "o": o}


@register("pdf")
class PdfEngine(HashEngine):
    """PDF RC4 user-password check (hashcat 10400/10500; revision is
    read per target from the $pdf$ line)."""

    name = "pdf"
    digest_size = 16            # R3+ compare width; R2 targets carry 32
    salted = True
    max_candidate_len = 27      # device NTLM-free, but keep one cap

    def parse_target(self, text: str) -> Target:
        params = parse_pdf(text)
        width = 32 if params["rev"] == 2 else 16
        return Target(raw=text.strip(),
                      digest=params["u"][:width], params=params)

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError("pdf needs target params ($pdf$ fields)")
        width = 32 if params["rev"] == 2 else 16
        return [pdf_user_check(c, params["o"], params["p"],
                               params["id"], params["rev"],
                               params["key_len"],
                               params["enc_metadata"])[:width]
                for c in candidates]
