"""sha256crypt ($5$ modular crypt; hashcat 7400) reference, following
the public crypt(3)/glibc algorithm.  Identical structure to
sha512crypt (see sha512crypt.py) with SHA-256 and its own base64
permutation (10 rotating triplets + a 2-byte tail)."""

from __future__ import annotations

import hashlib

from dprf_tpu.engines.cpu.phpass import decode64, encode64

MAX_SALT_LEN = 16
DEFAULT_ROUNDS = 5000
MIN_ROUNDS, MAX_ROUNDS = 1000, 999999999


def _perm_rows():
    rows = []
    a, b, c = 0, 10, 20
    for _ in range(10):
        rows.append((a, b, c))
        a, b, c = c + 1, a + 1, b + 1
    return rows


#: see sha512crypt._PERM; the final group is (0, d[31], d[30]) -> the
#: little-endian encode64 pair [30, 31]
_PERM = [i for (a, b, c) in _perm_rows() for i in (c, b, a)] + [30, 31]


def sha256crypt_raw(password: bytes, salt: bytes,
                    rounds: int = DEFAULT_ROUNDS) -> bytes:
    sha = lambda d: hashlib.sha256(d).digest()  # noqa: E731
    B = sha(password + salt + password)
    ctx = password + salt
    for i in range(len(password)):
        ctx += B[i % 32:i % 32 + 1]
    cnt = len(password)
    while cnt > 0:
        ctx += B if cnt & 1 else password
        cnt >>= 1
    A = sha(ctx)
    DP = sha(password * len(password))
    P = bytes(DP[i % 32] for i in range(len(password)))
    DS = sha(salt * (16 + A[0]))
    S = bytes(DS[i % 32] for i in range(len(salt)))
    prev = A
    for i in range(rounds):
        msg = P if i & 1 else prev
        if i % 3:
            msg += S
        if i % 7:
            msg += P
        msg += prev if i & 1 else P
        prev = sha(msg)
    return prev


def encode_digest(digest: bytes) -> str:
    return encode64(bytes(digest[p] for p in _PERM))


def decode_digest(text: str) -> bytes:
    permuted = decode64(text, 32)
    out = bytearray(32)
    for where, src in zip(_PERM, permuted):
        out[where] = src
    return bytes(out)


def parse_sha256crypt(text: str):
    t = text.strip()
    if not t.startswith("$5$"):
        raise ValueError(f"not a sha256crypt hash: {text!r}")
    rest = t[3:]
    rounds = DEFAULT_ROUNDS
    if rest.startswith("rounds="):
        spec, sep, rest = rest.partition("$")
        if not sep:
            raise ValueError(f"malformed sha256crypt hash: {text!r}")
        rounds = int(spec[len("rounds="):])
        if not MIN_ROUNDS <= rounds <= MAX_ROUNDS:
            raise ValueError(f"sha256crypt rounds out of range: {rounds}")
    salt_text, sep, digest_text = rest.partition("$")
    if not sep or len(digest_text) != 43:
        raise ValueError(f"malformed sha256crypt hash: {text!r}")
    salt = salt_text.encode("latin-1")[:MAX_SALT_LEN]
    return rounds, salt, decode_digest(digest_text)


def sha256crypt_hash(password: bytes, salt: bytes,
                     rounds: int = DEFAULT_ROUNDS) -> str:
    prefix = "$5$"
    if rounds != DEFAULT_ROUNDS:
        prefix += f"rounds={rounds}$"
    return (prefix + salt.decode("latin-1") + "$"
            + encode_digest(sha256crypt_raw(password, salt, rounds)))
