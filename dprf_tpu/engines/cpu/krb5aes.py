"""Kerberos 5 AES etypes 17/18 engines: TGS-REP, Pre-Auth, AS-REP.

The modern Kerberoasting / AS-REP-roasting modes (hashcat 19600/19700
TGS-REP, 19800/19900 Pre-Auth timestamp, 32100 AS-REP) — AD realms
have been etype-17/18-by-default for years, so a hashcat-class
framework must carry them next to the legacy RC4 modes
(engines/cpu/krb5.py; SURVEY.md §A fixes only the five acceptance
engines, reference citations impossible — empty mount).

RFC 3962 (AES-CTS Kerberos encryption) over the RFC 3961 simplified
profile:

    base  = PBKDF2-HMAC-SHA1(password, salt, 4096, keylen)
    key   = DK(base, "kerberos")            # string-to-key, final step
    Ke    = DK(key, usage_be4 || 0xAA)      # encryption subkey
    Ki    = DK(key, usage_be4 || 0x55)      # integrity subkey
    plain = CBC-CS3-decrypt(Ke, edata2, IV=0)
    valid <=> HMAC-SHA1(Ki, plain)[:12] == checksum

with keylen 16 (etype 17, AES-128) or 32 (etype 18, AES-256), DK the
RFC 3961 derive function (n-fold the constant to 16 bytes, then an
AES-ECB chain under the deriving key), and usage 2 for TGS-REP ticket
encryption, 1 for the AS-REQ PA-ENC-TIMESTAMP, 3 for the AS-REP
enc-part.  Salt = realm || principal exactly as carried in the hash
line (MIT default salt; hashcat does the same).

The oracle computes the full chain; the device path
(engines/device/krb5aes.py) prefilters on the decrypted DER header
and oracle-verifies hits, mirroring the etype-23 design.
"""

from __future__ import annotations

import hashlib
import hmac as _hmac
import math
from typing import Optional, Sequence

from dprf_tpu.engines import register
from dprf_tpu.engines.base import HashEngine, Target
from dprf_tpu.ops.aes import aes_decrypt_block, aes_encrypt_block

#: RFC 3961 key-usage numbers for the three carried modes.
USAGE_PA_TIMESTAMP = 1       # AS-REQ PA-ENC-TIMESTAMP (krb5pa)
USAGE_TGS_REP_TICKET = 2     # TGS-REP ticket enc-part (krb5tgs)
USAGE_AS_REP = 3             # AS-REP enc-part (krb5asrep)

PBKDF2_ITERATIONS = 4096     # MIT/AD default (no s2kparams in lines)

#: ciphertext floor: 16-byte confounder block + at least one more
#: block for the CTS pair.
MIN_EDATA = 32


def nfold(data: bytes, nbytes: int) -> bytes:
    """RFC 3961 n-fold: stretch/compress `data` to `nbytes` with
    13-bit-rotation replication and ones'-complement addition."""
    def rot13(b: bytes, step: int) -> bytes:
        bits = int.from_bytes(b, "big")
        n = 8 * len(b)
        r = (13 * step) % n
        bits = ((bits >> r) | (bits << (n - r))) & ((1 << n) - 1)
        return bits.to_bytes(len(b), "big")

    lcm = len(data) * nbytes // math.gcd(len(data), nbytes)
    buf = b"".join(rot13(data, i) for i in range(lcm // len(data)))
    # ones'-complement add of the nbytes-sized chunks
    mask = (1 << (8 * nbytes)) - 1
    total = 0
    for i in range(0, lcm, nbytes):
        total += int.from_bytes(buf[i:i + nbytes], "big")
    while total >> (8 * nbytes):
        total = (total & mask) + (total >> (8 * nbytes))
    return total.to_bytes(nbytes, "big")


def dk(key: bytes, constant: bytes) -> bytes:
    """RFC 3961 DK for AES (random-to-key = identity): n-fold the
    constant to one block, then chain ECB encryptions under `key`
    until len(key) bytes of derived material exist."""
    block = constant if len(constant) == 16 else nfold(constant, 16)
    out = b""
    while len(out) < len(key):
        block = aes_encrypt_block(key, block)
        out += block
    return out[:len(key)]


def string_to_key(password: bytes, salt: bytes, key_len: int,
                  iterations: int = PBKDF2_ITERATIONS) -> bytes:
    """RFC 3962 string-to-key: PBKDF2 then DK with "kerberos"."""
    base = hashlib.pbkdf2_hmac("sha1", password, salt, iterations,
                               key_len)
    return dk(base, b"kerberos")


def usage_keys(key: bytes, usage: int) -> tuple[bytes, bytes]:
    """(Ke, Ki) for a key-usage number."""
    u = usage.to_bytes(4, "big")
    return dk(key, u + b"\xaa"), dk(key, u + b"\x55")


def cts_decrypt(key: bytes, data: bytes) -> bytes:
    """AES-CBC-CS3 (ciphertext stealing) decrypt with a zero IV —
    RFC 3962's ciphertext layout.  len(data) >= 16; a lone full block
    is plain CBC."""
    n = len(data)
    if n < 16:
        raise ValueError("CTS needs at least one block")
    if n == 16:
        return aes_decrypt_block(key, data)
    full, tail = divmod(n, 16)
    if tail == 0:
        # CS3 swaps the last two (full) blocks even when aligned
        blocks = [data[16 * i:16 * i + 16] for i in range(full)]
        blocks[-1], blocks[-2] = blocks[-2], blocks[-1]
        prev = bytes(16)
        out = b""
        for b in blocks:
            out += bytes(x ^ y for x, y in
                         zip(aes_decrypt_block(key, b), prev))
            prev = b
        return out
    # ragged tail: decrypt C_{n-1} (the LAST sent block, which is the
    # stolen full block) to recover the tail and rebuild C_n
    head = data[:16 * (full - 1)]
    c_last_full = data[16 * (full - 1):16 * full]      # swapped position
    c_tail = data[16 * full:]
    d = aes_decrypt_block(key, c_last_full)
    tail_plain = bytes(x ^ y for x, y in zip(d[:tail], c_tail))
    c_prev_rebuilt = c_tail + d[tail:]
    prev = bytes(16)
    out = b""
    for i in range(full - 1):
        b = head[16 * i:16 * i + 16]
        out += bytes(x ^ y for x, y in
                     zip(aes_decrypt_block(key, b), prev))
        prev = b
    out += bytes(x ^ y for x, y in
                 zip(aes_decrypt_block(key, c_prev_rebuilt), prev))
    return out + tail_plain


def cts_encrypt(key: bytes, plain: bytes) -> bytes:
    """Inverse of cts_decrypt (test/forward-construction helper)."""
    n = len(plain)
    if n < 16:
        raise ValueError("CTS needs at least one block")
    if n == 16:
        return aes_encrypt_block(key, plain)
    full, tail = divmod(n, 16)
    blocks = [plain[16 * i:16 * i + 16] for i in range(full)]
    prev = bytes(16)
    cts = []
    for b in blocks:
        prev = aes_encrypt_block(
            key, bytes(x ^ y for x, y in zip(b, prev)))
        cts.append(prev)
    if tail:
        last = plain[16 * full:] + bytes(16 - tail)
        cn = aes_encrypt_block(
            key, bytes(x ^ y for x, y in zip(last, prev)))
        return (b"".join(cts[:-1]) + cn + cts[-1][:tail])
    cts[-1], cts[-2] = cts[-2], cts[-1]
    return b"".join(cts)


def krb5_aes_checksum(password: bytes, salt: bytes, key_len: int,
                      usage: int, edata: bytes,
                      iterations: int = PBKDF2_ITERATIONS) -> bytes:
    """Recompute the 12-byte HMAC-SHA1-96 tag for one candidate."""
    key = string_to_key(password, salt, key_len, iterations)
    ke, ki = usage_keys(key, usage)
    plain = cts_decrypt(ke, edata)
    return _hmac.new(ki, plain, hashlib.sha1).digest()[:12]


# ---------------------------------------------------------------------------
# hash-line parsing: $krb5tgs$17|18$user$realm$checksum$edata2 and the
# krb5pa / krb5asrep variants (hashcat 19600/19700/19800/19900/32100)

def parse_krb5aes(text: str, tag: str) -> tuple[int, bytes, bytes, bytes]:
    """-> (etype, salt, checksum12, edata2).

    checksum/edata2 are parsed from the RIGHT and user/realm split at
    the last middle '$', so principals containing '$' (AD machine
    accounts like WS01$) parse; realm names cannot contain '$'."""
    text = text.strip()
    for et in ("17", "18"):
        prefix = f"${tag}${et}$"
        if text.startswith(prefix):
            etype = int(et)
            rest = text[len(prefix):]
            break
    else:
        raise ValueError(f"not a ${tag}$17/18 line")
    try:
        middle, chk_hex, edata_hex = rest.rsplit("$", 2)
        user, realm = middle.rsplit("$", 1)
    except ValueError:
        raise ValueError(f"${tag}$: expected user$realm$checksum$"
                         "edata2 fields") from None
    if not user or not realm or "*" in realm or "*" in user:
        # extra starred metadata fields (etype-23 style) would land in
        # the middle and silently corrupt the salt; reject loudly
        raise ValueError(f"${tag}$: malformed user/realm fields "
                         f"{middle!r}")
    checksum = bytes.fromhex(chk_hex)
    edata = bytes.fromhex(edata_hex)
    if len(checksum) != 12:
        raise ValueError(f"${tag}$: checksum must be 12 bytes")
    if len(edata) < MIN_EDATA:
        raise ValueError(f"${tag}$: edata2 shorter than {MIN_EDATA}")
    salt = (realm + user).encode()
    return etype, salt, checksum, edata


class _Krb5AesEngine(HashEngine):
    """Shared RFC 3962 oracle; subclasses fix usage + line tag."""

    digest_size = 12
    salted = True
    max_candidate_len = 55      # one PBKDF2 HMAC key block
    #: PBKDF2 iteration count; no deployed realm ships s2kparams, so
    #: this stays the MIT/AD default -- overridable (tests, dry runs)
    #: exactly like the PMKID engine's attribute
    iterations = PBKDF2_ITERATIONS
    _usage: int = 0
    _tag: str = ""

    def parse_target(self, text: str) -> Target:
        etype, salt, checksum, edata = parse_krb5aes(text, self._tag)
        return Target(raw=text.strip(), digest=checksum,
                      params={"etype": etype, "salt": salt,
                              "checksum": checksum, "edata": edata,
                              "usage": self._usage,
                              "key_len": 16 if etype == 17 else 32})

    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        if not params:
            raise ValueError(f"{self.name} needs target params")
        return [krb5_aes_checksum(c, params["salt"], params["key_len"],
                                  params["usage"], params["edata"],
                                  self.iterations)
                for c in candidates]


@register("krb5tgs17")
@register("krb5tgs18")
@register("krb5tgs-aes")
class Krb5TgsAesEngine(_Krb5AesEngine):
    """TGS-REP etypes 17/18, modern Kerberoasting (hashcat
    19600/19700; the etype field of the line picks the width)."""

    name = "krb5tgs-aes"
    _usage = USAGE_TGS_REP_TICKET
    _tag = "krb5tgs"


@register("krb5pa17")
@register("krb5pa18")
@register("krb5pa")
class Krb5PaAesEngine(_Krb5AesEngine):
    """AS-REQ Pre-Auth timestamp etypes 17/18 (hashcat 19800/19900)."""

    name = "krb5pa"
    _usage = USAGE_PA_TIMESTAMP
    _tag = "krb5pa"


@register("krb5asrep17")
@register("krb5asrep18")
@register("krb5asrep-aes")
class Krb5AsRepAesEngine(_Krb5AesEngine):
    """AS-REP enc-part etypes 17/18 (hashcat 32100)."""

    name = "krb5asrep-aes"
    _usage = USAGE_AS_REP
    _tag = "krb5asrep"
