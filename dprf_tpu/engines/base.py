"""HashEngine plugin interface.

This is the fixed public API named by BASELINE.json's north star ("behind
its existing HashEngine plugin interface"): an engine turns candidate
passwords into digests and checks them against targets.  CPU engines are
the bit-exact oracles; device engines (dprf_tpu.engines.device) implement
the same digests as fused JAX/Pallas programs.
"""

from __future__ import annotations

import abc
import dataclasses
from typing import Any, ClassVar, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Target:
    """A single crack target.

    digest: the binary value a candidate's digest must equal.
    params: per-target parameters needed to *compute* candidate digests
        (salt and cost for bcrypt; essid/macs for WPA2-PMKID).  Empty for
        unsalted fast hashes, where one digest computation serves every
        target in a list (the multi-target path of benchmark config 2).
    """

    raw: str
    digest: bytes
    params: dict = dataclasses.field(default_factory=dict)


class HashEngine(abc.ABC):
    """Algorithm plugin: candidate bytes -> digest -> compare vs targets."""

    name: ClassVar[str]
    digest_size: ClassVar[int]
    #: salted engines need Target.params to hash a candidate, so a digest
    #: must be recomputed per (candidate, target) rather than per candidate.
    salted: ClassVar[bool] = False
    #: longest candidate (in bytes, pre-encoding) the engine accepts.
    max_candidate_len: ClassVar[int] = 55

    def parse_target(self, text: str) -> Target:
        """Parse one hashlist line.  Default: a bare hex digest."""
        text = text.strip()
        digest = bytes.fromhex(text)
        if len(digest) != self.digest_size:
            raise ValueError(
                f"{self.name}: expected {self.digest_size}-byte digest, "
                f"got {len(digest)} bytes from {text!r}")
        return Target(raw=text, digest=digest)

    @abc.abstractmethod
    def hash_batch(self, candidates: Sequence[bytes],
                   params: Optional[dict] = None) -> list[bytes]:
        """Digest a batch of candidate passwords (oracle / CPU path)."""

    def verify(self, candidate: bytes, target: Target) -> bool:
        return self.hash_batch([candidate], params=target.params)[0] == target.digest

    def __repr__(self) -> str:  # pragma: no cover
        return f"<{type(self).__name__} {self.name}>"


class DeviceHashEngine(abc.ABC):
    """Device-side engine: digests computed inside jit on packed blocks.

    The unit of work is a *packed batch*: candidates laid out as fixed-size
    uint32 message blocks (SoA in HBM), produced on device by a
    CandidateGenerator so plaintext never crosses the host boundary.
    """

    name: ClassVar[str]
    digest_size: ClassVar[int]
    #: number of uint32 words of digest output
    digest_words: ClassVar[int]

    @abc.abstractmethod
    def digest_packed(self, blocks: Any, lengths: Any) -> Any:
        """blocks: uint32[batch, words]; lengths: int32[batch] (bytes).

        Returns uint32[batch, digest_words].  Must be jit-traceable.
        """
