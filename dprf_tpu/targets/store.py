"""TargetStore: bulk hashlist ingest for multi-target jobs.

One object owning what `dprf crack --targets-file` and the `jobs
submit` spec key both need from a hashcat-style `hash[:salt]` file:
the parsed/deduped Target list (utils/hashlist.py does the per-line
work), a malformed-line report, and a content fingerprint that is
stable across line order and duplicates -- so a worker host rebuilding
the job from shipped lines (jobs/build.py) can prove it holds the
same target set the submitter hashed.
"""

from __future__ import annotations

import hashlib
import threading
from typing import Optional, Sequence

from dprf_tpu.utils.hashlist import parse_lines

GUARDED_BY = {
    "TargetStore": {"_lock": ("_fingerprint",)},
}


class TargetStore:
    """Parsed target set + ingest report + cached fingerprint."""

    def __init__(self, engine, targets: Sequence, skipped=(),
                 duplicates: int = 0, source: Optional[str] = None):
        self.engine = engine
        self.targets = list(targets)
        self.skipped = list(skipped)     # (line_no, text, error)
        self.duplicates = int(duplicates)
        self.source = source
        self._lock = threading.Lock()
        self._fingerprint: Optional[str] = None

    @classmethod
    def from_lines(cls, engine, lines: Sequence[str],
                   source: Optional[str] = None,
                   log=None) -> "TargetStore":
        hl = parse_lines(engine, lines)
        store = cls(engine, hl.targets, hl.skipped, hl.duplicates,
                    source=source)
        if log is not None:
            for no, _text, err in hl.skipped:
                log.warn("targets file: skipping malformed line",
                         source=source or "<lines>", line=no,
                         error=err)
            log.info("loaded target set", source=source or "<lines>",
                     targets=len(store.targets),
                     duplicates=store.duplicates,
                     malformed=len(store.skipped))
        return store

    @classmethod
    def from_file(cls, engine, path: str, log=None) -> "TargetStore":
        with open(path, encoding="utf-8", errors="replace") as fh:
            lines = fh.readlines()
        return cls.from_lines(engine, lines, source=path, log=log)

    def __len__(self) -> int:
        return len(self.targets)

    def lines(self) -> list:
        """The deduped target lines, ready to ship as a job spec's
        `targets` list (the coordinator re-parses them)."""
        return [t.raw for t in self.targets]

    @property
    def fingerprint(self) -> str:
        """sha256 over the engine name and the SORTED raw target
        lines: line order and dropped duplicates do not change it, a
        different target set always does."""
        with self._lock:
            if self._fingerprint is None:
                h = hashlib.sha256()
                h.update(getattr(self.engine, "name",
                                 "?").encode("utf-8"))
                for raw in sorted(t.raw for t in self.targets):
                    h.update(b"\x00")
                    h.update(raw.encode("utf-8", errors="replace"))
                self._fingerprint = h.hexdigest()
            return self._fingerprint

    def report(self) -> dict:
        """Ingest summary for logs / the jobs-submit reply."""
        return {
            "targets": len(self.targets),
            "duplicates": self.duplicates,
            "malformed": [
                {"line": no, "error": err}
                for no, _text, err in self.skipped],
            "fingerprint": self.fingerprint,
            "source": self.source,
        }
