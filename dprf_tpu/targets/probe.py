"""Device-resident probe tables: O(1)-per-candidate multi-target compare.

The replicated compare path (ops/compare.make_target_table) keeps every
target digest in one sorted device array and runs a searchsorted per
candidate -- right for the 10^3-hash list, but the bulk-recovery
scenario ("here are millions of leaked hashes") needs per-candidate
cost independent of N.  The probe table gets there in two stages:

  1. a blocked Bloom prefilter: one 512-bit block (16 uint32 words)
     per candidate, k double-hashed bit probes derived from the first
     two digest words -- constant work per candidate, sized on the
     host from N and a false-positive budget (DPRF_TARGETS_FP_BUDGET);
  2. the rare prefilter survivors are compacted into a small fixed
     buffer and verified EXACTLY against the sorted digest table --
     the same maybe-then-oracle discipline the krb5 DER prefilter
     uses, so a false positive can never surface as a hit.

Survivor-buffer overflow inflates the reported count past the lane
buffer, which lands in the workers' existing hit_capacity
rescan/redrive machinery; correctness never depends on the filter.

Sizing consults the devstats HBM-headroom plane before building: a
table that will not fit its byte budget degrades to the bloom-only
HOST-VERIFY layout (survivor lanes return to the host, one oracle
hash each) instead of OOMing the device.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Optional, Sequence

import numpy as np
import jax.numpy as jnp

from dprf_tpu.ops import compare as cmp_ops

#: words per Bloom block: 16 x uint32 = 512 bits, one lane-width row --
#: all k probes of a candidate land in the same block, so the gather
#: footprint per candidate is constant regardless of bitmap size
BLOCK_WORDS = 16
BLOCK_BITS = BLOCK_WORDS * 32

#: Knuth multiplicative constant spreading digest word0 over blocks
_GOLDEN = 0x9E3779B1

_MAX_K = 8
#: smallest bitmap a degraded (host-verify) table keeps: 8 KiB
_MIN_BITS = 1 << 16

MODE_DEVICE = "device"
MODE_HOST_VERIFY = "host-verify"


@dataclasses.dataclass(frozen=True)
class ProbeTable:
    """Host-built, device-resident multi-target probe structure."""

    bits: jnp.ndarray        # uint32[n_blocks * BLOCK_WORDS] bitmap
    block_bits: int          # log2(n_blocks); static
    k: int                   # bit probes per digest; static
    #: exact-verify buckets (device mode); None in host-verify mode
    table: Optional[cmp_ops.TargetTable]
    order: np.ndarray        # host: sorted pos -> original target idx
    num_targets: int
    mode: str                # MODE_DEVICE | MODE_HOST_VERIFY
    fp_est: float            # analytic false-positive rate of `bits`
    nbytes: int              # device bytes: bitmap + exact table


def _pow2ceil(x: int) -> int:
    return 1 << max(0, int(x) - 1).bit_length() if x > 1 else 1


def _geometry(n: int, m_bits: int):
    """(k, fp_est) for n keys in an m_bits bitmap."""
    k = int(round(m_bits / n * math.log(2)))
    k = min(max(k, 1), _MAX_K)
    fp_est = (1.0 - math.exp(-k * n / m_bits)) ** k
    return k, fp_est


def byte_budget() -> Optional[int]:
    """Device-byte cap for a probe table, or None when unbounded.
    DPRF_TARGETS_MAX_BYTES wins when set; otherwise a fraction
    (DPRF_TARGETS_HEADROOM_FRAC) of the devstats free-HBM reading.
    Backends without memory stats (CPU) give no signal -> no cap."""
    from dprf_tpu.telemetry import devstats
    from dprf_tpu.utils import env as envreg
    hard = envreg.get_int("DPRF_TARGETS_MAX_BYTES")
    if hard and hard > 0:
        return hard
    free = devstats.bytes_free()
    if free is None:
        return None
    frac = envreg.get_float("DPRF_TARGETS_HEADROOM_FRAC")
    return int(free * min(max(frac, 0.0), 1.0))


def probe_eligible(targets: Sequence, engine=None) -> bool:
    """Should this target list use the probe-table path?  Needs enough
    targets to beat the replicated compare (DPRF_TARGETS_PROBE_MIN),
    uniform unsalted digests, and at least two uint32 words for the
    double-hashed probes."""
    from dprf_tpu.utils import env as envreg
    floor = envreg.get_int("DPRF_TARGETS_PROBE_MIN")
    if floor <= 0 or len(targets) < floor:
        return False
    if engine is not None and getattr(engine, "salted", False):
        return False
    dlen = len(targets[0].digest)
    if dlen < 8 or dlen % 4:
        return False
    return all(len(t.digest) == dlen and not t.params for t in targets)


def bloom_fill(rows: np.ndarray, m_bits: int, k: int) -> np.ndarray:
    """uint32[N, W>=2] digest words -> the blocked-Bloom bitmap as
    uint32[m_bits // 32].  This is the ONE definition of the bit
    layout: one 512-bit block per key picked by a multiplicative hash
    of word0, then k double-hashed probes inside the block.  Both the
    XLA-path ProbeTable and the Pallas in-kernel probe rows are filled
    through here, so the host builder and the kernel can never drift
    on which bit means what."""
    W = rows.shape[1]
    h1 = rows[:, 0].astype(np.uint64)
    h2 = (rows[:, 1].astype(np.uint64) | 1)
    # probes alternate between TWO independent double-hash pairs
    # (words 0/1 and words 2/3): inside one 512-bit block a single
    # pair carries only ~17 bits of entropy, so a lone progression
    # floors the false-positive rate near n_keys * 2^-17 no matter
    # how many probes run; requiring both pairs to collide squares
    # that floor away (every fast-hash digest has >= 4 words).
    h3 = rows[:, 2].astype(np.uint64) if W > 3 else h1
    h4 = (rows[:, 3].astype(np.uint64) | 1) if W > 3 else h2
    n_blocks = m_bits // BLOCK_BITS
    block_bits = n_blocks.bit_length() - 1
    if block_bits:
        block = ((h1 * _GOLDEN) & 0xFFFFFFFF) >> np.uint64(
            32 - block_bits)
    else:
        block = np.zeros(len(rows), dtype=np.uint64)
    words = np.zeros(m_bits // 32, dtype=np.uint32)
    for j in range(k):
        i = j >> 1
        a, b = (h3, h4) if j & 1 else (h1, h2)
        g = (a + (2 * i + 1) * b) & 0xFFFFFFFF
        bit = g & (BLOCK_BITS - 1)
        w = (block * BLOCK_WORDS + (bit >> np.uint64(5))).astype(np.int64)
        np.bitwise_or.at(
            words, w,
            np.uint32(1) << (bit & np.uint64(31)).astype(np.uint32))
    return words


def kernel_bloom_geometry(n: int, fp: float, max_bits: int):
    """(m_bits, k, fp_est) for an in-kernel probe bitmap: sized for the
    fp budget like build_probe_table, but capped at ``max_bits`` (the
    kernel gathers its block via a bounded per-group select tree, so
    the bitmap must stay VMEM-small -- the fp estimate reports what the
    cap actually buys)."""
    fp = min(max(fp, 1e-9), 0.5)
    m_bits = max(BLOCK_BITS, _pow2ceil(int(math.ceil(
        -n * math.log(fp) / (math.log(2) ** 2)))))
    m_bits = min(m_bits, _pow2ceil(max_bits))
    k, fp_est = _geometry(n, m_bits)
    return m_bits, k, fp_est


def build_probe_table(digests: Sequence[bytes],
                      little_endian: bool = True,
                      fp_budget: Optional[float] = None,
                      max_bytes: Optional[int] = None,
                      log=None) -> ProbeTable:
    """N raw digests -> a ProbeTable sized for the fp budget and the
    device byte budget (see module docstring for the degrade rule)."""
    from dprf_tpu.utils import env as envreg
    n = len(digests)
    if n == 0:
        raise ValueError("empty target list")
    dlen = len(digests[0])
    if dlen < 8 or dlen % 4:
        raise ValueError(
            "probe tables need digests of >= 2 whole uint32 words")
    if any(len(d) != dlen for d in digests):
        raise ValueError("inconsistent digest sizes in target list")
    fp = fp_budget if fp_budget is not None else \
        envreg.get_float("DPRF_TARGETS_FP_BUDGET")
    fp = min(max(fp, 1e-9), 0.5)
    m_bits = max(BLOCK_BITS, _pow2ceil(int(math.ceil(
        -n * math.log(fp) / (math.log(2) ** 2)))))
    budget = max_bytes if max_bytes is not None else byte_budget()
    exact_bytes = n * dlen + n * 4       # words[T,W] + first[T]
    mode = MODE_DEVICE
    if budget is not None and m_bits // 8 + exact_bytes > budget:
        # the exact table is what dominates at 10^7 targets; shed it
        # and shrink the bitmap until it fits -- never OOM the device
        mode = MODE_HOST_VERIFY
        while m_bits > _MIN_BITS and m_bits // 8 > budget:
            m_bits //= 2
    k, fp_est = _geometry(n, m_bits)

    rows = np.frombuffer(
        b"".join(digests),
        dtype="<u4" if little_endian else ">u4").reshape(n, dlen // 4)
    words = bloom_fill(rows, m_bits, k)
    block_bits = (m_bits // BLOCK_BITS).bit_length() - 1

    table = None
    order = np.arange(n, dtype=np.int64)
    if mode == MODE_DEVICE:
        table = cmp_ops.make_target_table(
            list(digests), little_endian=little_endian)
        order = table.order
    nbytes = words.nbytes + (exact_bytes if table is not None else 0)
    if log is not None:
        log.info("built probe table", targets=n, mode=mode,
                 bits=m_bits, k=k, fp=round(fp_est, 8),
                 mbytes=round(nbytes / 1e6, 3))
    return ProbeTable(bits=jnp.asarray(words), block_bits=block_bits,
                      k=k, table=table, order=order, num_targets=n,
                      mode=mode, fp_est=fp_est, nbytes=nbytes)


def bloom_maybe(digest: jnp.ndarray, pt: ProbeTable) -> jnp.ndarray:
    """uint32[B, W] candidate digests -> bool[B] "possibly a target".

    Per candidate: one multiplicative block pick from word0, then k
    double-hashed bit tests inside that single 512-bit block -- the
    whole prefilter is a constant number of ops in N."""
    W = digest.shape[1]
    h1 = digest[:, 0]
    h2 = digest[:, 1] | jnp.uint32(1)
    # the alternating probe pairs of bloom_fill (the ONE bit layout)
    h3 = digest[:, 2] if W > 3 else h1
    h4 = (digest[:, 3] | jnp.uint32(1)) if W > 3 else h2
    if pt.block_bits:
        base = ((h1 * jnp.uint32(_GOLDEN))
                >> (32 - pt.block_bits)).astype(jnp.int32) * BLOCK_WORDS
    else:
        base = jnp.zeros(digest.shape[0], jnp.int32)
    maybe = jnp.ones(digest.shape[0], dtype=bool)
    for j in range(pt.k):
        i = j >> 1
        a, b = (h3, h4) if j & 1 else (h1, h2)
        g = a + jnp.uint32(2 * i + 1) * b
        bit = g & jnp.uint32(BLOCK_BITS - 1)
        w = base + (bit >> 5).astype(jnp.int32)
        mask = jnp.left_shift(jnp.uint32(1), bit & jnp.uint32(31))
        maybe = maybe & ((pt.bits[w] & mask) != 0)
    return maybe


def survivor_cap(pt: ProbeTable, batch: int) -> int:
    """Fixed survivor-buffer length for a batch-lane step: ~4x the
    expected false-positive count plus slack for real hits, clamped to
    [64, 8192]; DPRF_TARGETS_SURVIVOR_CAP overrides."""
    from dprf_tpu.utils import env as envreg
    fixed = envreg.get_int("DPRF_TARGETS_SURVIVOR_CAP")
    if fixed and fixed > 0:
        return fixed
    want = int(4 * batch * pt.fp_est) + 64
    return min(max(_pow2ceil(want), 64), 8192)


def probe_hits(digest: jnp.ndarray, pt: ProbeTable,
               valid: jnp.ndarray, hit_capacity: int,
               survivors: int):
    """Digests -> the workers' (count, lanes, tpos) hit-buffer shape.

    Device mode: Bloom survivors compact into a `survivors`-slot
    buffer, their digests are re-gathered and verified exactly against
    the sorted table, and true hits compact into the hit_capacity
    buffer.  A survivor overflow (n_maybe > survivors) could hide a
    real hit, so the count is inflated past the lane buffer and the
    callers' existing overflow rescan/redrive path re-covers the
    window exactly.

    Host-verify mode (no exact table on device): the lane buffer IS
    the survivor buffer (tpos all -1) and count is the survivor count;
    the worker verifies each lane with one oracle hash.  Overflow
    falls out of the same count > capacity comparison."""
    nlanes = digest.shape[0]
    lane = jnp.arange(nlanes, dtype=jnp.int32)
    maybe = bloom_maybe(digest, pt) & valid
    n_maybe = maybe.sum(dtype=jnp.int32)
    slot = jnp.cumsum(maybe.astype(jnp.int32)) - 1
    slot = jnp.where(maybe, slot, survivors)
    surv = jnp.full((survivors,), -1, jnp.int32).at[slot].set(
        lane, mode="drop")
    if pt.table is None:
        return n_maybe, surv, jnp.full((survivors,), -1, jnp.int32)
    sdig = digest[jnp.maximum(surv, 0)]
    found, tpos = cmp_ops.compare_multi(sdig, pt.table)
    found = found & (surv >= 0)
    count, slots, tpos = cmp_ops.compact_hits(found, tpos, hit_capacity)
    lanes = jnp.where(slots >= 0, surv[jnp.maximum(slots, 0)],
                      jnp.int32(-1))
    count = jnp.where(n_maybe <= survivors, count,
                      jnp.int32(hit_capacity) + n_maybe)
    return count, lanes, tpos
