"""Multi-target probe tables: bulk (10^6-10^7 hash) recovery support.

`probe` builds the device-resident Bloom-prefilter + exact-verify
structure the mask workers swap in when the target count crosses
DPRF_TARGETS_PROBE_MIN; `store` is the hashlist ingest layer behind
`dprf crack --targets-file` and the jobs-submit spec key.
"""

from dprf_tpu.targets.probe import (MODE_DEVICE, MODE_HOST_VERIFY,
                                    ProbeTable, bloom_maybe,
                                    build_probe_table, byte_budget,
                                    probe_eligible, probe_hits,
                                    survivor_cap)
from dprf_tpu.targets.store import TargetStore

__all__ = [
    "MODE_DEVICE", "MODE_HOST_VERIFY", "ProbeTable", "TargetStore",
    "bloom_maybe", "build_probe_table", "byte_budget",
    "probe_eligible", "probe_hits", "survivor_cap",
]
