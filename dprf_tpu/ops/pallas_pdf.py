"""Pallas PDF user-password kernel: vector-rate RC4 cascade.

The XLA PDF R3 check measured 3.2 kH/s on chip (BASELINE.md iterated
table): its 20 RC4 passes per candidate each lower the KSA's
data-dependent swaps to per-lane SERIAL gathers — the bcrypt/krb5
failure mode, 20x over.  This kernel applies the proven krb5 RC4
layout (ops/pallas_krb5.py, measured 23x its XLA step) to the whole
Algorithm-4/5 check:

- candidates on the SUBLANE axis, every working value an (SUBC, 128)
  lane-replicated tile;
- each candidate's 256-entry RC4 S state is two (SUBC, 128) uint32
  halves with the ENTRY INDEX along lanes, so S[j] is the hardware's
  per-sublane `take_along_axis` gather and swap writes are lane-iota
  selects — no scatter (ops/pallas_mask.gather256/swap256, shared);
- the whole chain runs in one kernel with zero HBM round-trips:
  mask decode -> Algorithm-2 MD5 (block 1 = padded password + O,
  block 2 target-constant) -> the 50-fold MD5 stretch (R3+) -> the
  RC4 cascade (R2: one KSA + 4 keystream bytes; R3+: 20 passes of
  KSA + 16-byte PRGA over U', key XOR pass-index per RFC/hashcat
  10500) -> exact compare;
- the spec PAD fill of block 1 is COMPILE-TIME wiring (mask attacks
  have one static length), and O / block-2 / MD5(PAD||ID) / stored-U
  words are runtime SMEM scalars, so ONE compiled kernel per
  (mask, rev, key_len) serves every target in a hashlist.

Per-candidate cost at R3/128-bit: 52 MD5 compressions + 20 x (256-step
KSA + 16 PRGA steps) — ~21x the krb5 kernel's RC4 work, so the
expected rate is a few tens of kH/s against the XLA path's 3.2 kH/s.

Spec reference: engines/cpu/pdf.py (Algorithm 2/4/5); device XLA form
engines/device/pdf.py.
"""

from __future__ import annotations


import numpy as np

from dprf_tpu.utils import env as envreg  # noqa: E402 -- stdlib-only
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.engines.cpu.pdf import PAD
from dprf_tpu.ops import pallas_krb5 as _krb5
from dprf_tpu.ops.pallas_mask import (decode_candidate_bytes,
                                      gather256, mask_supported,
                                      segment_tables, swap256)

#: chunks per grid cell (tile = SUBC * CHUNKS candidates).  The PDF
#: body is ~21x heavier than krb5's, so the default tile is smaller
#: to keep single-dispatch time near the tunnel deadline's safe zone.
CHUNKS = envreg.get_int("DPRF_PDF_CHUNKS")

_PAD_BYTES = np.frombuffer(PAD, np.uint8)


def pdf_kernel_eligible(gen, rev: int, key_len: int,
                        on_hardware: bool = False) -> bool:
    """Mask-attack jobs the kernel covers: any mask charset order
    (builtin segments or the Markov/scrambled unbounded mux), password
    no longer than the 32-byte Algorithm-2 pad buffer, the two
    deployed key widths (40-bit R2/R3, 128-bit R3+).

    key_len=5 is GATED OFF on real hardware until re-measured: its
    only recorded Mosaic compile attempt hung the remote helper
    silently and wedged the tunnel (r5; the lax.rem suspect is fixed
    but unproven on chip).  DPRF_PDF_K5_KERNEL=1 re-enables it for the
    measuring session; interpret mode (tests) is always allowed."""
    if key_len == 5 and on_hardware and \
            not envreg.get_bool("DPRF_PDF_K5_KERNEL"):
        return False
    return (hasattr(gen, "charsets") and gen.length <= 32
            and mask_supported(gen.charsets)
            and rev >= 2 and key_len in (5, 16))


from dprf_tpu.ops.pallas_mask import (  # noqa: E402 -- shared
    md5_compress_lanes as _compress, md5_init_lanes as _md5_init)


def _block1_words(byts, length: int, o_ref, shape):
    """Algorithm-2 block 1: pad32(password) || O.  Bytes past the
    candidate come from the spec PAD string at static offsets (the
    mask length is compile-time), O words are runtime scalars."""
    words = []
    for w in range(8):
        acc = jnp.zeros(shape, jnp.uint32)
        for q in range(4):
            pos = 4 * w + q
            if pos < length:
                byte = byts[pos]
            else:
                byte = jnp.full(shape,
                                jnp.uint32(int(_PAD_BYTES[pos - length])))
            acc = acc | (byte << jnp.uint32(8 * q))
        words.append(acc)
    for w in range(8):
        words.append(jnp.full(shape, o_ref[w].astype(jnp.uint32)))
    return words


def _stretch50(digest, key_len: int, shape):
    """R3+ Algorithm-2 tail: 50 x MD5 over digest[:key_len]."""
    nw, rem = divmod(key_len, 4)
    keep = jnp.uint32((1 << (8 * rem)) - 1)
    zero = jnp.zeros(shape, jnp.uint32)

    def body(_, d):
        m = [zero] * 16
        for w in range(nw):
            m[w] = d[w]
        if rem:
            m[nw] = (d[nw] & keep) | jnp.uint32(0x80 << (8 * rem))
        else:
            m[nw] = jnp.full(shape, jnp.uint32(0x80))
        m[14] = jnp.full(shape, jnp.uint32(key_len * 8))
        return _compress(_md5_init(shape), m)

    return lax.fori_loop(0, 50, body, digest)


def _key_lanes(digest, key_len: int, shape):
    """Key bytes digest[:key_len] spread along the first key_len
    lanes (the krb5 KSA key layout, gathered by i % key_len)."""
    lane = lax.broadcasted_iota(jnp.int32, shape, 1)
    kb = jnp.zeros(shape, jnp.uint32)
    for t in range(key_len):
        kb = jnp.where(lane == t,
                       (digest[t // 4] >> jnp.uint32(8 * (t % 4)))
                       & jnp.uint32(0xFF), kb)
    return kb


def _rc4_words(kb, key_len: int, pass_val, nwords: int, shape):
    """One full RC4 run: KSA with key bytes (kb lanes) XOR pass_val,
    then the first 4*nwords keystream bytes packed LE.  The KSA is the
    krb5 kernel's fori_loop form (3-array carry — the shape proven to
    lower; the unrolled form SIGABRTs Mosaic, see pallas_krb5.UNROLL).
    """
    lane = lax.broadcasted_iota(jnp.int32, shape, 1)
    S_lo0 = lane.astype(jnp.uint32)
    S_hi0 = S_lo0 + jnp.uint32(128)

    def ksa(i, carry):
        # the key index i % key_len rides the carry as a wrapping
        # counter: key_len = 5 would need a real scalar modulo
        # (lax.rem), an op this toolchain's Mosaic helper is not
        # trusted to lower (the r5 pdf-2 compile hang, tunnel-wedging
        # like TPU_PROBE_LOG_r04 finding 8, pointed here)
        S_lo, S_hi, j, t = carry
        i_rep = jnp.full(shape, i.astype(jnp.uint32))
        si = gather256(S_lo, S_hi, i_rep)
        ki = jnp.take_along_axis(
            kb, jnp.full(shape, t, jnp.int32), axis=1) ^ pass_val
        j = (j + si + ki) & jnp.uint32(255)
        sj = gather256(S_lo, S_hi, j)
        S_lo, S_hi = swap256(S_lo, S_hi, i_rep, sj, lane)
        S_lo, S_hi = swap256(S_lo, S_hi, j, si, lane)
        t = jnp.where(t + 1 == key_len, 0, t + 1)
        return S_lo, S_hi, j, t

    S_lo, S_hi, _, _ = lax.fori_loop(
        0, 256, ksa, (S_lo0, S_hi0, jnp.zeros(shape, jnp.uint32),
                      jnp.int32(0)))

    j = jnp.zeros(shape, jnp.uint32)
    words = []
    word = jnp.zeros(shape, jnp.uint32)
    for t in range(4 * nwords):         # PRGA, static i = t + 1 < 128
        i = t + 1
        si = jnp.broadcast_to(S_lo[:, i:i + 1], shape)
        j = (j + si) & jnp.uint32(255)
        sj = gather256(S_lo, S_hi, j)
        i_rep = jnp.full(shape, jnp.uint32(i))
        S_lo, S_hi = swap256(S_lo, S_hi, i_rep, sj, lane)
        S_lo, S_hi = swap256(S_lo, S_hi, j, si, lane)
        k = gather256(S_lo, S_hi, (si + sj) & jnp.uint32(255))
        word = word | (k << jnp.uint32(8 * (t % 4)))
        if t % 4 == 3:
            words.append(word)
            word = jnp.zeros(shape, jnp.uint32)
    return words


def _build_body(radices, seg_tables, length: int, rev: int,
                key_len: int, sub: int, chunks: int):
    """(pid, base, n_valid, o[8], b2[16], x0[4], u[4]) ->
    (count, hit_index) scalars, hit_index tile-local."""
    tile = sub * chunks

    def body(pid, base, n_valid, o_ref, b2_ref, x0_ref, u_ref):
        shape = (sub, 128)
        row = lax.broadcasted_iota(jnp.int32, shape, 0)

        def chunk(c, acc):
            count, hit = acc
            gidx = pid * tile + c * sub + row
            byts = decode_candidate_bytes(radices, seg_tables, length,
                                          base, gidx)
            b1 = _block1_words(byts, length, o_ref, shape)
            state = _compress(_md5_init(shape), b1)
            b2 = [jnp.full(shape, b2_ref[w].astype(jnp.uint32))
                  for w in range(16)]
            digest = _compress(state, b2)
            if rev >= 3:
                digest = _stretch50(digest, key_len, shape)
            kb = _key_lanes(digest, key_len, shape)
            if rev == 2:
                ks = _rc4_words(kb, key_len, jnp.uint32(0), 1, shape)
                found = ks[0] == jnp.full(shape,
                                          u_ref[0].astype(jnp.uint32))
            else:
                u0 = [jnp.full(shape, x0_ref[w].astype(jnp.uint32))
                      for w in range(4)]

                def cascade(p, u):
                    ks = _rc4_words(kb, key_len,
                                    p.astype(jnp.uint32), 4, shape)
                    return tuple(uw ^ kw for uw, kw in zip(u, ks))

                u = lax.fori_loop(0, 20, cascade, tuple(u0))
                found = jnp.full(shape, True)
                for w in range(4):
                    found = found & (u[w] == jnp.full(
                        shape, u_ref[w].astype(jnp.uint32)))
            found = found & (gidx < n_valid)
            lane0 = lax.broadcasted_iota(jnp.int32, shape, 1) == 0
            found = found & lane0
            count = count + jnp.sum(found.astype(jnp.int32))
            hit = jnp.maximum(
                hit, jnp.max(jnp.where(found, c * sub + row, -1)))
            return count, hit

        return lax.fori_loop(0, chunks, chunk,
                             (jnp.int32(0), jnp.int32(-1)))

    return body


def make_pdf_pallas_fn(gen, batch: int, rev: int, key_len: int,
                       sub: int = 0, chunks: int = 0,
                       interpret: bool = False):
    """fn(base_digits, n_valid[1], o[8], b2[16], x0[4], u[4]) ->
    (counts int32[grid, 1], hit_idx int32[grid, 1]); R2 ignores x0
    and reads only u[0] (pass zeros for the rest).  The sublane count
    defaults to the krb5 kernel's tuned SUBC (module attr, so tests
    patch one place)."""
    sub = sub or _krb5.SUBC
    chunks = chunks or CHUNKS
    tile = sub * chunks
    if batch % tile or batch <= 0:
        raise ValueError(f"batch {batch} must be a multiple of "
                         f"tile {tile}")
    if tile > 0x7FFF:
        raise ValueError(f"tile {tile} exceeds the 15-bit packed "
                         "output limit (lower DPRF_KRB5_SUBC/"
                         "DPRF_PDF_CHUNKS)")
    if not pdf_kernel_eligible(gen, rev, key_len,
                               on_hardware=not interpret):
        raise ValueError("pdf kernel: job not eligible")
    grid = batch // tile
    seg_tables = segment_tables(gen.charsets)
    body = _build_body(gen.radices, seg_tables, gen.length, rev,
                       key_len, sub, chunks)

    def kernel(base_ref, nvalid_ref, o_ref, b2_ref, x0_ref, u_ref,
               out_ref):
        count, hit = body(pl.program_id(0), base_ref, nvalid_ref[0],
                          o_ref, b2_ref, x0_ref, u_ref)
        out_ref[...] = jnp.full((8, 128), (count << 16) | (hit + 1),
                                jnp.int32)

    L = gen.length
    smem = lambda n: pl.BlockSpec((n,), lambda i: (0,),
                                  memory_space=pltpu.SMEM)
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[smem(L), smem(1), smem(8), smem(16), smem(4),
                  smem(4)],
        out_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32)],
        interpret=interpret,
    )

    def fn(base_digits, n_valid, o, b2, x0, u):
        (packed,) = raw(base_digits, n_valid, o, b2, x0, u)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_pdf_crack_step(gen, batch: int, rev: int, key_len: int,
                        hit_capacity: int = 64, sub: int = 0,
                        chunks: int = 0, interpret: bool = False):
    """Kernel crack step with the worker (count, lanes, tpos)
    contract: step(base_digits, n_valid, o, b2, x0, u)."""
    from dprf_tpu.ops.pallas_mask import reduce_tile_hits

    sub = sub or _krb5.SUBC
    chunks = chunks or CHUNKS
    tile = sub * chunks
    fn = make_pdf_pallas_fn(gen, batch, rev, key_len, sub=sub,
                            chunks=chunks, interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid, o, b2, x0, u):
        counts, lanes = fn(base_digits.astype(jnp.int32),
                           jnp.reshape(n_valid, (1,)).astype(jnp.int32),
                           o, b2, x0, u)
        return reduce_tile_hits(counts, lanes, hit_capacity, tile)

    return step


def target_scalars(target) -> tuple:
    """Target.params -> the kernel's four runtime SMEM arrays
    (o[8], b2[16], x0[4], u[4]); R2's u[0] carries the keystream
    expectation U[0:4] ^ PAD[0:4] (stored U = RC4(key, PAD)).

    PAIRED with engines/device/pdf._target_args: both marshal the same
    $pdf$ params (there into the XLA step's argument layout, here into
    flat SMEM scalars) via the shared _block2_words/_PAD_W0 — a format
    change must touch both or the kernel and XLA paths diverge."""
    import hashlib

    from dprf_tpu.engines.device.pdf import _PAD_W0, _block2_words

    p = target.params

    def i32(data: bytes) -> jnp.ndarray:
        return jnp.asarray(np.frombuffer(data, "<u4").view(np.int32))

    o = i32(p["o"])
    b2 = jnp.asarray(_block2_words(p).view(np.int32))
    if p["rev"] == 2:
        x0 = jnp.zeros((4,), jnp.int32)
        w0 = int.from_bytes(p["u"][:4], "little") ^ _PAD_W0
        u = jnp.asarray(np.array([w0, 0, 0, 0], np.uint32)
                        .view(np.int32))
    else:
        x0 = i32(hashlib.md5(PAD + p["id"]).digest())
        u = i32(p["u"][:16])
    return o, b2, x0, u
