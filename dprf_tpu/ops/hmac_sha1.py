"""HMAC-SHA1 and PBKDF2-HMAC-SHA1 as jit-traceable device ops.

The WPA2-PMKID path (benchmark config 5): PMK = PBKDF2-HMAC-SHA1(pass,
essid, 4096, 32), PMKID = HMAC-SHA1(PMK, "PMK Name"|AP|STA)[:16].

Structure exploited on device:

- Passphrases (<= 63 bytes) and PMKs (32 bytes) are shorter than the
  64-byte SHA-1 block, so the HMAC key pad is a single xor -- no key
  hashing.  The two keyed chaining states (inner/outer) are computed
  once per candidate and reused for all 4096 iterations.
- Every PBKDF2 iteration after the first hashes a 20-byte U value, so
  one iteration is exactly two sha1_compress calls on constant-padded
  blocks.  The iteration loop is a `lax.fori_loop` (sequential by
  definition; the batch dimension provides all the parallelism).
- The per-block-index first message (salt || INT(i)) is a host-built
  constant: the salt (essid) is shared by the whole job.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from dprf_tpu.ops.sha1 import INIT as SHA1_INIT, sha1_compress

_IPAD = np.uint32(0x36363636)
_OPAD = np.uint32(0x5C5C5C5C)


def hmac_key_states(key_words: jnp.ndarray):
    """Keyed chaining states from a zero-padded one-block key.

    key_words: uint32[B, 16] big-endian packed key bytes (<= 64), raw
    zero padding (NO 0x80 marker -- the key block is a full block).
    Returns (istate uint32[B, 5], ostate uint32[B, 5]).
    """
    init = jnp.broadcast_to(jnp.asarray(SHA1_INIT),
                            key_words.shape[:-1] + (5,))
    istate = sha1_compress(init, key_words ^ _IPAD)
    ostate = sha1_compress(init, key_words ^ _OPAD)
    return istate, ostate


def _block20(words5: jnp.ndarray) -> jnp.ndarray:
    """Pad a 20-byte (5-word) message into a SHA-1 block that follows a
    64-byte prefix block: 0x80 marker then bit length 64+20 bytes."""
    batch = words5.shape[:-1]
    block = jnp.zeros(batch + (16,), dtype=jnp.uint32)
    block = block.at[..., :5].set(words5)
    block = block.at[..., 5].set(jnp.uint32(0x80000000))
    block = block.at[..., 15].set(jnp.uint32((64 + 20) * 8))
    return block


def hmac_sha1_20(istate: jnp.ndarray, ostate: jnp.ndarray,
                 msg5: jnp.ndarray) -> jnp.ndarray:
    """HMAC-SHA1 of a 20-byte message given keyed states.

    msg5: uint32[B, 5].  Returns uint32[B, 5].  Two compressions.
    """
    inner = sha1_compress(istate, _block20(msg5))
    return sha1_compress(ostate, _block20(inner))


def salt_block(salt: bytes, block_index: int) -> np.ndarray:
    """Host-built constant block for U1's message: salt || INT32BE(i),
    padded as the second block of the inner hash (64-byte key prefix).

    Requires len(salt) <= 51 so salt+4+1 marker+8 length fit one block
    (an ESSID is at most 32 bytes)."""
    msg = salt + int(block_index).to_bytes(4, "big")
    if len(msg) > 55:
        raise ValueError(f"salt too long for one block: {len(salt)} bytes")
    buf = np.zeros(64, dtype=np.uint8)
    buf[:len(msg)] = np.frombuffer(msg, dtype=np.uint8)
    buf[len(msg)] = 0x80
    bitlen = (64 + len(msg)) * 8
    buf[56:] = np.frombuffer(bitlen.to_bytes(8, "big"), dtype=np.uint8)
    return buf.reshape(16, 4).astype(np.uint32) @ \
        np.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32)


def pbkdf2_sha1_block(istate: jnp.ndarray, ostate: jnp.ndarray,
                      salt: bytes, block_index: int,
                      iterations: int) -> jnp.ndarray:
    """One PBKDF2 output block T_i: uint32[B, 5].

    U1 = HMAC(key, salt || INT(i)); U_j = HMAC(key, U_{j-1});
    T_i = U1 ^ ... ^ U_iterations.
    """
    first = jnp.broadcast_to(jnp.asarray(salt_block(salt, block_index)),
                             istate.shape[:-1] + (16,))
    inner = sha1_compress(istate, first)
    u = sha1_compress(ostate, _block20(inner))

    def body(_, carry):
        u, t = carry
        u = hmac_sha1_20(istate, ostate, u)
        return u, t ^ u

    _, t = lax.fori_loop(1, iterations, body, (u, u))
    return t


def pbkdf2_sha1_pmk(key_words: jnp.ndarray, salt: bytes,
                    iterations: int = 4096) -> jnp.ndarray:
    """PBKDF2-HMAC-SHA1 with 32-byte output: uint32[B, 8] (T1 || T2[:3]).

    key_words: uint32[B, 16] zero-padded packed passphrases.
    """
    istate, ostate = hmac_key_states(key_words)
    t1 = pbkdf2_sha1_block(istate, ostate, salt, 1, iterations)
    t2 = pbkdf2_sha1_block(istate, ostate, salt, 2, iterations)
    return jnp.concatenate([t1, t2[..., :3]], axis=-1)


def pmkid_from_pmk(pmk_words: jnp.ndarray, mac_ap: bytes,
                   mac_sta: bytes) -> jnp.ndarray:
    """PMKID = HMAC-SHA1(PMK, "PMK Name" | AP | STA)[:16]: uint32[B, 4].

    The 32-byte PMK is the HMAC key (single xor pad); the 20-byte
    message is a host constant per target.
    """
    batch = pmk_words.shape[:-1]
    key = jnp.zeros(batch + (16,), dtype=jnp.uint32).at[..., :8].set(pmk_words)
    istate, ostate = hmac_key_states(key)
    msg = b"PMK Name" + mac_ap + mac_sta
    assert len(msg) == 20
    msg5 = np.frombuffer(msg, dtype=">u4").astype(np.uint32)
    msg5 = jnp.broadcast_to(jnp.asarray(msg5), batch + (5,))
    return hmac_sha1_20(istate, ostate, msg5)[..., :4]
