"""EksBlowfish / bcrypt as vectorized JAX ops (benchmark config 4).

bcrypt is the deliberately memory-hard, low-throughput path: every
candidate carries 4 KB of *mutating* S-box state, and the key schedule
is a long serial chain of Blowfish encryptions with data-dependent
S-box lookups.  That maps to TPU as:

- state kept as uint32[B, 1024] (4 boxes flat) + uint32[B, 18] P-array
  in HBM/VMEM, one row per candidate lane;
- the serial chains as `lax.fori_loop`s (they cannot be parallelized --
  that is bcrypt's whole design), with the batch dimension providing
  all the parallelism;
- the four S-box reads per Feistel round as one batched gather
  (`take_along_axis` over the flat 1024-entry axis).

The cost parameter is a *runtime* argument (`fori_loop` with a traced
trip count lowers to `while_loop`), so one compiled program serves any
cost and every target of a job.

Initial P/S constants come from engines/cpu/_blowfish_tables.py
(hex digits of pi computed by tools/gen_blowfish_constants.py).
Semantics match the CPU oracle in engines/cpu/bcrypt.py ($2a/$2b:
NUL-terminated key, 72-byte cap) bit-for-bit; tests/test_bcrypt_device.py
checks both the raw digest and the OpenBSD-style hash lines.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from dprf_tpu.engines.cpu._blowfish_tables import P_INIT, S_INIT

P0 = np.array(P_INIT, dtype=np.uint32)                      # [18]
S0 = np.array(S_INIT, dtype=np.uint32).reshape(-1)          # [1024]
# "OrpheanBeholderScryDoubt" -- the fixed bcrypt ECB plaintext, as three
# 64-bit blocks = six big-endian words.
MAGIC_WORDS = np.frombuffer(b"OrpheanBeholderScryDoubt",
                            dtype=">u4").astype(np.uint32)  # [6]
_BOX_OFF = np.array([0, 256, 512, 768], dtype=np.int32)


def _feistel(S: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """F(x) = ((S0[a] + S1[b]) ^ S2[c]) + S3[d], batched.

    S: uint32[B, 1024] (per-candidate flat boxes), x: uint32[B].
    The four per-lane reads are one gather of shape [B, 4].
    """
    idx = jnp.stack([x >> 24, (x >> 16) & 0xFF,
                     (x >> 8) & 0xFF, x & 0xFF], axis=-1).astype(jnp.int32)
    g = jnp.take_along_axis(S, idx + jnp.asarray(_BOX_OFF), axis=1)
    return ((g[:, 0] + g[:, 1]) ^ g[:, 2]) + g[:, 3]


def _encrypt(P: jnp.ndarray, S: jnp.ndarray,
             l: jnp.ndarray, r: jnp.ndarray):
    """One 16-round Blowfish ECB encryption, batched over lanes.

    P: uint32[B, 18] (or [18] broadcastable), S: uint32[B, 1024],
    l, r: uint32[B].  Rounds are unrolled at trace time.
    """
    for i in range(0, 16, 2):
        l = l ^ P[..., i]
        r = r ^ _feistel(S, l)
        r = r ^ P[..., i + 1]
        l = l ^ _feistel(S, r)
    return r ^ P[..., 17], l ^ P[..., 16]


def _salt_xor(i, l, r, salt_words):
    """XOR the alternating 64-bit salt halves into (l, r) for chain step
    i.  The CPU oracle's index pattern salt[(2n)%4], salt[(2n+1)%4]
    reduces to: even n -> words (0,1), odd n -> words (2,3)."""
    even = (i % 2) == 0
    l = l ^ jnp.where(even, salt_words[0], salt_words[2])
    r = r ^ jnp.where(even, salt_words[1], salt_words[3])
    return l, r


def expand_key(P: jnp.ndarray, S: jnp.ndarray, key_words: jnp.ndarray,
               salt_words=None):
    """One EksBlowfish ExpandKey: P ^= key, then regenerate P and S by
    chained encryption (salt-perturbed when salt_words is given).

    P uint32[B, 18], S uint32[B, 1024], key_words uint32[B, 18] or [18].
    Returns the new (P, S).
    """
    P = P ^ key_words
    B = P.shape[0]
    zero = jnp.zeros((B,), jnp.uint32)

    def p_body(i, carry):
        P, l, r = carry
        if salt_words is not None:
            l, r = _salt_xor(i, l, r, salt_words)
        l, r = _encrypt(P, S, l, r)
        P = lax.dynamic_update_slice(
            P, jnp.stack([l, r], axis=1), (0, 2 * i))
        return P, l, r

    P, l, r = lax.fori_loop(0, 9, p_body, (P, zero, zero))

    def s_body(j, carry):
        S, l, r = carry
        if salt_words is not None:
            l, r = _salt_xor(9 + j, l, r, salt_words)
        l, r = _encrypt(P, S, l, r)
        S = lax.dynamic_update_slice(
            S, jnp.stack([l, r], axis=1), (0, 2 * j))
        return S, l, r

    # (l, r) carry over from the P phase -- the chain is continuous.
    S, l, r = lax.fori_loop(0, 512, s_body, (S, l, r))
    return P, S


def key_words_from_candidates(cand: jnp.ndarray,
                              lengths: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, L] candidates + int32[B] lengths -> uint32[B, 18] key
    words: the NUL-terminated password cyclically extended over 72 bytes
    and read as big-endian 32-bit words ($2a/$2b key semantics)."""
    B, L = cand.shape
    klen = lengths + 1                       # password + NUL terminator
    pos = jnp.arange(72, dtype=jnp.int32)[None, :] % klen[:, None]
    byte = jnp.take_along_axis(cand, jnp.minimum(pos, L - 1), axis=1)
    byte = jnp.where(pos < lengths[:, None], byte, 0).astype(jnp.uint32)
    b = byte.reshape(B, 18, 4)
    return (b[..., 0] << 24) | (b[..., 1] << 16) | (b[..., 2] << 8) | b[..., 3]


def salt18_words(salt_words: jnp.ndarray) -> jnp.ndarray:
    """ExpandKey(salt) key words: the 16-byte salt cyclically extended
    over 72 bytes is word-periodic with period 4."""
    return jnp.tile(salt_words, 5)[:18]


def eks_setup_begin(key_words: jnp.ndarray, salt_words: jnp.ndarray):
    """EksBlowfish setup prologue: fresh P/S boxes plus the one
    salt-perturbed ExpandKey(key).  Returns (P, S) ready for the main
    cost loop (`eks_rounds`)."""
    B = key_words.shape[0]
    P = jnp.broadcast_to(jnp.asarray(P0), (B, 18))
    S = jnp.broadcast_to(jnp.asarray(S0), (B, 1024))
    return expand_key(P, S, key_words, salt_words)


def eks_rounds(P: jnp.ndarray, S: jnp.ndarray, key_words: jnp.ndarray,
               salt18: jnp.ndarray, n_rounds: jnp.ndarray):
    """Advance the EksBlowfish main loop by `n_rounds` iterations of
    {ExpandKey(key); ExpandKey(salt)}.  The body is independent of the
    absolute round index, so the 2**cost chain can be split across any
    number of calls with (P, S) carried between them -- the device
    engine uses this to keep each dispatch under a time budget."""

    def body(_, PS):
        P, S = PS
        P, S = expand_key(P, S, key_words)
        P, S = expand_key(P, S, salt18)
        return P, S

    return lax.fori_loop(0, n_rounds, body, (P, S))


def eks_setup(key_words: jnp.ndarray, salt_words: jnp.ndarray,
              n_rounds: jnp.ndarray):
    """Full EksBlowfish setup for a batch of candidates.

    key_words uint32[B, 18], salt_words uint32[4], n_rounds int32 scalar
    (= 2**cost, a runtime value).  Returns the final (P, S) state.
    """
    P, S = eks_setup_begin(key_words, salt_words)
    return eks_rounds(P, S, key_words, salt18_words(salt_words), n_rounds)


def bcrypt_digest_words(P: jnp.ndarray, S: jnp.ndarray) -> jnp.ndarray:
    """Final stage: encrypt the three magic blocks 64 times each.

    Returns uint32[B, 6] big-endian digest words (the 23-byte bcrypt
    digest is words[:5] plus the top 3 bytes of words[5])."""
    B = P.shape[0]
    out = []
    for blk in range(0, 6, 2):
        l = jnp.full((B,), MAGIC_WORDS[blk], jnp.uint32)
        r = jnp.full((B,), MAGIC_WORDS[blk + 1], jnp.uint32)

        def body(_, lr):
            return _encrypt(P, S, lr[0], lr[1])

        l, r = lax.fori_loop(0, 64, body, (l, r))
        out.extend([l, r])
    return jnp.stack(out, axis=1)


def bcrypt_batch(cand: jnp.ndarray, lengths: jnp.ndarray,
                 salt_words: jnp.ndarray,
                 n_rounds: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, L] candidates -> uint32[B, 6] bcrypt digest words."""
    kw = key_words_from_candidates(cand, lengths)
    P, S = eks_setup(kw, salt_words, n_rounds)
    return bcrypt_digest_words(P, S)


# ---------------- host-side target preparation ----------------

def salt_to_words(salt: bytes) -> np.ndarray:
    """16-byte bcrypt salt -> uint32[4] big-endian words."""
    if len(salt) != 16:
        raise ValueError("bcrypt salt must be 16 bytes")
    return np.frombuffer(salt, dtype=">u4").astype(np.uint32)


def digest_to_words(digest: bytes) -> np.ndarray:
    """23-byte bcrypt digest -> uint32[6]; word 5 holds only its top 3
    bytes (low byte zero), matching `compare_digest_words`."""
    if len(digest) != 23:
        raise ValueError("bcrypt digest must be 23 bytes")
    w = np.zeros(6, dtype=np.uint32)
    w[:5] = np.frombuffer(digest[:20], dtype=">u4").astype(np.uint32)
    w[5] = (digest[20] << 24) | (digest[21] << 16) | (digest[22] << 8)
    return w


def compare_digest_words(dwords: jnp.ndarray,
                         target: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, 6] computed words vs uint32[6] target -> bool[B].
    Only 23 of the 24 ciphertext bytes count (the last is discarded by
    the bcrypt format), so word 5 compares its top 24 bits only."""
    head = jnp.all(dwords[:, :5] == target[None, :5], axis=-1)
    tail = (dwords[:, 5] & jnp.uint32(0xFFFFFF00)) == target[5]
    return head & tail


def words_to_digests(dwords: np.ndarray) -> list[bytes]:
    """uint32[B, 6] -> 23-byte digests (host helper for hash_batch)."""
    raw = np.ascontiguousarray(dwords.astype(np.uint32)).astype(">u4")
    return [raw[i].tobytes()[:23] for i in range(raw.shape[0])]
