"""Generic HMAC over the shared 64-byte-block compression cores.

Powers the keyed-digest engine family (SURVEY.md §A fixes the
HashEngine plugin surface; these are the hashcat-class keyed modes on
top of the same cores every other path uses):

- hmac-md5 / hmac-sha1 / hmac-sha256, key = $pass (hashcat 50/150/1450)
  and key = $salt (60/160/1460), line format ``hexdigest:salt``.
- JWT HS256 (hashcat 16500): HMAC-SHA256 over the signing input
  ``b64url(header).b64url(payload)`` -- a per-target host constant that
  may span several blocks.

Device shape: the HMAC key pad is one xor when the key fits one block
(keys here are candidates <= 64 bytes or salts <= 32), so the keyed
chaining states cost two compressions per candidate and every message
block after them is either a runtime-built single block (salt/candidate
message) or a host-built constant chain (JWT signing input).  This is
the same structure ops/hmac_sha1.py exploits for PBKDF2; this module
generalizes it over {md5, sha1, sha256} without touching the SHA-1
specialization the PMKID hot loop uses.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from dprf_tpu.ops.md5 import INIT as MD5_INIT, md5_compress
from dprf_tpu.ops.sha1 import INIT as SHA1_INIT, sha1_compress
from dprf_tpu.ops.sha256 import INIT as SHA256_INIT, sha256_compress
from dprf_tpu.ops.pack import _words_from_bytes

_IPAD = np.uint32(0x36363636)
_OPAD = np.uint32(0x5C5C5C5C)

#: algo -> (compress(state, words16) -> state, init words, state words,
#: big-endian word packing)
ALGOS = {
    "md5": (md5_compress, MD5_INIT, 4, False),
    "sha1": (sha1_compress, SHA1_INIT, 5, True),
    "sha256": (sha256_compress, SHA256_INIT, 8, True),
}


def key_states(algo: str, key_words: jnp.ndarray):
    """Keyed chaining states from zero-padded one-block keys.

    key_words: uint32[..., 16], raw zero padding (no MD marker).
    Returns (istate, ostate) uint32[..., W].
    """
    compress, init, W, _ = ALGOS[algo]
    init_b = jnp.broadcast_to(jnp.asarray(init),
                              key_words.shape[:-1] + (W,))
    return (compress(init_b, key_words ^ _IPAD),
            compress(init_b, key_words ^ _OPAD))


def msg_block_after_prefix(msg: jnp.ndarray, lengths: jnp.ndarray,
                           big_endian: bool) -> jnp.ndarray:
    """Variable-length message bytes -> the MD-padded block that FOLLOWS
    a single 64-byte prefix block (the xored key block): bit count is
    (64 + len) * 8.

    msg: uint8[B, maxlen <= 55]; lengths: int32[B].  Bytes at or beyond
    each lane's length may be garbage -- they are masked here.
    """
    batch, maxlen = msg.shape
    if maxlen > 55:
        raise ValueError("one-block message needs maxlen <= 55")
    pos = jnp.arange(64, dtype=jnp.int32)
    lens = lengths[:, None]
    padded = jnp.zeros((batch, 64), dtype=jnp.uint8).at[:, :maxlen].set(msg)
    buf = jnp.where(pos < lens, padded, 0).astype(jnp.uint8)
    buf = buf + jnp.where(pos == lens, jnp.uint8(0x80), jnp.uint8(0))
    words = _words_from_bytes(buf, big_endian)
    bits = (lengths.astype(jnp.uint32) + 64) * 8
    return words.at[:, 15 if big_endian else 14].set(bits)


def pack_raw_varlen(cand: jnp.ndarray, lengths: jnp.ndarray,
                    big_endian: bool) -> jnp.ndarray:
    """Variable-length HMAC keys -> zero-extended full blocks
    uint32[B, 16] (no MD marker; bytes beyond each length are masked)."""
    batch, maxlen = cand.shape
    if maxlen > 64:
        raise ValueError("key block packing needs maxlen <= 64")
    pos = jnp.arange(64, dtype=jnp.int32)
    padded = jnp.zeros((batch, 64), dtype=jnp.uint8).at[:, :maxlen].set(cand)
    buf = jnp.where(pos < lengths[:, None], padded, 0).astype(jnp.uint8)
    return _words_from_bytes(buf, big_endian)


def digest_tail_block(algo: str, dwords: jnp.ndarray) -> jnp.ndarray:
    """Inner-hash digest -> the outer hash's message block (digest bytes
    after the 64-byte opad block): uint32[..., 16]."""
    _, _, W, big_endian = ALGOS[algo]
    batch = dwords.shape[:-1]
    block = jnp.zeros(batch + (16,), jnp.uint32).at[..., :W].set(dwords)
    marker = jnp.uint32(0x80000000 if big_endian else 0x80)
    block = block.at[..., W].set(marker)
    bits = jnp.uint32((64 + 4 * W) * 8)
    return block.at[..., 15 if big_endian else 14].set(bits)


def hmac_one_block_msg(algo: str, istate: jnp.ndarray, ostate: jnp.ndarray,
                       msg_block: jnp.ndarray) -> jnp.ndarray:
    """HMAC digest when the whole padded message fits one block after
    the key block.  msg_block: uint32[B, 16] or [16] (broadcast)."""
    compress = ALGOS[algo][0]
    if msg_block.ndim == 1:
        msg_block = jnp.broadcast_to(msg_block, istate.shape[:-1] + (16,))
    inner = compress(istate, msg_block)
    return compress(ostate, digest_tail_block(algo, inner))


def hmac_const_msg(algo: str, istate: jnp.ndarray, ostate: jnp.ndarray,
                   blocks: np.ndarray) -> jnp.ndarray:
    """HMAC digest of a host-constant message (pre-padded blocks from
    md_pad_blocks) -- the JWT signing-input shape."""
    compress = ALGOS[algo][0]
    state = istate
    for i in range(blocks.shape[0]):
        blk = jnp.broadcast_to(jnp.asarray(blocks[i]),
                               state.shape[:-1] + (16,))
        state = compress(state, blk)
    return compress(ostate, digest_tail_block(algo, state))


def md_pad_blocks(msg: bytes, big_endian: bool,
                  prefix_bytes: int = 64) -> np.ndarray:
    """Host-side MD padding of a constant message that follows
    `prefix_bytes` of already-hashed input -> uint32[N, 16] blocks."""
    total = prefix_bytes + len(msg)
    buf = bytearray(msg)
    buf.append(0x80)
    while (prefix_bytes + len(buf)) % 64 != 56:
        buf.append(0)
    buf += (total * 8).to_bytes(8, "big" if big_endian else "little")
    arr = np.frombuffer(bytes(buf), dtype=np.uint8).reshape(-1, 16, 4)
    coef = (np.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32)
            if big_endian else
            np.array([1, 1 << 8, 1 << 16, 1 << 24], dtype=np.uint32))
    return (arr.astype(np.uint32) * coef).sum(axis=-1, dtype=np.uint32)
