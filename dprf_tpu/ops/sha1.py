"""SHA-1 compression (FIPS 180-4) as vectorized uint32 jnp ops.

80 unrolled steps; the message schedule is kept as a rolling 16-entry
list so only W[t-3]^W[t-8]^W[t-14]^W[t-16] rotations materialize --
XLA keeps the whole schedule in registers/VMEM per batch tile.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

INIT = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476,
                 0xC3D2E1F0], dtype=np.uint32)
_K = (0x5A827999, 0x6ED9EBA1, 0x8F1BBCDC, 0xCA62C1D6)


def _rotl(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


def sha1_rounds(a, b, c, d, e, m):
    """The 80 SHA-1 steps over any uint32 array shape (no feed-forward).
    m: sequence of 16 message-word arrays.  Shared by the XLA path and
    the Pallas kernel (ops/pallas_mask.py)."""
    w = list(m)
    for t in range(80):
        if t >= 16:
            nw = _rotl(w[(t - 3) % 16] ^ w[(t - 8) % 16]
                       ^ w[(t - 14) % 16] ^ w[t % 16], 1)
            w[t % 16] = nw
        wt = w[t % 16]
        if t < 20:
            f = (b & c) | (~b & d)
        elif t < 40:
            f = b ^ c ^ d
        elif t < 60:
            f = (b & c) | (b & d) | (c & d)
        else:
            f = b ^ c ^ d
        tmp = _rotl(a, 5) + f + e + jnp.uint32(_K[t // 20]) + wt
        a, b, c, d, e = tmp, a, _rotl(b, 30), c, d
    return a, b, c, d, e


def sha1_compress(state: jnp.ndarray, words: jnp.ndarray) -> jnp.ndarray:
    """state uint32[..., 5] x words uint32[..., 16] (big-endian packed)
    -> uint32[..., 5]."""
    a, b, c, d, e = sha1_rounds(*(state[..., i] for i in range(5)),
                                [words[..., i] for i in range(16)])
    # Davies-Meyer feed-forward: add the *input* chaining state (not
    # INIT -- they only coincide on the first block; HMAC chains).
    return jnp.stack([a, b, c, d, e], axis=-1) + state


def sha1_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    state = jnp.broadcast_to(jnp.asarray(INIT), words.shape[:-1] + (5,))
    return sha1_compress(state, words)
