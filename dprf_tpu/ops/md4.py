"""MD4 compression (RFC 1320) as vectorized uint32 jnp ops -- the NTLM
digest core (MD4 over UTF-16LE candidates).  Mirrors the pure-Python
oracle in engines/cpu/md4.py.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

INIT = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476],
                dtype=np.uint32)
_R2_ORDER = (0, 4, 8, 12, 1, 5, 9, 13, 2, 6, 10, 14, 3, 7, 11, 15)
_R3_ORDER = (0, 8, 4, 12, 2, 10, 6, 14, 1, 9, 5, 13, 3, 11, 7, 15)
_SHIFTS = ((3, 7, 11, 19), (3, 5, 9, 13), (3, 9, 11, 15))


def _rotl(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


def md4_rounds(a, b, c, d, m):
    """The 48 MD4 steps over any uint32 array shape (no feed-forward).
    m: sequence of 16 message-word arrays.  Shared by the XLA path and
    the Pallas kernel (ops/pallas_mask.py)."""
    for i in range(16):
        f = (b & c) | (~b & d)
        a = _rotl(a + f + m[i], _SHIFTS[0][i % 4])
        a, b, c, d = d, a, b, c
    for i, k in enumerate(_R2_ORDER):
        g = (b & c) | (b & d) | (c & d)
        a = _rotl(a + g + m[k] + jnp.uint32(0x5A827999), _SHIFTS[1][i % 4])
        a, b, c, d = d, a, b, c
    for i, k in enumerate(_R3_ORDER):
        h = b ^ c ^ d
        a = _rotl(a + h + m[k] + jnp.uint32(0x6ED9EBA1), _SHIFTS[2][i % 4])
        a, b, c, d = d, a, b, c
    return a, b, c, d


def md4_compress(state: jnp.ndarray, words: jnp.ndarray) -> jnp.ndarray:
    a, b, c, d = md4_rounds(*(state[..., i] for i in range(4)),
                            [words[..., i] for i in range(16)])
    return jnp.stack([a, b, c, d], axis=-1) + state


def md4_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    state = jnp.broadcast_to(jnp.asarray(INIT), words.shape[:-1] + (4,))
    return md4_compress(state, words)
