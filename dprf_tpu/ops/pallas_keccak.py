"""Fused mask->Keccak->compare Pallas kernel for the SHA3/Keccak
family (sha3-224/256/384/512, keccak-224/256/384/512).

Same skeleton as ops/pallas_mask.py -- decode, hash, compare, and the
packed (count << 16) | (hit_lane + 1) per-tile output all stay in
VMEM -- but the sponge replaces the Merkle-Damgard framing: the
candidate absorbs into the rate lanes with the variant's pad byte at
the (static) message length and 0x80 at rate-1, then 24 unrolled
Keccak-f rounds run over (hi, lo) uint32 pairs
(ops/keccak.keccak_f_unrolled; a fori_loop with a 50-array dict carry
does not lower to Mosaic).

Register pressure is the sizing constraint: ~120 (hi, lo) pair tiles
are live through theta/rho-pi/chi, so the default sublane count SUBK
is smaller than the MD kernels' 128.  Single target only (multi-target
lists stay on the XLA sorted-table pipeline); TPU-only like the
SHA-256/512 kernels -- XLA:CPU takes minutes on the flat unrolled
graph, so correctness off-TPU is validated eagerly via
emulate_keccak_kernel.
"""

from __future__ import annotations


import numpy as np

from dprf_tpu.utils import env as envreg  # noqa: E402 -- stdlib-only
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops.keccak import keccak_f_unrolled, squeeze_words
from dprf_tpu.ops.pallas_mask import (check_batch,
                                      decode_candidate_bytes,
                                      mask_supported, reduce_tile_hits,
                                      segment_tables)

#: sublane count per grid cell (tile = SUBK * 128 lanes).  Keccak-f
#: holds ~120 pair registers live, ~4x the MD cores, so the default
#: tile is smaller; DPRF_PALLAS_SUBK overrides for hardware sweeps.
SUBK = envreg.get_int("DPRF_PALLAS_SUBK")


def keccak_kernel_eligible(gen, n_targets: int, rate: int) -> bool:
    """Kernel path eligibility: single target, mask generator whose
    charsets are segment-decodable, candidate fits the rate block,
    real TPU backend only (the flat unrolled graph takes XLA:CPU
    minutes even under pallas interpret, so off-TPU the family rides
    the XLA sponge and the body is validated via
    emulate_keccak_kernel, exactly like the SHA-256/512 kernels)."""
    if n_targets != 1:
        return False
    if not hasattr(gen, "charsets"):
        return False
    if jax.default_backend() != "tpu":
        return False
    return gen.length <= rate - 1 and mask_supported(gen.charsets)


def _build_keccak_body(radices, seg_tables, length: int, tw,
                       pad_byte: int, rate: int, out_bytes: int,
                       sub: int):
    """Kernel math as a pure function of (pid, base, n_valid) ->
    (count, hit_lane), mirroring pallas_mask._build_kernel_body."""
    tile = sub * 128
    tw_ints = [int(w) for w in np.asarray(tw).reshape(-1)]
    n_words = -(-out_bytes // 4)
    if len(tw_ints) != n_words:
        raise ValueError(f"expected {n_words} target words")

    def body(pid, base, n_valid):
        shape = (sub, 128)
        lane = (jax.lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
        carry = lane + pid * tile
        byts = decode_candidate_bytes(radices, seg_tables, length,
                                      base, carry)

        def const_byte(q: int) -> int:
            # the padding is STATIC: mask candidates all have length
            # `length`, so pad_byte lands at byte `length` and 0x80 at
            # rate-1 (merged when length == rate - 1, per pad10*1)
            v = 0
            if q == length:
                v |= pad_byte
            if q == rate - 1:
                v |= 0x80
            return v

        def half_lane(q0: int):
            """uint32 from bytes q0..q0+3 (little-endian)."""
            acc = None
            const = 0
            for j in range(4):
                q = q0 + j
                if q < length:
                    term = byts[q] << jnp.uint32(8 * j)
                    acc = term if acc is None else acc + term
                else:
                    const |= const_byte(q) << (8 * j)
            if const:
                c = jnp.uint32(const)
                acc = jnp.full(shape, c) if acc is None else acc + c
            return jnp.zeros(shape, jnp.uint32) if acc is None else acc

        zero = jnp.zeros(shape, jnp.uint32)
        state = {(x, y): (zero, zero)
                 for x in range(5) for y in range(5)}
        for i in range(rate // 8):
            state[(i % 5, i // 5)] = (half_lane(8 * i + 4),
                                      half_lane(8 * i))
        state = keccak_f_unrolled(state)
        digest = squeeze_words(state, out_bytes)

        valid = (lane + pid * tile) < n_valid
        found = valid
        for got, want in zip(digest, tw_ints):
            found = found & (got == jnp.uint32(want))
        count = jnp.sum(found.astype(jnp.int32))
        hit_lane = jnp.max(jnp.where(found, lane, -1))
        return count, hit_lane

    return body


def emulate_keccak_kernel(gen, tw, batch: int, base_digits, n_valid,
                          pad_byte: int, rate: int, out_bytes: int,
                          sub: int = SUBK):
    """Eager per-tile drive of the kernel body (the CPU validation
    vehicle; XLA:CPU cannot compile the unrolled graph)."""
    tile = sub * 128
    check_batch(batch, sub)
    seg_tables = segment_tables(gen.charsets)
    body = _build_keccak_body(gen.radices, seg_tables, gen.length, tw,
                              pad_byte, rate, out_bytes, sub)
    base = jnp.asarray(base_digits, jnp.int32)
    counts, lanes = [], []
    for pid in range(batch // tile):
        c, l = body(jnp.int32(pid), base, jnp.int32(n_valid))
        counts.append(int(c))
        lanes.append(int(l))
    return (np.asarray(counts, np.int32)[:, None],
            np.asarray(lanes, np.int32)[:, None])


def make_keccak_pallas_fn(gen, tw, batch: int, pad_byte: int,
                          rate: int, out_bytes: int, sub: int = SUBK,
                          interpret: bool = False):
    """fn(base_digits int32[L], n_valid int32[1]) ->
    (counts int32[G, 1], hit_lanes int32[G, 1])."""
    tile = sub * 128
    grid = check_batch(batch, sub)
    if not keccak_kernel_eligible(gen, 1, rate):
        raise ValueError("mask job not keccak-kernel eligible")
    seg_tables = segment_tables(gen.charsets)
    body = _build_keccak_body(gen.radices, seg_tables, gen.length, tw,
                              pad_byte, rate, out_bytes, sub)
    L = gen.length

    def kernel(base_ref, nvalid_ref, out_ref):
        count, hit_lane = body(pl.program_id(0), base_ref,
                               nvalid_ref[0])
        packed = (count << 16) | (hit_lane + 1)
        out_ref[...] = jnp.full((8, 128), packed, jnp.int32)

    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
        ],
        out_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32)],
        interpret=interpret,
    )

    def fn(base_digits, n_valid):
        (packed,) = raw(base_digits, n_valid)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_pallas_keccak_crack_step(gen, tw, batch: int, pad_byte: int,
                                  rate: int, out_bytes: int,
                                  hit_capacity: int = 64,
                                  interpret: bool = False):
    """Drop-in replacement for sha3.make_keccak_mask_step on the
    single-target kernel path: step(base_digits, n_valid) ->
    (count, lanes, tpos)."""
    tile = SUBK * 128
    fn = make_keccak_pallas_fn(gen, tw, batch, pad_byte, rate,
                               out_bytes, interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,))
                               .astype(jnp.int32))
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step
