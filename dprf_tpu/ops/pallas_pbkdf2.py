"""Fused PMKID Pallas kernel: decode -> PBKDF2-HMAC-SHA1 -> PMKID.

Config 5 (WPA2-PMKID) measured 17.4 kH/s through the XLA pipeline on
the real chip -- ~285 M SHA-1 compressions/s, ~12% of the sha1 mask
kernel's rate; the XLA fori_loop form leaves most of the VPU idle
between the small per-iteration fusions.  This kernel keeps the whole
chain in VMEM/registers per candidate lane:

  mask decode -> one-block HMAC key states (K^ipad / K^opad) ->
  two PBKDF2 blocks of `iterations` HMAC-SHA1 rounds (the fori_loop
  carries 10 digest-word registers -- small carries DO lower, unlike
  the big SoA tuples that crash the backend compiler, see
  TPU_PROBE_LOG_r04) -> PMK -> PMKID = HMAC(PMK, "PMK Name"|AP|STA)
  -> compare.

Per-target runtime inputs (SMEM scalars): ESSID bytes (length static
per compiled kernel, like the salted kernels' salt length), the
20-byte PMKID message words, the 4-word target, and the iteration
count -- so one compile per (mask, essid length) serves every target
and any iteration count (tests run 16, production 4096).

Semantics mirror ops/hmac_sha1.py exactly (same ipad/opad single-xor
key pad, same salt||INT(i) first message, same T1||T2[:3] PMK);
the hermetic tests drive the shared pure body (pmkid_lanes)\neagerly against hashlib, and the kernel itself is proven on real\nhardware (planted crack at 4096 iterations).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops import sha1 as sha1_ops
from dprf_tpu.ops.pallas_mask import (SUB, decode_candidate_bytes,
                                      mask_supported, reduce_tile_hits,
                                      segment_tables)

_IPAD = 0x36363636
_OPAD = 0x5C5C5C5C


def pmkid_kernel_eligible(gen, essid_lens) -> bool:
    """Any mask charset order (unbounded segment mux since r5);
    passphrase and ESSID must fit
    their single blocks (ESSID <= 32 by 802.11; belt and braces)."""
    if not hasattr(gen, "charsets") or not mask_supported(gen.charsets):
        return False
    if gen.length > 63:
        return False
    return all(0 < n <= 32 for n in essid_lens)


def _compress(state, m, shape):
    """SHA-1 compression with an arbitrary chaining state on
    (sub, 128) word arrays: rounds + Davies-Meyer feed-forward."""
    out = sha1_ops.sha1_rounds(*state, m)
    return tuple(o + s for o, s in zip(out, state))


def _init_state(shape):
    return tuple(jnp.full(shape, jnp.uint32(int(w)))
                 for w in sha1_ops.INIT)


def _block20(words5, shape):
    """20-byte message following a 64-byte key block: 0x80 marker and
    672-bit length (ops/hmac_sha1._block20 on kernel layouts)."""
    m = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
    for i in range(5):
        m[i] = words5[i]
    m[5] = jnp.full(shape, jnp.uint32(0x80000000))
    m[15] = jnp.full(shape, jnp.uint32((64 + 20) * 8))
    return m


def _hmac20(istate, ostate, msg5, shape):
    inner = _compress(istate, _block20(msg5, shape), shape)
    return _compress(ostate, _block20(inner, shape), shape)


def pbkdf2_lanes(byts, salt_vals, salt_len: int, iters, n_words: int,
                 shape):
    """Generic PBKDF2-HMAC-SHA1 on kernel layouts: candidate byte
    arrays -> the first n_words uint32 words of T1 || T2 (n_words <= 10
    covers every deployed key width: 4 for AES-128 string-to-key, 8
    for AES-256/PMK).  Same chaining as pmkid_lanes (shared _compress/
    _block20/_hmac20); the salt plays the ESSID's role."""
    K = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
    for p, b in enumerate(byts):
        K[p // 4] = K[p // 4] | (b << jnp.uint32(8 * (3 - p % 4)))
    init = _init_state(shape)
    istate = _compress(init, [k ^ jnp.uint32(_IPAD) for k in K], shape)
    ostate = _compress(init, [k ^ jnp.uint32(_OPAD) for k in K], shape)

    def as_u32(x):
        return x.astype(jnp.uint32) if hasattr(x, "astype") \
            else jnp.uint32(x)

    def block(block_index: int):
        msg_len = salt_len + 4
        first = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
        for p in range(salt_len):
            first[p // 4] = first[p // 4] | (
                as_u32(salt_vals[p]) << jnp.uint32(8 * (3 - p % 4)))
        for p, b in zip(range(salt_len, salt_len + 4),
                        int(block_index).to_bytes(4, "big")):
            first[p // 4] = first[p // 4] | (
                jnp.uint32(b) << jnp.uint32(8 * (3 - p % 4)))
        first[msg_len // 4] = first[msg_len // 4] | (
            jnp.uint32(0x80) << jnp.uint32(8 * (3 - msg_len % 4)))
        first[15] = first[15] | jnp.uint32((64 + msg_len) * 8)
        inner = _compress(istate, first, shape)
        u = _compress(ostate, _block20(inner, shape), shape)

        def body(_, uc):
            u, t = uc
            u = _hmac20(istate, ostate, u, shape)
            return u, tuple(a ^ b for a, b in zip(t, u))

        _, t = lax.fori_loop(1, iters, body, (u, u))
        return t

    out = list(block(1))
    if n_words > 5:
        out.extend(block(2))
    return tuple(out[:n_words])


def make_pbkdf2_kdf_pallas_fn(gen, batch: int, salt_len: int,
                              n_words: int, sub: int = SUB,
                              interpret: bool = False):
    """Generic fused mask-decode -> PBKDF2-HMAC-SHA1 kernel producing
    raw derived-key words (the 7z-kernel pattern: KDF on the kernel,
    cheap verdict in XLA downstream).  fn(base_digits int32[L],
    iters int32[1], salt int32[salt_len]) -> uint32[batch, n_words].
    One compile per (mask, salt_len) serves every target and
    iteration count."""
    if sub > 128:
        raise ValueError("sub > 128 overflows the tile layout")
    tile = sub * 128
    if batch % tile or batch <= 0:
        raise ValueError(f"batch {batch} must be a multiple of "
                         f"tile {tile}")
    if not (hasattr(gen, "charsets") and mask_supported(gen.charsets)
            and gen.length <= 63 and 0 < salt_len <= 51):
        raise ValueError("pbkdf2 kdf kernel: job not eligible")
    if not 1 <= n_words <= 10:
        raise ValueError("n_words must be in 1..10 (T1 || T2)")
    seg_tables = segment_tables(gen.charsets)
    radices, length = gen.radices, gen.length
    grid = batch // tile

    def kernel(iters_ref, salt_ref, base_ref, out_ref):
        shape = (sub, 128)
        pid = pl.program_id(0)
        lane = (lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + lax.broadcasted_iota(jnp.int32, shape, 1))
        carry = lane + pid * tile
        byts = decode_candidate_bytes(radices, seg_tables, length,
                                      base_ref, carry)
        t = pbkdf2_lanes(byts, [salt_ref[p] for p in range(salt_len)],
                         salt_len, iters_ref[0], n_words, shape)
        out_ref[...] = jnp.concatenate(list(t), axis=0)

    L = gen.length
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((salt_len,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
        ],
        out_specs=[pl.BlockSpec((n_words * sub, 128),
                                lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * n_words * sub, 128),
                                        jnp.uint32)],
        interpret=interpret,
    )

    @jax.jit
    def fn(base_digits, iters, salt):
        (packed,) = raw(jnp.reshape(iters, (1,)).astype(jnp.int32),
                        salt, base_digits.astype(jnp.int32))
        words = packed.reshape(grid, n_words, sub, 128)
        return words.transpose(0, 2, 3, 1).reshape(batch, n_words)

    return fn


def pmkid_lanes(byts, essid_vals, essid_len: int, msg_vals, iters,
                shape):
    """The kernel math as a PURE function: candidate byte arrays ->
    4 PMKID words, shared verbatim by the pallas kernel (SMEM scalar
    reads) and the eager oracle tests (python ints / tiny arrays) --
    one source of truth for the key padding, PBKDF2 chaining, PMK
    assembly, and PMKID truncation."""
    # one-block big-endian key words, RAW zero padding (the HMAC key
    # block is a full block -- no 0x80 marker)
    # PMK = first 8 words of T1 || T2 (the shared generic PBKDF2 body)
    pmk = pbkdf2_lanes(byts, essid_vals, essid_len, iters, 8, shape)
    init = _init_state(shape)
    K2 = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
    for i in range(8):
        K2[i] = pmk[i]
    istate2 = _compress(init, [k ^ jnp.uint32(_IPAD) for k in K2], shape)
    ostate2 = _compress(init, [k ^ jnp.uint32(_OPAD) for k in K2], shape)
    as_u32 = (lambda x: x.astype(jnp.uint32)
              if hasattr(x, "astype") else jnp.uint32(x))
    msg5 = tuple(jnp.full(shape, jnp.uint32(0)) | as_u32(msg_vals[i])
                 for i in range(5))
    return _hmac20(istate2, ostate2, msg5, shape)[:4]


def make_pmkid_pallas_fn(gen, batch: int, essid_len: int,
                         sub: int = SUB, interpret: bool = False):
    """fn(base_digits int32[L], n_valid int32[1], iters int32[1],
    essid int32[essid_len], msg5 int32[5], target int32[4]) ->
    (counts int32[G,1], hit_lanes int32[G,1])."""
    if sub > 128:
        # same guard as pallas_mask: count and hit_lane+1 must fit the
        # packed 16-bit output fields
        raise ValueError("sub > 128 overflows the packed 16-bit "
                         "count/lane output fields")
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    if not pmkid_kernel_eligible(gen, [essid_len]):
        raise ValueError("pmkid mask job not kernel-eligible")
    seg_tables = segment_tables(gen.charsets)
    radices = gen.radices
    length = gen.length
    grid = batch // tile

    def kernel(nvalid_ref, iters_ref, essid_ref, msg_ref, tgt_ref,
               base_ref, out_ref):
        shape = (sub, 128)
        pid = pl.program_id(0)
        lane = (lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + lax.broadcasted_iota(jnp.int32, shape, 1))
        carry = lane + pid * tile
        byts = decode_candidate_bytes(radices, seg_tables, length,
                                      base_ref, carry)
        pmkid = pmkid_lanes(byts, [essid_ref[p] for p in range(essid_len)],
                            essid_len, [msg_ref[i] for i in range(5)],
                            iters_ref[0], shape)
        valid = (lane + pid * tile) < nvalid_ref[0]
        found = valid
        for i in range(4):
            found = found & (pmkid[i] == tgt_ref[i].astype(jnp.uint32))
        count = jnp.sum(found.astype(jnp.int32))
        hit_lane = jnp.max(jnp.where(found, lane, -1))
        out_ref[...] = jnp.full((8, 128), (count << 16) | (hit_lane + 1),
                                jnp.int32)

    L = gen.length
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((essid_len,), lambda i: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((5,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((4,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
        ],
        out_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32)],
        interpret=interpret,
    )

    def fn(base_digits, n_valid, iters, essid, msg5, target):
        (packed,) = raw(n_valid, iters, essid, msg5, target,
                        base_digits)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_pmkid_kernel_step(gen, batch: int, essid_len: int,
                           hit_capacity: int = 64,
                           interpret: bool = False, sub: int = None):
    """Per-target crack step: step(base_digits, n_valid, iters,
    essid int32[essid_len], msg5 int32[5], target int32[4]) ->
    (count, lanes, tpos)."""
    sub = SUB if sub is None else sub
    tile = sub * 128
    batch = max(tile, (batch // tile) * tile)
    fn = make_pmkid_pallas_fn(gen, batch, essid_len, sub=sub,
                              interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid, iters, essid, msg5, target):
        counts, hit_lanes = fn(
            base_digits.astype(jnp.int32),
            jnp.reshape(n_valid, (1,)).astype(jnp.int32),
            jnp.reshape(iters, (1,)).astype(jnp.int32),
            essid, msg5, target)
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    step.batch = batch
    return step


def target_kernel_args(target):
    """Target -> (essid_len, essid int32, msg5 int32, tgt int32)."""
    essid = target.params["essid"]
    msg = b"PMK Name" + target.params["mac_ap"] + target.params["mac_sta"]
    return (len(essid),
            jnp.asarray(np.frombuffer(essid, np.uint8).astype(np.int32)),
            jnp.asarray(np.frombuffer(msg, ">u4").astype(np.uint32)
                        .view(np.int32)),
            jnp.asarray(np.frombuffer(target.digest, ">u4")
                        .astype(np.uint32).view(np.int32)))
