"""scrypt (RFC 7914) as a jit-traceable device pipeline.

The second memory-hard path next to bcrypt (SURVEY.md §2 class), and
the one that actually stresses HBM: ROMix keeps V = N x 128r bytes PER
CANDIDATE resident (16 MB at the common 16384:8:1), so the batch is
bounded by HBM, not lanes, and throughput is bandwidth-bound by
design -- each candidate writes V once and gathers it back once in
data-dependent order.

Device mapping:
- Both PBKDF2-HMAC-SHA256 passes (c=1) ride the shared sha256 core:
  pass 1 is one U1 HMAC per 32-byte output block (runtime salt via
  u1_block); pass 2 chains the compression over B's 64-byte sub-blocks
  (each is exactly one SHA-256 message block) plus one host-constant
  tail block -- no byte shuffling on device.
- Salsa20/8 and BlockMix are pure int32 vector ops over uint32[B,16]
  lanes.
- ROMix phase 1 is a fori_loop carrying V uint32[B, N, 128r/4] via
  dynamic_update_slice; phase 2 gathers V rows per lane with
  take_along_axis (Integerify is just word 0 of the last 64-byte
  sub-block, & (N-1), already in little-endian word domain).
- X lives in the Salsa word domain (little-endian words of the byte
  stream); the two byteswaps at the PBKDF2 boundaries are the only
  endianness work.

N, r, p are trace-time constants (shapes depend on them); the salt is
a runtime argument, so one compiled step serves every target sharing
one parameter tuple.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from dprf_tpu.ops.hmac import digest_tail_block
from dprf_tpu.ops.hmac_sha256 import hmac256_key_states
from dprf_tpu.ops.sha256 import sha256_compress


def bswap32(x: jnp.ndarray) -> jnp.ndarray:
    """Byte-reverse uint32 lanes (BE digest words <-> LE Salsa words)."""
    return ((x << 24) | ((x & jnp.uint32(0xFF00)) << 8)
            | ((x >> 8) & jnp.uint32(0xFF00)) | (x >> 24))


def _rotl(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x << n) | (x >> (32 - n))


# Salsa20 quarter-round index schedule (RFC 7914 / Salsa20 spec): four
# column quarter-rounds then four row quarter-rounds per double round.
_SALSA_QROUNDS = [
    (4, 0, 12, 7), (8, 4, 0, 9), (12, 8, 4, 13), (0, 12, 8, 18),
    (9, 5, 1, 7), (13, 9, 5, 9), (1, 13, 9, 13), (5, 1, 13, 18),
    (14, 10, 6, 7), (2, 14, 10, 9), (6, 2, 14, 13), (10, 6, 2, 18),
    (3, 15, 11, 7), (7, 3, 15, 9), (11, 7, 3, 13), (15, 11, 7, 18),
    (1, 0, 3, 7), (2, 1, 0, 9), (3, 2, 1, 13), (0, 3, 2, 18),
    (6, 5, 4, 7), (7, 6, 5, 9), (4, 7, 6, 13), (5, 4, 7, 18),
    (11, 10, 9, 7), (8, 11, 10, 9), (9, 8, 11, 13), (10, 9, 8, 18),
    (12, 15, 14, 7), (13, 12, 15, 9), (14, 13, 12, 13), (15, 14, 13, 18),
]


def salsa8(x: jnp.ndarray) -> jnp.ndarray:
    """Salsa20/8 core: uint32[..., 16] -> uint32[..., 16]."""
    w = [x[..., i] for i in range(16)]
    for _ in range(4):      # 8 rounds = 4 double rounds
        for dst, a, b, rot in _SALSA_QROUNDS:
            w[dst] = w[dst] ^ _rotl(w[a] + w[b], rot)
    return jnp.stack(w, axis=-1) + x


def blockmix(x: jnp.ndarray) -> jnp.ndarray:
    """scrypt BlockMix: uint32[B, 2r, 16] -> uint32[B, 2r, 16]."""
    two_r = x.shape[-2]
    t = x[:, -1]
    ys = []
    for i in range(two_r):
        t = salsa8(t ^ x[:, i])
        ys.append(t)
    # even-index outputs first, then odd (the RFC's shuffle)
    return jnp.stack(ys[0::2] + ys[1::2], axis=1)


def romix(x: jnp.ndarray, n: int) -> jnp.ndarray:
    """scrypt ROMix: uint32[B, 2r, 16], V of n rows per lane."""
    B, two_r, _ = x.shape
    F = two_r * 16

    def fill(i, carry):
        v, x = carry
        v = lax.dynamic_update_slice(
            v, x.reshape(B, 1, F), (0, i, 0))
        return v, blockmix(x)

    v0 = jnp.zeros((B, n, F), jnp.uint32)
    v, x = lax.fori_loop(0, n, fill, (v0, x))

    def mix(_, x):
        j = (x[:, -1, 0] & jnp.uint32(n - 1)).astype(jnp.int32)
        vj = jnp.take_along_axis(v, j[:, None, None], axis=1)
        return blockmix(x ^ vj.reshape(B, two_r, 16))

    return lax.fori_loop(0, n, mix, x)


def _final_tail_block(m: int) -> np.ndarray:
    """Host-constant last inner block of PBKDF2 pass 2: the message is
    B (m bytes, a whole number of 64-byte blocks) || INT32BE(1), so the
    tail holds INT(1), the 0x80 marker, and the bit length of
    (keyblock + m + 4) bytes."""
    buf = np.zeros(64, np.uint8)
    buf[3] = 1          # INT32BE(1)
    buf[4] = 0x80
    bitlen = (64 + m + 4) * 8
    buf[56:] = np.frombuffer(bitlen.to_bytes(8, "big"), np.uint8)
    return (buf.reshape(16, 4).astype(np.uint32)
            @ np.array([1 << 24, 1 << 16, 1 << 8, 1], np.uint32))


def scrypt_dk(key_words: jnp.ndarray, salt: jnp.ndarray, salt_len,
              n: int, r: int, p: int) -> jnp.ndarray:
    """scrypt derived key (32 bytes): uint32[B, 8] big-endian words.

    key_words: uint32[B, 16] zero-padded packed passwords (<= 64 bytes);
    salt: uint8[SALT_MAX] runtime buffer + salt_len; n, r, p static.
    """
    from dprf_tpu.engines.device.pbkdf2 import u1_block

    if n & (n - 1) or n < 2:
        raise ValueError("scrypt N must be a power of two >= 2")
    if p * 4 * r > 255:
        # u1_block encodes the PBKDF2 block index in one byte
        raise ValueError("scrypt r*p too large: p*4*r must be <= 255")
    istate, ostate = hmac256_key_states(key_words)
    B = key_words.shape[0]

    # PBKDF2 pass 1, c=1: p*4r output blocks of 8 BE words each.
    ts = []
    for i in range(1, p * 4 * r + 1):
        inner = sha256_compress(istate, u1_block(salt, salt_len, i))
        ts.append(sha256_compress(ostate, digest_tail_block("sha256",
                                                            inner)))
    x = bswap32(jnp.concatenate(ts, axis=-1)).reshape(B, p, 2 * r, 16)

    # ROMix each of the p blocks independently (p is 1 in practice).
    mixed = [romix(x[:, pi], n) for pi in range(p)]
    x = jnp.stack(mixed, axis=1)

    # PBKDF2 pass 2, c=1, dkLen=32: message is B' || INT(1); every
    # 64-byte sub-block of B' is exactly one SHA-256 message block.
    blocks = bswap32(x).reshape(B, p * 2 * r, 16)
    state = istate
    for i in range(p * 2 * r):
        state = sha256_compress(state, blocks[:, i])
    tail = jnp.broadcast_to(jnp.asarray(_final_tail_block(p * 128 * r)),
                            (B, 16))
    inner = sha256_compress(state, tail)
    return sha256_compress(ostate, digest_tail_block("sha256", inner))
