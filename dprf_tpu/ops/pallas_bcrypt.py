"""Pallas EksBlowfish advance kernel: vector-rate S-box gathers.

VERDICT r3 #4 asked for a real Pallas bcrypt attempt before accepting
the XLA form's throughput as the chip's ceiling.  The XLA batched form
(ops/blowfish.py) lowers each Feistel round's four per-candidate S-box
reads to per-lane SERIAL gathers -- measured 0.29 H/s at cost 12
(TPU_RESULTS_r03/r04), ~80M scalar gathers/s, far below any
bandwidth or ALU limit.

The kernel reshapes the problem so the gather is the hardware's native
per-sublane dynamic gather (the same `take_along_axis` shape the Bloom
prefilter kernel proved lowers and runs on this chip):

- candidates ride the SUBLANE axis, SUBC per grid cell;
- each candidate's 4 KB S state is uint32[SUBC, 1024] in VMEM -- the
  1024-entry flat box axis rides the LANES, so one 256-entry box is
  two 128-lane chunks;
- a Feistel lookup gathers along lanes per sublane: two chunk gathers
  + a bit-8 select per box, all (SUBC, 128) vector ops, ~12 vector
  ops per round instead of 4*SUBC serial loads;
- EksBlowfish's S rewrites happen at the SAME flat position for every
  candidate (the chain index is uniform), so the "scatter" is one
  iota==pos select over the lane axis -- no scatter support needed.

The kernel advances (P, S) by a RUNTIME n_rounds of
{ExpandKey(key); ExpandKey(salt)} with everything resident in VMEM,
and is a drop-in `advance` for ChunkedEks, so the deadline-bounded
chunking, sharded workers, and worker protocols all reuse it.

P and key are carried as uint32[B, 128] lane-padded arrays (words
0..17 live in lanes 0..17) to keep every block shape (8k, 128m);
pad_p18/unpad_p18 convert at the chunk boundary (host side, once per
batch -- noise next to seconds of cost loop).
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


from dprf_tpu.ops import blowfish as bf_ops
from dprf_tpu.utils import env as envreg

#: candidates (sublanes) per grid cell.  VMEM per cell is
#: SUBC * (4 KB S + padded P/key) ~= SUBC * 5 KB.  The r4 hardware
#: sweep (tools/tpu_case.py pallaseks cases, B=64): 19.6 / 11.9 /
#: 10.1 / 7.7 ms per cost round at SUBC 8/16/32/64 -- per-candidate
#: op count is SUBC-independent, so the gain is loop/control overhead
#: amortization; 64 is the measured winner (~320 KB VMEM).
SUBC = envreg.get_int("DPRF_BCRYPT_SUBC")


def pad_p18(x: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, 18] -> uint32[B, 128] (words in lanes 0..17)."""
    return jnp.pad(x, ((0, 0), (0, 110)))


def unpad_p18(x: jnp.ndarray) -> jnp.ndarray:
    return x[:, :18]


def _gather_box(S, box: int, idx):
    """S uint32[SUBC, 1024], box 0..3, idx uint32[SUBC, 128] (entry
    index 0..255, replicated along lanes) -> gathered value
    uint32[SUBC, 128].  Two per-sublane 128-lane gathers + a bit-8
    select."""
    # static slices (Mosaic has no dynamic_slice; box is a Python int)
    lo = S[:, box * 256:box * 256 + 128]
    hi = S[:, box * 256 + 128:box * 256 + 256]
    idx7 = (idx & jnp.uint32(127)).astype(jnp.int32)
    glo = jnp.take_along_axis(lo, idx7, axis=1)
    ghi = jnp.take_along_axis(hi, idx7, axis=1)
    return jnp.where(idx < 128, glo, ghi)


def _feistel_v(S, x):
    """F(x) on (SUBC, 128) lane-replicated x."""
    a = x >> jnp.uint32(24)
    b = (x >> jnp.uint32(16)) & jnp.uint32(0xFF)
    c = (x >> jnp.uint32(8)) & jnp.uint32(0xFF)
    d = x & jnp.uint32(0xFF)
    return ((_gather_box(S, 0, a) + _gather_box(S, 1, b))
            ^ _gather_box(S, 2, c)) + _gather_box(S, 3, d)


def _encrypt_v(P, S, l, r):
    """16-round Blowfish on lane-replicated (SUBC, 128) halves.
    P uint32[SUBC, 128] (words in lanes 0..17): P[..., i] reads are
    static lane slices broadcast back over the lanes."""
    def pw(i):
        return jnp.broadcast_to(P[:, i:i + 1], l.shape)

    for i in range(0, 16, 2):
        l = l ^ pw(i)
        r = r ^ _feistel_v(S, l)
        r = r ^ pw(i + 1)
        l = l ^ _feistel_v(S, r)
    return r ^ pw(17), l ^ pw(16)


def _expand_key_v(P, S, key):
    """ExpandKey (no salt -- the cost-loop form) on kernel layouts:
    P/key uint32[SUBC, 128] lane-padded, S uint32[SUBC, 1024]."""
    lane128 = lax.broadcasted_iota(jnp.int32, P.shape, 1)
    P = jnp.where(lane128 < 18, P ^ key, P)
    shape = (P.shape[0], 128)
    zero = jnp.zeros(shape, jnp.uint32)

    def p_body(i, carry):
        P, l, r = carry
        l, r = _encrypt_v(P, S, l, r)
        # uniform write positions 2i, 2i+1 (same for every candidate):
        # the l/r values are lane-replicated, so a lane-iota select IS
        # the scatter
        P = jnp.where(lane128 == 2 * i, l, P)
        P = jnp.where(lane128 == 2 * i + 1, r, P)
        return P, l, r

    P, l, r = lax.fori_loop(0, 9, p_body, (P, zero, zero))
    lane1024 = lax.broadcasted_iota(jnp.int32, S.shape, 1)

    def s_body(j, carry):
        S, l, r = carry
        l, r = _encrypt_v(P, S, l, r)
        pos = 2 * j
        lw = jnp.broadcast_to(l[:, 0:1], S.shape)
        rw = jnp.broadcast_to(r[:, 0:1], S.shape)
        S = jnp.where(lane1024 == pos, lw, S)
        S = jnp.where(lane1024 == pos + 1, rw, S)
        return S, l, r

    S, l, r = lax.fori_loop(0, 512, s_body, (S, l, r))
    return P, S


def _advance_kernel(nrounds_ref, salt18_ref, P_ref, S_ref, key_ref,
                    Pout_ref, Sout_ref):
    """Advance one SUBC-candidate block by n_rounds cost iterations."""
    P = P_ref[...]
    S = S_ref[...]
    key = key_ref[...]
    lane128 = lax.broadcasted_iota(jnp.int32, P.shape, 1)
    # salt18 as a lane-padded constant row (uniform across candidates)
    salt = jnp.zeros(P.shape, jnp.uint32)
    for i in range(18):
        salt = jnp.where(lane128 == i,
                         salt18_ref[i].astype(jnp.uint32), salt)

    def body(_, PS):
        P, S = PS
        P, S = _expand_key_v(P, S, key)
        P, S = _expand_key_v(P, S, salt)
        return P, S

    P, S = lax.fori_loop(0, nrounds_ref[0], body, (P, S))
    Pout_ref[...] = P
    Sout_ref[...] = S


@functools.lru_cache(maxsize=8)
def _advance_call(batch: int, interpret: bool, subc: int):
    grid = batch // subc

    raw = pl.pallas_call(
        _advance_kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((18,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((subc, 128), lambda i: (i, 0)),
            pl.BlockSpec((subc, 1024), lambda i: (i, 0)),
            pl.BlockSpec((subc, 128), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((subc, 128), lambda i: (i, 0)),
            pl.BlockSpec((subc, 1024), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((batch, 128), jnp.uint32),
            jax.ShapeDtypeStruct((batch, 1024), jnp.uint32),
        ],
        interpret=interpret,
    )
    return raw


@functools.lru_cache(maxsize=8)
def make_pallas_eks_advance(batch: int, interpret: bool = False,
                            subc: int = None):
    """Build `advance(P, S, key_words, salt18, n) -> (P, S)` with the
    ChunkedEks contract (18-word P/key, uint32[B, 1024] S) running the
    cost loop through the Pallas kernel.  A batch that doesn't tile
    into subc-candidate grid cells is row-padded into the kernel and
    sliced back (wordlist batches are word_batch * n_rules -- rarely a
    SUBC multiple).  Cached so the routing micro-bench and the worker
    share one compile."""
    subc = SUBC if subc is None else subc
    padded = -(-batch // subc) * subc
    raw = _advance_call(padded, interpret, subc)
    extra = padded - batch

    @jax.jit
    def advance(P, S, key_words, salt18, n):
        Pp = jnp.pad(pad_p18(P), ((0, extra), (0, 0)))
        kp = jnp.pad(pad_p18(key_words), ((0, extra), (0, 0)))
        Sp = jnp.pad(S, ((0, extra), (0, 0)))
        n1 = jnp.reshape(n, (1,)).astype(jnp.int32)
        s18 = salt18.astype(jnp.int32)
        Pp, Sp = raw(n1, s18, Pp, Sp, kp)
        return unpad_p18(Pp)[:batch], Sp[:batch]

    return advance


def make_best_eks_advance(batch: int):
    """The fastest available ChunkedEks advance for this batch: the
    Pallas kernel when the kernel path is on (measured 8x the XLA form
    at cost 12 on TPU v5 lite -- 1.59/2.32 H/s at B=64/512 vs 0.29,
    TPU_RESULTS_r04 session3 -- and per-round time scales linearly
    with batch where the XLA gathers serialize), else the donating
    jitted XLA form.

    Mosaic raises lowering errors at the first CALL, not at build, so
    the kernel is proven here with a 1-round run on zero state before
    being returned -- a lowering failure falls back to the XLA advance
    instead of crashing mid-job (the r4 dev loop hit exactly this with
    an unsupported dynamic_slice)."""
    from dprf_tpu.ops.pallas_mask import pallas_mode
    mode = pallas_mode()
    # real Mosaic only: the interpret path exists for the dedicated
    # equivalence test (make_pallas_eks_advance directly); a 2**cost
    # chain through interpreted Pallas would be slower than the oracle
    if mode is not None and not mode.get("interpret", False):
        try:
            adv = make_pallas_eks_advance(batch)
            Z = jnp.zeros
            out = adv(Z((batch, 18), jnp.uint32),
                      Z((batch, 1024), jnp.uint32),
                      Z((batch, 18), jnp.uint32),
                      Z((18,), jnp.uint32), jnp.int32(1))
            jax.device_get(out[0][0, 0])     # force the compile+run
            return adv
        except Exception as e:   # lowering failure -> proven XLA form
            from dprf_tpu.utils.logging import DEFAULT as log
            log.warn("pallas eks kernel failed to build/lower; using "
                     "the XLA advance", error=f"{type(e).__name__}: {e}")
    return jax.jit(bf_ops.eks_rounds, donate_argnums=(0, 1))
