"""DES as a BITSLICE kernel: the TPU-native way to run a
permutation-heavy 1977 cipher on a vector unit.

Why bitslice: DES is all bit permutations (IP, E, P, PC1/PC2) and
6->4-bit S-box lookups -- gather-per-candidate tables are the one
shape this VPU hates (see bcrypt's measured serialization).  In
bitslice form each of the 64 state BITS is one int32 plane holding 32
candidates, so every permutation is a free wire-rename at trace time
and each S-box becomes a fixed boolean circuit (a 6-level mux tree
with constant folding, ~60 vector ops per output bit) -- pure int32
and/xor/andnot streams at full lane width, no gathers at all.

The table constants below are the DES specification itself (FIPS
46-3, public standard); the scalar reference implementation next to
them is the CPU oracle and the test anchor for the bitslice form.
Used by the LM-hash engine (engines/device/lm.py) and NetNTLMv1.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

# ---------------------------------------------------------------------------
# FIPS 46-3 tables (1-based bit indices, MSB-first, as published)

_IP = [58, 50, 42, 34, 26, 18, 10, 2, 60, 52, 44, 36, 28, 20, 12, 4,
       62, 54, 46, 38, 30, 22, 14, 6, 64, 56, 48, 40, 32, 24, 16, 8,
       57, 49, 41, 33, 25, 17, 9, 1, 59, 51, 43, 35, 27, 19, 11, 3,
       61, 53, 45, 37, 29, 21, 13, 5, 63, 55, 47, 39, 31, 23, 15, 7]

_FP = [40, 8, 48, 16, 56, 24, 64, 32, 39, 7, 47, 15, 55, 23, 63, 31,
       38, 6, 46, 14, 54, 22, 62, 30, 37, 5, 45, 13, 53, 21, 61, 29,
       36, 4, 44, 12, 52, 20, 60, 28, 35, 3, 43, 11, 51, 19, 59, 27,
       34, 2, 42, 10, 50, 18, 58, 26, 33, 1, 41, 9, 49, 17, 57, 25]

_E = [32, 1, 2, 3, 4, 5, 4, 5, 6, 7, 8, 9, 8, 9, 10, 11, 12, 13,
      12, 13, 14, 15, 16, 17, 16, 17, 18, 19, 20, 21, 20, 21, 22, 23,
      24, 25, 24, 25, 26, 27, 28, 29, 28, 29, 30, 31, 32, 1]

_P = [16, 7, 20, 21, 29, 12, 28, 17, 1, 15, 23, 26, 5, 18, 31, 10,
     2, 8, 24, 14, 32, 27, 3, 9, 19, 13, 30, 6, 22, 11, 4, 25]

_PC1 = [57, 49, 41, 33, 25, 17, 9, 1, 58, 50, 42, 34, 26, 18,
        10, 2, 59, 51, 43, 35, 27, 19, 11, 3, 60, 52, 44, 36,
        63, 55, 47, 39, 31, 23, 15, 7, 62, 54, 46, 38, 30, 22,
        14, 6, 61, 53, 45, 37, 29, 21, 13, 5, 28, 20, 12, 4]

_PC2 = [14, 17, 11, 24, 1, 5, 3, 28, 15, 6, 21, 10,
        23, 19, 12, 4, 26, 8, 16, 7, 27, 20, 13, 2,
        41, 52, 31, 37, 47, 55, 30, 40, 51, 45, 33, 48,
        44, 49, 39, 56, 34, 53, 46, 42, 50, 36, 29, 32]

_SHIFTS = [1, 1, 2, 2, 2, 2, 2, 2, 1, 2, 2, 2, 2, 2, 2, 1]

_S = [
    # S1
    [14, 4, 13, 1, 2, 15, 11, 8, 3, 10, 6, 12, 5, 9, 0, 7,
     0, 15, 7, 4, 14, 2, 13, 1, 10, 6, 12, 11, 9, 5, 3, 8,
     4, 1, 14, 8, 13, 6, 2, 11, 15, 12, 9, 7, 3, 10, 5, 0,
     15, 12, 8, 2, 4, 9, 1, 7, 5, 11, 3, 14, 10, 0, 6, 13],
    # S2
    [15, 1, 8, 14, 6, 11, 3, 4, 9, 7, 2, 13, 12, 0, 5, 10,
     3, 13, 4, 7, 15, 2, 8, 14, 12, 0, 1, 10, 6, 9, 11, 5,
     0, 14, 7, 11, 10, 4, 13, 1, 5, 8, 12, 6, 9, 3, 2, 15,
     13, 8, 10, 1, 3, 15, 4, 2, 11, 6, 7, 12, 0, 5, 14, 9],
    # S3
    [10, 0, 9, 14, 6, 3, 15, 5, 1, 13, 12, 7, 11, 4, 2, 8,
     13, 7, 0, 9, 3, 4, 6, 10, 2, 8, 5, 14, 12, 11, 15, 1,
     13, 6, 4, 9, 8, 15, 3, 0, 11, 1, 2, 12, 5, 10, 14, 7,
     1, 10, 13, 0, 6, 9, 8, 7, 4, 15, 14, 3, 11, 5, 2, 12],
    # S4
    [7, 13, 14, 3, 0, 6, 9, 10, 1, 2, 8, 5, 11, 12, 4, 15,
     13, 8, 11, 5, 6, 15, 0, 3, 4, 7, 2, 12, 1, 10, 14, 9,
     10, 6, 9, 0, 12, 11, 7, 13, 15, 1, 3, 14, 5, 2, 8, 4,
     3, 15, 0, 6, 10, 1, 13, 8, 9, 4, 5, 11, 12, 7, 2, 14],
    # S5
    [2, 12, 4, 1, 7, 10, 11, 6, 8, 5, 3, 15, 13, 0, 14, 9,
     14, 11, 2, 12, 4, 7, 13, 1, 5, 0, 15, 10, 3, 9, 8, 6,
     4, 2, 1, 11, 10, 13, 7, 8, 15, 9, 12, 5, 6, 3, 0, 14,
     11, 8, 12, 7, 1, 14, 2, 13, 6, 15, 0, 9, 10, 4, 5, 3],
    # S6
    [12, 1, 10, 15, 9, 2, 6, 8, 0, 13, 3, 4, 14, 7, 5, 11,
     10, 15, 4, 2, 7, 12, 9, 5, 6, 1, 13, 14, 0, 11, 3, 8,
     9, 14, 15, 5, 2, 8, 12, 3, 7, 0, 4, 10, 1, 13, 11, 6,
     4, 3, 2, 12, 9, 5, 15, 10, 11, 14, 1, 7, 6, 0, 8, 13],
    # S7
    [4, 11, 2, 14, 15, 0, 8, 13, 3, 12, 9, 7, 5, 10, 6, 1,
     13, 0, 11, 7, 4, 9, 1, 10, 14, 3, 5, 12, 2, 15, 8, 6,
     1, 4, 11, 13, 12, 3, 7, 14, 10, 15, 6, 8, 0, 5, 9, 2,
     6, 11, 13, 8, 1, 4, 10, 7, 9, 5, 0, 15, 14, 2, 3, 12],
    # S8
    [13, 2, 8, 4, 6, 15, 11, 1, 10, 9, 3, 14, 5, 0, 12, 7,
     1, 15, 13, 8, 10, 3, 7, 4, 12, 5, 6, 11, 0, 14, 9, 2,
     7, 11, 4, 1, 9, 12, 14, 2, 0, 6, 10, 13, 15, 3, 5, 8,
     2, 1, 14, 7, 4, 10, 8, 13, 15, 12, 9, 0, 3, 5, 6, 11]]


def _sbox_flat(box: int) -> list[int]:
    """S-box as a flat 64-entry table indexed by the 6 input bits in
    stream order b1..b6 (row = b1b6, column = b2b3b4b5)."""
    out = []
    for idx in range(64):
        b = [(idx >> (5 - k)) & 1 for k in range(6)]
        row = 2 * b[0] + b[5]
        col = 8 * b[1] + 4 * b[2] + 2 * b[3] + b[4]
        out.append(_S[box][16 * row + col])
    return out


_S_FLAT = [_sbox_flat(i) for i in range(8)]


# ---------------------------------------------------------------------------
# scalar reference (the CPU oracle path)

def _permute(bits: list[int], table: list[int]) -> list[int]:
    return [bits[t - 1] for t in table]


def _key_schedule_bits(key_bits: list[int]) -> list[list[int]]:
    kp = _permute(key_bits, _PC1)
    c, d = kp[:28], kp[28:]
    out = []
    for sh in _SHIFTS:
        c = c[sh:] + c[:sh]
        d = d[sh:] + d[:sh]
        out.append(_permute(c + d, _PC2))
    return out


def _to_bits(data: bytes) -> list[int]:
    return [(data[i // 8] >> (7 - i % 8)) & 1 for i in range(8 * len(data))]


def _from_bits(bits: list[int]) -> bytes:
    out = bytearray(len(bits) // 8)
    for i, b in enumerate(bits):
        out[i // 8] |= b << (7 - i % 8)
    return bytes(out)


def _rounds16(l, r, rks, e_table):
    """The 16 Feistel rounds (shared by des_encrypt and des_crypt25;
    descrypt passes a salt-perturbed E table)."""
    for rk in rks:
        e = _permute(r, e_table)
        x = [a ^ b for a, b in zip(e, rk)]
        s_out = []
        for box in range(8):
            six = x[6 * box:6 * box + 6]
            idx = 0
            for b in six:
                idx = (idx << 1) | b
            v = _S_FLAT[box][idx]
            s_out += [(v >> 3) & 1, (v >> 2) & 1, (v >> 1) & 1, v & 1]
        f = _permute(s_out, _P)
        l, r = r, [a ^ b for a, b in zip(l, f)]
    return l, r


def des_encrypt(key8: bytes, block8: bytes) -> bytes:
    """Scalar single-block DES encryption (oracle/test anchor)."""
    rks = _key_schedule_bits(_to_bits(key8))
    bits = _permute(_to_bits(block8), _IP)
    l, r = _rounds16(bits[:32], bits[32:], rks, _E)
    return _from_bits(_permute(r + l, _FP))


def str_to_key(seven: bytes) -> bytes:
    """7 key bytes -> 8 DES key bytes (parity bit positions unused by
    the cipher itself): the LM/NTLM key expansion."""
    assert len(seven) == 7
    b = seven
    k = [b[0] >> 1,
         ((b[0] & 0x01) << 6) | (b[1] >> 2),
         ((b[1] & 0x03) << 5) | (b[2] >> 3),
         ((b[2] & 0x07) << 4) | (b[3] >> 4),
         ((b[3] & 0x0F) << 3) | (b[4] >> 5),
         ((b[4] & 0x1F) << 2) | (b[5] >> 6),
         ((b[5] & 0x3F) << 1) | (b[6] >> 7),
         b[6] & 0x7F]
    return bytes(x << 1 for x in k)


LM_MAGIC = b"KGS!@#$%"


def lm_half(password_half: bytes) -> bytes:
    """LM hash of one 7-byte half: DES_{str_to_key(upper(half))}(magic).
    Strict: a half longer than 7 bytes is a caller bug (silent
    truncation once produced false 'cracks' whose plaintexts did not
    hash to the target)."""
    if len(password_half) > 7:
        raise ValueError("an LM half is at most 7 bytes")
    pw = password_half.upper().ljust(7, b"\x00")
    return des_encrypt(str_to_key(pw), LM_MAGIC)


# ---------------------------------------------------------------------------
# bitslice form: planes are int32 vectors, one bit-plane per DES wire;
# lane j of vector word v holds candidate v*32+j.

def _mux_tree(sels, leaves):
    """Constant-folded 6-level mux over {0,1} leaves.  sels are bit
    planes MSB-first; returns an int32 plane (python 0 / -1 for the
    degenerate constant cases).  out = sels[0] ? high_half : low_half."""
    import jax.numpy as jnp

    if len(leaves) == 1:
        return -leaves[0]          # 0 -> 0x0, 1 -> ~0 (all-ones mask)
    half = len(leaves) // 2
    lo = _mux_tree(sels[1:], leaves[:half])
    hi = _mux_tree(sels[1:], leaves[half:])
    s = sels[0]
    if isinstance(lo, int) and isinstance(hi, int):
        if lo == hi:
            return lo
        # (0, ~0) -> s; (~0, 0) -> ~s
        return s if lo == 0 else ~s
    if isinstance(lo, int):
        return (s & hi) if lo == 0 else (hi | ~s)
    if isinstance(hi, int):
        return (lo & ~s) if hi == 0 else (lo | s)
    return lo ^ (s & (lo ^ hi))


def sbox_planes(box: int, six):
    """One S-box as a boolean circuit: 6 input planes -> 4 output
    planes (MSB first)."""
    flat = _S_FLAT[box]
    outs = []
    for bit in (3, 2, 1, 0):
        leaves = [(v >> bit) & 1 for v in flat]
        outs.append(_mux_tree(list(six), leaves))
    return outs


def _bitslice_schedule(key_planes, as_row):
    """Static key schedule -> one stacked [16, 48, Bv] round-key array
    (pure re-wiring at trace time)."""
    import jax.numpy as jnp

    kp = [key_planes[t - 1] for t in _PC1]
    c, d = kp[:28], kp[28:]
    rks = []
    for sh in _SHIFTS:
        c = c[sh:] + c[:sh]
        d = d[sh:] + d[:sh]
        rks.append(jnp.stack([as_row((c + d)[t - 1]) for t in _PC2]))
    return jnp.stack(rks)


def _bitslice_round_body(rk_all, e_table, as_row):
    """One traced Feistel round over [32, Bv] half planes; `e_table`
    is the (possibly salt-perturbed) E expansion as static row-takes."""
    import jax.numpy as jnp

    e_idx = jnp.asarray(np.asarray(e_table, np.int32) - 1)
    p_idx = jnp.asarray(np.asarray(_P, np.int32) - 1)

    def round_body(i, carry):
        l, r = carry
        x = r[e_idx] ^ rk_all[i]                 # [48, Bv]
        s_out = []
        for box in range(8):
            s_out += sbox_planes(box, [x[6 * box + k]
                                       for k in range(6)])
        f = jnp.stack([as_row(p) for p in s_out])[p_idx]
        return r, l ^ f

    return round_body


def des_encrypt_bitslice(key_planes, data_planes):
    """Bitslice DES: key_planes[64], data_planes[64] (int32 planes or
    0/-1 python constants, FIPS bit order 1..64) -> cipher planes[64].

    The 16 rounds run in a lax.fori_loop over PRE-WIRED round-key
    planes (the whole key schedule is static reindexing, materialized
    once as a [16, 48, Bv] array), so only ONE round body -- 48 xors +
    8 S-box mux circuits + 32 xors, with the E and P permutations as
    static row-takes -- is traced and compiled.  A fully unrolled form
    (~31k ops) takes XLA:CPU minutes to compile, the same lesson as
    the unrolled SHA-256 kernel.
    """
    import jax.numpy as jnp
    from jax import lax

    # find a concrete plane to learn Bv (keys always carry >= 56 real
    # planes; all-constant keys are not a cracking workload)
    proto = next(p for p in list(key_planes) + list(data_planes)
                 if not isinstance(p, int))
    Bv = proto.shape[0]

    def as_row(p):
        if isinstance(p, int):
            return jnp.full((Bv,), jnp.int32(p))
        return p

    rk_all = _bitslice_schedule(key_planes, as_row)

    bits = [data_planes[t - 1] for t in _IP]
    l = jnp.stack([as_row(p) for p in bits[:32]])   # [32, Bv]
    r = jnp.stack([as_row(p) for p in bits[32:]])

    round_body = _bitslice_round_body(rk_all, _E, as_row)
    l, r = lax.fori_loop(0, 16, round_body, (l, r))
    out = jnp.concatenate([r, l])                # pre-FP bit order
    return [out[t - 1] for t in _FP]


def const_planes(data: bytes) -> list[int]:
    """Constant data (e.g. the LM magic or a challenge) as degenerate
    0 / ~0 planes."""
    return [-b for b in _to_bits(data)]


def key_planes_from_bytes7(byte_planes: Sequence):
    """56 byte-bit planes (7 bytes x 8 bits, MSB-first per byte) ->
    64 DES-key planes via the str_to_key expansion (pure wiring: key
    byte k bit positions 1..7 are password bits, bit 8 is parity =
    constant 0 plane)."""
    # password bit stream p0..p55 (MSB of byte 0 first); str_to_key
    # places stream bits 7k..7k+6 into key byte k bits 1..7 (1-based
    # MSB order), parity bit 8 unused by the cipher.
    planes = []
    for k in range(8):
        for bit in range(7):
            planes.append(byte_planes[7 * k + bit])
        planes.append(0)      # parity position
    return planes


# ---------------------------------------------------------------------------
# descrypt (traditional crypt(3), hashcat 1500): 25 chained DES
# encryptions of the zero block under a salt-perturbed E expansion.

def _salted_e_table(salt: int) -> list[int]:
    """The crypt(3) salt perturbation: for each of the 12 salt bits
    that is set, E-expansion outputs i and i+24 swap (1-based FIPS
    table entries)."""
    e = list(_E)
    for i in range(12):
        if (salt >> i) & 1:
            e[i], e[i + 24] = e[i + 24], e[i]
    return e


def descrypt_key8(password: bytes) -> bytes:
    """crypt(3) key: the low 7 bits of each of the first 8 password
    bytes, left-shifted into DES key bit positions 1..7."""
    pw = password[:8].ljust(8, b"\x00")
    return bytes((c << 1) & 0xFF for c in pw)


def des_crypt25(key8: bytes, salt: int) -> bytes:
    """Scalar descrypt core (oracle/test anchor): 25 iterations of
    salt-perturbed DES on the zero block; returns the 8-byte (64-bit)
    ciphertext."""
    rks = _key_schedule_bits(_to_bits(key8))
    e_table = _salted_e_table(salt)
    l, r = [0] * 32, [0] * 32                  # IP(zero block)
    for _ in range(25):
        l, r = _rounds16(l, r, rks, e_table)
        # the end-of-encryption swap feeds the next iteration
        # (FP then IP between iterations cancel)
        l, r = r, l
    return _from_bits(_permute(l + r, _FP))


def descrypt_bitslice(key_planes, salt: int):
    """Bitslice descrypt: key_planes[64] (FIPS order; from
    (password << 1) byte planes) -> 64 cipher planes.  The salt is a
    TRACE-TIME constant -- the E swaps are free re-wiring of the
    static row-take index, so one compiled step serves one salt (the
    worker compiles per target; targets sharing a salt could share)."""
    import jax.numpy as jnp
    from jax import lax

    proto = next(p for p in key_planes if not isinstance(p, int))
    Bv = proto.shape[0]

    def as_row(p):
        if isinstance(p, int):
            return jnp.full((Bv,), jnp.int32(p))
        return p

    rk_all = _bitslice_schedule(key_planes, as_row)
    round_body = _bitslice_round_body(rk_all, _salted_e_table(salt),
                                      as_row)

    def outer(j, carry):
        l, r = lax.fori_loop(0, 16, round_body, carry)
        return r, l                             # end-of-encrypt swap

    zero = jnp.zeros((32, Bv), jnp.int32)
    l, r = lax.fori_loop(0, 25, outer, (zero, zero))
    out = jnp.concatenate([l, r])               # pre-FP order
    return [out[t - 1] for t in _FP]
