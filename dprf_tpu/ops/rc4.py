"""RC4 keystream prefix, vectorized over a candidate batch.

Only the Kerberos etype-23 filter needs RC4 on device, and it needs
just the first FEW keystream words (the DER header of the decrypted
ticket sits at offset 8, after RFC 4757's random confounder, and is
deterministic — see engines/device/krb5.py), so this op stops after
the KSA plus a statically-unrolled short PRGA.

TPU mapping: the 256-byte S state lives as an int32[B, 256] array —
swaps at the loop counter are dynamic column slices (the counter is
uniform across lanes), while the data-dependent j side is a per-lane
`take_along_axis` gather + one-position scatter, the same
batch-dimension pattern as the bcrypt S-boxes.  RC4's KSA is
inherently sequential (256 chained swaps), so the loop body is a
`lax.fori_loop`; throughput comes from the batch dimension.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def _swap(S: jnp.ndarray, i, si: jnp.ndarray,
          j: jnp.ndarray, sj: jnp.ndarray) -> jnp.ndarray:
    """S[:, i], S[lane, j[lane]] = sj, si — correct when j == i for a
    lane, because the per-lane scatter lands second."""
    B = S.shape[0]
    S = lax.dynamic_update_slice_in_dim(S, sj[:, None], i, axis=1)
    return S.at[jnp.arange(B), j].set(si)


def words_to_bytes(words: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, W] LE words -> int32[B, 4W] bytes."""
    B, W = words.shape
    shifts = jnp.asarray([0, 8, 16, 24], jnp.uint32)
    return ((words[:, :, None] >> shifts[None, None, :]) &
            jnp.uint32(0xFF)).reshape(B, 4 * W).astype(jnp.int32)


def rc4_ksa(key_bytes: jnp.ndarray) -> jnp.ndarray:
    """KSA for per-candidate keys: key_bytes int32[B, K] (K static)
    -> S int32[B, 256]."""
    B, K = key_bytes.shape
    S0 = jnp.broadcast_to(jnp.arange(256, dtype=jnp.int32),
                          (B, 256))
    j0 = jnp.zeros((B,), jnp.int32)

    def ksa(i, carry):
        S, j = carry
        si = lax.dynamic_slice_in_dim(S, i, 1, axis=1)[:, 0]
        ki = lax.dynamic_slice_in_dim(key_bytes, i % K, 1,
                                      axis=1)[:, 0]
        j = (j + si + ki) & 255
        sj = jnp.take_along_axis(S, j[:, None], axis=1)[:, 0]
        return _swap(S, i, si, j, sj), j

    S, _ = lax.fori_loop(0, 256, ksa, (S0, j0))
    return S


def rc4_keystream_bytes(key_bytes: jnp.ndarray,
                        nwords: int) -> jnp.ndarray:
    """First `nwords` 32-bit keystream words for per-candidate keys of
    any (static) length: key_bytes int32[B, K] -> uint32[B, nwords],
    each word packing 4 keystream bytes LE (byte 4w+t at shift 8t).
    The single PRGA implementation — every RC4 consumer (krb5 XLA
    filter, PDF R2/R3 checks) goes through here."""
    B = key_bytes.shape[0]
    S = rc4_ksa(key_bytes)
    j = jnp.zeros((B,), jnp.int32)
    words = []
    word = jnp.zeros((B,), jnp.uint32)
    for t in range(4 * nwords):     # PRGA, static i = t + 1
        i = t + 1
        si = S[:, i]
        j = (j + si) & 255
        sj = jnp.take_along_axis(S, j[:, None], axis=1)[:, 0]
        S = _swap(S, i, si, j, sj)
        k = jnp.take_along_axis(S, ((si + sj) & 255)[:, None],
                                axis=1)[:, 0]
        word = word | (k.astype(jnp.uint32) << (8 * (t % 4)))
        if t % 4 == 3:
            words.append(word)
            word = jnp.zeros((B,), jnp.uint32)
    return jnp.stack(words, axis=1)


def rc4_keystream_words(key4: jnp.ndarray, nwords: int) -> jnp.ndarray:
    """rc4_keystream_bytes for 16-byte keys given as uint32[B, 4] LE
    words (e.g. an MD5 digest straight from `md5_compress`)."""
    return rc4_keystream_bytes(words_to_bytes(key4), nwords)


def rc4_apply16(key_bytes: jnp.ndarray,
                data4: jnp.ndarray) -> jnp.ndarray:
    """RC4-transform a 16-byte buffer per candidate (the PDF R3+
    U-check runs 20 of these): key_bytes int32[B, K], data4
    uint32[B, 4] LE words -> uint32[B, 4].  A stream cipher is just
    keystream XOR."""
    return data4 ^ rc4_keystream_bytes(key_bytes, 4)


def rc4_keystream_words_reference(key: bytes, nwords: int) -> list[int]:
    """Host-side oracle for tests: same packed LE words from pure
    Python RC4 (engines/cpu/krb5.py)."""
    from dprf_tpu.engines.cpu.krb5 import rc4
    ks = rc4(key, bytes(4 * nwords))
    return [int.from_bytes(ks[4 * w:4 * w + 4], "little")
            for w in range(nwords)]
