"""Candidate packing: uint8 candidate bytes -> Merkle-Damgard message words.

All functions are jit-traceable with static candidate length (the mask
path -- every candidate in a batch shares one length) or traced lengths
(the wordlist path).  Words are built with integer multiply-adds rather
than bitcasts so behavior is identical on the TPU and CPU XLA backends.

Single-block only: candidates up to 55 bytes (27 chars for NTLM's
UTF-16LE widening), which covers every benchmark config; multi-block
chaining for long inputs goes through the engines' `compress` functions
directly (see HMAC in ops/sha1.py usage).
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

_LE_COEF = np.array([1, 1 << 8, 1 << 16, 1 << 24], dtype=np.uint32)
_BE_COEF = _LE_COEF[::-1].copy()


def _words_from_bytes(msg: jnp.ndarray, big_endian: bool) -> jnp.ndarray:
    """uint8[B, 64] -> uint32[B, 16]."""
    coef = jnp.asarray(_BE_COEF if big_endian else _LE_COEF)
    grouped = msg.reshape(*msg.shape[:-1], 16, 4).astype(jnp.uint32)
    return (grouped * coef).sum(axis=-1, dtype=jnp.uint32)


def _pad_const(length: int, big_endian: bool) -> np.ndarray:
    """Static MD padding for a fixed message length: 0x80 marker + 64-bit
    bit count (LE for MD4/MD5, BE for SHA-1/SHA-256)."""
    if length > 55:
        raise ValueError(f"single-block packing needs length <= 55, got {length}")
    const = np.zeros(64, dtype=np.uint8)
    const[length] = 0x80
    bitlen = length * 8
    if big_endian:
        const[56:64] = np.frombuffer(bitlen.to_bytes(8, "big"), dtype=np.uint8)
    else:
        const[56:64] = np.frombuffer(bitlen.to_bytes(8, "little"), dtype=np.uint8)
    return const


def pack_fixed(cand: jnp.ndarray, length: int,
               big_endian: bool = False) -> jnp.ndarray:
    """Pack fixed-length candidates uint8[B, length] -> uint32[B, 16].

    `length` is static, so the padding bytes are a compile-time constant
    XLA folds straight into the fused kernel.
    """
    batch = cand.shape[0]
    padded = jnp.zeros((batch, 64), dtype=jnp.uint8).at[:, :length].set(cand)
    msg = padded + jnp.asarray(_pad_const(length, big_endian))
    return _words_from_bytes(msg, big_endian)


def pack_varlen(cand: jnp.ndarray, lengths: jnp.ndarray,
                big_endian: bool = False) -> jnp.ndarray:
    """Pack variable-length candidates uint8[B, maxlen] -> uint32[B, 16].

    lengths: int32[B] actual byte counts (<= 55).  The 0x80 marker and
    bit-count are placed per lane with vectorized selects -- no gathers,
    no dynamic shapes.
    """
    batch, maxlen = cand.shape
    if maxlen > 55:
        raise ValueError("single-block packing needs maxlen <= 55")
    pos = jnp.arange(64, dtype=jnp.int32)
    lens = lengths[:, None]
    padded = jnp.zeros((batch, 64), dtype=jnp.uint8).at[:, :maxlen].set(cand)
    msg = jnp.where(pos < lens, padded, 0).astype(jnp.uint8)
    msg = msg + jnp.where(pos == lens, jnp.uint8(0x80), jnp.uint8(0))
    words = _words_from_bytes(msg, big_endian)
    bits = (lengths.astype(jnp.uint32) * 8)
    if big_endian:
        # bit count < 2^32 always (len <= 55): high word 14 stays 0.
        words = words.at[:, 15].set(bits)
    else:
        words = words.at[:, 14].set(bits)
    return words


def _words_from_bytes_wide(msg: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, 128] -> uint32[B, 32] big-endian (SHA-512 block)."""
    grouped = msg.reshape(*msg.shape[:-1], 32, 4).astype(jnp.uint32)
    return (grouped * jnp.asarray(_BE_COEF)).sum(axis=-1, dtype=jnp.uint32)


def pack_fixed_wide(cand: jnp.ndarray, length: int) -> jnp.ndarray:
    """Fixed-length candidates uint8[B, length] -> one 128-byte SHA-512
    block as uint32[B, 32] (big-endian words; 128-bit length field, of
    which only the low 32 bits can be nonzero for single-block input).
    """
    if length > 111:
        raise ValueError(
            f"single-block SHA-512 packing needs length <= 111, "
            f"got {length}")
    batch = cand.shape[0]
    const = np.zeros(128, dtype=np.uint8)
    const[length] = 0x80
    const[120:128] = np.frombuffer((length * 8).to_bytes(8, "big"),
                                   dtype=np.uint8)
    padded = jnp.zeros((batch, 128),
                       dtype=jnp.uint8).at[:, :length].set(cand)
    return _words_from_bytes_wide(padded + jnp.asarray(const))


def pack_varlen_wide(cand: jnp.ndarray, lengths: jnp.ndarray) -> jnp.ndarray:
    """Variable-length candidates uint8[B, maxlen] (lengths <= 111) ->
    uint32[B, 32] SHA-512 blocks, vectorized like pack_varlen."""
    batch, maxlen = cand.shape
    if maxlen > 111:
        raise ValueError("single-block SHA-512 packing needs maxlen <= 111")
    pos = jnp.arange(128, dtype=jnp.int32)
    lens = lengths[:, None]
    padded = jnp.zeros((batch, 128),
                       dtype=jnp.uint8).at[:, :maxlen].set(cand)
    msg = jnp.where(pos < lens, padded, 0).astype(jnp.uint8)
    msg = msg + jnp.where(pos == lens, jnp.uint8(0x80), jnp.uint8(0))
    words = _words_from_bytes_wide(msg)
    return words.at[:, 31].set(lengths.astype(jnp.uint32) * 8)


def pack_raw(cand: jnp.ndarray, length: int,
             big_endian: bool = True) -> jnp.ndarray:
    """Pack bytes into a full 64-byte block with ZERO padding (no MD
    marker/bit count) -- the HMAC key-block layout, where a short key is
    zero-extended to the block size."""
    if length > 64:
        raise ValueError(f"key block packing needs length <= 64, got {length}")
    batch = cand.shape[0]
    padded = jnp.zeros((batch, 64), dtype=jnp.uint8).at[:, :length].set(cand)
    return _words_from_bytes(padded, big_endian)


def utf16le_widen(cand: jnp.ndarray) -> jnp.ndarray:
    """uint8[B, L] latin-1 bytes -> uint8[B, 2L] UTF-16LE (NTLM input)."""
    batch, length = cand.shape
    wide = jnp.zeros((batch, length, 2), dtype=jnp.uint8).at[:, :, 0].set(cand)
    return wide.reshape(batch, 2 * length)
