"""MD5 compression (RFC 1321) as vectorized uint32 jnp ops.

The 64 steps are unrolled at trace time into straight-line int32 vector
code over the batch dimension -- exactly the shape XLA's TPU backend
vectorizes onto the VPU (8x128 lanes) with every temporary in registers
/VMEM.  The sine-derived constants are computed here (math.sin), not
copied from a listing.

Also exports an initial state + compress pair so multi-block uses
(HMAC, long inputs) can chain.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

K = np.array([int(abs(math.sin(i + 1)) * (1 << 32)) & 0xFFFFFFFF
              for i in range(64)], dtype=np.uint32)
_SHIFTS = ((7, 12, 17, 22), (5, 9, 14, 20), (4, 11, 16, 23), (6, 10, 15, 21))
INIT = np.array([0x67452301, 0xEFCDAB89, 0x98BADCFE, 0x10325476],
                dtype=np.uint32)


def _rotl(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x << jnp.uint32(n)) | (x >> jnp.uint32(32 - n))


def md5_rounds(a, b, c, d, m):
    """The 64 MD5 steps over any uint32 array shape (no feed-forward).

    m: sequence of 16 message-word arrays.  Shared by the XLA path
    (md5_compress) and the Pallas kernel (ops/pallas_mask.py) so the
    round structure has a single source of truth.
    """
    for i in range(64):
        rnd = i // 16
        if rnd == 0:
            f = (b & c) | (~b & d)
            g = i
        elif rnd == 1:
            f = (d & b) | (~d & c)
            g = (5 * i + 1) % 16
        elif rnd == 2:
            f = b ^ c ^ d
            g = (3 * i + 5) % 16
        else:
            f = c ^ (b | ~d)
            g = (7 * i) % 16
        tmp = a + f + jnp.uint32(int(K[i])) + m[g]
        a, d, c, b = d, c, b, (b + _rotl(tmp, _SHIFTS[rnd][i % 4]))
    return a, b, c, d


def md5_compress(state: jnp.ndarray, words: jnp.ndarray) -> jnp.ndarray:
    """state uint32[..., 4] x words uint32[..., 16] -> uint32[..., 4]."""
    a, b, c, d = md5_rounds(*(state[..., i] for i in range(4)),
                            [words[..., i] for i in range(16)])
    # Davies-Meyer feed-forward: add the *input* chaining state (not
    # INIT -- they only coincide on the first block).
    return jnp.stack([a, b, c, d], axis=-1) + state


def md5_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    """Single-block MD5: uint32[B, 16] packed message -> uint32[B, 4]
    little-endian digest words (word i = digest bytes 4i..4i+3 LE)."""
    state = jnp.broadcast_to(jnp.asarray(INIT), words.shape[:-1] + (4,))
    return md5_compress(state, words)
