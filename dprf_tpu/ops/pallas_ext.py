"""Extended fused mask kernels: salted, nested, and mysql41 variants.

VERDICT r3 #3: the hand-written Pallas kernel path covered only the
four unsalted single-block engines, leaving every other fast engine on
the XLA pipeline whose per-byte charset gather runs ~300x slower than
the kernel decode (12.6 MH/s vs 4.1 GH/s measured on TPU v5 lite).
The families this module covers all consume one or two 64-byte blocks
of the exact same compression cores, so they reuse pallas_mask's
decode machinery with a different message build / digest chain:

Markov/scrambled charsets decode here through the same lane-axis LUT
input as pallas_mask (position_tables): these families are in the
plain-mask speed class, where the unbounded segment mux's ~190 extra
VPU ops per worst-case Markov ?a position would have cost up to 2x.

- **salted** ``$pass.$salt`` / ``$salt.$pass`` md5/sha1/sha256
  (hashcat 10/20, 110/120, 1410/1420, plus postgres and LDAP {SSHA}
  which ride the same classes): the salt BYTES and the target digest
  are runtime SMEM scalars -- one compiled kernel per (mask,
  salt-length) serves every target, mirroring the XLA salted step's
  one-compile-for-the-hashlist design.  The salt length must be
  static (it fixes each message byte's position), and distinct salt
  lengths in a hashlist are a handful at most.
- **nested** ``outer(hex(inner(password)))`` (hashcat 2600/4500/4400/
  4700/20800/20700): the inner digest is hex-encoded in registers
  (nibble->char arithmetic, no gather) and fed to the outer
  compression.  Single- and multi-target (Bloom) compare both work,
  so these slot into the existing PallasMaskWorker unchanged.
- **mysql41** sha1(sha1($p)) over the RAW inner digest (hashcat 300):
  the inner digest words ARE the outer block words.

The kernel bodies follow pallas_mask's contract exactly -- pure
(pid, base digits, n_valid, [runtime scalars]) -> (count, hit_lane)
-- and reuse its packed (8, 128) output trick, tile reducers, Bloom
prefilter, and eligibility plumbing (pallas_mask.kernel_eligible and
the step factories dispatch here for non-CORES engine names).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops.pallas_mask import (CORES, MAX_TARGETS, SET_SIZE, SUB,
                                      _pack_message, bloom_found,
                                      bloom_tables,
                                      check_batch,
                                      decode_candidate_bytes,
                                      position_tables,
                                      mask_supported, reduce_tile_hits,
                                      reduce_tile_maybes)

#: nested combos this kernel supports: outer(hex(inner)).  The inner
#: hex (32 or 40 bytes) must fit one outer block; sha256 inner (64
#: hex bytes) would need two-block chaining, same rule as the XLA
#: nested engines.
NESTED_COMBOS = {
    "md5(md5)": ("md5", "md5"),
    "sha1(sha1)": ("sha1", "sha1"),
    "md5(sha1)": ("md5", "sha1"),
    "sha1(md5)": ("sha1", "md5"),
    "sha256(md5)": ("sha256", "md5"),
    "sha256(sha1)": ("sha256", "sha1"),
}

#: salted base algorithms with kernel cores (sha512 is 64-bit-word,
#: no core; mssql's UTF-16LE pre-salt widening is not built yet).
SALTED_ALGOS = ("md5", "sha1", "sha256")

#: single-block message byte budget (64 - 1 pad - 8 length).
BLOCK_LIMIT = 55


def _uses_sha256(name: str) -> bool:
    return "sha256" in name


def _tpu_ok_for(name: str) -> bool:
    """sha256 stages compile through Mosaic fine but take XLA:CPU many
    minutes (statically unrolled rounds) -- TPU-only, like the plain
    sha256 kernel."""
    if not _uses_sha256(name):
        return True
    return jax.default_backend() == "tpu"


def nested_eligible(engine_name: str, gen, n_targets: int) -> bool:
    """Eligibility for the nested/mysql41 kernel path (the dispatch
    target of pallas_mask.kernel_eligible for non-CORES names)."""
    if engine_name != "mysql41" and engine_name not in NESTED_COMBOS:
        return False
    if not 1 <= n_targets <= MAX_TARGETS:
        return False
    if not hasattr(gen, "charsets"):
        return False
    if not _tpu_ok_for(engine_name):
        return False
    return gen.length <= BLOCK_LIMIT and mask_supported(gen.charsets)


def salted_eligible(engine_algo: str, order: str, gen,
                    salt_lens: Sequence[int]) -> bool:
    """Eligibility for the salted kernel path.  `salt_lens` are the
    job's ACTUAL salt lengths (each compiles its own kernel)."""
    if engine_algo not in SALTED_ALGOS or order not in ("ps", "sp"):
        return False
    if not hasattr(gen, "charsets"):
        return False
    if not _tpu_ok_for(engine_algo):
        return False
    if not salt_lens or len(set(salt_lens)) > 8:
        # a hashlist with many distinct salt lengths would compile a
        # kernel per length; past a handful the XLA step (one compile
        # total) is the better trade
        return False
    return (gen.length + max(salt_lens) <= BLOCK_LIMIT
            and mask_supported(gen.charsets))


def _hex_byts(digest, little_endian: bool):
    """Digest word arrays -> list of 8W lowercase-hex byte arrays in
    the digest's canonical byte order (registers only, no gather)."""
    shifts = (0, 8, 16, 24) if little_endian else (24, 16, 8, 0)
    out = []
    for w in digest:
        for s in shifts:
            b = (w >> jnp.uint32(s)) & jnp.uint32(0xFF)
            for nib in (b >> jnp.uint32(4), b & jnp.uint32(0xF)):
                out.append(nib + jnp.where(nib < 10, jnp.uint32(ord("0")),
                                           jnp.uint32(ord("a") - 10)))
    return out


def _digest_chain(name: str, m, shape):
    """Message words -> final digest tuple for any supported variant
    name ('md5', 'sha1(md5)', 'mysql41', ...)."""
    if name == "mysql41":
        inner = CORES["sha1"][0](m, shape)
        m2 = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
        for i, w in enumerate(inner):
            m2[i] = w
        m2[5] = jnp.full(shape, jnp.uint32(0x80000000))
        m2[15] = jnp.full(shape, jnp.uint32(160))      # 20 bytes
        return CORES["sha1"][0](m2, shape)
    if name in NESTED_COMBOS:
        outer, inner = NESTED_COMBOS[name]
        icore, iw, ibig, _ = CORES[inner]
        ocore, _, obig, _ = CORES[outer]
        d = icore(m, shape)
        hexb = _hex_byts(d, little_endian=not ibig)
        m2 = _pack_message(hexb, len(hexb), shape, obig, False)
        return ocore(m2, shape)
    return CORES[name][0](m, shape)


def variant_words(name: str) -> tuple[int, bool]:
    """(digest words, big_endian) of a variant's FINAL digest."""
    if name == "mysql41":
        return 5, True
    if name in NESTED_COMBOS:
        outer = NESTED_COMBOS[name][0]
        return CORES[outer][1], CORES[outer][2]
    return CORES[name][1], CORES[name][2]


def _inner_big_endian(name: str) -> bool:
    """Byte order of the FIRST block (what the candidate packs into)."""
    if name == "mysql41":
        return True
    if name in NESTED_COMBOS:
        return CORES[NESTED_COMBOS[name][1]][2]
    return CORES[name][2]


def _build_ext_body(name: str, radices, seg_tables, length: int,
                    target, sub: int, order: Optional[str] = None,
                    salt_len: int = 0, has_lut: bool = False):
    """Kernel math as a pure function.  Two shapes:

    - nested/mysql41 (order None): (pid, base, n_valid[, tables])
      -> (count, hit_lane); target is trace-time (uint32[W] single or
      uint32[N, W] Bloom multi), exactly like pallas_mask.
    - salted (order 'ps'/'sp'): (pid, base, n_valid, salt, tgt)
      -> (count, hit_lane); salt bytes (int32[>=salt_len]) and target
      words (uint32[W]) are RUNTIME scalar refs, salt_len is static.
    """
    n_words, _ = variant_words(name)
    big_endian = _inner_big_endian(name)
    tile = sub * 128
    salted = order is not None
    if salted:
        if length + salt_len > BLOCK_LIMIT:
            raise ValueError("candidate+salt exceeds one block")
        multi = False
        tw = None
    else:
        target = np.asarray(target)
        multi = target.ndim == 2 and target.shape[0] > 1
        if multi:
            n_sets = -(-target.shape[0] // SET_SIZE)
            tw = None
        else:
            tw = [int(w) for w in target.reshape(-1)]
            if len(tw) != n_words:
                raise ValueError(f"{name}: expected {n_words} "
                                 "target words")

    def body(pid, base, n_valid, *rest):
        # rest order: [tables (multi) | salt, tgt (salted)] then, when
        # the mask has LUT positions, the charset LUT rows LAST
        rest = list(rest)
        luts = rest.pop() if has_lut else None
        shape = (sub, 128)
        lane = (jax.lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
        carry = lane + pid * tile
        cand = decode_candidate_bytes(radices, seg_tables, length,
                                      base, carry, luts)
        if salted:
            salt_ref, tgt_ref = rest
            salt_b = [salt_ref[j].astype(jnp.uint32)
                      for j in range(salt_len)]
            byts = cand + salt_b if order == "ps" else salt_b + cand
        else:
            byts = cand
        m = _pack_message(byts, len(byts), shape, big_endian, False)
        digest = _digest_chain(name, m, shape)
        valid = (lane + pid * tile) < n_valid
        if salted:
            found = valid
            for i, got in enumerate(digest):
                # int32 -> uint32 astype is modular, preserving the
                # bit pattern (scalar bitcast doesn't lower on Mosaic)
                want = tgt_ref[i].astype(jnp.uint32)
                found = found & (got == want)
        elif not multi:
            found = valid
            for got, want in zip(digest, tw):
                found = found & (got == jnp.uint32(want))
        else:
            found = bloom_found(digest, rest[0], valid, n_sets, shape)
        count = jnp.sum(found.astype(jnp.int32))
        hit_lane = jnp.max(jnp.where(found, lane, -1))
        return count, hit_lane

    return body


# shared packed-output factory guard (pallas_mask.check_batch)
_check_batch = check_batch


def make_ext_pallas_fn(name: str, gen, target_words, batch: int,
                       sub: int = SUB, interpret: bool = False):
    """Nested/mysql41 variant of pallas_mask.make_mask_pallas_fn:
    fn(base_digits, n_valid) -> (counts[G,1], hit_lanes[G,1])."""
    tile = sub * 128
    grid = _check_batch(batch, sub)
    target_words = np.asarray(target_words)
    multi = target_words.ndim == 2 and target_words.shape[0] > 1
    if not nested_eligible(name, gen,
                           target_words.shape[0] if multi else 1):
        raise ValueError(f"{name} mask job not ext-kernel-eligible")
    seg_tables, luts_np = position_tables(gen.charsets)
    has_lut = luts_np is not None
    body = _build_ext_body(name, gen.radices, seg_tables, gen.length,
                           target_words, sub, has_lut=has_lut)

    def kernel(base_ref, nvalid_ref, *rest):
        out_ref = rest[-1]
        count, hit_lane = body(pl.program_id(0), base_ref,
                               nvalid_ref[0], *rest[:-1])
        out_ref[...] = jnp.full((8, 128), (count << 16) | (hit_lane + 1),
                                jnp.int32)

    L = gen.length
    in_specs = [
        pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
    ]
    if multi:
        tables = bloom_tables(target_words)
        in_specs.append(pl.BlockSpec((tables.shape[0], 128),
                                     lambda i: (0, 0)))
    if has_lut:
        in_specs.append(pl.BlockSpec(luts_np.shape, lambda i: (0, 0)))
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32)],
        interpret=interpret,
    )
    tables_dev = jnp.asarray(tables) if multi else None
    luts_dev = jnp.asarray(luts_np) if has_lut else None

    def fn(base_digits, n_valid):
        args = [base_digits, n_valid]
        if multi:
            args.append(tables_dev)
        if has_lut:
            args.append(luts_dev)
        (packed,) = raw(*args)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_salted_pallas_fn(algo: str, order: str, gen, batch: int,
                          salt_len: int, sub: int = SUB,
                          interpret: bool = False):
    """Salted kernel: fn(base_digits, n_valid int32[1],
    salt int32[salt_len..], target int32[W]) -> (counts, hit_lanes).
    Salt bytes and target words are runtime; one compiled fn per
    (mask, salt_len) serves every same-length target."""
    tile = sub * 128
    grid = _check_batch(batch, sub)
    if not salted_eligible(algo, order, gen, [salt_len]):
        raise ValueError(f"{algo}-{order} mask job not kernel-eligible")
    n_words, _ = variant_words(algo)
    seg_tables, luts_np = position_tables(gen.charsets)
    has_lut = luts_np is not None
    body = _build_ext_body(algo, gen.radices, seg_tables, gen.length,
                           None, sub, order=order, salt_len=salt_len,
                           has_lut=has_lut)
    SW = max(salt_len, 1)

    def kernel(base_ref, nvalid_ref, *rest):
        out_ref = rest[-1]
        count, hit_lane = body(pl.program_id(0), base_ref,
                               nvalid_ref[0], *rest[:-1])
        out_ref[...] = jnp.full((8, 128), (count << 16) | (hit_lane + 1),
                                jnp.int32)

    L = gen.length
    in_specs = [
        pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((SW,), lambda i: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((n_words,), lambda i: (0,),
                     memory_space=pltpu.SMEM),
    ]
    if has_lut:
        in_specs.append(pl.BlockSpec(luts_np.shape, lambda i: (0, 0)))
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32)],
        interpret=interpret,
    )
    luts_dev = jnp.asarray(luts_np) if has_lut else None

    def fn(base_digits, n_valid, salt, target):
        args = [base_digits, n_valid, salt[:SW], target]
        if has_lut:
            args.append(luts_dev)
        (packed,) = raw(*args)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_ext_mask_crack_step(name: str, gen, target_words, batch: int,
                             hit_capacity: int = 64,
                             interpret: bool = False):
    """Single-target nested/mysql41 crack step with the standard
    (count, lanes, tpos) contract."""
    tile = SUB * 128
    fn = make_ext_pallas_fn(name, gen, target_words, batch,
                            interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step


def make_ext_multi_crack_step(name: str, gen, target_words, batch: int,
                              hit_capacity: int = 64,
                              rescan_capacity: int = 16,
                              interpret: bool = False):
    """Multi-target (Bloom) nested/mysql41 crack step; contract of
    pallas_mask.make_pallas_multi_crack_step."""
    tile = SUB * 128
    fn = make_ext_pallas_fn(name, gen, target_words, batch,
                            interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_maybes(counts, hit_lanes, hit_capacity,
                                  rescan_capacity, tile)

    return step


def make_salted_crack_step(algo: str, order: str, gen, batch: int,
                           salt_len: int, hit_capacity: int = 64,
                           interpret: bool = False):
    """Salted kernel crack step:
    step(base_digits, n_valid, salt int32[SALT_MAX], target int32[W])
    -> (count, lanes, tpos) -- the SaltedMaskWorker._invoke contract
    with runtime per-target args."""
    tile = SUB * 128
    fn = make_salted_pallas_fn(algo, order, gen, batch, salt_len,
                               interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid, salt, target):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32),
                               salt.astype(jnp.int32), target)
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step


def emulate_ext_kernel(name: str, gen, target_words, batch: int,
                       base_digits, n_valid: int, sub: int = SUB,
                       order: Optional[str] = None,
                       salt: Optional[bytes] = None):
    """Run a variant body eagerly per grid cell (no pallas_call) --
    the validation vehicle for sha256-stage variants off-TPU, exactly
    like pallas_mask.emulate_mask_kernel."""
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    seg_tables, luts_np = position_tables(gen.charsets)
    has_lut = luts_np is not None
    salted = order is not None
    tables = None
    if salted:
        body = _build_ext_body(name, gen.radices, seg_tables, gen.length,
                               None, sub, order=order, salt_len=len(salt),
                               has_lut=has_lut)
        target_words = np.asarray(target_words)
        extra = (jnp.asarray(np.frombuffer(salt, np.uint8)
                             .astype(np.int32)),
                 jnp.asarray(target_words.astype(np.uint32)
                             .view(np.int32)))
    else:
        target_words = np.asarray(target_words)
        multi = target_words.ndim == 2 and target_words.shape[0] > 1
        body = _build_ext_body(name, gen.radices, seg_tables, gen.length,
                               target_words, sub, has_lut=has_lut)
        if multi:
            tables = jnp.asarray(bloom_tables(target_words))
        extra = (tables,) if multi else ()
    if has_lut:
        extra = extra + (jnp.asarray(luts_np),)
    base = jnp.asarray(base_digits, jnp.int32)
    counts, lanes = [], []
    for pid in range(batch // tile):
        c, l = body(jnp.int32(pid), base, jnp.int32(n_valid), *extra)
        counts.append(int(c))
        lanes.append(int(l))
    return (np.asarray(counts, np.int32)[:, None],
            np.asarray(lanes, np.int32)[:, None])
