"""SHA-256 compression (FIPS 180-4) as vectorized uint32 jnp ops.

The round constants (fractional cube roots of the first 64 primes) and
initial state (fractional square roots of the first 8 primes) are
computed here with exact integer arithmetic rather than copied from a
listing.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp


def _primes(n: int) -> list[int]:
    out, cand = [], 2
    while len(out) < n:
        if all(cand % p for p in out if p * p <= cand):
            out.append(cand)
        cand += 1
    return out


def _icbrt(n: int) -> int:
    lo, hi = 0, 1 << ((n.bit_length() + 2) // 3 + 1)
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if mid ** 3 <= n:
            lo = mid
        else:
            hi = mid - 1
    return lo


def _frac_root_word(p: int, root: int) -> int:
    """First 32 fractional bits of p**(1/root)."""
    if root == 2:
        import math
        return math.isqrt(p << 64) & 0xFFFFFFFF
    return _icbrt(p << 96) & 0xFFFFFFFF


_PRIMES = _primes(64)
K = np.array([_frac_root_word(p, 3) for p in _PRIMES], dtype=np.uint32)
INIT = np.array([_frac_root_word(p, 2) for p in _PRIMES[:8]],
                dtype=np.uint32)
assert K[0] == 0x428A2F98 and INIT[0] == 0x6A09E667   # FIPS 180-4 spot check

# SHA-224 IV: the SECOND 32 fractional bits of sqrt of primes 9..16
# (the low half of SHA-384's 64-bit IV words; FIPS 180-4)
INIT224 = np.array(
    [__import__("math").isqrt(p << 128) & 0xFFFFFFFF
     for p in _primes(16)[8:]], dtype=np.uint32)
assert INIT224[0] == 0xC1059ED8   # FIPS 180-4 spot check


def _rotr(x: jnp.ndarray, n: int) -> jnp.ndarray:
    return (x >> jnp.uint32(n)) | (x << jnp.uint32(32 - n))


def _round(vars8: tuple, wt: jnp.ndarray, kt) -> tuple:
    a, b, c, d, e, f, g, h = vars8
    S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
    ch = (e & f) ^ (~e & g)
    t1 = h + S1 + ch + kt + wt
    S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
    maj = (a & b) ^ (a & c) ^ (b & c)
    return (t1 + S0 + maj, a, b, c, d + t1, e, f, g)


def sha256_rounds(a, b, c, d, e, f, g, h, m):
    """The 64 SHA-256 rounds over any uint32 array shape (no
    feed-forward), STATICALLY unrolled with a rolling 16-word schedule
    so every W[t] lives in registers -- the form the Pallas kernel
    needs (fori_loop + concatenate does not lower to Mosaic; see
    ops/pallas_mask.py).  m: sequence of 16 message-word arrays.

    The XLA path (sha256_compress below) keeps the fori_loop form
    instead: on XLA:CPU the flat ~3k-op unrolled graph compiles for
    minutes, and under jit there is no throughput difference.
    """
    w = list(m)
    vars8 = (a, b, c, d, e, f, g, h)
    for t in range(64):
        if t >= 16:
            w15 = w[(t - 15) % 16]
            w2 = w[(t - 2) % 16]
            s0 = _rotr(w15, 7) ^ _rotr(w15, 18) ^ (w15 >> jnp.uint32(3))
            s1 = _rotr(w2, 17) ^ _rotr(w2, 19) ^ (w2 >> jnp.uint32(10))
            w[t % 16] = w[t % 16] + s0 + w[(t - 7) % 16] + s1
        vars8 = _round(vars8, w[t % 16], jnp.uint32(int(K[t])))
    return vars8


def sha256_compress(state: jnp.ndarray, words: jnp.ndarray) -> jnp.ndarray:
    """state uint32[..., 8] x words uint32[..., 16] (big-endian packed)
    -> uint32[..., 8].

    The first 16 rounds are unrolled (message words indexed statically);
    the remaining 48 run under lax.fori_loop with a rolling 16-word
    schedule buffer.  Fully unrolling all 64 rounds produces a flat
    ~3k-op graph that XLA:CPU's backend takes minutes to compile (the
    80-round SHA-1 graph is fine -- the schedule-extension dataflow is
    what blows up), and the loop form also keeps TPU compile time down
    at no throughput cost: the body is still batch-vectorized.
    """
    from jax import lax

    vars8 = tuple(state[..., i] for i in range(8))
    for t in range(16):
        vars8 = _round(vars8, words[..., t], jnp.uint32(int(K[t])))

    k_arr = jnp.asarray(K)

    def body(t, carry):
        vars8, w = carry
        w1 = w[..., 1]
        w14 = w[..., 14]
        s0 = _rotr(w1, 7) ^ _rotr(w1, 18) ^ (w1 >> jnp.uint32(3))
        s1 = _rotr(w14, 17) ^ _rotr(w14, 19) ^ (w14 >> jnp.uint32(10))
        w_new = w[..., 0] + s0 + w[..., 9] + s1
        vars8 = _round(vars8, w_new, k_arr[t])
        w = jnp.concatenate([w[..., 1:], w_new[..., None]], axis=-1)
        return vars8, w

    vars8, _ = lax.fori_loop(16, 64, body, (vars8, words))
    return jnp.stack(vars8, axis=-1) + state


def sha256_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    state = jnp.broadcast_to(jnp.asarray(INIT), words.shape[:-1] + (8,))
    return sha256_compress(state, words)


def sha224_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    """SHA-224: SHA-256 with its own IV, digest truncated to 7 words."""
    state = jnp.broadcast_to(jnp.asarray(INIT224),
                             words.shape[:-1] + (8,))
    return sha256_compress(state, words)[..., :7]
