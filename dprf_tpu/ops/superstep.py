"""Super-step: one device dispatch covering many worker batches.

Why: over the axon tunnel a host<->device round trip costs ~0.1-0.4 s
and every dispatch enqueue / argument transfer adds fixed overhead.
The production workers process a WorkUnit as `unit_strides` separate
step dispatches plus one flag readback; at fast-engine rates (~1 ms of
device work per 4M-candidate batch) that fixed cost dominated -- the
round-4 session1 measurements put the config-1 worker path at 960 MH/s
against a 3.66 GH/s kernel bench whose `inner`-loop wrapper amortized
exactly this overhead (TPU_RESULTS_r04.json).

This module is the *production-grade* version of that bench wrapper
(dprf_tpu/bench.py make_looped_step is measurement-only: it discards
hit lanes).  A super-step wraps a worker crack step in a `lax.scan` of
`inner` iterations inside ONE jit:

  - xs carries each iteration's leading step argument, precomputed on
    host: a [inner, L] matrix of mixed-radix digit vectors for mask
    steps, or an [inner] vector of word-window starts for wordlist
    steps.  Host-side digit math is microseconds; shipping it as one
    array replaces `inner` separate small transfers.
  - n_valid is the TOTAL valid candidates (or words) across the super
    chunk; each iteration clips its own share, so partial tails are
    exact.
  - The per-iteration step outputs are returned STACKED (scan ys), so
    hit decoding on the host sees exactly the same (count, lanes, ...)
    tuples the per-batch path produces -- same overflow semantics,
    same rescan granularity (one batch), no on-device merge logic.
  - The unit-level "does the host need to look at this" flag is
    accumulated in the scan carry and returned as one scalar: a
    hitless unit still costs a single scalar readback, never a
    stacked-buffer fetch.

The scan body compiles once regardless of `inner`; carrying only an
int32 scalar (probe-log finding: large tuple carries can upset the
TPU backend compiler, and the bench's scalar-carry fori_loop over the
same Pallas step is hardware-proven at inner=512).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

#: per-dispatch int32 lane budget: batch * inner must stay below 2^31
#: (step-internal lane arithmetic and the n_valid clip are int32).
INT32_BUDGET = (1 << 31) - 256


def max_inner(batch: int, cap: int = 512) -> int:
    """Largest power-of-two inner length whose super chunk fits int32
    arithmetic (and an optional cap)."""
    n = min(cap, INT32_BUDGET // max(1, batch))
    return 1 << (n.bit_length() - 1) if n >= 1 else 0


def make_super_step(step, inner: int, batch: int, flag_fn=None):
    """Wrap `step(x, n_valid) -> tuple` in a device-side scan.

    Returns super_step(xs, n_valid_total) -> (flag, stacked_outputs)
    where xs[i] is iteration i's leading argument and stacked_outputs
    mirrors the step's output tuple with a leading [inner] axis.

    flag_fn(out) -> int32 scalar marks an iteration as needing host
    attention (default: out[0], the hit count).  The returned flag is
    the sum over iterations.
    """
    if inner < 1:
        raise ValueError("inner must be >= 1")
    if inner * batch > INT32_BUDGET:
        raise ValueError(
            f"inner*batch = {inner * batch} overflows int32 lane "
            f"arithmetic (max {INT32_BUDGET}); lower inner")

    @jax.jit
    def super_step(xs, n_valid):
        n_valid = jnp.asarray(n_valid, jnp.int32)

        def body(acc, xi):
            x, i = xi
            nv = jnp.clip(n_valid - i * batch, 0, batch)
            out = step(x, nv)
            f = flag_fn(out) if flag_fn is not None else out[0]
            return acc + f.astype(jnp.int32), out

        acc, outs = lax.scan(
            body, jnp.int32(0),
            (xs, jnp.arange(inner, dtype=jnp.int32)))
        return acc, outs

    return super_step


def make_loop_super_step(step, inner: int, batch: int, groups):
    """The KERNEL-path superstep: a scalar/small-buffer-carry
    ``fori_loop`` over an OFFSET-AWARE per-batch step, fusing ``inner``
    batches into one dispatch with device-resident hit accumulation --
    the sharded runtime's superstep discipline brought to the
    single-chip Pallas path.

    Why not make_super_step: the scan shape re-traces the step per
    iteration with a fresh leading argument, and scan-of-pallas_call
    wedged the TPU compile helper (TPU_PROBE_LOG_r04 round 4b).  Here
    ONE compiled kernel is invoked ``inner`` times with only the
    window offset varying (the proven bench fori_loop shape, carrying
    a few hundred int32s instead of stacked per-batch outputs), and
    per-batch hits fold into fixed window-relative buffers on device.

    step(x, n_valid, offset) -> tuple of scalars and buffers; `groups`
    describes the accumulation, one entry per (count, buffer) pair:

        (count_idx, buf_idx, payload_idx | None, scale, capacity)

    - out[count_idx]: the batch's authoritative count (may exceed the
      batch buffer on collision/overflow -- the inflation survives
      accumulation, so window drains keep the exact-redrive
      discipline);
    - out[buf_idx]: compacted indices, valid entries first, -1
      padding; iteration i's entries are globalized by ``+ i * scale``
      (scale = batch for lane buffers, grid for tile buffers);
    - out[payload_idx]: optional same-shape payload riding along;
    - capacity: the WINDOW buffer length for this group.

    Returns super_step(x, n_valid_total) -> the step's output tuple
    shape with window-relative buffers -- decodable exactly like a
    wide-mode result.  n_valid_total is the whole window's bound; the
    offset-aware step masks validity globally, so partial tails are
    exact without per-iteration clips.
    """
    if inner < 1:
        raise ValueError("inner must be >= 1")
    if inner * batch > INT32_BUDGET:
        raise ValueError(
            f"inner*batch = {inner * batch} overflows int32 lane "
            f"arithmetic (max {INT32_BUDGET}); lower inner")

    @jax.jit
    def super_step(x, n_valid):
        n_valid = jnp.asarray(n_valid, jnp.int32)
        init = []
        for (_, _, pi, _, cap) in groups:
            init.append(jnp.int32(0))
            init.append(jnp.full((cap,), -1, jnp.int32))
            if pi is not None:
                init.append(jnp.full((cap,), -1, jnp.int32))
        init = tuple(init)

        def body(i, carry):
            out = step(x, n_valid, (i * batch).astype(jnp.int32))
            new, at = [], 0
            for (ci, bi, pi, scale, cap) in groups:
                count, buf = carry[at], carry[at + 1]
                c_i = out[ci].astype(jnp.int32)
                idx_i = out[bi]
                ok = idx_i >= 0
                rel = jnp.where(ok, idx_i + i * jnp.int32(scale), -1)
                slots = jnp.where(
                    ok, count + jnp.arange(idx_i.shape[0],
                                           dtype=jnp.int32), cap)
                new.append(count + c_i)
                new.append(buf.at[slots].set(rel, mode="drop"))
                at += 2
                if pi is not None:
                    pay = carry[at]
                    new.append(pay.at[slots].set(out[pi], mode="drop"))
                    at += 1
            return tuple(new)

        fin = lax.fori_loop(0, inner, body, init)
        out, at = {}, 0
        for (ci, bi, pi, _, _) in groups:
            out[ci] = fin[at]
            out[bi] = fin[at + 1]
            at += 2
            if pi is not None:
                out[pi] = fin[at]
                at += 1
        return tuple(out[k] for k in sorted(out))

    return super_step
