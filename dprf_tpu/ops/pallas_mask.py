"""Fused mask->hash->compare Pallas TPU kernels for the single-block
unsalted engines (MD5, SHA-1, NTLM).

Why a kernel at all: the XLA path (ops/pipeline.py) materializes the
candidate block uint8[B, L] and the digest uint32[B, W] in HBM between
fusions.  At the throughputs these engines target, those intermediate
writes are the bandwidth floor.  This kernel keeps the whole chain --
mixed-radix decode, charset lookup, message packing (with UTF-16LE
widening for NTLM), the full compression rounds, compare, hit
reduction -- in VMEM/registers, and writes one packed int32 per grid
cell -- (count << 16) | (hit_lane + 1), splatted over the minimum
(8, 128) Mosaic output block -- back to HBM: ~4096/TILE bytes per
candidate (1 byte at sub=32) instead of ~(L+4W).

The compression rounds themselves are imported from the same modules
the XLA path uses (md5_rounds/sha1_rounds/md4_rounds/sha256_rounds/
sha512_rounds), so there is one source of truth per algorithm.  The
SHA-256 and SHA-512-family kernels use the statically-unrolled
rolling-schedule round forms (fori_loop+concatenate carries do not
lower to Mosaic) and are TPU-only: XLA:CPU takes minutes to compile
the flat unrolled graphs, so off-TPU those engines ride the XLA
pipeline and the kernel bodies are validated eagerly via
emulate_mask_kernel.

Design choices forced by the VPU:
- Charset lookup is arithmetic where possible: a charset in digit
  order is piecewise byte = digit + delta, so the lookup is a few
  vectorized `where` adds (7 segments for ?a, 1 for ?l/?u/?d).
  Positions needing more than MAX_SEGMENTS segments (Markov-permuted
  orders, scrambled custom charsets) use a 256-entry LUT with the
  digit index along the LANE axis instead — one per-sublane
  `take_along_axis` gather, the krb5/bcrypt S-box layout — so every
  mask now rides the kernel path (r5; previously the XLA fallback).
- Hit extraction per tile is count + single-lane arithmetic max.  Two
  hits in one TILE-candidate tile (vanishingly rare for random
  targets; always visible in the count) force the caller's exact host
  rescan, so correctness never depends on the rarity.
- All lane arithmetic is int32, so a step's batch is capped below 2^31
  candidates (the factory enforces it); larger sweeps are driven as
  multiple steps by the worker, exactly like the XLA path.
"""

from __future__ import annotations

from typing import Optional, Sequence

from dprf_tpu.utils import env as envreg

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops import md4 as md4_ops
from dprf_tpu.ops import md5 as md5_ops
from dprf_tpu.ops import sha1 as sha1_ops
from dprf_tpu.ops import sha256 as sha256_ops
from dprf_tpu.ops import sha512 as sha512_ops

#: sublane count per grid cell; TILE = SUB * 128 candidate lanes.
#: DPRF_PALLAS_SUB overrides for tuning (tools/tpu_session.py sweeps
#: it on real hardware).  The round-3 sweep on TPU v5 lite
#: (TPU_RESULTS_r03.json) measured the md5 kernel at 0.91/1.75/2.97/
#: 3.97/4.14 GH/s for SUB 8/16/32/64/128: bigger tiles amortize the
#: per-grid-cell scalar work, so the packed-output format's maximum
#: (128) is the default.
SUB = envreg.get_int("DPRF_PALLAS_SUB")
TILE = SUB * 128
#: charsets needing more piecewise segments than MAX_SEGMENTS use the
#: lane-axis LUT decode in kernels (charset_lut below) and the gather
#: decode in the XLA pipeline; the bound and the segment model are
#: shared with the generator's mux decode.
from dprf_tpu.generators.mask import (MAX_SEGMENTS,  # noqa: E402,F401
                                      charset_segments, segment_mux)

# -- multi-target Bloom prefilter parameters --------------------------------
#: probes per target set; each probe consumes 12 digest bits (7 bits
#: word index into a 128-word bitmap row + 5 bits bit index), so 8
#: probes use 96 bits -- available in every CORES digest (>= 128 bits).
K_PROBES = 8
#: targets per Bloom set.  Fill factor per 4096-bit set bitmap is
#: <= 1024/4096 = 0.25, so a non-matching lane passes all 8 probes of
#: one set with p <= 0.25**8 ~ 1.5e-5: ~0.06 false maybe-lanes per
#: 4096-lane tile per set.  False maybes cost one host oracle hash
#: (single) or one 4096-candidate tile rescan (collision) -- both
#: negligible at those rates.
SET_SIZE = 1024
#: hard cap on kernel-path targets (gather cost grows one probe row per
#: set: ceil(N/1024) * 8 gathers per tile).
MAX_TARGETS = 8192


def check_batch(batch: int, sub: int) -> int:
    """Shared guard for every packed-output mask kernel factory
    (this module's, pallas_ext's, pallas_keccak's): sub bound for the
    16-bit packed count/lane fields, tile alignment, and the int32
    lane-arithmetic headroom (the first mixed-radix addition computes
    base_digit + lane with base_digit <= 255, so the lane index needs
    256 of headroom below 2^31 or the last lanes wrap and decode
    wrong candidates).  Returns the grid size."""
    if sub > 128:
        raise ValueError("sub > 128 overflows the packed 16-bit "
                         "count/lane output fields")
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    if batch > (1 << 31) - 256:
        raise ValueError("batch must fit in int32 lane arithmetic "
                         "(max 2**31 - 256)")
    return batch // tile


def _make_core(rounds_fn, init_words):
    """Wrap a shared rounds function into a kernel digest core:
    broadcast the initial state, run the rounds, add the Davies-Meyer
    feed-forward."""
    def core(m, shape):
        init = [jnp.uint32(int(w)) for w in init_words]
        out = rounds_fn(*(jnp.full(shape, w) for w in init), m)
        return tuple(x + i for x, i in zip(out, init))
    return core


_md5_core = _make_core(md5_ops.md5_rounds, md5_ops.INIT)
_md4_core = _make_core(md4_ops.md4_rounds, md4_ops.INIT)
_sha1_core = _make_core(sha1_ops.sha1_rounds, sha1_ops.INIT)
_sha256_core = _make_core(sha256_ops.sha256_rounds, sha256_ops.INIT)


def _make_sha512_core(init_words, out_words: int):
    """SHA-512-family digest core over (hi, lo) uint32 pairs: m is the
    32 words of one 128-byte block; returns the first out_words uint32
    digest words (16 for sha512, 12 for the sha384 truncation)."""
    def core(m, shape):
        pairs = [(m[2 * i], m[2 * i + 1]) for i in range(16)]
        init = [(jnp.uint32(v >> 32), jnp.uint32(v & 0xFFFFFFFF))
                for v in init_words]
        vars8 = tuple((jnp.full(shape, h), jnp.full(shape, l))
                      for h, l in init)
        out = sha512_ops.sha512_rounds(vars8, pairs)
        res = []
        for v, iv in zip(out, init):
            h, l = sha512_ops._add64(v, iv)
            res.extend([h, l])
        return tuple(res[:out_words])
    return core


_sha512_core = _make_sha512_core(sha512_ops.INIT512, 16)
_sha384_core = _make_sha512_core(sha512_ops.INIT384, 12)

#: engine name -> (rounds core, digest words, big-endian packing,
#: UTF-16LE widening)
CORES = {
    "md5": (_md5_core, 4, False, False),
    "sha1": (_sha1_core, 5, True, False),
    "sha-1": (_sha1_core, 5, True, False),
    "sha256": (_sha256_core, 8, True, False),
    "sha-256": (_sha256_core, 8, True, False),
    "ntlm": (_md4_core, 4, False, True),
    "sha512": (_sha512_core, 16, True, False),
    "sha-512": (_sha512_core, 16, True, False),
    "sha384": (_sha384_core, 12, True, False),
    "sha-384": (_sha384_core, 12, True, False),
}

#: engines whose compression consumes a 128-byte block (32 message
#: words, 128-bit length field) instead of the 64-byte default.
WIDE_BLOCK = frozenset(("sha512", "sha-512", "sha384", "sha-384"))


def pallas_mode() -> Optional[dict]:
    """Whether the Pallas kernel path should be used, and how.

    DPRF_PALLAS=0 disables it; =1 forces it (interpret mode off-TPU,
    for tests); default "auto" uses it on real TPU only.  Returns
    kwargs for the step factory, or None for the XLA path.
    """
    mode = envreg.get_str("DPRF_PALLAS")
    if mode == "0":
        return None
    import jax
    if jax.default_backend() == "tpu":
        return {"interpret": False}
    if mode == "1":
        return {"interpret": True}
    return None


# charset_segments / MAX_SEGMENTS: canonical segment model lives with
# the generator (generators/mask.py -- the XLA mux uses the same
# tables); imported above and re-exported for the kernel builders.


def mask_supported(charsets: Sequence[bytes]) -> bool:
    """True if every position decodes on the kernel path.  Since r5
    that is EVERY well-formed mask: positions within MAX_SEGMENTS
    arithmetic pieces use the segment mux; arbitrary orders (Markov
    permutations, scrambled custom charsets) use a 256-entry LUT on
    the lane axis (charset_lut below) -- the per-sublane gather layout
    proven by the bcrypt/krb5 kernels.  The predicate keeps only the
    structural requirement: nonempty byte charsets."""
    return all(1 <= len(cs) <= 256 for cs in charsets)


def charset_lut(cs: bytes) -> np.ndarray:
    """Arbitrary charset -> (2, 128) uint32 LUT with the DIGIT INDEX
    along lanes (row 0 digits 0..127, row 1 digits 128..255) -- the
    krb5 S-box layout, so the lookup is one per-sublane
    `take_along_axis` gather + a row select, independent of how many
    contiguous runs the byte values form."""
    tbl = np.zeros((2, 128), np.uint32)
    arr = np.frombuffer(cs, np.uint8)
    tbl.reshape(-1)[:len(arr)] = arr
    return tbl


def position_tables(charsets: Sequence[bytes]):
    """Per-position decode tables for THIS module's fast mask kernels:
    (proc_tables, luts) where proc entries are segment lists
    (arithmetic mux) or ("lut", k) markers, and luts is the stacked
    uint32[2 * n_lut, 128] LUT array (None when every position is
    arithmetic).  pallas_call forbids captured vector constants, so
    the LUT rides as a kernel INPUT (this module's fast cores and the
    pallas_ext salted/nested kernels); the heavy kernel families
    (krb5/pdf/7z/pbkdf2/keccak) instead run the segment mux UNBOUNDED
    -- up to ~2 ops per contiguous run per position, noise next to
    their per-candidate work -- via segment_tables below."""
    proc, luts = [], []
    for cs in charsets:
        segs = charset_segments(cs)
        if len(segs) <= MAX_SEGMENTS:
            proc.append(segs)
        else:
            proc.append(("lut", len(luts)))
            luts.append(charset_lut(cs))
    luts_np = (np.concatenate(luts, axis=0).astype(np.uint32)
               if luts else None)
    return proc, luts_np


def segment_tables(charsets: Sequence[bytes]) -> list:
    """Unbounded per-position segment lists: correct for ANY charset
    (segment_mux reconstructs arbitrary orders with one compare+select
    per contiguous run).  The heavy kernel families use this so Markov
    and scrambled custom charsets stay kernel-eligible without LUT
    input plumbing."""
    return [charset_segments(cs) for cs in charsets]


def md5_init_lanes(shape):
    """MD5 initial state as lane-replicated word tuples -- shared by
    the kernel bodies that chain raw compressions (krb5 HMAC tower,
    PDF Algorithm 2) rather than the one-shot digest cores above."""
    return tuple(jnp.full(shape, jnp.uint32(int(w)))
                 for w in md5_ops.INIT)


def md5_compress_lanes(state, m):
    """One MD5 compression on lane-replicated word tuples (state 4,
    m 16) with the Davies-Meyer feed-forward."""
    out = md5_ops.md5_rounds(*state, m)
    return tuple(x + s for x, s in zip(out, state))


def gather256(lo, hi, idx):
    """Per-sublane 256-entry lookup: table halves lo/hi uint32[sub, 128]
    with the ENTRY INDEX along lanes, idx uint32[sub, 128] in 0..255 ->
    values uint32[sub, 128].  The hardware's native per-sublane
    `take_along_axis` gather + a half select -- the S-box layout proven
    by the bcrypt/krb5 kernels; shared by the RC4 kernels (krb5, pdf)
    and the LUT charset decode."""
    idx7 = (idx & jnp.uint32(127)).astype(jnp.int32)
    glo = jnp.take_along_axis(lo, idx7, axis=1)
    ghi = jnp.take_along_axis(hi, idx7, axis=1)
    return jnp.where(idx < jnp.uint32(128), glo, ghi)


def swap256(lo, hi, pos, val, lane):
    """table[pos] = val via lane-iota compare + select (no scatter);
    lane is the int32 lane-index iota of the tile."""
    at = lane == (pos & jnp.uint32(127)).astype(jnp.int32)
    lo = jnp.where((pos < jnp.uint32(128)) & at, val, lo)
    hi = jnp.where((pos >= jnp.uint32(128)) & at, val, hi)
    return lo, hi


def _lut_byte(digit, lo_row, hi_row):
    """Lane-axis LUT lookup for int32 digit tiles of shape (sub, 128):
    rows are (128,) uint32 halves of the 256-entry table."""
    shape = digit.shape
    return gather256(jnp.broadcast_to(lo_row[None, :], shape),
                     jnp.broadcast_to(hi_row[None, :], shape),
                     digit.astype(jnp.uint32))


def kernel_eligible(engine_name: str, gen, n_targets: int) -> bool:
    """One kernel-eligibility predicate for engine selection and bench.
    Non-CORES names (nested double-hash, mysql41) dispatch to the
    extended-kernel module."""
    if engine_name not in CORES:
        from dprf_tpu.ops import pallas_ext
        return pallas_ext.nested_eligible(engine_name, gen, n_targets)
    if not 1 <= n_targets <= MAX_TARGETS:
        return False
    if not hasattr(gen, "charsets"):
        return False
    if engine_name in ("sha256", "sha-256") or engine_name in WIDE_BLOCK:
        # The statically-unrolled SHA-256 graph (and the even larger
        # 80-round SHA-512 pair graph) compiles fine through Mosaic's
        # path but takes XLA:CPU many minutes, so these kernels are
        # TPU-only; off-TPU (tests, --device cpu fallback) they use
        # the XLA pipeline.  The kernel bodies themselves are
        # validated eagerly via emulate_mask_kernel.
        import jax as _jax
        if _jax.default_backend() != "tpu":
            return False
    widen = CORES[engine_name][3]
    max_len = (27 if widen
               else 111 if engine_name in WIDE_BLOCK   # 128-byte block
               else 55)
    return gen.length <= max_len and mask_supported(gen.charsets)


def bloom_tables(twords: np.ndarray) -> np.ndarray:
    """Target digest words uint32[N, W] -> Bloom bitmap rows
    uint32[n_sets * K_PROBES, 128].

    Set s, probe p lives in row s*K_PROBES + p: a 4096-bit bitmap over
    128 uint32 words, with one bit set per target in the set, keyed by
    12 bits of the target's own digest (targets ARE uniform hash
    outputs, so no extra hashing is needed).
    """
    N = twords.shape[0]
    if N > MAX_TARGETS:
        raise ValueError(f"kernel path supports <= {MAX_TARGETS} targets")
    n_sets = -(-N // SET_SIZE)
    T = np.zeros((n_sets * K_PROBES, 128), np.uint32)
    for s in range(n_sets):
        chunk = twords[s * SET_SIZE:(s + 1) * SET_SIZE]
        for p in range(K_PROBES):
            o = 12 * p
            j, sh = divmod(o, 32)
            bits = (chunk[:, j] >> np.uint32(sh)).astype(np.uint64)
            if sh > 20:
                bits |= chunk[:, j + 1].astype(np.uint64) << np.uint64(32 - sh)
            bits = (bits & np.uint64(0xFFF)).astype(np.uint32)
            np.bitwise_or.at(T[s * K_PROBES + p], bits >> 5,
                             np.uint32(1) << (bits & np.uint32(31)))
    return T


def _probe_bits(digest, p: int):
    """12 Bloom-probe bits [12p, 12p+12) of the digest bit string."""
    j, sh = divmod(12 * p, 32)
    bits = digest[j] >> jnp.uint32(sh)
    if sh > 20:
        bits = bits | (digest[j + 1] << jnp.uint32(32 - sh))
    return bits & jnp.uint32(0xFFF)


# piecewise charset lookup shared with the generator's XLA mux
_decode_byte = segment_mux


def decode_candidate_bytes(radices, seg_tables, length: int, base, carry,
                           luts=None):
    """Mixed-radix add (base digits + per-lane carry) fused with the
    per-position charset lookup, least significant position first --
    the shared decode of every mask kernel body.  seg_tables entries
    are segment lists (arithmetic mux, any length) or ("lut", k)
    markers resolving into `luts` rows [2k, 2k+2) (position_tables;
    carry must then be a (sub, 128) tile -- every kernel body's is)."""
    lut_arr = luts[...] if luts is not None else None
    byts: list = [None] * length
    for p in range(length - 1, -1, -1):
        r = radices[p]
        s = base[p] + carry
        d = s % r
        t = seg_tables[p]
        if isinstance(t, tuple) and t[0] == "lut":
            byts[p] = _lut_byte(d, lut_arr[2 * t[1]],
                                lut_arr[2 * t[1] + 1]).astype(jnp.uint32)
        else:
            byts[p] = _decode_byte(d, t).astype(jnp.uint32)
        carry = s // r
    return byts


def bloom_found(digest, tables, valid, n_sets: int, shape):
    """Bloom prefilter shared by the kernel bodies: a lane survives if
    it passes ALL K_PROBES of ANY target set.  Real hits always
    survive (their probe bits come from the matching target's own
    digest); false maybes are rare enough that the caller verifies
    single maybes with one host oracle hash and exactly rescans
    collided tiles (see reduce_tile_maybes)."""
    probes = []
    for p in range(K_PROBES):
        bits = _probe_bits(digest, p)
        probes.append(((bits >> jnp.uint32(5)).astype(jnp.int32),
                       (bits & jnp.uint32(31))))
    found = jnp.zeros(shape, jnp.bool_)
    for s in range(n_sets):
        m_set = valid
        for p, (idx7, bit5) in enumerate(probes):
            row = jnp.broadcast_to(tables[s * K_PROBES + p][None, :],
                                   shape)
            word = jnp.take_along_axis(row, idx7, axis=1)
            m_set = m_set & (((word >> bit5) & jnp.uint32(1)) == 1)
        found = found | m_set
    return found


def _pack_message(byts, length: int, shape, big_endian: bool,
                  widen_utf16: bool, block_words: int = 16):
    """Candidate bytes -> the padded single-block message words
    (16 words / 64-byte block by default; 32 words / 128-byte block
    with a 128-bit length field for the SHA-512 family)."""
    def put(m, q, byte):
        shift = 8 * (3 - q % 4) if big_endian else 8 * (q % 4)
        m[q // 4] = m[q // 4] | (byte << jnp.uint32(shift))

    m = [jnp.zeros(shape, jnp.uint32) for _ in range(block_words)]
    stride = 2 if widen_utf16 else 1        # UTF-16LE: byte p -> pos 2p
    for p, byte in enumerate(byts):
        put(m, stride * p, byte)
    msg_len = stride * length
    put(m, msg_len, jnp.uint32(0x80))
    bitlen = jnp.full(shape, jnp.uint32(8 * msg_len))
    if big_endian:
        m[block_words - 1] = bitlen   # 64/128-bit BE length, low word
    else:
        m[14] = bitlen       # 64-bit LE length, low word
    return m


def _build_kernel_body(engine_name: str, radices, seg_tables, length: int,
                       target, sub: int):
    """The kernel math as a PURE function of (pid, base digits, n_valid)
    -> (count, hit_lane) scalars.  Shared verbatim by the pallas_call
    wrapper (TPU) and by emulate_mask_kernel (eager CPU validation --
    XLA:CPU cannot compile the statically-unrolled SHA-256 graph in
    reasonable time, so correctness tests drive this body op-by-op)."""
    core, n_words, big_endian, widen = CORES[engine_name]
    tile = sub * 128
    target = np.asarray(target)
    multi = target.ndim == 2 and target.shape[0] > 1
    if multi:
        n_sets = -(-target.shape[0] // SET_SIZE)
        tw = None
    else:
        # plain python ints: jnp scalars here would be captured closure
        # constants, which pallas_call rejects
        tw = [int(w) for w in target.reshape(-1)]
        if len(tw) != n_words:
            raise ValueError(f"{engine_name}: expected {n_words} "
                             "target words")

    def kernel_body(pid, base, n_valid, tables=None, luts=None):
        shape = (sub, 128)
        lane = (jax.lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
        # The base index of this *tile* is folded into the scalar side
        # (pid * tile) before vector carry propagation.
        carry = lane + pid * tile
        byts = decode_candidate_bytes(radices, seg_tables, length,
                                      base, carry, luts)
        m = _pack_message(byts, length, shape, big_endian, widen,
                          32 if engine_name in WIDE_BLOCK else 16)
        digest = core(m, shape)
        valid = (lane + pid * tile) < n_valid
        if not multi:
            found = valid
            for got, want in zip(digest, tw):
                found = found & (got == jnp.uint32(want))
        else:
            found = bloom_found(digest, tables, valid, n_sets, shape)
        count = jnp.sum(found.astype(jnp.int32))
        # single-hit extraction: max lane among hits (-1 if none); the
        # caller rescans any tile whose count exceeds 1.
        hit_lane = jnp.max(jnp.where(found, lane, -1))
        return count, hit_lane

    return kernel_body


def _build_kernel(engine_name: str, radices, seg_tables, length: int,
                  target, sub: int, multi: bool = False,
                  has_lut: bool = False):
    """pallas_call kernel wrapper around the pure body.  Optional
    positional inputs follow (base, n_valid) in a fixed order: the
    Bloom tables (multi-target), then the charset LUT rows (masks with
    positions past the segment budget -- pallas_call forbids captured
    vector constants, so the LUT is a real input)."""
    body = _build_kernel_body(engine_name, radices, seg_tables, length,
                              target, sub)

    # Mosaic requires output blocks of (8k, 128m) lanes (or whole-array),
    # so the two per-tile scalars are packed into one int32 --
    # (count << 16) | (hit_lane + 1) -- splat across a full (8, 128)
    # block per grid cell (~1 byte/candidate of HBM traffic at sub=32;
    # noise next to the compression rounds).  count and hit_lane+1 both
    # fit 15/16 bits because tile = sub*128 <= 16384 (sub <= 128).
    def kernel(base_ref, nvalid_ref, *rest):
        out_ref = rest[-1]
        extras = list(rest[:-1])
        tables_ref = extras.pop(0) if multi else None
        luts_ref = extras.pop(0) if has_lut else None
        count, hit_lane = body(pl.program_id(0), base_ref,
                               nvalid_ref[0], tables_ref, luts_ref)
        packed = (count << 16) | (hit_lane + 1)
        out_ref[...] = jnp.full((8, 128), packed, jnp.int32)

    return kernel


def emulate_mask_kernel(engine_name: str, gen, target_words: np.ndarray,
                        batch: int, base_digits, n_valid: int,
                        sub: int = SUB):
    """Run the kernel body eagerly (no pallas_call, no jit) over every
    grid cell; returns (counts int32[G,1], hit_lanes int32[G,1]) with
    the exact layout pallas_call produces.  Test/validation vehicle."""
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    target_words = np.asarray(target_words)
    multi = target_words.ndim == 2 and target_words.shape[0] > 1
    tables = jnp.asarray(bloom_tables(target_words)) if multi else None
    seg_tables, luts_np = position_tables(gen.charsets)
    luts = jnp.asarray(luts_np) if luts_np is not None else None
    body = _build_kernel_body(engine_name, gen.radices, seg_tables,
                              gen.length, target_words, sub)
    base = jnp.asarray(base_digits, jnp.int32)
    counts, lanes = [], []
    for pid in range(batch // tile):
        c, l = body(jnp.int32(pid), base, jnp.int32(n_valid), tables,
                    luts)
        counts.append(int(c))
        lanes.append(int(l))
    return (np.asarray(counts, np.int32)[:, None],
            np.asarray(lanes, np.int32)[:, None])


def make_mask_pallas_fn(engine_name: str, gen, target_words: np.ndarray,
                        batch: int, sub: int = SUB,
                        interpret: bool = False):
    """Build fn(base_digits int32[L], n_valid int32[1]) ->
    (counts int32[G, 1], hit_lanes int32[G, 1]) over a `batch`-lane
    sweep.  batch must be a multiple of sub*128.

    target_words uint32[W] (single target: counts are exact hit counts)
    or uint32[N, W] (multi target: counts are Bloom maybe-counts; see
    reduce_tile_maybes for the caller contract).
    """
    tile = sub * 128
    grid = check_batch(batch, sub)
    target_words = np.asarray(target_words)
    multi = target_words.ndim == 2 and target_words.shape[0] > 1
    n_targets = target_words.shape[0] if multi else 1
    if not kernel_eligible(engine_name, gen, n_targets):
        raise ValueError(f"{engine_name} mask job not kernel-eligible; "
                         "use the XLA path")
    seg_tables, luts_np = position_tables(gen.charsets)
    has_lut = luts_np is not None
    kernel = _build_kernel(engine_name, gen.radices, seg_tables,
                           gen.length, target_words, sub, multi=multi,
                           has_lut=has_lut)
    L = gen.length
    in_specs = [
        pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
    ]
    if multi:
        tables = bloom_tables(target_words)
        R = tables.shape[0]
        in_specs.append(pl.BlockSpec((R, 128), lambda i: (0, 0)))
    if has_lut:
        in_specs.append(pl.BlockSpec(luts_np.shape, lambda i: (0, 0)))
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((8, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32),
        ],
        interpret=interpret,
    )
    tables_dev = jnp.asarray(tables) if multi else None
    luts_dev = jnp.asarray(luts_np) if has_lut else None

    def fn(base_digits, n_valid):
        args = [base_digits, n_valid]
        if multi:
            args.append(tables_dev)
        if has_lut:
            args.append(luts_dev)
        (packed,) = raw(*args)
        p = packed[::8, 0:1]          # row 0 of each tile's block
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_pallas_mask_crack_step(engine_name: str, gen,
                                target_words: np.ndarray, batch: int,
                                hit_capacity: int = 64,
                                interpret: bool = False):
    """Drop-in replacement for ops/pipeline.make_mask_crack_step on the
    single-target kernel path: step(base_digits, n_valid) ->
    (count, lanes, tpos)."""
    if engine_name not in CORES:
        from dprf_tpu.ops import pallas_ext
        return pallas_ext.make_ext_mask_crack_step(
            engine_name, gen, target_words, batch, hit_capacity,
            interpret=interpret)
    tile = SUB * 128
    fn = make_mask_pallas_fn(engine_name, gen, target_words, batch,
                             interpret=interpret)

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step


def make_pallas_multi_crack_step(engine_name: str, gen,
                                 target_words: np.ndarray, batch: int,
                                 hit_capacity: int = 64,
                                 rescan_capacity: int = 16,
                                 interpret: bool = False):
    """Multi-target kernel step: step(base_digits, n_valid) ->
    (n_single, maybe_lanes int32[hit_capacity],
     n_collided, collided_tiles int32[rescan_capacity]).

    Contract (see PallasMaskWorker): each maybe lane holds >= 0
    candidates that passed the Bloom prefilter and must be verified by
    ONE host oracle hash; each collided tile (>= 2 maybes) must be
    exactly rescanned over its TILE-candidate range.  n_single >
    hit_capacity or n_collided > rescan_capacity means the whole batch
    needs the exact rescan (astronomically rare at the Bloom FP rates
    documented at SET_SIZE)."""
    if engine_name not in CORES:
        from dprf_tpu.ops import pallas_ext
        return pallas_ext.make_ext_multi_crack_step(
            engine_name, gen, target_words, batch, hit_capacity,
            rescan_capacity, interpret=interpret)
    tile = SUB * 128
    fn = make_mask_pallas_fn(engine_name, gen, target_words, batch,
                             interpret=interpret)

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_maybes(counts, hit_lanes, hit_capacity,
                                  rescan_capacity, tile)

    return step


def reduce_tile_maybes(counts: jnp.ndarray, hit_lanes: jnp.ndarray,
                       hit_capacity: int, rescan_capacity: int, tile: int):
    """Per-tile Bloom maybe-counts -> (n_single, maybe_lanes,
    n_collided, collided_tiles) for the multi-target worker."""
    from dprf_tpu.ops import compare as cmp_ops

    c = counts[:, 0]
    single = c == 1
    collided = c > 1
    n_single = jnp.sum(single.astype(jnp.int32))
    n_collided = jnp.sum(collided.astype(jnp.int32))
    _, stiles, _ = cmp_ops.compact_hits(single, jnp.zeros_like(c),
                                        hit_capacity)
    maybe_lanes = jnp.where(
        stiles >= 0,
        stiles * tile + hit_lanes[jnp.maximum(stiles, 0), 0], -1)
    _, ctiles, _ = cmp_ops.compact_hits(collided, jnp.zeros_like(c),
                                        rescan_capacity)
    return n_single, maybe_lanes, n_collided, ctiles


def reduce_tile_hits(counts: jnp.ndarray, hit_lanes: jnp.ndarray,
                     hit_capacity: int, tile: int):
    """Per-tile kernel outputs -> the worker's (count, lanes, tpos)
    contract.  A tile holding 2+ hits can only report one lane, so any
    such tile forces count > hit_capacity: the worker's exact host
    rescan then recovers every hit."""
    from dprf_tpu.ops import compare as cmp_ops

    c = counts[:, 0]
    total = jnp.sum(c)
    collision = jnp.any(c > 1)
    _, tiles, _ = cmp_ops.compact_hits(c > 0, jnp.zeros_like(c),
                                       hit_capacity)
    glanes = jnp.where(
        tiles >= 0,
        tiles * tile + hit_lanes[jnp.maximum(tiles, 0), 0], -1)
    count = jnp.where(collision, jnp.int32(hit_capacity + 1), total)
    return count, glanes, jnp.zeros_like(glanes)
