"""Fused mask->hash->compare Pallas TPU kernels for the single-block
unsalted engines (MD5, SHA-1, NTLM).

Why a kernel at all: the XLA path (ops/pipeline.py) materializes the
candidate block uint8[B, L] and the digest uint32[B, W] in HBM between
fusions.  At the throughputs these engines target, those intermediate
writes are the bandwidth floor.  This kernel keeps the whole chain --
mixed-radix decode, charset lookup, message packing (with UTF-16LE
widening for NTLM), the full compression rounds, compare, hit
reduction -- in VMEM/registers, and writes one packed int32 per grid
cell -- (count << 16) | (hit_lane + 1), splatted over the minimum
(8, 128) Mosaic output block -- back to HBM: ~4096/TILE bytes per
candidate (1 byte at sub=32) instead of ~(L+4W).

The compression rounds themselves are imported from the same modules
the XLA path uses (md5_rounds/sha1_rounds/md4_rounds/sha256_rounds/
sha512_rounds), so there is one source of truth per algorithm.  The
SHA-256 and SHA-512-family kernels use the statically-unrolled
rolling-schedule round forms (fori_loop+concatenate carries do not
lower to Mosaic) and are TPU-only: XLA:CPU takes minutes to compile
the flat unrolled graphs, so off-TPU those engines ride the XLA
pipeline and the kernel bodies are validated eagerly via
emulate_mask_kernel.

Design choices forced by the VPU:
- Charset lookup is arithmetic where possible: a charset in digit
  order is piecewise byte = digit + delta, so the lookup is a few
  vectorized `where` adds (7 segments for ?a, 1 for ?l/?u/?d).
  Positions needing more than MAX_SEGMENTS segments (Markov-permuted
  orders, scrambled custom charsets) use a 256-entry LUT with the
  digit index along the LANE axis instead — one per-sublane
  `take_along_axis` gather, the krb5/bcrypt S-box layout — so every
  mask now rides the kernel path (r5; previously the XLA fallback).
- Hit extraction per tile is count + single-lane arithmetic max.  Two
  hits in one TILE-candidate tile (vanishingly rare for random
  targets; always visible in the count) force the caller's exact host
  rescan, so correctness never depends on the rarity.
- All lane arithmetic is int32, so a step's batch is capped below 2^31
  candidates (the factory enforces it); larger sweeps are driven as
  multiple steps by the worker, exactly like the XLA path.
"""

from __future__ import annotations

from typing import Optional, Sequence

from dprf_tpu.utils import env as envreg

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops import md4 as md4_ops
from dprf_tpu.ops import md5 as md5_ops
from dprf_tpu.ops import sha1 as sha1_ops
from dprf_tpu.ops import sha256 as sha256_ops
from dprf_tpu.ops import sha512 as sha512_ops

#: sublane count per grid cell; TILE = SUB * 128 candidate lanes.
#: DPRF_PALLAS_SUB overrides for tuning (tools/tpu_session.py sweeps
#: it on real hardware).  The round-3 sweep on TPU v5 lite
#: (TPU_RESULTS_r03.json) measured the md5 kernel at 0.91/1.75/2.97/
#: 3.97/4.14 GH/s for SUB 8/16/32/64/128: bigger tiles amortize the
#: per-grid-cell scalar work, so the packed-output format's maximum
#: (128) is the default.
SUB = envreg.get_int("DPRF_PALLAS_SUB")
TILE = SUB * 128
#: charsets needing more piecewise segments than MAX_SEGMENTS use the
#: lane-axis LUT decode in kernels (charset_lut below) and the gather
#: decode in the XLA pipeline; the bound and the segment model are
#: shared with the generator's mux decode.
from dprf_tpu.generators.mask import (MAX_SEGMENTS,  # noqa: E402,F401
                                      charset_segments, segment_mux)

# -- multi-target Bloom prefilter parameters --------------------------------
#: probes per target set; each probe consumes 12 digest bits (7 bits
#: word index into a 128-word bitmap row + 5 bits bit index), so 8
#: probes use 96 bits -- available in every CORES digest (>= 128 bits).
K_PROBES = 8
#: targets per Bloom set.  Fill factor per 4096-bit set bitmap is
#: <= 1024/4096 = 0.25, so a non-matching lane passes all 8 probes of
#: one set with p <= 0.25**8 ~ 1.5e-5: ~0.06 false maybe-lanes per
#: 4096-lane tile per set.  False maybes cost one host oracle hash
#: (single) or one 4096-candidate tile rescan (collision) -- both
#: negligible at those rates.
SET_SIZE = 1024
#: hard cap on kernel-path targets (gather cost grows one probe row per
#: set: ceil(N/1024) * 8 gathers per tile).
MAX_TARGETS = 8192

#: 128-block groups the in-kernel blocked-probe bitmap may span.  The
#: kernel gathers a lane's 512-bit block with one take_along_axis per
#: (group, word) pair, so groups bound both the gather count (16 per
#: group) and the bitmap footprint (64 KiB at 8 groups) -- VMEM-small
#: and constant in N.  At MAX_TARGETS the capped bitmap still reaches
#: the DPRF_PALLAS_PROBE_FP budget (~4e-8 analytic at 8192 keys).
KERNEL_PROBE_GROUPS = 8


def check_batch(batch: int, sub: int) -> int:
    """Shared guard for every packed-output mask kernel factory
    (this module's, pallas_ext's, pallas_keccak's): sub bound for the
    16-bit packed count/lane fields, tile alignment, and the int32
    lane-arithmetic headroom (the first mixed-radix addition computes
    base_digit + lane with base_digit <= 255, so the lane index needs
    256 of headroom below 2^31 or the last lanes wrap and decode
    wrong candidates).  Returns the grid size."""
    if sub > 128:
        raise ValueError("sub > 128 overflows the packed 16-bit "
                         "count/lane output fields")
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    if batch > (1 << 31) - 256:
        raise ValueError("batch must fit in int32 lane arithmetic "
                         "(max 2**31 - 256)")
    return batch // tile


def _make_core(rounds_fn, init_words):
    """Wrap a shared rounds function into a kernel digest core:
    broadcast the initial state, run the rounds, add the Davies-Meyer
    feed-forward."""
    def core(m, shape):
        init = [jnp.uint32(int(w)) for w in init_words]
        out = rounds_fn(*(jnp.full(shape, w) for w in init), m)
        return tuple(x + i for x, i in zip(out, init))
    return core


_md5_core = _make_core(md5_ops.md5_rounds, md5_ops.INIT)
_md4_core = _make_core(md4_ops.md4_rounds, md4_ops.INIT)
_sha1_core = _make_core(sha1_ops.sha1_rounds, sha1_ops.INIT)
_sha256_core = _make_core(sha256_ops.sha256_rounds, sha256_ops.INIT)


def _make_sha512_core(init_words, out_words: int):
    """SHA-512-family digest core over (hi, lo) uint32 pairs: m is the
    32 words of one 128-byte block; returns the first out_words uint32
    digest words (16 for sha512, 12 for the sha384 truncation)."""
    def core(m, shape):
        pairs = [(m[2 * i], m[2 * i + 1]) for i in range(16)]
        init = [(jnp.uint32(v >> 32), jnp.uint32(v & 0xFFFFFFFF))
                for v in init_words]
        vars8 = tuple((jnp.full(shape, h), jnp.full(shape, l))
                      for h, l in init)
        out = sha512_ops.sha512_rounds(vars8, pairs)
        res = []
        for v, iv in zip(out, init):
            h, l = sha512_ops._add64(v, iv)
            res.extend([h, l])
        return tuple(res[:out_words])
    return core


_sha512_core = _make_sha512_core(sha512_ops.INIT512, 16)
_sha384_core = _make_sha512_core(sha512_ops.INIT384, 12)

#: engine name -> (rounds core, digest words, big-endian packing,
#: UTF-16LE widening)
CORES = {
    "md5": (_md5_core, 4, False, False),
    "sha1": (_sha1_core, 5, True, False),
    "sha-1": (_sha1_core, 5, True, False),
    "sha256": (_sha256_core, 8, True, False),
    "sha-256": (_sha256_core, 8, True, False),
    "ntlm": (_md4_core, 4, False, True),
    "sha512": (_sha512_core, 16, True, False),
    "sha-512": (_sha512_core, 16, True, False),
    "sha384": (_sha384_core, 12, True, False),
    "sha-384": (_sha384_core, 12, True, False),
}

#: engines whose compression consumes a 128-byte block (32 message
#: words, 128-bit length field) instead of the 64-byte default.
WIDE_BLOCK = frozenset(("sha512", "sha-512", "sha384", "sha-384"))


def pallas_mode() -> Optional[dict]:
    """Whether the Pallas kernel path should be used, and how.

    DPRF_PALLAS=0 disables it; =1 forces it (interpret mode off-TPU,
    for tests); default "auto" uses it on real TPU only.  Returns
    kwargs for the step factory, or None for the XLA path.
    """
    mode = envreg.get_str("DPRF_PALLAS")
    if mode == "0":
        return None
    import jax
    if jax.default_backend() == "tpu":
        return {"interpret": False}
    if mode == "1":
        return {"interpret": True}
    return None


# charset_segments / MAX_SEGMENTS: canonical segment model lives with
# the generator (generators/mask.py -- the XLA mux uses the same
# tables); imported above and re-exported for the kernel builders.


def mask_supported(charsets: Sequence[bytes]) -> bool:
    """True if every position decodes on the kernel path.  Since r5
    that is EVERY well-formed mask: positions within MAX_SEGMENTS
    arithmetic pieces use the segment mux; arbitrary orders (Markov
    permutations, scrambled custom charsets) use a 256-entry LUT on
    the lane axis (charset_lut below) -- the per-sublane gather layout
    proven by the bcrypt/krb5 kernels.  The predicate keeps only the
    structural requirement: nonempty byte charsets."""
    return all(1 <= len(cs) <= 256 for cs in charsets)


def charset_lut(cs: bytes) -> np.ndarray:
    """Arbitrary charset -> (2, 128) uint32 LUT with the DIGIT INDEX
    along lanes (row 0 digits 0..127, row 1 digits 128..255) -- the
    krb5 S-box layout, so the lookup is one per-sublane
    `take_along_axis` gather + a row select, independent of how many
    contiguous runs the byte values form."""
    tbl = np.zeros((2, 128), np.uint32)
    arr = np.frombuffer(cs, np.uint8)
    tbl.reshape(-1)[:len(arr)] = arr
    return tbl


def position_tables(charsets: Sequence[bytes]):
    """Per-position decode tables for THIS module's fast mask kernels:
    (proc_tables, luts) where proc entries are segment lists
    (arithmetic mux) or ("lut", k) markers, and luts is the stacked
    uint32[2 * n_lut, 128] LUT array (None when every position is
    arithmetic).  pallas_call forbids captured vector constants, so
    the LUT rides as a kernel INPUT (this module's fast cores and the
    pallas_ext salted/nested kernels); the heavy kernel families
    (krb5/pdf/7z/pbkdf2/keccak) instead run the segment mux UNBOUNDED
    -- up to ~2 ops per contiguous run per position, noise next to
    their per-candidate work -- via segment_tables below."""
    proc, luts = [], []
    for cs in charsets:
        segs = charset_segments(cs)
        if len(segs) <= MAX_SEGMENTS:
            proc.append(segs)
        else:
            proc.append(("lut", len(luts)))
            luts.append(charset_lut(cs))
    luts_np = (np.concatenate(luts, axis=0).astype(np.uint32)
               if luts else None)
    return proc, luts_np


def segment_tables(charsets: Sequence[bytes]) -> list:
    """Unbounded per-position segment lists: correct for ANY charset
    (segment_mux reconstructs arbitrary orders with one compare+select
    per contiguous run).  The heavy kernel families use this so Markov
    and scrambled custom charsets stay kernel-eligible without LUT
    input plumbing."""
    return [charset_segments(cs) for cs in charsets]


def md5_init_lanes(shape):
    """MD5 initial state as lane-replicated word tuples -- shared by
    the kernel bodies that chain raw compressions (krb5 HMAC tower,
    PDF Algorithm 2) rather than the one-shot digest cores above."""
    return tuple(jnp.full(shape, jnp.uint32(int(w)))
                 for w in md5_ops.INIT)


def md5_compress_lanes(state, m):
    """One MD5 compression on lane-replicated word tuples (state 4,
    m 16) with the Davies-Meyer feed-forward."""
    out = md5_ops.md5_rounds(*state, m)
    return tuple(x + s for x, s in zip(out, state))


def gather256(lo, hi, idx):
    """Per-sublane 256-entry lookup: table halves lo/hi uint32[sub, 128]
    with the ENTRY INDEX along lanes, idx uint32[sub, 128] in 0..255 ->
    values uint32[sub, 128].  The hardware's native per-sublane
    `take_along_axis` gather + a half select -- the S-box layout proven
    by the bcrypt/krb5 kernels; shared by the RC4 kernels (krb5, pdf)
    and the LUT charset decode."""
    idx7 = (idx & jnp.uint32(127)).astype(jnp.int32)
    glo = jnp.take_along_axis(lo, idx7, axis=1)
    ghi = jnp.take_along_axis(hi, idx7, axis=1)
    return jnp.where(idx < jnp.uint32(128), glo, ghi)


def swap256(lo, hi, pos, val, lane):
    """table[pos] = val via lane-iota compare + select (no scatter);
    lane is the int32 lane-index iota of the tile."""
    at = lane == (pos & jnp.uint32(127)).astype(jnp.int32)
    lo = jnp.where((pos < jnp.uint32(128)) & at, val, lo)
    hi = jnp.where((pos >= jnp.uint32(128)) & at, val, hi)
    return lo, hi


def _lut_byte(digit, lo_row, hi_row):
    """Lane-axis LUT lookup for int32 digit tiles of shape (sub, 128):
    rows are (128,) uint32 halves of the 256-entry table."""
    shape = digit.shape
    return gather256(jnp.broadcast_to(lo_row[None, :], shape),
                     jnp.broadcast_to(hi_row[None, :], shape),
                     digit.astype(jnp.uint32))


def kernel_eligible(engine_name: str, gen, n_targets: int) -> bool:
    """One kernel-eligibility predicate for engine selection and bench.
    Non-CORES names (nested double-hash, mysql41) dispatch to the
    extended-kernel module."""
    if engine_name not in CORES:
        from dprf_tpu.ops import pallas_ext
        return pallas_ext.nested_eligible(engine_name, gen, n_targets)
    if not 1 <= n_targets <= MAX_TARGETS:
        return False
    if not hasattr(gen, "charsets"):
        return False
    if engine_name in ("sha256", "sha-256") or engine_name in WIDE_BLOCK:
        # The statically-unrolled SHA-256 graph (and the even larger
        # 80-round SHA-512 pair graph) compiles fine through Mosaic's
        # path but takes XLA:CPU many minutes, so these kernels are
        # TPU-only; off-TPU (tests, --device cpu fallback) they use
        # the XLA pipeline.  The kernel bodies themselves are
        # validated eagerly via emulate_mask_kernel.
        import jax as _jax
        if _jax.default_backend() != "tpu":
            return False
    widen = CORES[engine_name][3]
    max_len = (27 if widen
               else 111 if engine_name in WIDE_BLOCK   # 128-byte block
               else 55)
    return gen.length <= max_len and mask_supported(gen.charsets)


def bloom_tables(twords: np.ndarray) -> np.ndarray:
    """Target digest words uint32[N, W] -> Bloom bitmap rows
    uint32[n_sets * K_PROBES, 128].

    Set s, probe p lives in row s*K_PROBES + p: a 4096-bit bitmap over
    128 uint32 words, with one bit set per target in the set, keyed by
    12 bits of the target's own digest (targets ARE uniform hash
    outputs, so no extra hashing is needed).
    """
    N = twords.shape[0]
    if N > MAX_TARGETS:
        raise ValueError(f"kernel path supports <= {MAX_TARGETS} targets")
    n_sets = -(-N // SET_SIZE)
    T = np.zeros((n_sets * K_PROBES, 128), np.uint32)
    for s in range(n_sets):
        chunk = twords[s * SET_SIZE:(s + 1) * SET_SIZE]
        for p in range(K_PROBES):
            o = 12 * p
            j, sh = divmod(o, 32)
            bits = (chunk[:, j] >> np.uint32(sh)).astype(np.uint64)
            if sh > 20:
                bits |= chunk[:, j + 1].astype(np.uint64) << np.uint64(32 - sh)
            bits = (bits & np.uint64(0xFFF)).astype(np.uint32)
            np.bitwise_or.at(T[s * K_PROBES + p], bits >> 5,
                             np.uint32(1) << (bits & np.uint32(31)))
    return T


def _probe_bits(digest, p: int):
    """12 Bloom-probe bits [12p, 12p+12) of the digest bit string."""
    j, sh = divmod(12 * p, 32)
    bits = digest[j] >> jnp.uint32(sh)
    if sh > 20:
        bits = bits | (digest[j + 1] << jnp.uint32(32 - sh))
    return bits & jnp.uint32(0xFFF)


def kernel_probe_rows(twords: np.ndarray, fp: Optional[float] = None):
    """Target digest words uint32[N, W] -> the PR 14 blocked-Bloom
    probe bitmap in the kernel's lane-major layout.

    The bit layout is targets/probe.bloom_fill -- the SAME bits the XLA
    ProbeTable path sets -- transposed so the BLOCK index runs along
    the 128-lane axis: row g*BLOCK_WORDS + w, lane b holds word w of
    block g*128 + b.  A lane's whole 512-bit block then gathers with
    one take_along_axis per (group, word) pair, the proven S-box
    idiom, and the k double-hashed probes resolve inside registers.

    Sized by DPRF_PALLAS_PROBE_FP (NOT the XLA path's
    DPRF_TARGETS_FP_BUDGET): a superstep window drains through a tiny
    device-resident hit buffer, so false maybes must be rare per
    *window*, not merely per batch.  Capped at KERNEL_PROBE_GROUPS
    groups so the gather tree stays bounded.

    Returns (rows uint32[n_grp * BLOCK_WORDS, 128], block_bits, k,
    n_grp, fp_est)."""
    from dprf_tpu.targets import probe as probe_mod
    if fp is None:
        fp = envreg.get_float("DPRF_PALLAS_PROBE_FP")
    n = int(twords.shape[0])
    max_bits = KERNEL_PROBE_GROUPS * 128 * probe_mod.BLOCK_BITS
    m_bits, k, fp_est = probe_mod.kernel_bloom_geometry(n, fp, max_bits)
    words = probe_mod.bloom_fill(np.ascontiguousarray(twords), m_bits, k)
    bw = probe_mod.BLOCK_WORDS
    n_blocks = m_bits // probe_mod.BLOCK_BITS
    block_bits = n_blocks.bit_length() - 1
    n_grp = max(1, n_blocks // 128)
    if n_blocks < 128:
        # pad to one full 128-block group: block indices stay below
        # n_blocks, so the zero lanes are never addressed
        pad = np.zeros(128 * bw, np.uint32)
        pad[:words.size] = words
        words = pad
    rows = words.reshape(n_grp, 128, bw).transpose(0, 2, 1)
    return (np.ascontiguousarray(rows).reshape(n_grp * bw, 128),
            block_bits, k, n_grp, fp_est)


def probe_block_found(digest, rows, valid, block_bits: int, k: int,
                      n_grp: int, shape):
    """In-kernel blocked-Bloom probe over kernel_probe_rows state: a
    lane survives iff all k double-hashed bits of its block are set.
    Real hits always survive (their bits were set from the matching
    target's own digest words); the caller treats survivors as
    sentinel-tagged maybes and verifies each with one host oracle
    hash, so a false positive can never surface as a hit."""
    from dprf_tpu.targets.probe import BLOCK_BITS, BLOCK_WORDS, _GOLDEN
    h1 = digest[0]
    h2 = digest[1] | jnp.uint32(1)
    # the alternating probe pairs of targets/probe.bloom_fill
    h3 = digest[2] if len(digest) > 3 else h1
    h4 = (digest[3] | jnp.uint32(1)) if len(digest) > 3 else h2
    if block_bits:
        block = ((h1 * jnp.uint32(_GOLDEN))
                 >> jnp.uint32(32 - block_bits)).astype(jnp.int32)
    else:
        block = jnp.zeros(shape, jnp.int32)
    lane_idx = block & 127
    grp = block >> 7
    # gather the lane's full 512-bit block: one per-sublane gather per
    # (group, word), selected by the lane's group index
    bw = []
    for w in range(BLOCK_WORDS):
        acc = None
        for g in range(n_grp):
            row = jnp.broadcast_to(rows[g * BLOCK_WORDS + w][None, :],
                                   shape)
            got = jnp.take_along_axis(row, lane_idx, axis=1)
            acc = got if acc is None else jnp.where(grp == g, got, acc)
        bw.append(acc)
    found = valid
    for j in range(k):
        i = j >> 1
        a, b = (h3, h4) if j & 1 else (h1, h2)
        g = a + jnp.uint32(2 * i + 1) * b
        bit = g & jnp.uint32(BLOCK_BITS - 1)
        widx = (bit >> jnp.uint32(5)).astype(jnp.int32)
        word = bw[0]
        for w in range(1, BLOCK_WORDS):
            word = jnp.where(widx == w, bw[w], word)
        found = found & (((word >> (bit & jnp.uint32(31)))
                          & jnp.uint32(1)) == 1)
    return found


# piecewise charset lookup shared with the generator's XLA mux
_decode_byte = segment_mux


def decode_candidate_bytes(radices, seg_tables, length: int, base, carry,
                           luts=None):
    """Mixed-radix add (base digits + per-lane carry) fused with the
    per-position charset lookup, least significant position first --
    the shared decode of every mask kernel body.  seg_tables entries
    are segment lists (arithmetic mux, any length) or ("lut", k)
    markers resolving into `luts` rows [2k, 2k+2) (position_tables;
    carry must then be a (sub, 128) tile -- every kernel body's is)."""
    lut_arr = luts[...] if luts is not None else None
    byts: list = [None] * length
    for p in range(length - 1, -1, -1):
        r = radices[p]
        s = base[p] + carry
        d = s % r
        t = seg_tables[p]
        if isinstance(t, tuple) and t[0] == "lut":
            byts[p] = _lut_byte(d, lut_arr[2 * t[1]],
                                lut_arr[2 * t[1] + 1]).astype(jnp.uint32)
        else:
            byts[p] = _decode_byte(d, t).astype(jnp.uint32)
        carry = s // r
    return byts


def bloom_found(digest, tables, valid, n_sets: int, shape):
    """Bloom prefilter shared by the kernel bodies: a lane survives if
    it passes ALL K_PROBES of ANY target set.  Real hits always
    survive (their probe bits come from the matching target's own
    digest); false maybes are rare enough that the caller verifies
    single maybes with one host oracle hash and exactly rescans
    collided tiles (see reduce_tile_maybes)."""
    probes = []
    for p in range(K_PROBES):
        bits = _probe_bits(digest, p)
        probes.append(((bits >> jnp.uint32(5)).astype(jnp.int32),
                       (bits & jnp.uint32(31))))
    found = jnp.zeros(shape, jnp.bool_)
    for s in range(n_sets):
        m_set = valid
        for p, (idx7, bit5) in enumerate(probes):
            row = jnp.broadcast_to(tables[s * K_PROBES + p][None, :],
                                   shape)
            word = jnp.take_along_axis(row, idx7, axis=1)
            m_set = m_set & (((word >> bit5) & jnp.uint32(1)) == 1)
        found = found | m_set
    return found


def _pack_message(byts, length: int, shape, big_endian: bool,
                  widen_utf16: bool, block_words: int = 16):
    """Candidate bytes -> the padded single-block message words
    (16 words / 64-byte block by default; 32 words / 128-byte block
    with a 128-bit length field for the SHA-512 family)."""
    def put(m, q, byte):
        shift = 8 * (3 - q % 4) if big_endian else 8 * (q % 4)
        m[q // 4] = m[q // 4] | (byte << jnp.uint32(shift))

    m = [jnp.zeros(shape, jnp.uint32) for _ in range(block_words)]
    stride = 2 if widen_utf16 else 1        # UTF-16LE: byte p -> pos 2p
    for p, byte in enumerate(byts):
        put(m, stride * p, byte)
    msg_len = stride * length
    put(m, msg_len, jnp.uint32(0x80))
    bitlen = jnp.full(shape, jnp.uint32(8 * msg_len))
    if big_endian:
        m[block_words - 1] = bitlen   # 64/128-bit BE length, low word
    else:
        m[14] = bitlen       # 64-bit LE length, low word
    return m


def _build_kernel_body(engine_name: str, radices, seg_tables, length: int,
                       target, sub: int, probe=None):
    """The kernel math as a PURE function of (pid, base digits, n_valid
    [, offset]) -> (count, hit_lane) scalars.  Shared verbatim by the
    pallas_call wrapper (TPU) and by emulate_mask_kernel (eager CPU
    validation -- XLA:CPU cannot compile the statically-unrolled
    SHA-256 graph in reasonable time, so correctness tests drive this
    body op-by-op).

    probe: None for the per-set Bloom prefilter, or the
    (block_bits, k, n_grp) geometry from kernel_probe_rows -- the
    multi-target compare then runs the blocked probe (`tables` holds
    the probe rows) and every survivor is a sentinel maybe.

    An `offset` scalar (the sharded/superstep window start) shifts
    both the decoded keyspace index and the validity bound, so ONE
    compiled kernel serves every window of a superstep; hit_lane stays
    tile-relative (the caller adds tile * pid + offset back)."""
    core, n_words, big_endian, widen = CORES[engine_name]
    tile = sub * 128
    target = np.asarray(target)
    multi = target.ndim == 2 and target.shape[0] > 1
    if multi:
        n_sets = -(-target.shape[0] // SET_SIZE)
        tw = None
    else:
        # plain python ints: jnp scalars here would be captured closure
        # constants, which pallas_call rejects
        tw = [int(w) for w in target.reshape(-1)]
        if len(tw) != n_words:
            raise ValueError(f"{engine_name}: expected {n_words} "
                             "target words")

    def kernel_body(pid, base, n_valid, tables=None, luts=None,
                    offset=None):
        shape = (sub, 128)
        lane = (jax.lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
        # The base index of this *tile* is folded into the scalar side
        # (pid * tile, plus the window offset) before vector carry
        # propagation.
        gidx = lane + pid * tile
        if offset is not None:
            gidx = gidx + offset
        byts = decode_candidate_bytes(radices, seg_tables, length,
                                      base, gidx, luts)
        m = _pack_message(byts, length, shape, big_endian, widen,
                          32 if engine_name in WIDE_BLOCK else 16)
        digest = core(m, shape)
        valid = gidx < n_valid
        if not multi:
            found = valid
            for got, want in zip(digest, tw):
                found = found & (got == jnp.uint32(want))
        elif probe is not None:
            found = probe_block_found(digest, tables, valid, *probe,
                                      shape)
        else:
            found = bloom_found(digest, tables, valid, n_sets, shape)
        count = jnp.sum(found.astype(jnp.int32))
        # single-hit extraction: max lane among hits (-1 if none); the
        # caller rescans any tile whose count exceeds 1.
        hit_lane = jnp.max(jnp.where(found, lane, -1))
        return count, hit_lane

    return kernel_body


def _build_kernel(engine_name: str, radices, seg_tables, length: int,
                  target, sub: int, multi: bool = False,
                  has_lut: bool = False, with_offset: bool = False,
                  probe=None):
    """pallas_call kernel wrapper around the pure body.  Optional
    positional inputs follow (base, n_valid) in a fixed order: the
    window offset scalar (sharded/superstep callers), then the Bloom
    or probe tables (multi-target), then the charset LUT rows (masks
    with positions past the segment budget -- pallas_call forbids
    captured vector constants, so the LUT is a real input)."""
    body = _build_kernel_body(engine_name, radices, seg_tables, length,
                              target, sub, probe=probe)

    # Mosaic requires output blocks of (8k, 128m) lanes (or whole-array),
    # so the two per-tile scalars are packed into one int32 --
    # (count << 16) | (hit_lane + 1) -- splat across a full (8, 128)
    # block per grid cell (~1 byte/candidate of HBM traffic at sub=32;
    # noise next to the compression rounds).  count and hit_lane+1 both
    # fit 15/16 bits because tile = sub*128 <= 16384 (sub <= 128).
    def kernel(base_ref, nvalid_ref, *rest):
        out_ref = rest[-1]
        extras = list(rest[:-1])
        offset_ref = extras.pop(0) if with_offset else None
        tables_ref = extras.pop(0) if multi else None
        luts_ref = extras.pop(0) if has_lut else None
        count, hit_lane = body(
            pl.program_id(0), base_ref, nvalid_ref[0], tables_ref,
            luts_ref,
            offset_ref[0] if offset_ref is not None else None)
        packed = (count << 16) | (hit_lane + 1)
        out_ref[...] = jnp.full((8, 128), packed, jnp.int32)

    return kernel


def emulate_mask_kernel(engine_name: str, gen, target_words: np.ndarray,
                        batch: int, base_digits, n_valid: int,
                        sub: int = SUB, offset: int = 0,
                        probe_fp: Optional[float] = None):
    """Run the kernel body eagerly (no pallas_call, no jit) over every
    grid cell; returns (counts int32[G,1], hit_lanes int32[G,1]) with
    the exact layout pallas_call produces.  Test/validation vehicle.

    offset / probe_fp mirror make_mask_pallas_fn's with_offset and
    probe-compare modes, so the sharded kernel bodies validate through
    the same eager loop off-TPU."""
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    target_words = np.asarray(target_words)
    multi = target_words.ndim == 2 and target_words.shape[0] > 1
    probe = None
    if multi and probe_fp is not None:
        rows, block_bits, k, n_grp, _ = kernel_probe_rows(
            target_words, probe_fp)
        tables = jnp.asarray(rows)
        probe = (block_bits, k, n_grp)
    else:
        tables = (jnp.asarray(bloom_tables(target_words))
                  if multi else None)
    seg_tables, luts_np = position_tables(gen.charsets)
    luts = jnp.asarray(luts_np) if luts_np is not None else None
    body = _build_kernel_body(engine_name, gen.radices, seg_tables,
                              gen.length, target_words, sub,
                              probe=probe)
    base = jnp.asarray(base_digits, jnp.int32)
    off = jnp.int32(offset) if offset else None
    counts, lanes = [], []
    for pid in range(batch // tile):
        c, l = body(jnp.int32(pid), base, jnp.int32(n_valid), tables,
                    luts, off)
        counts.append(int(c))
        lanes.append(int(l))
    return (np.asarray(counts, np.int32)[:, None],
            np.asarray(lanes, np.int32)[:, None])


def make_mask_pallas_fn(engine_name: str, gen, target_words: np.ndarray,
                        batch: int, sub: int = SUB,
                        interpret: bool = False,
                        with_offset: bool = False,
                        probe_fp: Optional[float] = None):
    """Build fn(base_digits int32[L], n_valid int32[1][, offset
    int32[1]]) -> (counts int32[G, 1], hit_lanes int32[G, 1]) over a
    `batch`-lane sweep.  batch must be a multiple of sub*128.

    target_words uint32[W] (single target: counts are exact hit counts)
    or uint32[N, W] (multi target: counts are Bloom maybe-counts; see
    reduce_tile_maybes for the caller contract).

    with_offset adds the traced window-start scalar (SMEM, like
    n_valid): candidates decode from base + offset + lane and validity
    checks against the WINDOW n_valid, so sharded shards and superstep
    iterations reuse one compiled kernel.  probe_fp switches the
    multi-target compare to the blocked-probe bitmap
    (kernel_probe_rows): counts become probe maybe-counts at that fp
    budget."""
    tile = sub * 128
    grid = check_batch(batch, sub)
    target_words = np.asarray(target_words)
    multi = target_words.ndim == 2 and target_words.shape[0] > 1
    n_targets = target_words.shape[0] if multi else 1
    if not kernel_eligible(engine_name, gen, n_targets):
        raise ValueError(f"{engine_name} mask job not kernel-eligible; "
                         "use the XLA path")
    seg_tables, luts_np = position_tables(gen.charsets)
    has_lut = luts_np is not None
    probe = None
    if multi and probe_fp is not None:
        tables, block_bits, k, n_grp, _ = kernel_probe_rows(
            target_words, probe_fp)
        probe = (block_bits, k, n_grp)
    elif multi:
        tables = bloom_tables(target_words)
    kernel = _build_kernel(engine_name, gen.radices, seg_tables,
                           gen.length, target_words, sub, multi=multi,
                           has_lut=has_lut, with_offset=with_offset,
                           probe=probe)
    L = gen.length
    in_specs = [
        pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
        pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
    ]
    if with_offset:
        in_specs.append(
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM))
    if multi:
        R = tables.shape[0]
        in_specs.append(pl.BlockSpec((R, 128), lambda i: (0, 0)))
    if has_lut:
        in_specs.append(pl.BlockSpec(luts_np.shape, lambda i: (0, 0)))
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((8, 128), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32),
        ],
        interpret=interpret,
    )
    tables_dev = jnp.asarray(tables) if multi else None
    luts_dev = jnp.asarray(luts_np) if has_lut else None

    def fn(base_digits, n_valid, offset=None):
        args = [base_digits, n_valid]
        if with_offset:
            args.append(jnp.zeros((1,), jnp.int32)
                        if offset is None else offset)
        if multi:
            args.append(tables_dev)
        if has_lut:
            args.append(luts_dev)
        (packed,) = raw(*args)
        p = packed[::8, 0:1]          # row 0 of each tile's block
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_pallas_mask_crack_step(engine_name: str, gen,
                                target_words: np.ndarray, batch: int,
                                hit_capacity: int = 64,
                                interpret: bool = False,
                                with_offset: bool = False,
                                sub: Optional[int] = None):
    """Drop-in replacement for ops/pipeline.make_mask_crack_step on the
    single-target kernel path: step(base_digits, n_valid) ->
    (count, lanes, tpos).

    with_offset appends a traced window-start argument --
    step(base_digits, n_valid, offset) -- with lanes still
    batch-relative, so ops/superstep.make_loop_super_step can fuse
    `inner` invocations of ONE compiled kernel per dispatch.  `sub`
    overrides the tile sublane count (the `dprf tune` tile rung)."""
    if engine_name not in CORES:
        from dprf_tpu.ops import pallas_ext
        return pallas_ext.make_ext_mask_crack_step(
            engine_name, gen, target_words, batch, hit_capacity,
            interpret=interpret)
    sub = SUB if sub is None else sub
    tile = sub * 128
    fn = make_mask_pallas_fn(engine_name, gen, target_words, batch,
                             sub=sub, interpret=interpret,
                             with_offset=with_offset)

    if with_offset:
        @jax.jit
        def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray,
                 offset: jnp.ndarray):
            counts, hit_lanes = fn(
                base_digits.astype(jnp.int32),
                jnp.reshape(n_valid, (1,)).astype(jnp.int32),
                jnp.reshape(offset, (1,)).astype(jnp.int32))
            return reduce_tile_hits(counts, hit_lanes, hit_capacity,
                                    tile)
        return step

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step


def make_pallas_multi_crack_step(engine_name: str, gen,
                                 target_words: np.ndarray, batch: int,
                                 hit_capacity: int = 64,
                                 rescan_capacity: int = 16,
                                 interpret: bool = False,
                                 with_offset: bool = False,
                                 sub: Optional[int] = None):
    """Multi-target kernel step: step(base_digits, n_valid) ->
    (n_single, maybe_lanes int32[hit_capacity],
     n_collided, collided_tiles int32[rescan_capacity]).

    Contract (see PallasMaskWorker): each maybe lane holds >= 0
    candidates that passed the Bloom prefilter and must be verified by
    ONE host oracle hash; each collided tile (>= 2 maybes) must be
    exactly rescanned over its TILE-candidate range.  n_single >
    hit_capacity or n_collided > rescan_capacity means the whole batch
    needs the exact rescan (astronomically rare at the Bloom FP rates
    documented at SET_SIZE).

    with_offset / sub: as make_pallas_mask_crack_step (loop-superstep
    fusion and the tune tile rung)."""
    if engine_name not in CORES:
        from dprf_tpu.ops import pallas_ext
        return pallas_ext.make_ext_multi_crack_step(
            engine_name, gen, target_words, batch, hit_capacity,
            rescan_capacity, interpret=interpret)
    sub = SUB if sub is None else sub
    tile = sub * 128
    fn = make_mask_pallas_fn(engine_name, gen, target_words, batch,
                             sub=sub, interpret=interpret,
                             with_offset=with_offset)

    if with_offset:
        @jax.jit
        def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray,
                 offset: jnp.ndarray):
            counts, hit_lanes = fn(
                base_digits.astype(jnp.int32),
                jnp.reshape(n_valid, (1,)).astype(jnp.int32),
                jnp.reshape(offset, (1,)).astype(jnp.int32))
            return reduce_tile_maybes(counts, hit_lanes, hit_capacity,
                                      rescan_capacity, tile)
        return step

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_maybes(counts, hit_lanes, hit_capacity,
                                  rescan_capacity, tile)

    return step


def reduce_tile_maybes(counts: jnp.ndarray, hit_lanes: jnp.ndarray,
                       hit_capacity: int, rescan_capacity: int, tile: int):
    """Per-tile Bloom maybe-counts -> (n_single, maybe_lanes,
    n_collided, collided_tiles) for the multi-target worker."""
    from dprf_tpu.ops import compare as cmp_ops

    c = counts[:, 0]
    single = c == 1
    collided = c > 1
    n_single = jnp.sum(single.astype(jnp.int32))
    n_collided = jnp.sum(collided.astype(jnp.int32))
    _, stiles, _ = cmp_ops.compact_hits(single, jnp.zeros_like(c),
                                        hit_capacity)
    maybe_lanes = jnp.where(
        stiles >= 0,
        stiles * tile + hit_lanes[jnp.maximum(stiles, 0), 0], -1)
    _, ctiles, _ = cmp_ops.compact_hits(collided, jnp.zeros_like(c),
                                        rescan_capacity)
    return n_single, maybe_lanes, n_collided, ctiles


def make_shard_mask_compute(engine_name: str, gen,
                            target_words: np.ndarray,
                            batch_per_device: int, hit_capacity: int,
                            sub: Optional[int] = None,
                            interpret: bool = False,
                            probe_fp: Optional[float] = None):
    """The fused kernel as a sharded compute callback: the tentpole
    bridge between this module and parallel/sharded.make_sharded_step.

    compute(offset, base_digits, n_valid) ->
        (found bool[G], payload int32[G], rel int32[G], count int32)

    -- the runtime's TILE-compute contract: per-grid-cell hit flags,
    window-relative hit lanes (offset + tile start + in-tile lane),
    and the authoritative count.  Candidate generation happens ON
    DEVICE inside the kernel from base + shard/window offset, so a
    sharded superstep's only host traffic is the base digit vector.

    Single target: found marks exactly-one-hit tiles; payload is tpos
    0.  Multi target (2..MAX_TARGETS): the compare is the blocked
    PR 14 probe bitmap (kernel_probe_rows) and every surviving lane
    comes back SENTINEL-tagged (payload == n_targets, out of range) --
    the workers' lane decode verifies each with one oracle hash.  Any
    tile holding 2+ hits/maybes can only report one lane, so the
    count is inflated past hit_capacity and the workers' existing
    overflow redrive re-covers the window exactly."""
    if engine_name not in CORES:
        raise ValueError(f"{engine_name}: sharded kernel computes "
                         "cover the CORES engines only")
    sub = SUB if sub is None else sub
    tile = sub * 128
    grid = check_batch(batch_per_device, sub)
    target_words = np.asarray(target_words)
    multi = target_words.ndim == 2 and target_words.shape[0] > 1
    sentinel = int(target_words.shape[0]) if multi else 0
    fn = make_mask_pallas_fn(
        engine_name, gen, target_words, batch_per_device, sub=sub,
        interpret=interpret, with_offset=True,
        probe_fp=(probe_fp if probe_fp is not None
                  else envreg.get_float("DPRF_PALLAS_PROBE_FP"))
        if multi else None)
    tile_starts = jnp.arange(grid, dtype=jnp.int32) * tile

    def compute(offset, base_digits, n_valid):
        counts, hit_lanes = fn(
            base_digits.astype(jnp.int32),
            jnp.reshape(n_valid, (1,)).astype(jnp.int32),
            jnp.reshape(offset, (1,)).astype(jnp.int32))
        c = counts[:, 0]
        found = c == 1
        rel = offset + tile_starts + hit_lanes[:, 0]
        payload = jnp.full((grid,), sentinel, jnp.int32)
        count = jnp.sum(c) + jnp.where(
            jnp.any(c > 1), jnp.int32(hit_capacity + 1), 0)
        return found, payload, rel, count

    compute.tile = tile
    compute.grid = grid
    return compute


def reduce_tile_hits(counts: jnp.ndarray, hit_lanes: jnp.ndarray,
                     hit_capacity: int, tile: int):
    """Per-tile kernel outputs -> the worker's (count, lanes, tpos)
    contract.  A tile holding 2+ hits can only report one lane, so any
    such tile forces count > hit_capacity: the worker's exact host
    rescan then recovers every hit."""
    from dprf_tpu.ops import compare as cmp_ops

    c = counts[:, 0]
    total = jnp.sum(c)
    collision = jnp.any(c > 1)
    _, tiles, _ = cmp_ops.compact_hits(c > 0, jnp.zeros_like(c),
                                       hit_capacity)
    glanes = jnp.where(
        tiles >= 0,
        tiles * tile + hit_lanes[jnp.maximum(tiles, 0), 0], -1)
    count = jnp.where(collision, jnp.int32(hit_capacity + 1), total)
    return count, glanes, jnp.zeros_like(glanes)
