"""Fused mask->hash->compare Pallas TPU kernels for the single-block
unsalted engines (MD5, SHA-1, NTLM).

Why a kernel at all: the XLA path (ops/pipeline.py) materializes the
candidate block uint8[B, L] and the digest uint32[B, W] in HBM between
fusions.  At the throughputs these engines target, those intermediate
writes are the bandwidth floor.  This kernel keeps the whole chain --
mixed-radix decode, charset lookup, message packing (with UTF-16LE
widening for NTLM), the full compression rounds, compare, hit
reduction -- in VMEM/registers, and writes only TWO int32 scalars per
grid cell (hit count + hit lane) back to HBM: the HBM traffic per
candidate is ~8/TILE bytes instead of ~(L+4W).

The compression rounds themselves are imported from the same modules
the XLA path uses (md5_rounds/sha1_rounds/md4_rounds), so there is one
source of truth per algorithm.  SHA-256 stays on the XLA path: its
rolling message schedule is written as a fori_loop+concatenate carry
(see ops/sha256.py) that does not lower to Mosaic.

Design choices forced by the VPU:
- Charset lookup is arithmetic, not a gather: a charset in digit order
  is piecewise byte = digit + delta, so the lookup is a few vectorized
  `where` adds (7 segments for ?a, 1 for ?l/?u/?d).  Charsets needing
  more than MAX_SEGMENTS segments fall back to the XLA path.
- Hit extraction per tile is count + single-lane arithmetic max.  Two
  hits in one TILE-candidate tile (vanishingly rare for random
  targets; always visible in the count) force the caller's exact host
  rescan, so correctness never depends on the rarity.
- All lane arithmetic is int32, so a step's batch is capped below 2^31
  candidates (the factory enforces it); larger sweeps are driven as
  multiple steps by the worker, exactly like the XLA path.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops import md4 as md4_ops
from dprf_tpu.ops import md5 as md5_ops
from dprf_tpu.ops import sha1 as sha1_ops

#: sublane count per grid cell; TILE = SUB * 128 candidate lanes.
SUB = 32
TILE = SUB * 128
#: charsets needing more piecewise segments than this use the XLA path.
MAX_SEGMENTS = 16


def _make_core(rounds_fn, init_words):
    """Wrap a shared rounds function into a kernel digest core:
    broadcast the initial state, run the rounds, add the Davies-Meyer
    feed-forward."""
    def core(m, shape):
        init = [jnp.uint32(int(w)) for w in init_words]
        out = rounds_fn(*(jnp.full(shape, w) for w in init), m)
        return tuple(x + i for x, i in zip(out, init))
    return core


_md5_core = _make_core(md5_ops.md5_rounds, md5_ops.INIT)
_md4_core = _make_core(md4_ops.md4_rounds, md4_ops.INIT)
_sha1_core = _make_core(sha1_ops.sha1_rounds, sha1_ops.INIT)

#: engine name -> (rounds core, digest words, big-endian packing,
#: UTF-16LE widening)
CORES = {
    "md5": (_md5_core, 4, False, False),
    "sha1": (_sha1_core, 5, True, False),
    "sha-1": (_sha1_core, 5, True, False),
    "ntlm": (_md4_core, 4, False, True),
}


def pallas_mode() -> Optional[dict]:
    """Whether the Pallas kernel path should be used, and how.

    DPRF_PALLAS=0 disables it; =1 forces it (interpret mode off-TPU,
    for tests); default "auto" uses it on real TPU only.  Returns
    kwargs for the step factory, or None for the XLA path.
    """
    env = os.environ.get("DPRF_PALLAS", "auto")
    if env == "0":
        return None
    import jax
    if jax.default_backend() == "tpu":
        return {"interpret": False}
    if env == "1":
        return {"interpret": True}
    return None


def charset_segments(charset: bytes):
    """Charset (digit order) -> [(start_digit, byte_delta)] pieces where
    byte = digit + delta for digit >= start_digit (until next piece)."""
    segs = []
    for d, byte in enumerate(charset):
        delta = byte - d
        if not segs or segs[-1][1] != delta:
            segs.append((d, delta))
    return segs


def mask_supported(charsets: Sequence[bytes]) -> bool:
    """True if every position's charset decodes in <= MAX_SEGMENTS
    arithmetic pieces (all builtin charsets do)."""
    return all(len(charset_segments(cs)) <= MAX_SEGMENTS
               for cs in charsets)


def kernel_eligible(engine_name: str, gen, n_targets: int) -> bool:
    """One kernel-eligibility predicate for engine selection and bench."""
    if engine_name not in CORES or n_targets != 1:
        return False
    if not hasattr(gen, "charsets"):
        return False
    widen = CORES[engine_name][3]
    max_len = 27 if widen else 55
    return gen.length <= max_len and mask_supported(gen.charsets)


def _decode_byte(digit, segs):
    """Vectorized piecewise charset lookup: digit array -> byte array."""
    byte = digit + segs[0][1]
    for start, delta in segs[1:]:
        byte = jnp.where(digit >= start, digit + delta, byte)
    return byte


def _pack_message(byts, length: int, shape, big_endian: bool,
                  widen_utf16: bool):
    """Candidate bytes -> the 16 padded single-block message words."""
    def put(m, q, byte):
        shift = 8 * (3 - q % 4) if big_endian else 8 * (q % 4)
        m[q // 4] = m[q // 4] | (byte << jnp.uint32(shift))

    m = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
    stride = 2 if widen_utf16 else 1        # UTF-16LE: byte p -> pos 2p
    for p, byte in enumerate(byts):
        put(m, stride * p, byte)
    msg_len = stride * length
    put(m, msg_len, jnp.uint32(0x80))
    bitlen = jnp.full(shape, jnp.uint32(8 * msg_len))
    if big_endian:
        m[15] = bitlen       # 64-bit BE length, low word
    else:
        m[14] = bitlen       # 64-bit LE length, low word
    return m


def _build_kernel(engine_name: str, radices, seg_tables, length: int,
                  target, sub: int):
    """Kernel closure: radices/charset segments/target words are baked
    in as constants (one compile per job, like the XLA step)."""
    core, n_words, big_endian, widen = CORES[engine_name]
    tile = sub * 128
    # plain python ints: jnp scalars here would be captured closure
    # constants, which pallas_call rejects
    tw = [int(w) for w in target]
    if len(tw) != n_words:
        raise ValueError(f"{engine_name}: expected {n_words} target words")

    def kernel(base_ref, nvalid_ref, counts_ref, hitlane_ref):
        pid = pl.program_id(0)
        shape = (sub, 128)
        lane = (jax.lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + jax.lax.broadcasted_iota(jnp.int32, shape, 1))
        # mixed-radix add (base digits + global offset), least
        # significant (rightmost mask position) first, fused with the
        # charset lookup.  The base index of this *tile* is folded into
        # the scalar side (pid * tile) before vector carry propagation.
        carry = lane + pid * tile
        byts: list = [None] * length
        for p in range(length - 1, -1, -1):
            r = radices[p]
            s = base_ref[p] + carry
            byts[p] = _decode_byte(s % r, seg_tables[p]).astype(jnp.uint32)
            carry = s // r
        m = _pack_message(byts, length, shape, big_endian, widen)
        digest = core(m, shape)
        valid = (lane + pid * tile) < nvalid_ref[0]
        found = valid
        for got, want in zip(digest, tw):
            found = found & (got == jnp.uint32(want))
        counts_ref[0, 0] = jnp.sum(found.astype(jnp.int32))
        # single-hit extraction: max lane among hits (-1 if none); the
        # caller rescans any tile whose count exceeds 1.
        hitlane_ref[0, 0] = jnp.max(jnp.where(found, lane, -1))

    return kernel


def make_mask_pallas_fn(engine_name: str, gen, target_words: np.ndarray,
                        batch: int, sub: int = SUB,
                        interpret: bool = False):
    """Build fn(base_digits int32[L], n_valid int32[1]) ->
    (counts int32[G, 1], hit_lanes int32[G, 1]) over a `batch`-lane
    sweep.  batch must be a multiple of sub*128."""
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    if batch > (1 << 31) - 256:
        # the first mixed-radix addition computes base_digit + lane with
        # base_digit <= 255, so the lane index needs 256 of headroom
        # below 2^31 or the last lanes wrap and decode wrong candidates
        raise ValueError("batch must fit in int32 lane arithmetic "
                         "(max 2**31 - 256)")
    if not kernel_eligible(engine_name, gen, 1):
        raise ValueError(f"{engine_name} mask job not kernel-eligible; "
                         "use the XLA path")
    grid = batch // tile
    seg_tables = [charset_segments(cs) for cs in gen.charsets]
    kernel = _build_kernel(engine_name, gen.radices, seg_tables,
                           gen.length, target_words, sub)
    L = gen.length
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
        ],
        interpret=interpret,
    )


def make_pallas_mask_crack_step(engine_name: str, gen,
                                target_words: np.ndarray, batch: int,
                                hit_capacity: int = 64,
                                interpret: bool = False):
    """Drop-in replacement for ops/pipeline.make_mask_crack_step on the
    single-target kernel path: step(base_digits, n_valid) ->
    (count, lanes, tpos)."""
    tile = SUB * 128
    fn = make_mask_pallas_fn(engine_name, gen, target_words, batch,
                             interpret=interpret)

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step


def reduce_tile_hits(counts: jnp.ndarray, hit_lanes: jnp.ndarray,
                     hit_capacity: int, tile: int):
    """Per-tile kernel outputs -> the worker's (count, lanes, tpos)
    contract.  A tile holding 2+ hits can only report one lane, so any
    such tile forces count > hit_capacity: the worker's exact host
    rescan then recovers every hit."""
    from dprf_tpu.ops import compare as cmp_ops

    c = counts[:, 0]
    total = jnp.sum(c)
    collision = jnp.any(c > 1)
    _, tiles, _ = cmp_ops.compact_hits(c > 0, jnp.zeros_like(c),
                                       hit_capacity)
    glanes = jnp.where(
        tiles >= 0,
        tiles * tile + hit_lanes[jnp.maximum(tiles, 0), 0], -1)
    count = jnp.where(collision, jnp.int32(hit_capacity + 1), total)
    return count, glanes, jnp.zeros_like(glanes)
