"""Fused combinator crack steps: (left x right) -> concat -> digest ->
compare -> hits, entirely on device.

The decode is two gathers (one row per side) plus a vectorized
variable-shift concatenation -- out[b, p] = left[b, p] for p < llen[b],
else right[b, p - llen[b]] -- followed by the engines' varlen packing.
Lanes whose combined length exceeds the single-block limit are masked
invalid (keyspace holes, same contract as rejected rules).
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from jax import lax

from dprf_tpu.ops import compare as cmp_ops


def _decode_combine(gen, lbuf, llens, rbuf, rlens, base_digits,
                    batch: int, lane_offset=0):
    """base_digits int32[2] + lane -> (cand uint8[B, W], lengths
    int32[B], within-block bool[B]).  W = min(max_len, Lw + Rw)."""
    R = gen.n_right
    lane = lane_offset + jnp.arange(batch, dtype=jnp.int32)
    s = base_digits[1] + lane
    ri = s % R
    li = base_digits[0] + s // R
    lw = jnp.take(lbuf, li, axis=0)          # [B, Lw]
    ll = jnp.take(llens, li)
    rw = jnp.take(rbuf, ri, axis=0)          # [B, Rw]
    rl = jnp.take(rlens, ri)
    width = min(gen.max_len, lbuf.shape[1] + rbuf.shape[1])
    pos = jnp.arange(width, dtype=jnp.int32)[None, :]
    lpad = jnp.zeros((batch, width), jnp.uint8).at[
        :, :min(width, lbuf.shape[1])].set(
            lw[:, :min(width, lbuf.shape[1])])
    ridx = jnp.clip(pos - ll[:, None], 0, rbuf.shape[1] - 1)
    rshift = jnp.take_along_axis(rw, ridx, axis=1)
    cand = jnp.where(pos < ll[:, None], lpad, rshift)
    lengths = ll + rl
    fits = lengths <= gen.max_len
    return cand, jnp.minimum(lengths, gen.max_len), fits


def make_combinator_crack_step(engine, gen,
                               targets: Union[jnp.ndarray,
                                              cmp_ops.TargetTable],
                               batch: int, hit_capacity: int = 64,
                               widen_utf16: bool = False):
    """step(base_digits int32[2], n_valid int32) ->
    (count, lanes int32[cap], tpos int32[cap]) -- the DeviceMaskWorker
    contract, so the standard worker machinery drives it unchanged."""
    from dprf_tpu.ops import pack as pack_ops
    from dprf_tpu.targets import probe as probe_mod

    lbuf, llens, rbuf, rlens = map(jnp.asarray, gen.tables())
    multi = isinstance(targets, cmp_ops.TargetTable)
    probe = isinstance(targets, probe_mod.ProbeTable)
    survivors = probe_mod.survivor_cap(targets, batch) if probe else 0

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        cand, lengths, fits = _decode_combine(
            gen, lbuf, llens, rbuf, rlens, base_digits, batch)
        if widen_utf16:
            cand = pack_ops.utf16le_widen(cand)
            lengths = lengths * 2
        digest = engine.digest_candidates(cand, lengths)
        valid = fits & (jnp.arange(batch, dtype=jnp.int32) < n_valid)
        if probe:
            return probe_mod.probe_hits(digest, targets, valid,
                                        hit_capacity, survivors)
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, targets)
        else:
            found = cmp_ops.compare_single(digest, targets)
            tpos = jnp.zeros((batch,), jnp.int32)
        return cmp_ops.compact_hits(found & valid, tpos, hit_capacity)

    return step


def make_sharded_combinator_crack_step(
        engine, gen, targets: Union[jnp.ndarray, cmp_ops.TargetTable],
        mesh, batch_per_device: int, hit_capacity: int = 64,
        widen_utf16: bool = False):
    """Multi-chip combinator step through the ONE sharded runtime
    (parallel/sharded.py): only the per-shard compute lives here."""
    from dprf_tpu.ops import pack as pack_ops
    from dprf_tpu.parallel.sharded import (make_sharded_step,
                                           probe_lane_compare)
    from dprf_tpu.targets import probe as probe_mod

    lbuf, llens, rbuf, rlens = map(jnp.asarray, gen.tables())
    multi = isinstance(targets, cmp_ops.TargetTable)
    probe = isinstance(targets, probe_mod.ProbeTable)
    B = batch_per_device
    _probe_compute = probe_lane_compare(targets, B) if probe else None

    def compute(offset, base_digits, n_valid):
        cand, lengths, fits = _decode_combine(
            gen, lbuf, llens, rbuf, rlens, base_digits, B,
            lane_offset=offset)
        if widen_utf16:
            cand = pack_ops.utf16le_widen(cand)
            lengths = lengths * 2
        digest = engine.digest_candidates(cand, lengths)
        lane = offset + jnp.arange(B, dtype=jnp.int32)
        valid = fits & (lane < n_valid)
        if probe:
            return _probe_compute(
                digest, probe_mod.bloom_maybe(digest, targets) & valid)
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, targets)
        else:
            found = cmp_ops.compare_single(digest, targets)
            tpos = jnp.zeros((B,), jnp.int32)
        return found & valid, tpos

    step = make_sharded_step(compute, mesh, B, 2,
                             hit_capacity=hit_capacity)
    step.super_batch = step.super_span
    return step
