"""Fused 7-Zip KDF Pallas kernel: the 2^cycles SHA-256 counter stream.

The 7z check is KDF-bound (~2^cycles * unit/64 SHA-256 compressions
per candidate; the AES+CRC tail is noise), and the XLA fori_loop form
leaves most of the VPU idle between small per-group fusions — the
same gap the PBKDF2/PMKID kernel closed for config 5.  This kernel
keeps the whole stream walk in registers per candidate lane:

  mask decode -> lcm(64, unit)-byte group loop (every byte's source
  is compile-time wiring: salt const / candidate byte / counter
  shift, exactly the scheme of engines/device/sevenzip.py's XLA
  walker) -> final padding block -> 8 key words to HBM.

The AES-256-CBC decrypt + CRC32 verdict stays in XLA downstream
(per-candidate S-box gathers don't belong in the candidate-per-lane
layout); the kernel output is uint32[B, 8] key states consumed by
the engine's `_check_from_state`.

The group loop is `lax.fori_loop` with an 8-register carry — the
small-carry shape proven to lower (TPU_PROBE_LOG_r04 finding 2 /
the PBKDF2 kernel); the bpg compress calls inside the body are
statically unrolled.
"""

from __future__ import annotations

import math

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops import sha256 as sha256_ops
from dprf_tpu.ops.pallas_mask import (SUB, decode_candidate_bytes,
                                      mask_supported, segment_tables)


def sevenzip_kernel_eligible(gen, cycles: int, salt_len: int) -> bool:
    """Any mask charset order (segment mux, unbounded since r5); the
    counter stream must tile into whole groups (always true for
    cycles >= 6, the realistic range)."""
    if not hasattr(gen, "charsets") or not mask_supported(gen.charsets):
        return False
    unit = salt_len + 2 * gen.length + 8
    upg = 64 // math.gcd(64, unit)
    return (1 << cycles) % upg == 0 and 0 < cycles <= 24


def _compress(state, m):
    out = sha256_ops.sha256_rounds(*state, m)
    return tuple(o + s for o, s in zip(out, state))


def _kdf_lanes(byts, length: int, salt: bytes, cycles: int, shape):
    """Candidate byte arrays -> 8 SHA-256 key words; pure function
    shared by the pallas kernel and eager validation tests."""
    sl = len(salt)
    unit = sl + 2 * length + 8
    g = math.gcd(64, unit)
    bpg, upg = unit // g, 64 // g
    n_units = 1 << cycles
    n_groups = n_units // upg

    def byte_at(q: int, grp):
        u, off = divmod(q, unit)
        if off < sl:
            return jnp.full(shape, jnp.uint32(salt[off]))
        off -= sl
        if off < 2 * length:
            if off % 2:
                return jnp.zeros(shape, jnp.uint32)
            return byts[off // 2]
        cb = off - 2 * length
        if cb >= 4:
            return jnp.zeros(shape, jnp.uint32)
        counter = (grp * upg + u).astype(jnp.uint32)
        return jnp.full(shape,
                        (counter >> jnp.uint32(8 * cb))
                        & jnp.uint32(0xFF))

    def group(grp, state):
        for b in range(bpg):
            m = []
            for w in range(16):
                q = 64 * b + 4 * w
                m.append((byte_at(q, grp) << jnp.uint32(24))
                         | (byte_at(q + 1, grp) << jnp.uint32(16))
                         | (byte_at(q + 2, grp) << jnp.uint32(8))
                         | byte_at(q + 3, grp))
            state = _compress(state, m)
        return state

    state = tuple(jnp.full(shape, jnp.uint32(int(w)))
                  for w in sha256_ops.INIT)
    state = lax.fori_loop(0, n_groups, group, state)

    bitlen = n_units * unit * 8
    pad = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
    pad[0] = jnp.full(shape, jnp.uint32(0x80000000))
    pad[14] = jnp.full(shape, jnp.uint32((bitlen >> 32) & 0xFFFFFFFF))
    pad[15] = jnp.full(shape, jnp.uint32(bitlen & 0xFFFFFFFF))
    return _compress(state, pad)


def make_7z_kdf_pallas_fn(gen, batch: int, salt: bytes, cycles: int,
                          sub: int = SUB, interpret: bool = False):
    """fn(base_digits) -> uint32[batch, 8] key states (invalid lanes
    produce garbage keys; the downstream step masks by n_valid)."""
    tile = sub * 128
    if batch % tile or batch <= 0:
        raise ValueError(f"batch {batch} must be a multiple of "
                         f"tile {tile}")
    if not sevenzip_kernel_eligible(gen, cycles, len(salt)):
        raise ValueError("7z KDF kernel: job not eligible")
    grid = batch // tile
    seg_tables = segment_tables(gen.charsets)
    radices, length = gen.radices, gen.length

    def kernel(base_ref, out_ref):
        shape = (sub, 128)
        pid = pl.program_id(0)
        lane = (lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + lax.broadcasted_iota(jnp.int32, shape, 1))
        carry = lane + pid * tile
        byts = decode_candidate_bytes(radices, seg_tables, length,
                                      base_ref, carry)
        state = _kdf_lanes(byts, length, salt, cycles, shape)
        out_ref[...] = jnp.concatenate(list(state), axis=0)

    L = gen.length
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[pl.BlockSpec((L,), lambda i: (0,),
                               memory_space=pltpu.SMEM)],
        out_specs=[pl.BlockSpec((8 * sub, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8 * sub, 128),
                                        jnp.uint32)],
        interpret=interpret,
    )

    @jax.jit
    def fn(base_digits):
        # jit even in interpret mode: an eager interpreter walk of
        # the unrolled sha256 rounds is ~100k op dispatches
        (packed,) = raw(base_digits.astype(jnp.int32))
        # rows (grid, word, sub) x lanes -> candidate-major (batch, 8)
        words = packed.reshape(grid, 8, sub, 128)
        return words.transpose(0, 2, 3, 1).reshape(batch, 8)

    return fn
