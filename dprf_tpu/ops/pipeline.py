"""The fused crack step: index -> candidate -> digest -> compare -> hits.

This is the framework's hot loop (SURVEY.md section 3): one jitted
program in which candidates are materialized, hashed, and compared
entirely on device.  Only a fixed-size hit buffer and a count ever cross
back to the host.

The step takes the work unit's base index as a mixed-radix digit vector
(int32[L]) plus a valid-lane count, so a single compiled program serves
every unit of a job regardless of keyspace size.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp

from dprf_tpu.engines.base import DeviceHashEngine
from dprf_tpu.generators.mask import MaskGenerator
from dprf_tpu.ops import compare as cmp_ops


def make_mask_crack_step(engine, gen: MaskGenerator,
                         targets: Union[jnp.ndarray, cmp_ops.TargetTable],
                         batch: int, hit_capacity: int = 64,
                         widen_utf16: bool = False):
    """Build the jitted fused step for a mask attack.

    engine: a DeviceHashEngine (jax device variant).
    targets: uint32[W] single target words, a TargetTable, or a
        targets.probe.ProbeTable (bulk lists; see dprf_tpu/targets/).
    Returns step(base_digits int32[L], n_valid int32) ->
        (count int32, lanes int32[cap], target_pos int32[cap]).
    """
    from dprf_tpu.targets import probe as probe_mod

    flat = gen.flat_charsets
    length = gen.length
    multi = isinstance(targets, cmp_ops.TargetTable)
    probe = isinstance(targets, probe_mod.ProbeTable)
    survivors = probe_mod.survivor_cap(targets, batch) if probe else 0

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        cand = gen.decode_batch(base_digits, flat, batch)
        if widen_utf16:
            cand_bytes = jnp.reshape(
                jnp.stack([cand, jnp.zeros_like(cand)], axis=-1),
                (batch, 2 * length))
            words = engine.pack(cand_bytes, 2 * length)
        else:
            words = engine.pack(cand, length)
        digest = engine.digest_packed(words)
        valid = jnp.arange(batch, dtype=jnp.int32) < n_valid
        if probe:
            return probe_mod.probe_hits(digest, targets, valid,
                                        hit_capacity, survivors)
        if multi:
            found, tpos = cmp_ops.compare_multi(digest, targets)
        else:
            found = cmp_ops.compare_single(digest, targets)
            tpos = jnp.zeros((batch,), jnp.int32)
        return cmp_ops.compact_hits(found & valid, tpos, hit_capacity)

    return step


def target_words(digest: bytes, little_endian: bool = True) -> jnp.ndarray:
    """Raw digest bytes -> uint32[W] in the engine's word layout."""
    import numpy as np
    words = np.frombuffer(digest, dtype="<u4" if little_endian else ">u4")
    return jnp.asarray(words.astype(np.uint32))   # native byte order for jax
