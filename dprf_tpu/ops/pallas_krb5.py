"""Pallas Kerberos etype-23 prefilter kernel: vector-rate RC4.

The XLA krb5 filter step (engines/device/krb5.py) measured 21 kH/s on
the real chip (TPU_RESULTS_r04 case krb5-20): its RC4 KSA is a
fori_loop whose per-candidate S-box swap lowers to per-lane SERIAL
gathers + scatters, the same failure mode the bcrypt XLA form hit.
This kernel applies the pallas_bcrypt layout cure to RC4:

- candidates ride the SUBLANE axis, SUBC per chunk; every working
  value (digest words, j, keystream) is an (SUBC, 128) lane-replicated
  tile;
- each candidate's 256-entry S state is two (SUBC, 128) uint32 halves
  with the ENTRY INDEX along lanes, so `S[j]` is the hardware's native
  per-sublane `take_along_axis` gather (two halves + a bit-7 select)
  and the swap WRITES are lane-iota compare + select — no scatter;
- the KSA runs as an in-kernel `lax.fori_loop` with a 3-array carry
  (S_lo, S_hi, j) — the small-carry shape proven to lower by the
  PBKDF2 kernel (TPU_PROBE_LOG_r04 finding 2 applies only to large
  SoA-tuple carries);
- upstream of RC4, the whole chain — mask decode, UTF-16LE widening,
  MD4 (NTLM), HMAC-MD5(K, msg_type), HMAC-MD5(K1, checksum) — runs
  lane-replicated in the same kernel, so nothing touches HBM between
  decode and verdict;
- one grid cell sweeps CHUNKS × SUBC candidates through a fori_loop
  (accumulating count / hit-index scalars) so the mandatory (8, 128)
  output block amortizes to ~2 B/candidate of HBM traffic.

Like the decrypted-header filter it accelerates, the kernel checks
keystream bytes [8, 12) (past the RFC 4757 confounder) against the
DER expectation; the checksum, ciphertext word, expectation, and mask
are RUNTIME SMEM scalars, so ONE compiled kernel per mask serves every
target of both krb5tgs and krb5asrep (the msg_type is a scalar too).
"""

from __future__ import annotations


import numpy as np

from dprf_tpu.utils import env as envreg  # noqa: E402 -- stdlib-only
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops import md4 as md4_ops
from dprf_tpu.ops import md5 as md5_ops
from dprf_tpu.ops.pallas_mask import (decode_candidate_bytes,
                                      mask_supported, segment_tables,
                                      _pack_message)

#: candidates per sublane chunk / chunks per grid cell.  VMEM per
#: chunk is ~SUBC * 1 KB of S state plus the lane-replicated words.
SUBC = envreg.get_int("DPRF_KRB5_SUBC")
CHUNKS = envreg.get_int("DPRF_KRB5_CHUNKS")
#: statically unroll the 256-step KSA: the loop counter's S read
#: becomes a static lane slice and the key byte a trace-time shift
#: (no gather), leaving ONE dynamic gather per step instead of three.
#: DEFAULT OFF: the unrolled graph SIGABRTs this toolchain's Mosaic
#: compile helper at every SUBC tried (r4 sweep, krb5cfg-20-*-1 --
#: clean HTTP 500, no tunnel wedge); the fori_loop form compiles in
#: ~10 s and measured 474-497 kH/s.  Re-try on newer toolchains.
UNROLL = envreg.get_bool("DPRF_KRB5_UNROLL")

_IPAD = 0x36363636
_OPAD = 0x5C5C5C5C


def krb5_kernel_eligible(gen, max_len: int = 27) -> bool:
    """Mask-attack jobs the kernel covers: any charset order
    (unbounded segment mux since r5),
    NTLM's single-block UTF-16LE candidate limit."""
    return (hasattr(gen, "charsets") and gen.length <= max_len
            and mask_supported(gen.charsets))


# lane-replicated MD5 compress now shared via pallas_mask (also used
# by the PDF kernel); historical local name kept for the bodies below.
from dprf_tpu.ops.pallas_mask import md5_compress_lanes as _compress  # noqa: E402


def _hmac_md5(key4, msg_words, msg_len: int, shape):
    """HMAC-MD5 with a per-candidate 16-byte key and a short
    word-aligned message (msg_len in {4, 16} bytes) -> 4 words."""
    init = tuple(jnp.full(shape, jnp.uint32(int(w)))
                 for w in md5_ops.INIT)
    zero = jnp.zeros(shape, jnp.uint32)
    ipad = [key4[t] ^ jnp.uint32(_IPAD) for t in range(4)] + \
        [jnp.full(shape, jnp.uint32(_IPAD)) for _ in range(12)]
    opad = [key4[t] ^ jnp.uint32(_OPAD) for t in range(4)] + \
        [jnp.full(shape, jnp.uint32(_OPAD)) for _ in range(12)]
    istate = _compress(init, ipad)
    ostate = _compress(init, opad)
    nw = msg_len // 4
    inner_m = list(msg_words[:nw]) + [zero] * (16 - nw)
    inner_m[nw] = jnp.full(shape, jnp.uint32(0x80))
    inner_m[14] = jnp.full(shape, jnp.uint32((64 + msg_len) * 8))
    inner = _compress(istate, inner_m)
    outer_m = list(inner) + [zero] * 12
    outer_m[4] = jnp.full(shape, jnp.uint32(0x80))
    outer_m[14] = jnp.full(shape, jnp.uint32((64 + 16) * 8))
    return _compress(ostate, outer_m)


# the 256-entry lane-axis lookup/swap pair now lives in pallas_mask
# (shared with the PDF RC4 kernel and the LUT charset decode); kept
# under the historical names for this module's KSA/PRGA bodies.
from dprf_tpu.ops.pallas_mask import gather256 as _gather256  # noqa: E402
from dprf_tpu.ops.pallas_mask import swap256 as _swap256  # noqa: E402


def _rc4_word2(key4, shape, unroll: bool):
    """RC4 keystream bytes [8, 12) for 16-byte keys, packed LE."""
    lane = lax.broadcasted_iota(jnp.int32, shape, 1)
    S_lo0 = lane.astype(jnp.uint32)
    S_hi0 = S_lo0 + jnp.uint32(128)

    if unroll:
        S_lo, S_hi = S_lo0, S_hi0
        j = jnp.zeros(shape, jnp.uint32)
        for i in range(256):        # static i: S[i] is a lane slice,
            half = S_lo if i < 128 else S_hi          # key a shift
            si = jnp.broadcast_to(half[:, i % 128:i % 128 + 1], shape)
            t = i % 16
            ki = (key4[t // 4] >> jnp.uint32(8 * (t % 4))) \
                & jnp.uint32(0xFF)
            j = (j + si + ki) & jnp.uint32(255)
            sj = _gather256(S_lo, S_hi, j)
            at_i = lane == i % 128
            if i < 128:
                S_lo = jnp.where(at_i, sj, S_lo)
            else:
                S_hi = jnp.where(at_i, sj, S_hi)
            S_lo, S_hi = _swap256(S_lo, S_hi, j, si, lane)
    else:
        # key bytes along the first 16 lanes (gathered by i % 16)
        kb = jnp.zeros(shape, jnp.uint32)
        for t in range(16):
            kb = jnp.where(lane == t,
                           (key4[t // 4] >> jnp.uint32(8 * (t % 4)))
                           & jnp.uint32(0xFF), kb)

        def ksa(i, carry):
            S_lo, S_hi, j = carry
            i_rep = jnp.full(shape, i.astype(jnp.uint32))
            si = _gather256(S_lo, S_hi, i_rep)
            ki = jnp.take_along_axis(
                kb, jnp.full(shape, i % 16, jnp.int32), axis=1)
            j = (j + si + ki) & jnp.uint32(255)
            sj = _gather256(S_lo, S_hi, j)
            S_lo, S_hi = _swap256(S_lo, S_hi, i_rep, sj, lane)
            S_lo, S_hi = _swap256(S_lo, S_hi, j, si, lane)
            return S_lo, S_hi, j

        S_lo, S_hi, _ = lax.fori_loop(
            0, 256, ksa, (S_lo0, S_hi0, jnp.zeros(shape, jnp.uint32)))

    j = jnp.zeros(shape, jnp.uint32)
    word = jnp.zeros(shape, jnp.uint32)
    for t in range(12):             # PRGA, static i = t + 1 < 128
        i = t + 1
        si = jnp.broadcast_to(S_lo[:, i:i + 1], shape)
        j = (j + si) & jnp.uint32(255)
        sj = _gather256(S_lo, S_hi, j)
        i_rep = jnp.full(shape, jnp.uint32(i))
        S_lo, S_hi = _swap256(S_lo, S_hi, i_rep, sj, lane)
        S_lo, S_hi = _swap256(S_lo, S_hi, j, si, lane)
        k = _gather256(S_lo, S_hi, (si + sj) & jnp.uint32(255))
        if t >= 8:
            word = word | (k << jnp.uint32(8 * (t - 8)))
    return word


def _build_body(radices, seg_tables, length: int, sub: int,
                chunks: int, unroll: bool):
    """(pid, base, n_valid, type_w, chk_ref, cipher_w, mask_w, exp_w)
    -> (count, hit_index) scalars; hit_index is tile-local
    (chunk * sub + row), tile = sub * chunks."""
    tile = sub * chunks

    def body(pid, base, n_valid, type_w, chk_ref, cipher_w, mask_w,
             exp_w):
        shape = (sub, 128)
        row = lax.broadcasted_iota(jnp.int32, shape, 0)

        def chunk(c, acc):
            count, hit = acc
            gidx = pid * tile + c * sub + row
            byts = decode_candidate_bytes(radices, seg_tables, length,
                                          base, gidx)
            m = _pack_message(byts, length, shape, False, True)
            init = tuple(jnp.full(shape, jnp.uint32(int(w)))
                         for w in md4_ops.INIT)
            out = md4_ops.md4_rounds(*init, m)
            nt = tuple(x + s for x, s in zip(out, init))
            k1 = _hmac_md5(nt, [jnp.full(shape, type_w)], 4, shape)
            chk = [jnp.full(shape, chk_ref[i].astype(jnp.uint32))
                   for i in range(4)]
            k3 = _hmac_md5(k1, chk, 16, shape)
            ks = _rc4_word2(k3, shape, unroll)
            plain = ks ^ cipher_w
            found = ((plain & mask_w) == exp_w) & (gidx < n_valid)
            # lanes are replicated: count each candidate (row) once
            lane0 = lax.broadcasted_iota(jnp.int32, shape, 1) == 0
            found = found & lane0
            count = count + jnp.sum(found.astype(jnp.int32))
            hit = jnp.maximum(
                hit, jnp.max(jnp.where(found, c * sub + row, -1)))
            return count, hit

        return lax.fori_loop(0, chunks, chunk,
                             (jnp.int32(0), jnp.int32(-1)))

    return body


def make_krb5_pallas_fn(gen, batch: int, sub: int = 0,
                        chunks: int = 0, unroll: bool = None,
                        interpret: bool = False):
    """fn(base_digits, n_valid int32[1], type_w int32[1],
    chk int32[4], cipher int32[1], mask int32[1], expected int32[1])
    -> (counts int32[grid, 1], hit_idx int32[grid, 1]), tile-local
    hit indices; tile = sub * chunks."""
    sub = sub or SUBC
    chunks = chunks or CHUNKS
    unroll = UNROLL if unroll is None else unroll
    tile = sub * chunks
    if batch % tile or batch <= 0:
        raise ValueError(f"batch {batch} must be a multiple of "
                         f"tile {tile}")
    if tile > 0x7FFF:
        # hit+1 and count share one int32 as (count << 16) | (hit+1);
        # a larger tile would bleed into the count bits and report the
        # WRONG candidate index (a silent false negative after oracle
        # rejection)
        raise ValueError(f"tile {tile} exceeds the 15-bit packed "
                         "output limit (lower DPRF_KRB5_SUBC/CHUNKS)")
    if not krb5_kernel_eligible(gen):
        raise ValueError("krb5 kernel: mask not eligible")
    grid = batch // tile
    seg_tables = segment_tables(gen.charsets)
    body = _build_body(gen.radices, seg_tables, gen.length, sub,
                       chunks, unroll)

    def kernel(base_ref, nvalid_ref, type_ref, chk_ref, cipher_ref,
               mask_ref, exp_ref, out_ref):
        count, hit = body(
            pl.program_id(0), base_ref, nvalid_ref[0],
            type_ref[0].astype(jnp.uint32), chk_ref,
            cipher_ref[0].astype(jnp.uint32),
            mask_ref[0].astype(jnp.uint32),
            exp_ref[0].astype(jnp.uint32))
        out_ref[...] = jnp.full((8, 128), (count << 16) | (hit + 1),
                                jnp.int32)

    L = gen.length
    smem = lambda n: pl.BlockSpec((n,), lambda i: (0,),
                                  memory_space=pltpu.SMEM)
    raw = pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[smem(L), smem(1), smem(1), smem(4), smem(1),
                  smem(1), smem(1)],
        out_specs=[pl.BlockSpec((8, 128), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((grid * 8, 128), jnp.int32)],
        interpret=interpret,
    )

    def fn(base_digits, n_valid, type_w, chk, cipher, mask, expected):
        (packed,) = raw(base_digits, n_valid, type_w, chk, cipher,
                        mask, expected)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    return fn


def make_krb5_crack_step(gen, batch: int, hit_capacity: int = 64,
                         sub: int = 0, chunks: int = 0,
                         unroll: bool = None,
                         interpret: bool = False):
    """Kernel crack step with the worker (count, lanes, tpos)
    contract and runtime per-target scalars:
    step(base_digits, n_valid, type_w, chk, cipher, mask, expected).
    """
    from dprf_tpu.ops.pallas_mask import reduce_tile_hits

    sub = sub or SUBC
    chunks = chunks or CHUNKS
    tile = sub * chunks
    fn = make_krb5_pallas_fn(gen, batch, sub=sub, chunks=chunks,
                             unroll=unroll, interpret=interpret)

    @jax.jit
    def step(base_digits, n_valid, type_w, chk, cipher, mask,
             expected):
        counts, lanes = fn(base_digits.astype(jnp.int32),
                           jnp.reshape(n_valid, (1,)).astype(jnp.int32),
                           type_w, chk, cipher, mask, expected)
        return reduce_tile_hits(counts, lanes, hit_capacity, tile)

    return step


def target_scalars(target) -> tuple:
    """Target.params -> the kernel's five runtime scalar arrays."""
    from dprf_tpu.engines.device.krb5 import CONF, der_filter_words

    p = target.params
    expected, mask = der_filter_words(len(p["edata"]), p["msg_type"])

    def i32(v: int) -> jnp.ndarray:
        # uint32 bit pattern -> int32 SMEM scalar (no x64 needed)
        return jnp.asarray(np.array([v], np.uint32).view(np.int32))

    chk = np.frombuffer(p["checksum"], "<u4").view(np.int32).copy()
    return (i32(p["msg_type"]), jnp.asarray(chk),
            i32(int.from_bytes(p["edata"][CONF:CONF + 4], "little")),
            i32(mask), i32(expected))
