"""Fused wordlist+rules crack step (benchmark config 3).

One jitted program per job: slice a word batch out of the HBM-resident
packed wordlist, expand it through EVERY rule of the set *on device*
(config 3's "on-device rule expansion"), pack, digest, compare, compact
hits.  Rule application is trace-time-unrolled straight-line vector code
(rules/device.py), and the R-fold expanded candidate block [R*B, L] goes
through the engine's digest exactly once per step, so the hash — the
actual hot loop — dominates.

Index mapping (matches WordlistRulesGenerator): the concatenated
candidate block is rule-major, flat lane = r*B + b, and global keyspace
index = (w0 + b) * R + r.

Multi-chip: the sharded variant gives each chip a contiguous
`word_batch`-word slice of the super-batch; the wordlist array is
replicated to every chip's HBM once per job and sliced locally, so the
only steady-state cross-chip traffic is the psum'd hit count.
"""

from __future__ import annotations

from typing import Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from dprf_tpu.generators.wordlist import WordlistRulesGenerator
from dprf_tpu.ops import compare as cmp_ops
from dprf_tpu.ops import pack as pack_ops
from dprf_tpu.rules.device import apply_rule as apply_rule_device


def expand_rules(rules, wslice, lslice, base_valid, max_len: int):
    """Apply every rule to the word slice on device.

    Returns (cand uint8[R*B, L], lens int32[R*B], valid bool[R*B]) in
    rule-major flat-lane order (lane = r*B + b) -- the contract every
    wordlist worker's lane->keyspace-index decode relies on.
    """
    cands, clens, cvalid = [], [], []
    for rule in rules:
        cw, cl, cv = apply_rule_device(wslice, lslice, base_valid,
                                       rule, max_len)
        cands.append(cw)
        clens.append(cl)
        cvalid.append(cv)
    return (jnp.concatenate(cands, axis=0),
            jnp.concatenate(clens, axis=0),
            jnp.concatenate(cvalid, axis=0))


def _expand_and_digest(engine, rules, wslice, lslice, base_valid,
                       max_len: int, widen_utf16: bool):
    """Apply every rule to the word slice, digest the whole block.

    Returns (digest uint32[R*B, W], valid bool[R*B]) in rule-major
    flat-lane order."""
    cw, cl, cv = expand_rules(rules, wslice, lslice, base_valid, max_len)
    if widen_utf16:
        cw = pack_ops.utf16le_widen(cw)
        cl = cl * 2
    return engine.digest_candidates(cw, cl), cv


def _compare(digest, targets, multi):
    if multi:
        return cmp_ops.compare_multi(digest, targets)
    found = cmp_ops.compare_single(digest, targets)
    return found, jnp.zeros(digest.shape[0], jnp.int32)


def make_wordlist_crack_step(
        engine, gen: WordlistRulesGenerator,
        targets: Union[jnp.ndarray, cmp_ops.TargetTable],
        word_batch: int, hit_capacity: int = 64,
        widen_utf16: bool = False):
    """Returns step(w0 int32, n_valid_words int32) ->
    (count int32, lanes int32[cap], tpos int32[cap]); lanes are flat
    r*B+b indices into the step's candidate block."""
    from dprf_tpu.targets import probe as probe_mod

    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(pad_to=B,
                                         min_size=gen.n_words + B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    multi = isinstance(targets, cmp_ops.TargetTable)
    probe = isinstance(targets, probe_mod.ProbeTable)
    survivors = (probe_mod.survivor_cap(targets, B * len(rules))
                 if probe else 0)

    @jax.jit
    def step(w0: jnp.ndarray, n_valid_words: jnp.ndarray):
        wslice = lax.dynamic_slice(words_dev, (w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (w0,), (B,))
        base_valid = jnp.arange(B, dtype=jnp.int32) < n_valid_words
        digest, cv = _expand_and_digest(engine, rules, wslice, lslice,
                                        base_valid, L, widen_utf16)
        if probe:
            # bulk lists: Bloom-prefilter + on-device exact verify over
            # the rule-expanded block; lanes keep the same rule-major
            # flat indices the compact path emits
            return probe_mod.probe_hits(digest, targets, cv,
                                        hit_capacity, survivors)
        found, tpos = _compare(digest, targets, multi)
        return cmp_ops.compact_hits(found & cv, tpos, hit_capacity)

    return step


def make_sharded_wordlist_crack_step(
        engine, gen: WordlistRulesGenerator,
        targets: Union[jnp.ndarray, cmp_ops.TargetTable],
        mesh: Mesh, word_batch: int, hit_capacity: int = 64,
        widen_utf16: bool = False):
    """Multi-chip variant through the ONE sharded runtime
    (parallel/sharded.py): chip c expands+hashes words
    [w0 + offset, w0 + offset + word_batch) with the word cursor
    advancing ON DEVICE across superstep iterations.

    Returns step(w0 int32, n_valid_words int32) ->
        (total int32, counts int32[n_dev], lanes int32[n_dev, cap],
         tpos int32[n_dev, cap]); lanes are window-relative KEYSPACE
    offsets (relative to ``w0 * n_rules``): the runtime's globalize
    hook maps each rule-major flat lane r*B + b to
    ``(offset + b) * n_rules + r``, so the host decode is simply
    ``w0 * n_rules + lane``.
    """
    from dprf_tpu.parallel.sharded import (make_sharded_step,
                                           probe_lane_compare)
    from dprf_tpu.targets import probe as probe_mod

    n_dev = mesh.devices.size
    B, L = word_batch, gen.max_len
    words_np, lens_np = gen.packed_words(
        pad_to=n_dev * B, min_size=gen.n_words + n_dev * B - 1)
    words_dev = jnp.asarray(words_np)
    lens_dev = jnp.asarray(lens_np)
    rules = gen.rules
    R = len(rules)
    multi = isinstance(targets, cmp_ops.TargetTable)
    probe = isinstance(targets, probe_mod.ProbeTable)
    _probe_compute = (probe_lane_compare(targets, R * B)
                      if probe else None)

    def compute(offset, w0, n_valid_words):
        my_w0 = (w0 + offset).astype(jnp.int32)
        wslice = lax.dynamic_slice(words_dev, (my_w0, 0), (B, L))
        lslice = lax.dynamic_slice(lens_dev, (my_w0,), (B,))
        word_lane = offset + jnp.arange(B, dtype=jnp.int32)
        base_valid = word_lane < n_valid_words
        digest, cv = _expand_and_digest(engine, rules, wslice, lslice,
                                        base_valid, L, widen_utf16)
        if probe:
            return _probe_compute(
                digest, probe_mod.bloom_maybe(digest, targets) & cv)
        found, tpos = _compare(digest, targets, multi)
        return found & cv, tpos

    def globalize(lane, offset):
        # rule-major flat lane r*B + b -> window-relative keyspace
        # offset (offset + b) * R + r
        return (offset + lane % B) * R + lane // B

    step = make_sharded_step(compute, mesh, B, 2,
                             hit_capacity=hit_capacity,
                             globalize=globalize)
    step.super_words = step.super_span
    step.n_rules = R
    return step
