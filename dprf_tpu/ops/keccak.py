"""Keccak-f[1600] on uint32 lane pairs -- SHA3-256 and the original
Keccak-256 (Ethereum's hash; pre-NIST 0x01 padding).

The 25 64-bit lanes live as 50 uint32 planes (hi, lo per lane), so
every rotation is two shifts and an or -- the same 64-bit-emulation
recipe the SHA-512 core uses.  Round constants come from the
specification's LFSR, generated here rather than pasted.  Message
support is single-block (<= 135 bytes at rate 1088), which covers the
MAC shapes password cracking needs (Ethereum: 48 bytes).
"""

from __future__ import annotations

import numpy as np


def _rc_constants() -> list[int]:
    """Round constants via the Keccak LFSR (x^8+x^6+x^5+x^4+1)."""
    out = []
    r = 1
    for _ in range(24):
        rc = 0
        for j in range(7):
            if r & 1:
                rc |= 1 << ((1 << j) - 1)
            r = ((r << 1) ^ (0x71 if r & 0x80 else 0)) & 0xFF
        out.append(rc)
    return out


RC = _rc_constants()

#: rho rotation offsets, by lane (x, y) -> offset (generated from the
#: spec's t-iteration rather than written as a table)
_RHO = np.zeros((5, 5), np.int32)
_x, _y = 1, 0
for _t in range(24):
    _RHO[_x, _y] = ((_t + 1) * (_t + 2) // 2) % 64
    _x, _y = _y, (2 * _x + 3 * _y) % 5


def _rot64(hi, lo, n: int):
    n %= 64
    if n == 0:
        return hi, lo
    if n == 32:
        return lo, hi
    if n < 32:
        return ((hi << n) | (lo >> (32 - n)),
                (lo << n) | (hi >> (32 - n)))
    n -= 32
    return ((lo << n) | (hi >> (32 - n)),
            (hi << n) | (lo >> (32 - n)))


def _keccak_round(state, rc_hi, rc_lo):
    """One Keccak-f round over dict (x, y) -> (hi, lo) uint32 arrays;
    rc_hi/rc_lo may be traced gathers (fori form) or static scalars
    (unrolled form).  Shared by both so the round math has one source
    of truth."""
    # theta
    c = [(state[(x, 0)][0] ^ state[(x, 1)][0] ^ state[(x, 2)][0]
          ^ state[(x, 3)][0] ^ state[(x, 4)][0],
          state[(x, 0)][1] ^ state[(x, 1)][1] ^ state[(x, 2)][1]
          ^ state[(x, 3)][1] ^ state[(x, 4)][1])
         for x in range(5)]
    d = []
    for x in range(5):
        rh, rl = _rot64(*c[(x + 1) % 5], 1)
        d.append((c[(x - 1) % 5][0] ^ rh, c[(x - 1) % 5][1] ^ rl))
    for x in range(5):
        for y in range(5):
            hi, lo = state[(x, y)]
            state[(x, y)] = (hi ^ d[x][0], lo ^ d[x][1])
    # rho + pi
    b = {}
    for x in range(5):
        for y in range(5):
            hi, lo = state[(x, y)]
            b[(y, (2 * x + 3 * y) % 5)] = _rot64(hi, lo,
                                                 int(_RHO[x, y]))
    # chi
    for x in range(5):
        for y in range(5):
            bh, bl = b[(x, y)]
            nh, nl = b[((x + 1) % 5, y)]
            fh, fl = b[((x + 2) % 5, y)]
            state[(x, y)] = (bh ^ (~nh & fh), bl ^ (~nl & fl))
    # iota
    hi, lo = state[(0, 0)]
    state[(0, 0)] = (hi ^ rc_hi, lo ^ rc_lo)
    return state


def keccak_f(state):
    """state: dict (x, y) -> (hi, lo) uint32 arrays.

    The 24 rounds run in a lax.fori_loop: every rotation offset and
    permutation is round-INDEPENDENT (only iota's constant varies, so
    it indexes a [24, 2] table) -- one ~200-op round body compiles,
    not a 5k-op unroll (the unrolled-SHA256/DES compile lesson)."""
    import jax.numpy as jnp
    from jax import lax

    rc_tab = jnp.asarray(
        np.array([[c >> 32, c & 0xFFFFFFFF] for c in RC], np.uint32))

    def round_body(rnd, state):
        return _keccak_round(state, rc_tab[rnd, 0], rc_tab[rnd, 1])

    return lax.fori_loop(0, 24, round_body, dict(state))


def keccak_f_unrolled(state):
    """24 STATICALLY-unrolled rounds with python-int round constants --
    the Mosaic-lowerable form for the Pallas kernel (a fori_loop with
    a 50-array dict carry does not lower; see ops/sha256.py for the
    same split).  XLA:CPU compile time for the flat graph is minutes,
    so this form is TPU/emulator-only."""
    import jax.numpy as jnp

    state = dict(state)
    for rnd in range(24):
        state = _keccak_round(state, jnp.uint32(RC[rnd] >> 32),
                              jnp.uint32(RC[rnd] & 0xFFFFFFFF))
    return state


def keccak_words(msg: "jnp.ndarray", lengths, pad_byte: int = 0x01,
                 rate: int = 136, out_bytes: int = 32):
    """Single-block Keccak/SHA3 sponge: msg uint8[B, maxlen <= rate-1]
    + per-lane lengths -> digest uint32[B, ceil(out_bytes/4)]
    (big-endian word view).  pad_byte 0x01 = original Keccak;
    0x06 = SHA3.  rate = 200 - 2*out for standard digests
    (136/144/104/72 for 256/224/384/512)."""
    import jax.numpy as jnp

    B, maxlen = msg.shape
    if maxlen > rate - 1:
        raise ValueError(
            f"single-block keccak at rate {rate} needs <= {rate - 1} "
            "bytes")
    pos = jnp.arange(rate, dtype=jnp.int32)
    buf = jnp.zeros((B, rate), jnp.uint8).at[:, :maxlen].set(msg)
    lens = lengths[:, None]
    buf = jnp.where(pos < lens, buf, 0).astype(jnp.uint8)
    buf = buf + jnp.where(pos == lens, jnp.uint8(pad_byte), jnp.uint8(0))
    buf = buf.at[:, rate - 1].set(buf[:, rate - 1] | jnp.uint8(0x80))
    # lanes are little-endian 64-bit: lane i = bytes 8i..8i+7
    grouped = buf.reshape(B, rate // 8, 2, 4).astype(jnp.uint32)
    coef = jnp.asarray(np.array([1, 1 << 8, 1 << 16, 1 << 24],
                                np.uint32))
    words = (grouped * coef).sum(axis=-1, dtype=jnp.uint32)  # [B,17,2] lo,hi
    state = {(x, y): (jnp.zeros((B,), jnp.uint32),
                      jnp.zeros((B,), jnp.uint32))
             for x in range(5) for y in range(5)}
    for i in range(rate // 8):
        x, y = i % 5, i // 5
        hi, lo = state[(x, y)]
        state[(x, y)] = (hi ^ words[:, i, 1], lo ^ words[:, i, 0])
    state = keccak_f(state)
    return jnp.stack(squeeze_words(state, out_bytes), axis=-1)


def keccak256_words(msg: "jnp.ndarray", lengths, pad_byte: int = 0x01):
    """Single-block Keccak-256 (see keccak_words)."""
    return keccak_words(msg, lengths, pad_byte, rate=136, out_bytes=32)


def squeeze_words(state, out_bytes: int) -> list:
    """Digest squeeze: the first out_bytes of the state (row-major
    lanes, little-endian within a lane), exposed as BIG-endian uint32
    words so the framework's ">u4" target tables compare directly.  A
    half-lane tail (224: 28 bytes = 3.5 lanes) emits its low word.
    Shared by the XLA sponge (keccak_words) and the Pallas kernel body
    (ops/pallas_keccak.py)."""
    out = []
    for i in range(out_bytes // 8):
        hi, lo = state[(i % 5, i // 5)]
        out.append(_bswap(lo))
        out.append(_bswap(hi))
    if out_bytes % 8:
        i = out_bytes // 8
        out.append(_bswap(state[(i % 5, i // 5)][1]))
    return out


def _bswap(x):
    return ((x << 24) | ((x & 0xFF00) << 8) | ((x >> 8) & 0xFF00)
            | (x >> 24))


def _keccak_f_scalar(lanes: list[int]) -> list[int]:
    """Pure-python keccak-f[1600] on 25 ints (x + 5y indexing)."""
    M = (1 << 64) - 1

    def rot(v, n):
        n %= 64
        return ((v << n) | (v >> (64 - n))) & M if n else v

    for rnd in range(24):
        c = [lanes[x] ^ lanes[x + 5] ^ lanes[x + 10] ^ lanes[x + 15]
             ^ lanes[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ rot(c[(x + 1) % 5], 1) for x in range(5)]
        lanes = [lanes[i] ^ d[i % 5] for i in range(25)]
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                b[y + 5 * ((2 * x + 3 * y) % 5)] = rot(
                    lanes[x + 5 * y], int(_RHO[x, y]))
        lanes = [b[i] ^ ((~b[(i + 1) % 5 + 5 * (i // 5)] & M)
                         & b[(i + 2) % 5 + 5 * (i // 5)])
                 for i in range(25)]
        lanes[0] ^= RC[rnd]
    return lanes


def keccak_digest(data: bytes, pad_byte: int = 0x01, rate: int = 136,
                  out_bytes: int = 32) -> bytes:
    """Host scalar Keccak sponge (CPU oracle / test anchor); pad 0x01 =
    original Keccak, 0x06 = SHA3.  Multi-block capable (the device
    path is single-block; oracles may see longer data)."""
    buf = bytearray(data)
    buf.append(pad_byte)
    while len(buf) % rate:
        buf.append(0)
    buf[-1] |= 0x80
    lanes = [0] * 25
    for off in range(0, len(buf), rate):
        for i in range(rate // 8):
            lanes[i] ^= int.from_bytes(buf[off + 8 * i:off + 8 * i + 8],
                                       "little")
        lanes = _keccak_f_scalar(lanes)
    full = b"".join(lanes[i].to_bytes(8, "little")
                    for i in range((out_bytes + 7) // 8))
    return full[:out_bytes]


def keccak256(data: bytes, pad_byte: int = 0x01) -> bytes:
    """Host scalar Keccak-256 (see keccak_digest)."""
    return keccak_digest(data, pad_byte, rate=136, out_bytes=32)
