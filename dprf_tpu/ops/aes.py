"""AES-128 for verifier checks (MS Office, and any future AES-gated
format).

Scope deliberately narrow: the password-cracking use of AES here is
ONE to THREE block decryptions per candidate at the END of an
iterated-hash chain (Office 2007 runs 50,002 SHA-1 compressions
first), so a gather-based device implementation is fine -- the
measured per-lane gather serialization that makes bcrypt slow costs
~3% here because the hash chain dominates.  The S-box is FIPS-197
specification data; the inverse box and round constants are derived
from it at import.

Scalar encrypt/decrypt double as the CPU oracle and the test-vector
builders; `aes128_decrypt_blocks` is the jit-traceable batched form.
"""

from __future__ import annotations

import numpy as np

# FIPS-197 S-box (specification constant).
SBOX = bytes.fromhex(
    "637c777bf26b6fc53001672bfed7ab76ca82c97dfa5947f0add4a2af9ca472c0"
    "b7fd9326363ff7cc34a5e5f171d8311504c723c31896059a071280e2eb27b275"
    "09832c1a1b6e5aa0523bd6b329e32f8453d100ed20fcb15b6acbbe394a4c58cf"
    "d0efaafb434d338545f9027f503c9fa851a3408f929d38f5bcb6da2110fff3d2"
    "cd0c13ec5f974417c4a77e3d645d197360814fdc222a908846eeb814de5e0bdb"
    "e0323a0a4906245cc2d3ac629195e479e7c8376d8dd54ea96c56f4ea657aae08"
    "ba78252e1ca6b4c6e8dd741f4bbd8b8a703eb5664803f60e613557b986c11d9e"
    "e1f8981169d98e949b1e87e9ce5528df8ca1890dbfe6426841992d0fb054bb16")

_inv = bytearray(256)
for _i, _v in enumerate(SBOX):
    _inv[_v] = _i
INV_SBOX = bytes(_inv)


def _xtime(a: int) -> int:
    a <<= 1
    return (a ^ 0x1B) & 0xFF if a & 0x100 else a


def _gmul(a: int, b: int) -> int:
    out = 0
    for _ in range(8):
        if b & 1:
            out ^= a
        a = _xtime(a)
        b >>= 1
    return out


_RCON = []
_r = 1
for _ in range(10):
    _RCON.append(_r)
    _r = _xtime(_r)


def key_schedule(key: bytes) -> list[bytes]:
    """AES expanded round keys: 11 x 16 bytes for a 16-byte key,
    15 x 16 for a 32-byte key (FIPS-197 expansion, Nk = 4 or 8)."""
    nk = len(key) // 4
    if nk not in (4, 8):
        raise ValueError("AES-128 or AES-256 keys only")
    rounds = {4: 10, 8: 14}[nk]
    w = [list(key[4 * i:4 * i + 4]) for i in range(nk)]
    for i in range(nk, 4 * (rounds + 1)):
        t = list(w[i - 1])
        if i % nk == 0:
            t = t[1:] + t[:1]
            t = [SBOX[b] for b in t]
            t[0] ^= _RCON[i // nk - 1]
        elif nk == 8 and i % nk == 4:
            t = [SBOX[b] for b in t]
        w.append([a ^ b for a, b in zip(w[i - nk], t)])
    return [bytes(sum(w[4 * r:4 * r + 4], []))
            for r in range(rounds + 1)]


def _sub(state, box):
    return [box[b] for b in state]


def _shift_rows(s, inv=False):
    out = list(s)
    for r in range(1, 4):
        row = [s[r + 4 * c] for c in range(4)]
        k = (-r) % 4 if inv else r
        row = row[k:] + row[:k]
        for c in range(4):
            out[r + 4 * c] = row[c]
    return out


def _mix_columns(s, inv=False):
    m = ([[14, 11, 13, 9], [9, 14, 11, 13], [13, 9, 14, 11],
          [11, 13, 9, 14]] if inv else
         [[2, 3, 1, 1], [1, 2, 3, 1], [1, 1, 2, 3], [3, 1, 1, 2]])
    out = [0] * 16
    for c in range(4):
        col = s[4 * c:4 * c + 4]
        for r in range(4):
            out[4 * c + r] = (_gmul(m[r][0], col[0]) ^ _gmul(m[r][1], col[1])
                              ^ _gmul(m[r][2], col[2])
                              ^ _gmul(m[r][3], col[3]))
    return out


def aes_encrypt_block(key: bytes, block16: bytes) -> bytes:
    rks = key_schedule(key)
    last = len(rks) - 1
    s = [b ^ k for b, k in zip(block16, rks[0])]
    for rnd in range(1, last):
        s = _mix_columns(_shift_rows(_sub(s, SBOX)))
        s = [b ^ k for b, k in zip(s, rks[rnd])]
    s = _shift_rows(_sub(s, SBOX))
    return bytes(b ^ k for b, k in zip(s, rks[last]))


def aes_decrypt_block(key: bytes, block16: bytes) -> bytes:
    rks = key_schedule(key)
    last = len(rks) - 1
    s = [b ^ k for b, k in zip(block16, rks[last])]
    for rnd in range(last - 1, 0, -1):
        s = _sub(_shift_rows(s, inv=True), INV_SBOX)
        s = [b ^ k for b, k in zip(s, rks[rnd])]
        s = _mix_columns(s, inv=True)
    s = _sub(_shift_rows(s, inv=True), INV_SBOX)
    return bytes(b ^ k for b, k in zip(s, rks[0]))


# back-compat names used by the office2007 oracle/tests
aes128_encrypt_block = aes_encrypt_block
aes128_decrypt_block = aes_decrypt_block


# ---------------------------------------------------------------------------
# batched device form (gather S-boxes; keys differ per candidate)

def _dev_tables():
    import jax.numpy as jnp
    return (jnp.asarray(np.frombuffer(SBOX, np.uint8)),
            jnp.asarray(np.frombuffer(INV_SBOX, np.uint8)),
            jnp.asarray(_mul_table(), np.uint8))


def _mul_table() -> np.ndarray:
    """GF(2^8) multiply tables for the InvMixColumns coefficients
    {9, 11, 13, 14}: uint8[4, 256]."""
    out = np.zeros((4, 256), np.uint8)
    for i, coef in enumerate((9, 11, 13, 14)):
        for x in range(256):
            out[i, x] = _gmul(coef, x)
    return out


def _take(table, idx):
    import jax.numpy as jnp
    return jnp.take(table, idx.astype(jnp.int32), axis=0)


def aes_key_schedule_batch(key: "jnp.ndarray"):
    """uint8[B, 16|32] keys -> uint8[B, rounds+1, 16] round keys
    (vectorized FIPS-197 expansion; a few dozen shared S-box gathers)."""
    import jax.numpy as jnp

    sbox, _, _ = _dev_tables()
    nk = key.shape[1] // 4
    rounds = {4: 10, 8: 14}[nk]
    w = [key[:, 4 * i:4 * i + 4] for i in range(nk)]
    for i in range(nk, 4 * (rounds + 1)):
        t = w[i - 1]
        if i % nk == 0:
            t = jnp.concatenate([t[:, 1:], t[:, :1]], axis=1)
            t = _take(sbox, t)
            t = t.at[:, 0].set(t[:, 0] ^ np.uint8(_RCON[i // nk - 1]))
        elif nk == 8 and i % nk == 4:
            t = _take(sbox, t)
        w.append(w[i - nk] ^ t)
    return jnp.stack(w, axis=1).reshape(key.shape[0], rounds + 1, 16)


_INV_SHIFT = np.array(
    [0, 13, 10, 7, 4, 1, 14, 11, 8, 5, 2, 15, 12, 9, 6, 3], np.int32)


def aes_decrypt_blocks(keys: "jnp.ndarray",
                       blocks: np.ndarray) -> "jnp.ndarray":
    """Per-candidate keys uint8[B, 16|32] + CONSTANT ciphertext blocks
    uint8[N, 16] -> plaintext uint8[B, N, 16] (ECB; CBC callers xor
    the IV/previous ciphertext themselves -- both are constants)."""
    import jax.numpy as jnp

    _, inv_sbox, mul = _dev_tables()
    B = keys.shape[0]
    rks = aes_key_schedule_batch(keys)
    last = rks.shape[1] - 1
    ct = jnp.broadcast_to(jnp.asarray(blocks, jnp.uint8)[None],
                          (B,) + blocks.shape)
    out = []
    inv_shift = jnp.asarray(_INV_SHIFT)
    for n in range(blocks.shape[0]):
        s = ct[:, n] ^ rks[:, last]
        for rnd in range(last - 1, 0, -1):
            s = _take(inv_sbox, s[:, inv_shift])
            s = s ^ rks[:, rnd]
            # InvMixColumns over the 4 columns
            cols = s.reshape(B, 4, 4)
            g = [_take(mul[i], cols) for i in range(4)]   # 9,11,13,14
            m9, m11, m13, m14 = g
            r0 = m14[..., 0] ^ m11[..., 1] ^ m13[..., 2] ^ m9[..., 3]
            r1 = m9[..., 0] ^ m14[..., 1] ^ m11[..., 2] ^ m13[..., 3]
            r2 = m13[..., 0] ^ m9[..., 1] ^ m14[..., 2] ^ m11[..., 3]
            r3 = m11[..., 0] ^ m13[..., 1] ^ m9[..., 2] ^ m14[..., 3]
            s = jnp.stack([r0, r1, r2, r3], axis=-1).reshape(B, 16)
        s = _take(inv_sbox, s[:, inv_shift])
        out.append(s ^ rks[:, 0])
    return jnp.stack(out, axis=1)


# back-compat name used by the office2007 device engine
aes128_decrypt_blocks = aes_decrypt_blocks


_SHIFT = np.array(
    [0, 5, 10, 15, 4, 9, 14, 3, 8, 13, 2, 7, 12, 1, 6, 11], np.int32)


def _mul23_table() -> np.ndarray:
    """GF(2^8) multiply tables for the MixColumns coefficients
    {2, 3}: uint8[2, 256] (the forward-cipher counterpart of
    _mul_table's {9, 11, 13, 14})."""
    out = np.zeros((2, 256), np.uint8)
    for i, coef in enumerate((2, 3)):
        for x in range(256):
            out[i, x] = _gmul(coef, x)
    return out


def aes_encrypt_block_batch(keys: "jnp.ndarray",
                            block: "jnp.ndarray") -> "jnp.ndarray":
    """Per-candidate keys uint8[B, 16|32] + per-candidate plaintext
    block uint8[B, 16] -> ciphertext uint8[B, 16].  The forward cipher
    the RFC 3961 DK derivation chains (1-2 calls per derived key);
    per-candidate plaintext because the second chain block IS the
    prior per-candidate output."""
    import jax.numpy as jnp

    sbox, _, _ = _dev_tables()
    mul23 = jnp.asarray(_mul23_table())
    B = keys.shape[0]
    rks = aes_key_schedule_batch(keys)
    last = rks.shape[1] - 1
    shift = jnp.asarray(_SHIFT)
    s = block ^ rks[:, 0]
    for rnd in range(1, last):
        s = _take(sbox, s)[:, shift]
        cols = s.reshape(B, 4, 4)
        m2 = _take(mul23[0], cols)
        m3 = _take(mul23[1], cols)
        r0 = m2[..., 0] ^ m3[..., 1] ^ cols[..., 2] ^ cols[..., 3]
        r1 = cols[..., 0] ^ m2[..., 1] ^ m3[..., 2] ^ cols[..., 3]
        r2 = cols[..., 0] ^ cols[..., 1] ^ m2[..., 2] ^ m3[..., 3]
        r3 = m3[..., 0] ^ cols[..., 1] ^ cols[..., 2] ^ m2[..., 3]
        s = jnp.stack([r0, r1, r2, r3], axis=-1).reshape(B, 16)
        s = s ^ rks[:, rnd]
    s = _take(sbox, s)[:, shift]
    return s ^ rks[:, last]
