"""On-device digest comparison and hit compaction.

Single-target: direct word compare.  Multi-target (benchmark config 2):
targets are pre-sorted by their first digest word on the host; on device
a vectorized `searchsorted` narrows each candidate to a run of targets
sharing that word, and a small static window of full-digest compares
resolves it exactly.  The window size is computed on the host from the
actual maximum duplicate-run length, so the device code is always
correct, not just probabilistically so.

Hit extraction is data-dependent-shape-free (SURVEY.md section 7): a
fixed-capacity buffer filled by scatter, plus a total count.  Overflow
beyond the capacity loses lane detail but never the count, and the host
rescans the unit with the CPU oracle in that (pathological) case.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class TargetTable:
    """Host-prepared multi-target compare table (device arrays)."""

    words: jnp.ndarray        # uint32[T, W] sorted digests
    first: jnp.ndarray        # uint32[T] = words[:, 0] (sort key)
    window: int               # max duplicate run of `first`, static
    order: np.ndarray         # host: sorted position -> original target idx

    @property
    def num_targets(self) -> int:
        return self.words.shape[0]


def make_target_table(digests: list[bytes], word_bytes: int = 4,
                      little_endian: bool = True) -> TargetTable:
    """Build the device compare table from raw digest bytes.

    word_bytes=4: digests are split into uint32 words matching the
    engine's digest word layout (LE for MD4/MD5 family, BE for SHA).
    """
    if not digests:
        raise ValueError("empty target list")
    nwords = len(digests[0]) // word_bytes
    rows = np.zeros((len(digests), nwords), dtype=np.uint32)
    for i, d in enumerate(digests):
        if len(d) != nwords * word_bytes:
            raise ValueError("inconsistent digest sizes in target list")
        rows[i] = np.frombuffer(
            d, dtype="<u4" if little_endian else ">u4").astype(np.uint32)
    order = np.lexsort(rows.T[::-1])   # sort by word0, then word1, ...
    rows = rows[order]
    first = rows[:, 0]
    # Longest run of equal word0 values decides how many full compares the
    # device needs per candidate.  For random hashes this is 1.
    runs = np.diff(np.flatnonzero(
        np.concatenate(([True], first[1:] != first[:-1], [True]))))
    window = int(runs.max())
    return TargetTable(words=jnp.asarray(rows), first=jnp.asarray(first),
                      window=window, order=order)


def compare_single(digest: jnp.ndarray, target: jnp.ndarray) -> jnp.ndarray:
    """uint32[B, W] vs uint32[W] -> bool[B]."""
    return jnp.all(digest == target[None, :], axis=-1)


def compare_multi(digest: jnp.ndarray, table: TargetTable):
    """uint32[B, W] vs sorted table -> (found bool[B], target_pos int32[B]).

    target_pos indexes the *sorted* table; map back through table.order
    on the host.
    """
    t = table.num_targets
    pos = jnp.searchsorted(table.first, digest[:, 0])      # int[B], leftmost
    found = jnp.zeros(digest.shape[0], dtype=bool)
    tpos = jnp.zeros(digest.shape[0], dtype=jnp.int32)
    for k in range(table.window):
        idx = jnp.minimum(pos + k, t - 1).astype(jnp.int32)
        hit = jnp.all(table.words[idx] == digest, axis=-1)
        tpos = jnp.where(hit & ~found, idx, tpos)
        found = found | hit
    return found, tpos


def compact_hits(found: jnp.ndarray, lane_payload: jnp.ndarray,
                 capacity: int):
    """(found bool[B], payload int32[B]) -> fixed-size hit buffer.

    Returns (count int32, lanes int32[capacity], payload int32[capacity]);
    unused slots are -1.  Pure scatter -- no data-dependent shapes.
    """
    lane = jnp.arange(found.shape[0], dtype=jnp.int32)
    slot = jnp.cumsum(found.astype(jnp.int32)) - 1
    slot = jnp.where(found, slot, capacity)   # out-of-range -> dropped
    lanes = jnp.full((capacity,), -1, jnp.int32).at[slot].set(
        lane, mode="drop")
    payload = jnp.full((capacity,), -1, jnp.int32).at[slot].set(
        lane_payload, mode="drop")
    return found.sum(dtype=jnp.int32), lanes, payload
