"""Fused mask->MD5->compare Pallas TPU kernel (benchmark config 1's
hot loop as a single hand-scheduled kernel).

Why a kernel at all: the XLA path (ops/pipeline.py) materializes the
candidate block uint8[B, L] and the digest uint32[B, 4] in HBM between
fusions.  At the throughputs this engine targets, those intermediate
writes are the bandwidth floor.  This kernel keeps the whole chain --
mixed-radix decode, charset lookup, message packing, 64 MD5 steps,
compare, hit reduction -- in VMEM/registers, and writes only TWO int32
scalars per grid cell (hit count + hit lane) back to HBM: the HBM
traffic per candidate is ~8/TILE bytes instead of ~(L+16).

Design choices forced by the VPU:
- Charset lookup is arithmetic, not a gather: a charset in digit order
  is piecewise byte = digit + delta, so the lookup is a few vectorized
  `where` adds (7 segments for ?a, 1 for ?l/?u/?d).  Charsets needing
  more than MAX_SEGMENTS segments fall back to the XLA path.
- Hit extraction per tile is count + single-lane arithmetic max.  Two
  hits in one TILE-candidate tile (vanishingly rare below ~2^-40 for
  random targets; guaranteed visible in the count) force the caller's
  exact host rescan, so correctness never depends on the rarity.
- All lane arithmetic is int32, so a step's batch is capped below 2^31
  candidates (the factory enforces it); larger sweeps are driven as
  multiple steps by the worker, exactly like the XLA path.
"""

from __future__ import annotations

import os
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops.md5 import INIT, md5_rounds

#: sublane count per grid cell; TILE = SUB * 128 candidate lanes.
SUB = 32
TILE = SUB * 128
#: charsets needing more piecewise segments than this use the XLA path.
MAX_SEGMENTS = 16


def pallas_mode() -> Optional[dict]:
    """Whether the Pallas kernel path should be used, and how.

    DPRF_PALLAS=0 disables it; =1 forces it (interpret mode off-TPU,
    for tests); default "auto" uses it on real TPU only.  Returns
    kwargs for the step factory, or None for the XLA path.
    """
    env = os.environ.get("DPRF_PALLAS", "auto")
    if env == "0":
        return None
    import jax
    if jax.default_backend() == "tpu":
        return {"interpret": False}
    if env == "1":
        return {"interpret": True}
    return None


def charset_segments(charset: bytes):
    """Charset (digit order) -> [(start_digit, byte_delta)] pieces where
    byte = digit + delta for digit >= start_digit (until next piece)."""
    segs = []
    for d, byte in enumerate(charset):
        delta = byte - d
        if not segs or segs[-1][1] != delta:
            segs.append((d, delta))
    return segs


def mask_supported(charsets: Sequence[bytes]) -> bool:
    """True if every position's charset decodes in <= MAX_SEGMENTS
    arithmetic pieces (all builtin charsets do)."""
    return all(len(charset_segments(cs)) <= MAX_SEGMENTS
               for cs in charsets)


def _decode_byte(digit, segs):
    """Vectorized piecewise charset lookup: digit array -> byte array."""
    byte = digit + segs[0][1]
    for start, delta in segs[1:]:
        byte = jnp.where(digit >= start, digit + delta, byte)
    return byte


def _build_kernel(radices, seg_tables, length: int, target, sub: int):
    """Kernel closure: radices/charset segments/target words are baked
    in as constants (one compile per job, like the XLA step)."""
    tile = sub * 128
    # plain python ints: jnp scalars here would be captured closure
    # constants, which pallas_call rejects
    tw = [int(w) for w in target]

    def kernel(base_ref, nvalid_ref, counts_ref, hitlane_ref):
        pid = pl.program_id(0)
        lane = (jax.lax.broadcasted_iota(jnp.int32, (sub, 128), 0) * 128
                + jax.lax.broadcasted_iota(jnp.int32, (sub, 128), 1))
        # mixed-radix add (base digits + global offset), least
        # significant (rightmost mask position) first, fused with the
        # charset lookup.  The base index of this *tile* is folded into
        # the scalar side (pid * tile) before vector carry propagation.
        carry = lane + pid * tile
        byts: list = [None] * length
        for p in range(length - 1, -1, -1):
            r = radices[p]
            s = base_ref[p] + carry
            byts[p] = _decode_byte(s % r, seg_tables[p]).astype(jnp.uint32)
            carry = s // r
        # pack bytes + Merkle-Damgard padding into the 16 message words
        m = [jnp.zeros((sub, 128), jnp.uint32) for _ in range(16)]
        for p in range(length):
            m[p // 4] = m[p // 4] | (byts[p] << (8 * (p % 4)))
        m[length // 4] = m[length // 4] | jnp.uint32(0x80 << (8 * (length % 4)))
        m[14] = jnp.full((sub, 128), jnp.uint32(8 * length))
        a, b, c, d = md5_rounds(
            jnp.full((sub, 128), jnp.uint32(int(INIT[0]))),
            jnp.full((sub, 128), jnp.uint32(int(INIT[1]))),
            jnp.full((sub, 128), jnp.uint32(int(INIT[2]))),
            jnp.full((sub, 128), jnp.uint32(int(INIT[3]))),
            m)
        a = a + jnp.uint32(int(INIT[0]))
        b = b + jnp.uint32(int(INIT[1]))
        c = c + jnp.uint32(int(INIT[2]))
        d = d + jnp.uint32(int(INIT[3]))
        valid = (lane + pid * tile) < nvalid_ref[0]
        found = ((a == jnp.uint32(tw[0])) & (b == jnp.uint32(tw[1]))
                 & (c == jnp.uint32(tw[2])) & (d == jnp.uint32(tw[3]))
                 & valid)
        counts_ref[0, 0] = jnp.sum(found.astype(jnp.int32))
        # single-hit extraction: max lane among hits (-1 if none); the
        # caller rescans any tile whose count exceeds 1.
        hitlane_ref[0, 0] = jnp.max(jnp.where(found, lane, -1))

    return kernel


def make_md5_mask_pallas_fn(gen, target_words: np.ndarray, batch: int,
                            sub: int = SUB, interpret: bool = False):
    """Build fn(base_digits int32[L], n_valid int32[1]) ->
    (counts int32[G, 1], hit_lanes int32[G, 1]) over a `batch`-lane
    sweep.  batch must be a multiple of sub*128."""
    tile = sub * 128
    if batch % tile:
        raise ValueError(f"batch {batch} not a multiple of tile {tile}")
    if batch >= 1 << 31:
        raise ValueError("batch must fit in int32 lane arithmetic")
    if gen.length > 55:
        raise ValueError("mask longer than the 55-byte single-block "
                         "limit; use the XLA path")
    grid = batch // tile
    charsets = gen.charsets
    if not mask_supported(charsets):
        raise ValueError("charset needs too many segments for the "
                         "arithmetic decode; use the XLA path")
    seg_tables = [charset_segments(cs) for cs in charsets]
    kernel = _build_kernel(gen.radices, seg_tables, gen.length,
                           target_words, sub)
    L = gen.length
    return pl.pallas_call(
        kernel,
        grid=(grid,),
        in_specs=[
            pl.BlockSpec((L,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (0,), memory_space=pltpu.SMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, 1), lambda i: (i, 0),
                         memory_space=pltpu.SMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
            jax.ShapeDtypeStruct((grid, 1), jnp.int32),
        ],
        interpret=interpret,
    )


def make_pallas_mask_crack_step(gen, target_words: np.ndarray, batch: int,
                                hit_capacity: int = 64,
                                interpret: bool = False):
    """Drop-in replacement for ops/pipeline.make_mask_crack_step on the
    single-target MD5 path: step(base_digits, n_valid) ->
    (count, lanes, tpos).

    Tile collisions (2+ hits in one tile) are folded into the overflow
    convention: the returned count exceeds hit_capacity, which makes
    the worker fall back to an exact host rescan of the batch.
    """
    tile = SUB * 128
    fn = make_md5_mask_pallas_fn(gen, target_words, batch,
                                 interpret=interpret)

    @jax.jit
    def step(base_digits: jnp.ndarray, n_valid: jnp.ndarray):
        counts, hit_lanes = fn(base_digits.astype(jnp.int32),
                               jnp.reshape(n_valid, (1,)).astype(jnp.int32))
        return reduce_tile_hits(counts, hit_lanes, hit_capacity, tile)

    return step


def reduce_tile_hits(counts: jnp.ndarray, hit_lanes: jnp.ndarray,
                     hit_capacity: int, tile: int):
    """Per-tile kernel outputs -> the worker's (count, lanes, tpos)
    contract.  A tile holding 2+ hits can only report one lane, so any
    such tile forces count > hit_capacity: the worker's exact host
    rescan then recovers every hit."""
    from dprf_tpu.ops import compare as cmp_ops

    c = counts[:, 0]
    total = jnp.sum(c)
    collision = jnp.any(c > 1)
    _, tiles, _ = cmp_ops.compact_hits(c > 0, jnp.zeros_like(c),
                                       hit_capacity)
    glanes = jnp.where(
        tiles >= 0,
        tiles * tile + hit_lanes[jnp.maximum(tiles, 0), 0], -1)
    count = jnp.where(collision, jnp.int32(hit_capacity + 1), total)
    return count, glanes, jnp.zeros_like(glanes)
