"""SHA-512 / SHA-384 compression (FIPS 180-4) as vectorized jnp ops.

TPU-first design problem: the VPU has no 64-bit integer lanes (JAX's
x64 mode is off and TPUs lower int64 poorly anyway), so every 64-bit
word lives as an (hi, lo) pair of uint32 lanes.  Adds propagate one
carry via an unsigned compare; rotations decompose into cross-word
shift/or pairs.  That costs ~3x the int32 op count of SHA-256 per
round, which is the honest price of SHA-512 on this hardware -- the
batch dimension still vectorizes perfectly.

Round constants (fractional cube roots of the first 80 primes) and
initial states (fractional square roots of primes 1-8 for SHA-512,
9-16 for SHA-384) are computed with exact integer arithmetic, not
copied from a listing, with FIPS 180-4 spot-check asserts.

Message layout: a 128-byte block is uint32[..., 32] big-endian words;
64-bit word i is (words[2i], words[2i+1]) = (hi, lo).  Digests use the
same interleaved layout, so ">u4" serialization yields standard bytes.
"""

from __future__ import annotations

import math

import numpy as np
import jax.numpy as jnp

_MASK64 = (1 << 64) - 1


def _primes(n: int) -> list[int]:
    out, cand = [], 2
    while len(out) < n:
        if all(cand % p for p in out if p * p <= cand):
            out.append(cand)
        cand += 1
    return out


def _icbrt(n: int) -> int:
    lo, hi = 0, 1 << ((n.bit_length() + 2) // 3 + 1)
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if mid ** 3 <= n:
            lo = mid
        else:
            hi = mid - 1
    return lo


def _frac64(p: int, root: int) -> int:
    """First 64 fractional bits of p**(1/root)."""
    if root == 2:
        return math.isqrt(p << 128) & _MASK64
    return _icbrt(p << 192) & _MASK64


_PRIMES = _primes(80)
K = [_frac64(p, 3) for p in _PRIMES]
INIT512 = [_frac64(p, 2) for p in _PRIMES[:8]]
INIT384 = [_frac64(p, 2) for p in _PRIMES[8:16]]
# FIPS 180-4 spot checks
assert K[0] == 0x428A2F98D728AE22 and K[79] == 0x6C44198C4A475817
assert INIT512[0] == 0x6A09E667F3BCC908
assert INIT384[0] == 0xCBBB9D5DC1059ED8


def _split(v: int):
    return jnp.uint32(v >> 32), jnp.uint32(v & 0xFFFFFFFF)


def _add64(a, b):
    """(hi, lo) + (hi, lo) with one carry propagate."""
    lo = a[1] + b[1]
    carry = (lo < a[1]).astype(jnp.uint32)   # unsigned wrap detection
    return a[0] + b[0] + carry, lo


def _rotr64(x, n: int):
    h, l = x
    if n == 0:
        return x
    if n == 32:
        return l, h
    if n > 32:
        return _rotr64((l, h), n - 32)
    nh = (h >> jnp.uint32(n)) | (l << jnp.uint32(32 - n))
    nl = (l >> jnp.uint32(n)) | (h << jnp.uint32(32 - n))
    return nh, nl


def _shr64(x, n: int):
    h, l = x
    if n >= 32:
        return jnp.zeros_like(h), h >> jnp.uint32(n - 32)
    return (h >> jnp.uint32(n),
            (l >> jnp.uint32(n)) | (h << jnp.uint32(32 - n)))


def _xor64(*xs):
    h = xs[0][0]
    l = xs[0][1]
    for x in xs[1:]:
        h = h ^ x[0]
        l = l ^ x[1]
    return h, l


def _round(vars8, wt, kt):
    """One SHA-512 round; kt is an (hi, lo) pair (scalar constants or
    gathered arrays -- the fori_loop body passes lane-broadcast
    gathers)."""
    a, b, c, d, e, f, g, h = vars8
    S1 = _xor64(_rotr64(e, 14), _rotr64(e, 18), _rotr64(e, 41))
    ch = ((e[0] & f[0]) ^ (~e[0] & g[0]), (e[1] & f[1]) ^ (~e[1] & g[1]))
    t1 = _add64(_add64(_add64(h, S1), _add64(ch, kt)), wt)
    S0 = _xor64(_rotr64(a, 28), _rotr64(a, 34), _rotr64(a, 39))
    maj = ((a[0] & b[0]) ^ (a[0] & c[0]) ^ (b[0] & c[0]),
           (a[1] & b[1]) ^ (a[1] & c[1]) ^ (b[1] & c[1]))
    return (_add64(t1, _add64(S0, maj)), a, b, c, _add64(d, t1), e, f, g)


def _schedule_ext(w15, w2, w0, w7):
    """W[t] = s1(W[t-2]) + W[t-7] + s0(W[t-15]) + W[t-16]."""
    s0 = _xor64(_rotr64(w15, 1), _rotr64(w15, 8), _shr64(w15, 7))
    s1 = _xor64(_rotr64(w2, 19), _rotr64(w2, 61), _shr64(w2, 6))
    return _add64(_add64(s1, w7), _add64(s0, w0))


_KH = np.array([k >> 32 for k in K], dtype=np.uint32)
_KL = np.array([k & 0xFFFFFFFF for k in K], dtype=np.uint32)


def sha512_rounds(vars8, m):
    """The 80 SHA-512 rounds over (hi, lo) uint32 pairs (no
    feed-forward), STATICALLY unrolled with a rolling 16-pair schedule
    so every W[t] lives in registers -- the form the Pallas kernel
    needs (fori_loop with array-carried schedules does not lower to
    Mosaic; see ops/sha256.sha256_rounds for the same split).

    vars8: 8 (hi, lo) pairs; m: 16 (hi, lo) message-word pairs.
    The XLA path (sha512_compress_state below) keeps the fori_loop
    form: the flat ~80x70-op pair graph hits XLA:CPU's compile-time
    pathology, and under jit the loop form costs no throughput.
    """
    w = list(m)
    for t in range(80):
        if t >= 16:
            w[t % 16] = _schedule_ext(w[(t - 15) % 16], w[(t - 2) % 16],
                                      w[t % 16], w[(t - 7) % 16])
        vars8 = _round(vars8, w[t % 16], _split(K[t]))
    return vars8


def sha512_compress_state(state: jnp.ndarray,
                          words: jnp.ndarray) -> jnp.ndarray:
    """One SHA-512 compression: state uint32[..., 16] (interleaved
    (hi, lo) pairs) x message words uint32[..., 32] -> uint32[..., 16].
    The multi-block primitive sha512crypt-style schemes chain.

    The first 16 rounds are unrolled (static message indexing, static
    round constants); rounds 16..80 run under lax.fori_loop with a
    rolling (hi, lo) schedule pair.  A fully-unrolled 80x(~35 op)
    graph hits the same XLA:CPU compile-time pathology the unrolled
    SHA-256 does (minutes), and the loop form costs no throughput
    under jit -- the body is batch-vectorized either way.  There is no
    Pallas kernel for this engine, so Mosaic's dislike of the loop
    form (see ops/sha256.py) is moot.
    """
    from jax import lax

    vars8 = tuple((state[..., 2 * i], state[..., 2 * i + 1])
                  for i in range(8))
    wh = words[..., 0::2]
    wl = words[..., 1::2]
    for t in range(16):
        vars8 = _round(vars8, (wh[..., t], wl[..., t]), _split(K[t]))

    kh_arr = jnp.asarray(_KH)
    kl_arr = jnp.asarray(_KL)

    def body(t, carry):
        vars8, wh, wl = carry
        wn = _schedule_ext((wh[..., 1], wl[..., 1]),
                           (wh[..., 14], wl[..., 14]),
                           (wh[..., 0], wl[..., 0]),
                           (wh[..., 9], wl[..., 9]))
        vars8 = _round(vars8, wn, (kh_arr[t], kl_arr[t]))
        wh = jnp.concatenate([wh[..., 1:], wn[0][..., None]], axis=-1)
        wl = jnp.concatenate([wl[..., 1:], wn[1][..., None]], axis=-1)
        return vars8, wh, wl

    vars8, _, _ = lax.fori_loop(16, 80, body, (vars8, wh, wl))
    out = []
    for v, i in zip(vars8, range(8)):
        h, l = _add64(v, (state[..., 2 * i], state[..., 2 * i + 1]))
        out.extend([h, l])
    return jnp.stack(out, axis=-1)


def init_state(init, shape) -> jnp.ndarray:
    """8 python ints -> uint32[shape + (16,)] interleaved state."""
    flat = []
    for v in init:
        flat.extend([v >> 32, v & 0xFFFFFFFF])
    return jnp.broadcast_to(
        jnp.asarray(np.array(flat, dtype=np.uint32)), shape + (16,))


def sha512_compress(init, words: jnp.ndarray) -> jnp.ndarray:
    """init: 8 python ints; words uint32[..., 32] -> uint32[..., 16]."""
    return sha512_compress_state(init_state(init, words.shape[:-1]),
                                 words)


def sha512_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    """uint32[..., 32] packed block -> uint32[..., 16] digest words."""
    return sha512_compress(INIT512, words)


def sha384_digest_words(words: jnp.ndarray) -> jnp.ndarray:
    """SHA-384: SHA-512 with its own IV, digest truncated to 48 bytes
    (the first six 64-bit words = 12 uint32 words)."""
    return sha512_compress(INIT384, words)[..., :12]
