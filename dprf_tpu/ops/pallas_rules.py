"""Fused wordlist+rules Pallas kernel: an in-VMEM rule interpreter.

Config 3 ("on-device rule expansion") measured 4.58 MH/s through the
XLA pipeline on the real chip (TPU_RESULTS_r04) -- the per-lane
`take_along_axis` gathers in rules/device.py and pack_varlen serialize
exactly like the mask decode's charset gathers did, ~250x below the
sha256 kernel rate.  This kernel keeps the whole chain -- word load,
rule application, varlen message pack, compression, compare -- in
VMEM/registers.

Design: a rule VIRTUAL MACHINE instead of trace-time rule unrolling.
Unrolling all R rules into one program multiplies the hash core R-fold
(~150k vector ops for best64 -- Mosaic program size explodes), so
instead the grid is (word_tile, rule) and each cell INTERPRETS its
rule's bytecode from SMEM:

- candidates ride the lanes as in the mask kernels; words are
  stored SoA -- one (8, 128) register per byte position -- so rule
  ops are vector selects;
- each interpreter step reads (opcode, p1, p2) scalars and applies
  one unified transform: a scalar-dispatched SOURCE-INDEX formula per
  position (identity, reverse, rotate, duplicate, delete, ...), one
  generic per-lane position gather (L selects per position -- L**2
  total, all vector ops), a byte-map stage (case toggles, appends,
  substitutions), then scalar-dispatched length/validity updates;
- the interpreter steps are UNROLLED to the job's longest rule, with
  shorter rules padded by NOOP opcodes (a fori_loop carrying the SoA
  byte tuple crashes the TPU backend compiler -- bisected on hardware
  r4: the same body inline compiles, the loop-carried form exits the
  remote compile helper with code 1);
- the message is packed varlen (lengths differ per lane after rules)
  and digested by the same compression cores the mask kernels share.

Semantics mirror rules/device.py (which mirrors rules/cpu.py) -- the
equivalence tests drive all three on the same words x rules.
Unsupported opcodes (PURGE's compaction sort, TITLE's separator scan)
make the JOB fall back to the XLA pipeline at worker-build time.

Cited reference behavior: SURVEY.md section A names config 3
(wordlist + best64, on-device rule expansion) as an acceptance
workload; every best64 opcode is supported here.
"""

from __future__ import annotations

import functools
from typing import Sequence

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from dprf_tpu.ops.pallas_mask import CORES, pallas_mode  # noqa: F401
from dprf_tpu.rules.parser import Op, Opcode
from dprf_tpu.utils import env as envreg


#: word-tile geometry: SUBW sublanes x 128 lanes of words per grid
#: cell.  Bigger tiles amortize per-cell control overhead exactly like
#: the mask kernel's SUB (r3 sweep); DPRF_RULES_SUBW overrides for
#: hardware tuning.
SUBW = envreg.get_int("DPRF_RULES_SUBW")
TILE_W = SUBW * 128
# the packed (count << 16) | (hit_lane + 1) output needs both fields
# in 16 bits (same constraint as pallas_mask's sub <= 128)
assert TILE_W <= 0xFFFF, "DPRF_RULES_SUBW > 511 overflows the packed output"

#: interpreter step budget per rule (best64's longest rule is 8 ops)
MAX_STEPS = 8

#: opcodes the kernel interprets.  PURGE needs a compaction sort and
#: TITLE/TITLE_SEP a separator scan over the ORIGINAL bytes -- both
#: are expressible but not worth the op budget until a real rule set
#: needs them; jobs containing them use the XLA pipeline.
SUPPORTED = frozenset(op for op in Opcode) - {
    Opcode.PURGE, Opcode.TITLE, Opcode.TITLE_SEP}

O = Opcode   # brevity in the interpreter tables


def rules_supported(rules: Sequence[Sequence[Op]]) -> bool:
    return all(len(ops) <= MAX_STEPS
               and all(op.opcode in SUPPORTED for op in ops)
               for ops in rules)


def encode_rules(rules: Sequence[Sequence[Op]],
                 n_steps: int = None) -> np.ndarray:
    """Rule set -> bytecode int32[R, n_steps, 3].  Unused steps stay
    all-zero = (NOOP, 0, 0), so the unrolled interpreter needs no
    per-rule step count."""
    R = len(rules)
    n_steps = (max((len(ops) for ops in rules), default=1)
               if n_steps is None else n_steps)
    bc = np.zeros((R, max(1, n_steps), 3), np.int32)
    for r, ops in enumerate(rules):
        for s, op in enumerate(ops):
            bc[r, s] = (int(op.opcode), op.p1, op.p2)
    return bc


def kernel_rules_eligible(engine_name: str, gen, n_targets: int) -> bool:
    """Whole-job eligibility for the rules kernel."""
    if engine_name not in CORES or n_targets != 1:
        return False
    if not hasattr(gen, "rules") or not hasattr(gen, "packed_words"):
        return False
    widen = CORES[engine_name][3]
    # the 0x80 pad at position max_len still fits the block (byte 55 /
    # UTF-16 byte 54), so the limits are the block limits themselves
    if gen.max_len > (27 if widen else 55):
        return False
    if engine_name in ("sha256", "sha-256"):
        import jax as _jax
        if _jax.default_backend() != "tpu":
            return False    # unrolled sha256 doesn't compile on XLA:CPU
    return rules_supported(gen.rules)


def _sel(pred, a, b):
    return jnp.where(pred, a, b)


def _interp_step(w, lens, valid, op, p1, p2, L: int, shape):
    """One rule-VM step.  w: tuple of L int32[(SUBW,128)] byte arrays
    (values 0..255), lens: int32, valid: int32 0/1 mask (SUBW,128) --
    an INT mask, not bool: a scalar-conditional select over i1 vectors
    crashes the TPU backend compiler (minimal repro, r4 probe log),
    and every opcode dispatch here is a scalar-conditional select.
    op/p1/p2 are SMEM scalars.  Returns the new (w, lens, valid)."""
    i32 = jnp.int32
    onev = jnp.ones(shape, i32)

    def eq(code):
        return op == i32(int(code))

    safe = jnp.maximum(lens, 1)

    # ---- 1. source-index formulas (per output position) -------------
    # Ops that MOVE bytes express as: out[p] = in[src(p)]; everything
    # else uses identity.  Vector formulas (len-dependent) computed
    # once per position; the scalar `op` collapses the select chain.
    def src_for(p):
        s = p * onev                                   # identity
        s = _sel(eq(O.REVERSE), lens - 1 - p, s)
        s = _sel(eq(O.DUPLICATE), _sel(p < lens, p, p - lens), s)
        s = _sel(eq(O.DUPLICATE_N), p % safe, s)
        s = _sel(eq(O.REFLECT),
                 _sel(p < lens, p, 2 * lens - 1 - p), s)
        s = _sel(eq(O.ROT_LEFT),
                 _sel(lens > 1, (p + 1) % safe, p), s)
        s = _sel(eq(O.ROT_RIGHT),
                 _sel(lens > 1, (p - 1 + safe) % safe, p), s)
        s = _sel(eq(O.DEL_FIRST), (p + 1) * onev, s)
        s = _sel(eq(O.DEL_AT) & (p1 < lens),
                 _sel(p < p1, p, p + 1) * onev, s)
        s = _sel(eq(O.EXTRACT) & (p1 < lens), (p + p1) * onev, s)
        s = _sel(eq(O.OMIT) & (p1 < lens),
                 _sel(p * onev < p1, p, p + p2), s)
        s = _sel(eq(O.INSERT) & (p1 <= lens),
                 _sel(p * onev < p1, p, p - 1), s)
        s = _sel(eq(O.PREPEND), (p - 1) * onev, s)
        s = _sel(eq(O.DUP_FIRST) & (lens > 0),
                 _sel(p * onev < p1, 0, p - p1), s)
        s = _sel(eq(O.DUP_LAST) & (lens > 0),
                 _sel(p < lens, p, lens - 1), s)
        s = _sel(eq(O.DUP_ALL), (p // 2) * onev, s)
        s = _sel(eq(O.SWAP_FRONT) & (lens >= 2),
                 i32(1 if p == 0 else (0 if p == 1 else p)) * onev, s)
        s = _sel(eq(O.SWAP_BACK) & (lens >= 2),
                 _sel(p == lens - 1, lens - 2,
                      _sel(p == lens - 2, lens - 1, p)), s)
        s = _sel(eq(O.SWAP_AT) & (p1 < lens) & (p2 < lens),
                 _sel(p * onev == p1, p2,
                      _sel(p * onev == p2, p1, p)), s)
        s = _sel(eq(O.REPL_NEXT) & (p * onev == p1) & (p1 + 1 < lens),
                 p1 + 1, s)
        s = _sel(eq(O.REPL_PREV) & (p * onev == p1) & (p1 >= 1)
                 & (p1 < lens), p1 - 1, s)
        s = _sel(eq(O.DUP_BLOCK_FRONT) & (p1 <= lens),
                 _sel(p * onev < p1, p, p - p1), s)
        s = _sel(eq(O.DUP_BLOCK_BACK) & (p1 <= lens),
                 _sel(p < lens, p, p - p1), s)
        return jnp.clip(s, 0, L - 1)

    gathered = []
    for p in range(L):
        src = src_for(p)
        acc = w[0]
        for q in range(1, L):
            acc = _sel(src == q, w[q], acc)
        gathered.append(acc)

    # ---- 2. byte-map stage -----------------------------------------
    out = []
    app_here = eq(O.APPEND)
    for p in range(L):
        g = gathered[p]
        up = (g >= 0x41) & (g <= 0x5A)
        lo = (g >= 0x61) & (g <= 0x7A)
        glow = _sel(up, g + 0x20, g)
        gup = _sel(lo, g - 0x20, g)
        gtog = _sel(up, g + 0x20, _sel(lo, g - 0x20, g))
        b = g
        b = _sel(eq(O.LOWER), glow, b)
        b = _sel(eq(O.UPPER), gup, b)
        b = _sel(eq(O.CAPITALIZE), gup if p == 0 else glow, b)
        b = _sel(eq(O.INV_CAPITALIZE), glow if p == 0 else gup, b)
        b = _sel(eq(O.TOGGLE_ALL), gtog, b)
        b = _sel(eq(O.TOGGLE_AT) & (p * onev == p1) & (p1 < lens),
                 gtog, b)
        b = _sel(app_here & (p == lens), p1 * onev, b)
        b = _sel(eq(O.PREPEND) & (p == 0), p1 * onev, b)
        b = _sel(eq(O.INSERT) & (p * onev == p1) & (p1 <= lens),
                 p2 * onev, b)
        b = _sel(eq(O.OVERWRITE) & (p * onev == p1) & (p1 < lens),
                 p2 * onev, b)
        b = _sel(eq(O.SUBSTITUTE) & (g == p1), p2 * onev, b)
        at = (p * onev == p1) & (p1 < lens)
        b = _sel(eq(O.INCR_AT) & at, (g + 1) & 0xFF, b)
        b = _sel(eq(O.DECR_AT) & at, (g - 1) & 0xFF, b)
        b = _sel(eq(O.SHIFT_LEFT) & at, (g << 1) & 0xFF, b)
        b = _sel(eq(O.SHIFT_RIGHT) & at, g >> 1, b)
        out.append(b)

    # ---- 3. length update ------------------------------------------
    grow = None   # mirror rules/device.py's growth-clamp semantics
    newlen = lens
    newlen = _sel(eq(O.DEL_FIRST) | eq(O.DEL_LAST),
                  jnp.maximum(lens - 1, 0), newlen)
    newlen = _sel(eq(O.DEL_AT) & (p1 < lens), lens - 1, newlen)
    newlen = _sel(eq(O.EXTRACT) & (p1 < lens),
                  jnp.minimum(p2, lens - p1), newlen)
    newlen = _sel(eq(O.OMIT) & (p1 < lens),
                  lens - jnp.minimum(p2, lens - p1), newlen)
    newlen = _sel(eq(O.TRUNCATE), jnp.minimum(lens, p1), newlen)
    grow_v = lens
    grow_v = _sel(eq(O.DUPLICATE) | eq(O.REFLECT) | eq(O.DUP_ALL),
                  2 * lens, grow_v)
    grow_v = _sel(eq(O.DUPLICATE_N), (p1 + 1) * lens, grow_v)
    grow_v = _sel(eq(O.INSERT) & (p1 <= lens), lens + 1, grow_v)
    grow_v = _sel(eq(O.APPEND) | eq(O.PREPEND), lens + 1, grow_v)
    grow_v = _sel((eq(O.DUP_FIRST) | eq(O.DUP_LAST)) & (lens > 0),
                  lens + p1, grow_v)
    grow_v = _sel((eq(O.DUP_BLOCK_FRONT) | eq(O.DUP_BLOCK_BACK))
                  & (p1 <= lens), lens + p1, grow_v)
    is_grow = (eq(O.DUPLICATE) | eq(O.REFLECT) | eq(O.DUP_ALL)
               | eq(O.DUPLICATE_N) | eq(O.INSERT) | eq(O.APPEND)
               | eq(O.PREPEND) | eq(O.DUP_FIRST) | eq(O.DUP_LAST)
               | eq(O.DUP_BLOCK_FRONT) | eq(O.DUP_BLOCK_BACK))
    newvalid = _sel(is_grow, valid * (grow_v <= L).astype(i32), valid)
    newlen = _sel(is_grow, jnp.minimum(grow_v, L), newlen)

    # ---- 4. rejection ops ------------------------------------------
    def contains(ch):
        m = jnp.zeros(shape, jnp.bool_)
        for q in range(L):
            m = m | ((out[q] == ch) & (q < newlen))
        return m.astype(i32)

    def count_ch(ch):
        c = jnp.zeros(shape, i32)
        for q in range(L):
            c = c + ((out[q] == ch) & (q < newlen)).astype(i32)
        return c

    def char_at(idx):
        c = jnp.zeros(shape, i32)
        for q in range(L):
            c = _sel(idx == q, out[q], c)
        return c

    newvalid = _sel(eq(O.REJ_GT),
                    newvalid * (newlen <= p1).astype(i32), newvalid)
    newvalid = _sel(eq(O.REJ_LT),
                    newvalid * (newlen >= p1).astype(i32), newvalid)
    newvalid = _sel(eq(O.REJ_NEQ_LEN),
                    newvalid * (newlen == p1).astype(i32), newvalid)
    newvalid = _sel(eq(O.REJ_CONTAIN),
                    newvalid * (1 - contains(p1)), newvalid)
    newvalid = _sel(eq(O.REJ_NOT_CONTAIN),
                    newvalid * contains(p1), newvalid)
    newvalid = _sel(eq(O.REJ_NOT_FIRST),
                    newvalid * ((newlen > 0)
                                & (out[0] == p1)).astype(i32), newvalid)
    newvalid = _sel(eq(O.REJ_NOT_LAST),
                    newvalid * ((newlen > 0)
                                & (char_at(newlen - 1) == p1))
                    .astype(i32), newvalid)
    newvalid = _sel(eq(O.REJ_NOT_AT),
                    newvalid * ((p1 < newlen)
                                & (char_at(p1 * onev) == p2))
                    .astype(i32), newvalid)
    newvalid = _sel(eq(O.REJ_LT_COUNT),
                    newvalid * (count_ch(p2) >= p1).astype(i32),
                    newvalid)

    # ---- 5. zero-tail invariant ------------------------------------
    out = tuple(_sel(p < newlen, out[p], 0) for p in range(L))
    return out, newlen, newvalid


def _pack_varlen_words(w, lens, L: int, shape, big_endian: bool,
                       widen: bool):
    """SoA bytes + per-lane lengths -> 16 single-block message words
    with Merkle-Damgard padding (0x80 at the per-lane length, 64-bit
    bit length in the tail words)."""
    m = [jnp.zeros(shape, jnp.uint32) for _ in range(16)]
    stride = 2 if widen else 1

    def put(q, byte_u32):
        word, b = divmod(q, 4)
        shift = 8 * (3 - b) if big_endian else 8 * b
        m[word] = m[word] | (byte_u32 << jnp.uint32(shift))

    for p in range(L):
        byte = _sel(p < lens, w[p], 0).astype(jnp.uint32)
        put(stride * p, byte)
    # the 0x80 pad rides its own position select: one of L+1 slots
    for p in range(L + 1):
        pad = _sel(lens == p, jnp.uint32(0x80), jnp.uint32(0))
        put(stride * p, pad)
    bitlen = (lens * (16 if widen else 8)).astype(jnp.uint32)
    if big_endian:
        m[15] = bitlen
    else:
        m[14] = bitlen
    return m


def ceil_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def step_buckets(rules) -> dict:
    """Group rule INDICES by ceil-power-of-two op count, so each
    compiled kernel unrolls only as many interpreter steps as its
    bucket needs (best64: one 8-op rule must not tax the ~50 one-op
    rules 8 unrolled steps each)."""
    out: dict = {}
    for i, ops in enumerate(rules):
        out.setdefault(ceil_pow2(max(1, len(ops))), []).append(i)
    return out


def make_rules_pallas_fn(engine_name: str, gen, target_words,
                         tiles_per_step: int, interpret: bool = False,
                         rule_indices=None, shared_words=None):
    """Build fn(tile0 int32, n_valid_local int32[1]) ->
    (counts int32[G, 1], hit_lanes int32[G, 1]) over a window of
    tiles_per_step word tiles x ALL rules of the set.

    Cell (i, j) covers words [tile0*TILE_W + i*TILE_W, ...+TILE_W)
    under rule j; output row i * R + j.  n_valid_local is the valid
    word count RELATIVE to the window start.
    """
    core, n_words_d, big_endian, widen = CORES[engine_name]
    L = gen.max_len
    all_rules = gen.rules
    rule_indices = (list(range(len(all_rules)))
                    if rule_indices is None else list(rule_indices))
    rules = [all_rules[i] for i in rule_indices]
    R = len(rules)
    if not kernel_rules_eligible(engine_name, gen, 1):
        raise ValueError("job not rules-kernel eligible")
    if np.asarray(target_words).reshape(-1).shape[0] != n_words_d:
        raise ValueError(f"expected {n_words_d} target words")
    bc_np = encode_rules(rules)
    n_steps = bc_np.shape[1]

    # a window covers tiles_per_step*TILE_W words starting at ANY word
    # (units need not be tile-aligned), so it spans tiles_per_step + 1
    # tiles from the floor-aligned tile0
    Twin = tiles_per_step + 1
    if shared_words is not None:
        w4, l3 = shared_words
        n_tiles = w4.shape[0]
        # a window needs ceil(n_words/TILE_W) + Twin padding tiles;
        # arrays shared from a narrower-window build would let the
        # host-side dynamic_slice clamp and silently shift the whole
        # window to earlier words -- rebuild instead of reusing
        if n_tiles < -(-gen.n_words // TILE_W) + Twin:
            shared_words = None
    if shared_words is None:
        # words in HBM as (n_tiles, L, SUBW, 128) int32 SoA tiles,
        # padded so the host-side dynamic_slice can never clamp for
        # any in-range start tile (a clamped start would silently
        # shift the whole window to earlier words)
        words_np, lens_np = gen.packed_words(pad_to=TILE_W)
        N = words_np.shape[0]
        padN = (-(-max(N, 1) // TILE_W) + Twin) * TILE_W
        n_tiles = padN // TILE_W
        wpad = np.zeros((padN, L), np.uint8)
        wpad[:N] = words_np[:, :L]
        lpad = np.zeros((padN,), np.int32)
        lpad[:N] = lens_np
        w4 = jnp.asarray(wpad.astype(np.int32)
                         .reshape(n_tiles, SUBW, 128, L)
                         .transpose(0, 3, 1, 2))    # (T, L, SUBW, 128)
        l3 = jnp.asarray(lpad.reshape(n_tiles, SUBW, 128))

    shape = (SUBW, 128)

    def kernel(nvalid_ref, bc_ref, tgt_ref, w_ref, l_ref, out_ref):
        i = pl.program_id(0)
        j = pl.program_id(1)
        lane = (lax.broadcasted_iota(jnp.int32, shape, 0) * 128
                + lax.broadcasted_iota(jnp.int32, shape, 1))
        w = tuple(w_ref[0, q] for q in range(L))
        lens = l_ref[0]
        # window-relative word index; valid iff inside [lo, hi) --
        # lo is the unit start's offset within its floor tile, so
        # units need not be TILE_W-aligned.  int32 0/1 mask, not bool
        # (see _interp_step)
        lane_w = lane + i * TILE_W
        valid = ((lane_w >= nvalid_ref[0])
                 & (lane_w < nvalid_ref[1])).astype(jnp.int32)

        # unrolled to the job's longest rule; padded steps are NOOPs
        # (a loop-carried SoA tuple crashes the backend compiler)
        for s in range(n_steps):
            w, lens, valid = _interp_step(w, lens, valid,
                                          bc_ref[j, s, 0],
                                          bc_ref[j, s, 1],
                                          bc_ref[j, s, 2], L, shape)
        m = _pack_varlen_words(w, lens, L, shape, big_endian, widen)
        digest = core(m, shape)
        found = valid > 0
        for i_w, got in enumerate(digest):
            # runtime target: SMEM scalars (int32 bit pattern), so one
            # compiled step serves any target of the job
            found = found & (got == tgt_ref[i_w].astype(jnp.uint32))
        count = jnp.sum(found.astype(jnp.int32))
        hit_lane = jnp.max(jnp.where(found, lane, -1))
        out_ref[...] = jnp.full((8, 128), (count << 16) | (hit_lane + 1),
                                jnp.int32)

    grid = (Twin, R)
    raw = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((2,), lambda i, j: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((R, n_steps, 3), lambda i, j: (0, 0, 0),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((n_words_d,), lambda i, j: (0,),
                         memory_space=pltpu.SMEM),
            pl.BlockSpec((1, L, SUBW, 128), lambda i, j: (i, 0, 0, 0)),
            pl.BlockSpec((1, SUBW, 128), lambda i, j: (i, 0, 0)),
        ],
        out_specs=[pl.BlockSpec((8, 128), lambda i, j: (i * R + j, 0))],
        out_shape=[jax.ShapeDtypeStruct((Twin * R * 8, 128), jnp.int32)],
        interpret=interpret,
    )
    bc_dev = jnp.asarray(bc_np)
    tgt_default = jnp.asarray(np.asarray(target_words).reshape(-1)
                              .astype(np.uint32).view(np.int32))

    def fn(tile0, lohi, words4=w4, lens3=l3, target=None):
        # words4/lens3 default to the job's arrays but are real
        # ARGUMENTS (not closure constants): a closure jnp array would
        # be baked into the lowered module as an 84 MB constant for a
        # 1M-word list, which the tunnel's remote compile helper
        # rejects (measured r4)
        tgt = tgt_default if target is None else target
        ws = lax.dynamic_slice(words4, (tile0, 0, 0, 0),
                               (Twin, L, SUBW, 128))
        ls = lax.dynamic_slice(lens3, (tile0, 0, 0),
                               (Twin, SUBW, 128))
        (packed,) = raw(lohi, bc_dev, tgt, ws, ls)
        p = packed[::8, 0:1]
        return p >> 16, (p & 0xFFFF) - 1

    fn.n_tiles_total = n_tiles
    fn.tiles_per_step = tiles_per_step
    fn.n_rules = R
    fn.words4 = w4
    fn.lens3 = l3
    return fn


def make_rules_crack_step(engine_name: str, gen, target_words,
                          word_batch: int, hit_capacity: int = 64,
                          interpret: bool = False, shared_words=None):
    """DeviceWordlistWorker-contract step over the rules kernels:
    step(w0, n_valid_words) -> (count, lanes int32[cap], tpos) with
    flat rule-major lanes (lane = r * word_batch + b).

    w0 may start at ANY word (WorkUnits are not tile-aligned): the
    kernels get a floor-aligned tile window one tile wider plus a
    window-relative [lo, hi) valid range, and hit lanes are rebased
    to w0.

    The rule set is bucketed by op count (step_buckets) into one
    compiled kernel per bucket -- measured 39.5 MH/s for config 3 with
    the single 8-step kernel, where the one 8-op best64 rule taxed
    every cell -- and each bucket's cells pay only their own depth.
    All buckets share the words arrays and dispatch back to back
    before one merged hit compaction."""
    from dprf_tpu.ops import compare as cmp_ops

    T = max(1, word_batch // TILE_W)
    B = T * TILE_W
    buckets = step_buckets(gen.rules)
    fns = []
    # caller-provided arrays (e.g. a worker sharing one copy across
    # wide-step sizes) are reused when their padding suffices --
    # make_rules_pallas_fn checks and rebuilds otherwise, so always
    # re-read the arrays the first bucket ACTUALLY used
    shared = shared_words
    for nsteps in sorted(buckets):
        idxs = buckets[nsteps]
        fnb = make_rules_pallas_fn(engine_name, gen, target_words, T,
                                   interpret=interpret,
                                   rule_indices=idxs,
                                   shared_words=shared)
        shared = (fnb.words4, fnb.lens3)
        fns.append((fnb, jnp.asarray(np.asarray(idxs, np.int32)),
                    len(idxs)))

    @jax.jit
    def _step(words4, lens3, tgt, w0, n_valid_words):
        tile0 = (w0 // TILE_W).astype(jnp.int32)
        lo = (w0 - tile0 * TILE_W).astype(jnp.int32)
        lohi = jnp.stack([lo, lo + n_valid_words.astype(jnp.int32)])
        cs, flats = [], []
        for fnb, orig, Rb in fns:
            counts, hit_lanes = fnb(tile0, lohi, words4, lens3, tgt)
            c = counts[:, 0]
            hl = hit_lanes[:, 0]
            rows = jnp.arange(c.shape[0], dtype=jnp.int32)
            i = rows // Rb
            j = rows % Rb
            # bucket-local rule j -> ORIGINAL rule index; in-window
            # lane rebased to the unit's word start (subtract lo)
            flats.append(jnp.take(orig, j) * B + i * TILE_W + hl - lo)
            cs.append(c)
        c_all = jnp.concatenate(cs)
        flat_all = jnp.concatenate(flats)
        total = jnp.sum(c_all)
        collision = jnp.any(c_all > 1)
        _, rows, _ = cmp_ops.compact_hits(c_all > 0,
                                          jnp.zeros_like(c_all),
                                          hit_capacity)
        lanes = jnp.where(rows >= 0, flat_all[jnp.maximum(rows, 0)], -1)
        count = jnp.where(collision, jnp.int32(hit_capacity + 1), total)
        return count, lanes, jnp.zeros_like(lanes)

    w4, l3 = shared
    tgt0 = jnp.asarray(np.asarray(target_words).reshape(-1)
                       .astype(np.uint32).view(np.int32))

    def step(w0, n_valid_words, target=tgt0):
        return _step(w4, l3, target, w0, n_valid_words)

    step.word_batch = B
    step.words4, step.lens3 = w4, l3    # for cross-step sharing
    return step
