"""HMAC-SHA256 and PBKDF2-HMAC-SHA256 as jit-traceable device ops
(Django's default password hasher; hashcat 10900).

Same structure as ops/hmac_sha1.py: keys fit one block so the pad is a
single xor, keyed inner/outer states are computed once per candidate,
and every iteration after the first is exactly two sha256_compress
calls over a constant-padded 32-byte message under `lax.fori_loop`.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
from jax import lax

from dprf_tpu.ops.sha256 import INIT as SHA256_INIT, sha256_compress

_IPAD = np.uint32(0x36363636)
_OPAD = np.uint32(0x5C5C5C5C)


def hmac256_key_states(key_words: jnp.ndarray):
    """key_words uint32[B, 16] (zero-padded one-block key) ->
    (istate, ostate) uint32[B, 8] each."""
    init = jnp.broadcast_to(jnp.asarray(SHA256_INIT),
                            key_words.shape[:-1] + (8,))
    istate = sha256_compress(init, key_words ^ _IPAD)
    ostate = sha256_compress(init, key_words ^ _OPAD)
    return istate, ostate


def _block32(words8: jnp.ndarray) -> jnp.ndarray:
    """Pad a 32-byte (8-word) message into the block following a
    64-byte prefix: 0x80 marker, bit length (64+32)*8."""
    batch = words8.shape[:-1]
    block = jnp.zeros(batch + (16,), dtype=jnp.uint32)
    block = block.at[..., :8].set(words8)
    block = block.at[..., 8].set(jnp.uint32(0x80000000))
    block = block.at[..., 15].set(jnp.uint32((64 + 32) * 8))
    return block


def hmac_sha256_32(istate: jnp.ndarray, ostate: jnp.ndarray,
                   msg8: jnp.ndarray) -> jnp.ndarray:
    """HMAC-SHA256 of a 32-byte message: two compressions."""
    inner = sha256_compress(istate, _block32(msg8))
    return sha256_compress(ostate, _block32(inner))


def salt_block256(salt: bytes, block_index: int) -> np.ndarray:
    """Host-built U1 message block: salt || INT32BE(i), padded as the
    second block of the inner hash."""
    msg = salt + int(block_index).to_bytes(4, "big")
    if len(msg) > 55:
        raise ValueError(f"salt too long for one block: {len(salt)} bytes")
    buf = np.zeros(64, dtype=np.uint8)
    buf[:len(msg)] = np.frombuffer(msg, dtype=np.uint8)
    buf[len(msg)] = 0x80
    bitlen = (64 + len(msg)) * 8
    buf[56:] = np.frombuffer(bitlen.to_bytes(8, "big"), dtype=np.uint8)
    return buf.reshape(16, 4).astype(np.uint32) @ \
        np.array([1 << 24, 1 << 16, 1 << 8, 1], dtype=np.uint32)


def pbkdf2_sha256_block(istate: jnp.ndarray, ostate: jnp.ndarray,
                        salt: bytes, block_index: int,
                        iterations) -> jnp.ndarray:
    """One PBKDF2 output block T_i: uint32[B, 8].  `iterations` may be
    a traced scalar (runtime argument)."""
    first = jnp.broadcast_to(
        jnp.asarray(salt_block256(salt, block_index)),
        istate.shape[:-1] + (16,))
    inner = sha256_compress(istate, first)
    u = sha256_compress(ostate, _block32(inner))

    def body(_, carry):
        u, t = carry
        u = hmac_sha256_32(istate, ostate, u)
        return u, t ^ u

    _, t = lax.fori_loop(1, iterations, body, (u, u))
    return t


def pbkdf2_sha256(key_words: jnp.ndarray, salt: bytes,
                  iterations) -> jnp.ndarray:
    """PBKDF2-HMAC-SHA256 with 32-byte output (Django's dklen):
    uint32[B, 8] = T1."""
    istate, ostate = hmac256_key_states(key_words)
    return pbkdf2_sha256_block(istate, ostate, salt, 1, iterations)
