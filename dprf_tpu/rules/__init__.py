"""Rule engine for wordlist+rules attacks (benchmark config 3).

The rule language is the de-facto standard hashcat/John syntax (a public
specification): one rule per line, a rule being a sequence of
single-character operations with positional/character parameters.  This
package provides:

- `parser`   — rule text -> op tuples (validated, with opcode table)
- `cpu`      — host interpreter: the correctness oracle and CpuWorker path
- `device`   — jit-traceable batch application: each rule's ops are baked
               in as static constants so XLA sees straight-line vector code
- `best64`   — a built-in 64-rule general-purpose set (authored here, in
               the standard syntax) selectable as `--rules best64`

SURVEY.md section 2 ("CandidateGenerator — wordlist+rules") and section 7
item 7 ("on-device rule expansion") are the blueprint; no reference code
existed to consult (SURVEY.md critical note).
"""

from dprf_tpu.rules.parser import (Op, OpSpec, OPS, parse_rule, parse_rules,
                                   load_rules, resolve_rules_path,
                                   builtin_ruleset, BUILTIN_RULESETS)
from dprf_tpu.rules.cpu import apply_rule as apply_rule_cpu

__all__ = ["Op", "OpSpec", "OPS", "parse_rule", "parse_rules", "load_rules",
           "resolve_rules_path", "builtin_ruleset", "BUILTIN_RULESETS",
           "apply_rule_cpu"]
