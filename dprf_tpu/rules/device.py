"""Device rule application: one rule, a whole word batch, pure vector ops.

Each rule's operations are Python-level constants at trace time, so
applying a rule to a batch lowers to straight-line uint8 vector code —
selects, shifts, and per-lane gathers (`take_along_axis`) — that XLA
fuses with the downstream pack/digest/compare pipeline.  There is no
on-device bytecode interpreter loop: the "interpretation" happens once,
at trace time, which is both faster (no lax.switch dispatch) and exactly
as flexible because a job's rule set is static.

Semantics mirror rules/cpu.py byte-for-byte (see its docstring for the
no-op / reject conventions); tests/test_rules.py enforces equivalence on
random words x the full op set.

State per batch: (w uint8[B, L], lens int32[B], valid bool[B]).
Invariant maintained after every op: bytes at positions >= lens are 0,
and lens <= L even for rejected lanes (whose `valid` bit is cleared).
"""

from __future__ import annotations

from typing import Sequence

import jax.numpy as jnp

from dprf_tpu.rules.parser import Op, Opcode


def _pos(L: int) -> jnp.ndarray:
    return jnp.arange(L, dtype=jnp.int32)[None, :]


def _gather(w: jnp.ndarray, src: jnp.ndarray) -> jnp.ndarray:
    """Per-lane source-index gather, clamped so indices are always legal
    (masks applied by callers make clamped lanes irrelevant)."""
    L = w.shape[1]
    return jnp.take_along_axis(w, jnp.clip(src, 0, L - 1), axis=1)


def _lower(w):
    return jnp.where((w >= 0x41) & (w <= 0x5A), w + 0x20, w)


def _upper(w):
    return jnp.where((w >= 0x61) & (w <= 0x7A), w - 0x20, w)


def _togglec(w):
    up = (w >= 0x41) & (w <= 0x5A)
    lo = (w >= 0x61) & (w <= 0x7A)
    return jnp.where(up, w + 0x20, jnp.where(lo, w - 0x20, w))


def _contains(w, lens, ch: int):
    return ((w == jnp.uint8(ch)) & (_pos(w.shape[1]) < lens[:, None])).any(1)


def _count(w, lens, ch: int):
    return ((w == jnp.uint8(ch))
            & (_pos(w.shape[1]) < lens[:, None])).sum(1, dtype=jnp.int32)


def _char_at(w, idx):
    """Per-lane byte at (traced) index idx[B]; callers guard validity."""
    return jnp.take_along_axis(
        w, jnp.clip(idx, 0, w.shape[1] - 1)[:, None], axis=1)[:, 0]


def apply_rule(w: jnp.ndarray, lens: jnp.ndarray, valid: jnp.ndarray,
               ops: Sequence[Op], max_len: int):
    """Apply one parsed rule to a batch.  jit-traceable; ops are static.

    w: uint8[B, L] (L >= max_len), lens: int32[B], valid: bool[B].
    Returns the new (w, lens, valid).
    """
    B, L = w.shape
    pos = _pos(L)
    for op in ops:
        code, p1, p2 = op.opcode, op.p1, op.p2
        lc = lens[:, None]          # broadcastable per-lane length
        grow = None                 # (newlens,) set by growth ops

        if code == Opcode.NOOP:
            pass
        elif code == Opcode.LOWER:
            w = _lower(w)
        elif code == Opcode.UPPER:
            w = _upper(w)
        elif code == Opcode.CAPITALIZE:
            w = _lower(w)
            w = jnp.where(pos == 0, _upper(w), w)
        elif code == Opcode.INV_CAPITALIZE:
            w = _upper(w)
            w = jnp.where(pos == 0, _lower(w), w)
        elif code == Opcode.TOGGLE_ALL:
            w = _togglec(w)
        elif code == Opcode.TOGGLE_AT:
            if p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc), _togglec(w), w)
        elif code == Opcode.REVERSE:
            w = _gather(w, lc - 1 - pos)
        elif code == Opcode.DUPLICATE:
            w = jnp.where(pos < lc, w, _gather(w, pos - lc))
            grow = 2 * lens
        elif code == Opcode.DUPLICATE_N:
            safe = jnp.maximum(lc, 1)
            w = _gather(w, pos % safe)
            grow = (p1 + 1) * lens
        elif code == Opcode.REFLECT:
            w = jnp.where(pos < lc, w, _gather(w, 2 * lc - 1 - pos))
            grow = 2 * lens
        elif code == Opcode.ROT_LEFT:
            safe = jnp.maximum(lc, 1)
            w = jnp.where(lc > 1, _gather(w, (pos + 1) % safe), w)
        elif code == Opcode.ROT_RIGHT:
            safe = jnp.maximum(lc, 1)
            w = jnp.where(lc > 1, _gather(w, (pos - 1 + safe) % safe), w)
        elif code == Opcode.DEL_FIRST:
            w = _gather(w, pos + 1)
            lens = jnp.maximum(lens - 1, 0)
        elif code == Opcode.DEL_LAST:
            lens = jnp.maximum(lens - 1, 0)
        elif code == Opcode.DEL_AT:
            hit = p1 < lens
            w = jnp.where(hit[:, None],
                          _gather(w, jnp.where(pos < p1, pos, pos + 1)), w)
            lens = jnp.where(hit, lens - 1, lens)
        elif code == Opcode.EXTRACT:
            hit = p1 < lens
            w = jnp.where(hit[:, None], _gather(w, pos + p1), w)
            lens = jnp.where(hit, jnp.minimum(p2, lens - p1), lens)
        elif code == Opcode.OMIT:
            hit = p1 < lens
            w = jnp.where(hit[:, None],
                          _gather(w, jnp.where(pos < p1, pos, pos + p2)), w)
            lens = jnp.where(hit, lens - jnp.minimum(p2, lens - p1), lens)
        elif code == Opcode.INSERT:
            hit = p1 <= lens
            moved = _gather(w, jnp.where(pos < p1, pos, pos - 1))
            moved = jnp.where(pos == p1, jnp.uint8(p2), moved)
            w = jnp.where(hit[:, None], moved, w)
            grow = jnp.where(hit, lens + 1, lens)
        elif code == Opcode.OVERWRITE:
            if p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc), jnp.uint8(p2), w)
        elif code == Opcode.TRUNCATE:
            lens = jnp.minimum(lens, p1)
        elif code == Opcode.SUBSTITUTE:
            w = jnp.where((w == jnp.uint8(p1)) & (pos < lc),
                          jnp.uint8(p2), w)
        elif code == Opcode.PURGE:
            keep = (w != jnp.uint8(p1)) & (pos < lc)
            key = jnp.where(keep, pos, pos + L)
            order = jnp.argsort(key, axis=1)     # stable: keepers first
            w = jnp.take_along_axis(w, order, axis=1)
            lens = keep.sum(1, dtype=jnp.int32)
        elif code == Opcode.DUP_FIRST:
            nz = lens > 0
            out = jnp.where(pos < p1, w[:, 0:1], _gather(w, pos - p1))
            w = jnp.where(nz[:, None], out, w)
            grow = jnp.where(nz, lens + p1, lens)
        elif code == Opcode.DUP_LAST:
            nz = lens > 0
            last = _char_at(w, lens - 1)[:, None]
            out = jnp.where(pos < lc, w, last)
            w = jnp.where(nz[:, None], out, w)
            grow = jnp.where(nz, lens + p1, lens)
        elif code == Opcode.DUP_ALL:
            w = _gather(w, pos // 2)
            grow = 2 * lens
        elif code == Opcode.SWAP_FRONT:
            two = lens >= 2
            src = jnp.where(pos == 0, 1, jnp.where(pos == 1, 0, pos))
            w = jnp.where(two[:, None], _gather(w, src), w)
        elif code == Opcode.SWAP_BACK:
            two = lens >= 2
            src = jnp.where(pos == lc - 1, lc - 2,
                            jnp.where(pos == lc - 2, lc - 1, pos))
            w = jnp.where(two[:, None], _gather(w, src), w)
        elif code == Opcode.SWAP_AT:
            hit = (p1 < lens) & (p2 < lens)
            src = jnp.where(pos == p1, p2, jnp.where(pos == p2, p1, pos))
            w = jnp.where(hit[:, None], _gather(w, src), w)
        elif code == Opcode.SHIFT_LEFT:
            if p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc), w << 1, w)
        elif code == Opcode.SHIFT_RIGHT:
            if p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc), w >> 1, w)
        elif code == Opcode.INCR_AT:
            if p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc), w + jnp.uint8(1), w)
        elif code == Opcode.DECR_AT:
            if p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc), w - jnp.uint8(1), w)
        elif code == Opcode.REPL_NEXT:
            if p1 + 1 < L:
                w = jnp.where((pos == p1) & (p1 + 1 < lc),
                              w[:, p1 + 1:p1 + 2], w)
        elif code == Opcode.REPL_PREV:
            if 1 <= p1 < L:
                w = jnp.where((pos == p1) & (p1 < lc),
                              w[:, p1 - 1:p1], w)
        elif code == Opcode.DUP_BLOCK_FRONT:
            hit = p1 <= lens
            out = jnp.where(pos < p1, w, _gather(w, pos - p1))
            w = jnp.where(hit[:, None], out, w)
            grow = jnp.where(hit, lens + p1, lens)
        elif code == Opcode.DUP_BLOCK_BACK:
            hit = p1 <= lens
            out = jnp.where(pos < lc, w, _gather(w, pos - p1))
            w = jnp.where(hit[:, None], out, w)
            grow = jnp.where(hit, lens + p1, lens)
        elif code == Opcode.APPEND:
            w = jnp.where(pos == lc, jnp.uint8(p1), w)
            grow = lens + 1
        elif code == Opcode.PREPEND:
            w = _gather(w, pos - 1)
            w = jnp.where(pos == 0, jnp.uint8(p1), w)
            grow = lens + 1
        elif code in (Opcode.TITLE, Opcode.TITLE_SEP):
            sep = 0x20 if code == Opcode.TITLE else p1
            prev = _gather(w, pos - 1)     # original bytes, shifted right
            low = _lower(w)
            up_here = (pos == 0) | (prev == jnp.uint8(sep))
            w = jnp.where(up_here & (pos < lc), _upper(low), low)
        elif code == Opcode.REJ_GT:
            valid = valid & (lens <= p1)
        elif code == Opcode.REJ_LT:
            valid = valid & (lens >= p1)
        elif code == Opcode.REJ_NEQ_LEN:
            valid = valid & (lens == p1)
        elif code == Opcode.REJ_CONTAIN:
            valid = valid & ~_contains(w, lens, p1)
        elif code == Opcode.REJ_NOT_CONTAIN:
            valid = valid & _contains(w, lens, p1)
        elif code == Opcode.REJ_NOT_FIRST:
            valid = valid & (lens > 0) & (w[:, 0] == jnp.uint8(p1))
        elif code == Opcode.REJ_NOT_LAST:
            valid = valid & (lens > 0) & (
                _char_at(w, lens - 1) == jnp.uint8(p1))
        elif code == Opcode.REJ_NOT_AT:
            if p1 < L:
                valid = valid & (p1 < lens) & (w[:, p1] == jnp.uint8(p2))
            else:
                valid = valid & False
        elif code == Opcode.REJ_LT_COUNT:
            valid = valid & (_count(w, lens, p2) >= p1)
        else:  # pragma: no cover
            raise AssertionError(f"unhandled opcode {code}")

        if grow is not None:
            valid = valid & (grow <= max_len)
            lens = jnp.minimum(grow, jnp.int32(max_len))
        # Re-establish the zero-tail invariant (growth ops may have
        # written garbage past a rejected lane's clamped length).
        w = jnp.where(pos < lens[:, None], w, jnp.uint8(0))
    return w, lens, valid
