"""Host rule interpreter — the correctness oracle.

Semantics are defined HERE (and mirrored exactly by rules/device.py;
tests/test_rules.py holds the equivalence property tests):

- Case operations are ASCII-only (a-z / A-Z), like the standard engines.
- A positional parameter referring past the end of the word makes the
  operation a NO-OP (the word passes through unchanged).
- A growth operation (append, duplicate, reflect, ...) whose result
  would exceed `max_len` REJECTS the candidate (returns None) — the
  candidate is skipped, never hashed, matching the fixed-width device
  buffers where an oversized result cannot be represented.
- Rejection operations (`<`, `>`, `!`, `/`, ...) reject without editing.
"""

from __future__ import annotations

from typing import Optional, Sequence

from dprf_tpu.rules.parser import Op, Opcode


def _tolower(b: int) -> int:
    return b + 32 if 0x41 <= b <= 0x5A else b


def _toupper(b: int) -> int:
    return b - 32 if 0x61 <= b <= 0x7A else b


def _toggle(b: int) -> int:
    if 0x41 <= b <= 0x5A:
        return b + 32
    if 0x61 <= b <= 0x7A:
        return b - 32
    return b


def _title(w: list[int], sep: int) -> list[int]:
    out = [_tolower(b) for b in w]
    for i in range(len(out)):
        if i == 0 or w[i - 1] == sep:
            out[i] = _toupper(out[i])
    return out


def apply_rule(word: bytes, ops: Sequence[Op],
               max_len: int = 55) -> Optional[bytes]:
    """Apply one rule; returns the mangled word or None (rejected)."""
    w = list(word)
    for op in ops:
        code, p1, p2 = op.opcode, op.p1, op.p2
        n = len(w)
        if code == Opcode.NOOP:
            pass
        elif code == Opcode.LOWER:
            w = [_tolower(b) for b in w]
        elif code == Opcode.UPPER:
            w = [_toupper(b) for b in w]
        elif code == Opcode.CAPITALIZE:
            w = [_tolower(b) for b in w]
            if w:
                w[0] = _toupper(w[0])
        elif code == Opcode.INV_CAPITALIZE:
            w = [_toupper(b) for b in w]
            if w:
                w[0] = _tolower(w[0])
        elif code == Opcode.TOGGLE_ALL:
            w = [_toggle(b) for b in w]
        elif code == Opcode.TOGGLE_AT:
            if p1 < n:
                w[p1] = _toggle(w[p1])
        elif code == Opcode.REVERSE:
            w.reverse()
        elif code == Opcode.DUPLICATE:
            if 2 * n > max_len:
                return None
            w = w + w
        elif code == Opcode.DUPLICATE_N:
            if n * (p1 + 1) > max_len:
                return None
            w = w * (p1 + 1)
        elif code == Opcode.REFLECT:
            if 2 * n > max_len:
                return None
            w = w + w[::-1]
        elif code == Opcode.ROT_LEFT:
            if n > 1:
                w = w[1:] + w[:1]
        elif code == Opcode.ROT_RIGHT:
            if n > 1:
                w = w[-1:] + w[:-1]
        elif code == Opcode.DEL_FIRST:
            w = w[1:]
        elif code == Opcode.DEL_LAST:
            w = w[:-1]
        elif code == Opcode.DEL_AT:
            if p1 < n:
                del w[p1]
        elif code == Opcode.EXTRACT:
            if p1 < n:
                w = w[p1:p1 + p2]
        elif code == Opcode.OMIT:
            if p1 < n:
                w = w[:p1] + w[p1 + p2:]
        elif code == Opcode.INSERT:
            if p1 <= n:
                if n + 1 > max_len:
                    return None
                w.insert(p1, p2)
        elif code == Opcode.OVERWRITE:
            if p1 < n:
                w[p1] = p2
        elif code == Opcode.TRUNCATE:
            w = w[:p1]
        elif code == Opcode.SUBSTITUTE:
            w = [p2 if b == p1 else b for b in w]
        elif code == Opcode.PURGE:
            w = [b for b in w if b != p1]
        elif code == Opcode.DUP_FIRST:
            if n:
                if n + p1 > max_len:
                    return None
                w = [w[0]] * p1 + w
        elif code == Opcode.DUP_LAST:
            if n:
                if n + p1 > max_len:
                    return None
                w = w + [w[-1]] * p1
        elif code == Opcode.DUP_ALL:
            if 2 * n > max_len:
                return None
            w = [b for b in w for _ in (0, 1)]
        elif code == Opcode.SWAP_FRONT:
            if n >= 2:
                w[0], w[1] = w[1], w[0]
        elif code == Opcode.SWAP_BACK:
            if n >= 2:
                w[-1], w[-2] = w[-2], w[-1]
        elif code == Opcode.SWAP_AT:
            if p1 < n and p2 < n:
                w[p1], w[p2] = w[p2], w[p1]
        elif code == Opcode.SHIFT_LEFT:
            if p1 < n:
                w[p1] = (w[p1] << 1) & 0xFF
        elif code == Opcode.SHIFT_RIGHT:
            if p1 < n:
                w[p1] = w[p1] >> 1
        elif code == Opcode.INCR_AT:
            if p1 < n:
                w[p1] = (w[p1] + 1) & 0xFF
        elif code == Opcode.DECR_AT:
            if p1 < n:
                w[p1] = (w[p1] - 1) & 0xFF
        elif code == Opcode.REPL_NEXT:
            if p1 + 1 < n:
                w[p1] = w[p1 + 1]
        elif code == Opcode.REPL_PREV:
            if 1 <= p1 < n:
                w[p1] = w[p1 - 1]
        elif code == Opcode.DUP_BLOCK_FRONT:
            if p1 <= n:
                if n + p1 > max_len:
                    return None
                w = w[:p1] + w
        elif code == Opcode.DUP_BLOCK_BACK:
            if p1 <= n:
                if n + p1 > max_len:
                    return None
                w = w + w[n - p1:]
        elif code == Opcode.APPEND:
            if n + 1 > max_len:
                return None
            w.append(p1)
        elif code == Opcode.PREPEND:
            if n + 1 > max_len:
                return None
            w.insert(0, p1)
        elif code == Opcode.TITLE:
            w = _title(w, 0x20)
        elif code == Opcode.TITLE_SEP:
            w = _title(w, p1)
        elif code == Opcode.REJ_GT:
            if n > p1:
                return None
        elif code == Opcode.REJ_LT:
            if n < p1:
                return None
        elif code == Opcode.REJ_NEQ_LEN:
            if n != p1:
                return None
        elif code == Opcode.REJ_CONTAIN:
            if p1 in w:
                return None
        elif code == Opcode.REJ_NOT_CONTAIN:
            if p1 not in w:
                return None
        elif code == Opcode.REJ_NOT_FIRST:
            if not w or w[0] != p1:
                return None
        elif code == Opcode.REJ_NOT_LAST:
            if not w or w[-1] != p1:
                return None
        elif code == Opcode.REJ_NOT_AT:
            if p1 >= n or w[p1] != p2:
                return None
        elif code == Opcode.REJ_LT_COUNT:
            if sum(1 for b in w if b == p2) < p1:
                return None
        else:  # pragma: no cover
            raise AssertionError(f"unhandled opcode {code}")
    return bytes(w)
